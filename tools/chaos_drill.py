#!/usr/bin/env python
"""Chaos campaign: sweep every registered fault point × applicable mode
and assert the system's robustness invariants under each.

PRs 1–2 proved each fault-tolerance invariant with ONE hand-written
drill at ONE fault point; this campaign makes the guarantee structural
(the same move photonlint made for static contracts): it enumerates
``utils/faults.FAULT_POINTS``, runs a short real GAME training
subprocess under each armed (point, mode) cell via ``PHOTON_FAULTS``,
and asserts the invariant matrix:

1. **Documented exit semantics** — the process ends rc 0 (possibly
   degraded), rc 3 with a ``PHOTON_ABORT`` line (clean abort), rc 75
   with a ``PHOTON_PREEMPTED`` line (graceful stop), or the injected
   kill's exit code. NEVER a stack-trace crash.
2. **Restorable checkpoint directory** — after every cell,
   ``CheckpointManager.restore()`` either returns a snapshot or raises
   one of its documented exceptions; stale ``.tmp`` litter is gone.
3. **Bit-exact resume** — after every ``kill`` or ``signal`` cell, a
   relaunch completes and its final objective equals the fault-free
   reference run's, float-for-float (the resume-anywhere contract).
4. **Surviving observability** — ``metrics.jsonl`` / ``spans.jsonl``
   parse line-complete even after a mid-write kill, and
   ``run_manifest.json`` exists.
5. **Cell-specific**: shard-corruption cells must complete with the
   shard QUARANTINED and ``data_coverage < 1`` recorded in
   ``metrics.json`` (degraded, not dead).

Also runs the acceptance scenario from the issue directly: a training
run with one deliberately corrupted Avro shard (no fault injection at
all — real bytes flipped on disk) must complete with the shard
quarantined and coverage reported.

Usage::

    python tools/chaos_drill.py [--workdir DIR] [--smoke]
                                [--points P1,P2] [--report PATH]

``--smoke`` runs the curated tier-1 subset (< 60 s); the full campaign
covers every (point, mode) cell. Emits ``chaos_report.json`` and exits
0 on an all-green matrix, 2 otherwise (``CHAOS_OK`` / ``CHAOS_FAIL``).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

KILL_EXIT = 19
CLEAN_ABORT_EXIT = 3
PREEMPTED_EXIT = 75  # photon_ml_tpu.cli.PREEMPTED_EXIT (EX_TEMPFAIL)
N_SHARDS = 4


# ---------------------------------------------------------------------------
# Workload fixture: tiny sharded GAME dataset + pre-built feature sets
# ---------------------------------------------------------------------------


def build_fixture(root: str) -> dict:
    """Synthetic 4-shard GAME input + feature name/term sets. Small
    enough that one driver run is a few seconds; sharded so shard-level
    quarantine has something to lose."""
    import numpy as np

    from photon_ml_tpu.io import schemas
    from photon_ml_tpu.io.avro import write_container

    game_schema = {
        "name": "GameRecord", "type": "record", "namespace": "chaos",
        "fields": [
            {"name": "uid", "type": ["null", "string"], "default": None},
            {"name": "response", "type": "double"},
            {"name": "offset", "type": ["null", "double"],
             "default": None},
            {"name": "weight", "type": ["null", "double"],
             "default": None},
            {"name": "metadataMap",
             "type": ["null", {"type": "map", "values": "string"}],
             "default": None},
            {"name": "globalFeatures",
             "type": {"type": "array", "items": schemas.FEATURE}},
            {"name": "userFeatures",
             "type": {"type": "array", "items": "FeatureAvro"}},
        ],
    }
    data_dir = os.path.join(root, "data")
    os.makedirs(data_dir, exist_ok=True)
    d_g, d_u, n_users, rows_per_shard = 4, 2, 5, 40
    w_rng = np.random.default_rng(7)
    w_g = w_rng.normal(size=d_g)
    W_u = w_rng.normal(size=(n_users, d_u))
    for shard in range(N_SHARDS):
        rng = np.random.default_rng(100 + shard)
        records = []
        for i in range(rows_per_shard):
            u = int(rng.integers(0, n_users))
            xg = rng.normal(size=d_g)
            xu = rng.normal(size=d_u)
            margin = xg @ w_g + xu @ W_u[u]
            y = float(rng.uniform() < 1.0 / (1.0 + np.exp(-margin)))
            records.append({
                "uid": f"s{shard}_{i}", "response": y, "offset": None,
                "weight": None, "metadataMap": {"userId": f"user{u}"},
                "globalFeatures": [
                    {"name": f"g{j}", "term": "", "value": float(xg[j])}
                    for j in range(d_g)],
                "userFeatures": [
                    {"name": f"u{j}", "term": "", "value": float(xu[j])}
                    for j in range(d_u)],
            })
        write_container(
            os.path.join(data_dir, f"part-{shard:05d}.avro"),
            game_schema, records)

    fs_dir = os.path.join(root, "feature_sets")
    os.makedirs(fs_dir, exist_ok=True)
    for section, dim in (("globalFeatures", d_g), ("userFeatures", d_u)):
        with open(os.path.join(fs_dir, section), "w") as fh:
            prefix = "g" if section == "globalFeatures" else "u"
            for j in range(dim):
                fh.write(f"{prefix}{j}\t\n")
    return {"data_dir": data_dir, "fs_dir": fs_dir}


def driver_args(data_dir: str, fs_dir: str, out_dir: str, ckpt_dir: str,
                trace_dir: str) -> list[str]:
    # --telemetry-endpoint points at a unix socket NOBODY ever serves:
    # every cell (and the reference) trains under the live plane's
    # worst consumer — a permanently dead one — so the obs.export cells
    # drill the fault modes ON TOP of the dead-consumer fallback, and
    # the bit-exact checks prove the plane never touches training math
    return [
        "--telemetry-endpoint",
        "unix:" + os.path.join(trace_dir, "no_consumer.sock"),
        "--train-input-dirs", data_dir,
        "--output-dir", out_dir,
        "--task-type", "LOGISTIC_REGRESSION",
        "--feature-name-and-term-set-path", fs_dir,
        "--feature-shard-id-to-feature-section-keys-map",
        "global:globalFeatures|per_user:userFeatures",
        "--updating-sequence", "fixed,perUser",
        "--fixed-effect-data-configurations", "fixed:global,1",
        "--random-effect-data-configurations",
        "perUser:userId,per_user,1",
        "--fixed-effect-optimization-configurations",
        "fixed:10,1e-6,0.1,1,LBFGS,L2",
        "--random-effect-optimization-configurations",
        "perUser:10,1e-6,0.5,1,LBFGS,L2",
        "--num-iterations", "2",
        "--checkpoint-dir", ckpt_dir,
        "--checkpoint-every-coordinates", "1",
        "--recovery-policy", "skip",
        "--recovery-max-retries", "2",
        "--recovery-quarantine-after", "2",
        "--max-shard-loss-frac", "0.5",
        "--trace-dir", trace_dir,
        "--trace-heartbeat-seconds", "0.2",
        "--model-output-mode", "NONE",
        "--delete-output-dir-if-exists", "true",
    ]


# ---------------------------------------------------------------------------
# Cell matrix
# ---------------------------------------------------------------------------

#: expected ∈ {"ok", "degraded", "abort", "ok_or_abort", "killed",
#: "preempted"}.
#: "degraded" = rc 0 AND metrics.json records data_coverage < 1.
#: "preempted" = rc 75 + PHOTON_PREEMPTED line; resume is bit-exact.
CellDef = dict


def build_cells(smoke: bool) -> list[CellDef]:
    def cell(point, mode, spec, expected, smoke_cell=False,
             pre_run=False, note="", bit_exact=False,
             expect_drops=False, variant="", extra_args=None,
             bridge=False, serve=False):
        return {"point": point, "mode": mode, "spec": spec,
                "expected": expected, "smoke": smoke_cell,
                "pre_run": pre_run, "note": note,
                "bit_exact": bit_exact, "expect_drops": expect_drops,
                "variant": variant, "extra_args": extra_args or [],
                "bridge": bridge, "serve": serve}

    cells = [
        # --- I/O layer: retry → quarantine → coverage budget ----------
        cell("io.shard_open", "io_error", "io.shard_open=io_error:1",
             "ok", smoke_cell=True, note="one transient EIO: retried"),
        cell("io.shard_open", "flaky", "io.shard_open=flaky:999:0.7",
             "ok_or_abort",
             note="seeded flaky I/O; quarantine within or past budget"),
        cell("io.shard_open", "slow", "io.shard_open=slow:2:0.05", "ok"),
        cell("io.shard_open", "raise", "io.shard_open=raise:1", "ok"),
        cell("io.avro_read", "raise", "io.avro_read=raise:1", "ok",
             note="InjectedFault is retryable: recovered"),
        cell("io.avro_read", "io_error", "io.avro_read=io_error:1", "ok"),
        cell("io.avro_read", "corrupt", "io.avro_read=corrupt:1",
             "degraded", smoke_cell=True,
             note="shard bytes flipped on disk → quarantined"),
        cell("io.avro_read", "partial", "io.avro_read=partial:1",
             "degraded", note="shard truncated → quarantined"),
        cell("io.index_map", "raise", "io.index_map=raise:1", "ok"),
        cell("io.index_map", "io_error", "io.index_map=io_error:99",
             "abort", smoke_cell=True,
             note="feature maps are required state: clean abort"),
        # --- checkpoint write path ------------------------------------
        cell("ckpt.write_bytes", "enospc", "ckpt.write_bytes=enospc:1",
             "ok", note="transient full disk: rewrite recovered"),
        cell("ckpt.write_bytes", "io_error",
             "ckpt.write_bytes=io_error:99", "ok",
             note="persistently unwritable: snapshots skipped, "
                  "training continues"),
        cell("ckpt.write_bytes", "partial", "ckpt.write_bytes=partial:1",
             "ok", smoke_cell=True,
             note="torn write that still checksums: restore must fall "
                  "back past it"),
        cell("ckpt.write_bytes", "kill",
             f"ckpt.write_bytes=kill:1:{KILL_EXIT}", "killed",
             note="killed mid-write: stale .tmp cleaned on relaunch"),
        cell("ckpt.write_bytes", "signal",
             "ckpt.write_bytes=signal:1", "preempted",
             note="SIGTERM lands DURING a checkpoint write: the write "
                  "finishes, the run stops at the next barrier"),
        cell("ckpt.save", "raise", "ckpt.save=raise:1", "abort",
             note="post-write fault before rename fails the save "
                  "outright (documented drill semantics)"),
        cell("ckpt.save", "kill", f"ckpt.save=kill:1:{KILL_EXIT}",
             "killed",
             note="killed between fsync and rename (full campaign "
                  "only: smoke's kill+resume proof is cd.update=kill)"),
        cell("ckpt.restore", "raise", "ckpt.restore=raise:1", "abort",
             pre_run=True,
             note="restore drill fails outright → clean abort"),
        cell("ckpt.restore", "corrupt", "ckpt.restore=corrupt:1", "ok",
             pre_run=True,
             note="chosen step corrupted pre-read → falls back"),
        # --- training loop (recovery policy armed) --------------------
        cell("cd.update", "nan", "cd.update=nan:1", "ok",
             smoke_cell=True, note="poisoned update: damped retry"),
        cell("cd.update", "raise", "cd.update=raise:1", "ok"),
        cell("cd.update", "kill", f"cd.update@1.0=kill:1:{KILL_EXIT}",
             "killed", smoke_cell=True,
             note="killed mid-sweep: resume is bit-exact"),
        cell("cd.update", "delay", "cd.update=delay:1:0.2", "ok"),
        cell("cd.update", "signal", "cd.update@0.1=signal:1",
             "preempted", smoke_cell=True, variant="per_update",
             note="SIGTERM mid-update: latched, honored at the next "
                  "block barrier, resume bit-exact"),
        cell("cd.update", "signal", "cd.update@0.0=signal:1",
             "preempted", variant="mid_block",
             extra_args=["--cd-block-size", "2"],
             note="SIGTERM inside a 2-wide block: the WHOLE block "
                  "commits before the stop (barrier-only polling)"),
        cell("cd.sweep", "delay", "cd.sweep=delay:1:0.2", "ok"),
        cell("cd.sweep", "kill", f"cd.sweep@1=kill:1:{KILL_EXIT}",
             "killed"),
        cell("optimizer.gradient", "nan", "optimizer.gradient=nan:1",
             "ok"),
        cell("optimizer.gradient", "raise", "optimizer.gradient=raise:1",
             "ok"),
        # --- observability: must degrade, never kill ------------------
        cell("obs.flush", "io_error", "obs.flush=io_error:99", "ok",
             smoke_cell=True),
        cell("obs.flush", "enospc", "obs.flush=enospc:99", "ok"),
        cell("obs.flush", "flaky", "obs.flush=flaky:999:0.5", "ok"),
        # --- live telemetry plane: a dead/flaky/laggy consumer leaves
        # --- training exit-0 and BIT-EXACT, with only telemetry_dropped
        # --- as evidence anything was ever wrong ----------------------
        cell("obs.export", "io_error", "obs.export=io_error:99", "ok",
             smoke_cell=True, bit_exact=True, expect_drops=True,
             note="telemetry I/O hard down: batches dropped+counted, "
                  "training result bit-exact"),
        cell("obs.export", "slow", "obs.export=slow:20:0.05", "ok",
             bit_exact=True,
             note="laggy consumer path: writer thread absorbs the "
                  "latency, hot loop never blocks"),
        cell("obs.export", "flaky", "obs.export=flaky:999:0.5", "ok",
             bit_exact=True,
             note="seeded flaky telemetry I/O: retried or dropped, "
                  "never fatal"),
        # --- OTLP bridge: the fault point fires in the BRIDGE process
        # --- (training runs fault-free); the bridge posts to a dead
        # --- collector with the fault armed on top and must still exit
        # --- 0 with the batches dropped+counted, the training result
        # --- bit-exact either way ------------------------------------
        cell("obs.otlp", "io_error", "obs.otlp=io_error:99", "ok",
             smoke_cell=True, bridge=True, bit_exact=True,
             note="OTLP POST path hard down: batches dropped, bridge "
                  "exits 0, training untouched"),
        cell("obs.otlp", "flaky", "obs.otlp=flaky:999:0.5", "ok",
             bridge=True, bit_exact=True,
             note="seeded flaky collector I/O on top of a dead "
                  "collector: still dropped, still exit 0"),
        cell("obs.otlp", "slow", "obs.otlp=slow:20:0.05", "ok",
             bridge=True, bit_exact=True,
             note="laggy collector path: the bridge absorbs the "
                  "latency itself"),
        # --- scoring service: the fault point fires in a real
        # --- photon_serve subprocess; invariants are connection-scoped
        # --- failure (the service outlives its worst request) and the
        # --- batch-parity anchor (post-fault scores stay bit-identical
        # --- to the shared batch scoring core) -------------------------
        cell("serve.request", "io_error", "serve.request=io_error:1",
             "ok", serve=True,
             note="one request fails with an error response and drops "
                  "its connection; a fresh connection scores bit-exact"),
        cell("serve.batch", "io_error", "serve.batch=io_error:1", "ok",
             serve=True,
             note="one micro-batch fails, its requests get error "
                  "responses; the next batch scores bit-exact"),
        cell("serve.batch", "signal", "serve.batch=signal:1",
             "preempted", serve=True,
             note="SIGTERM lands during a batch: the batch completes "
                  "and replies, the service drains and exits 75"),
        cell("serve.batch", "kill",
             f"serve.batch=kill:1:{KILL_EXIT}", "killed", serve=True,
             note="killed mid-batch under photon_supervise --module: "
                  "relaunched (kill budget claimed across "
                  "incarnations), scores bit-exact after relaunch, "
                  "stop-file drains the supervisor to done"),
        # --- hot-swap: the swap state machine under fault; invariants
        # --- are "a refused swap leaves the CURRENT generation serving
        # --- bit-exact" and "a completed swap serves the candidate
        # --- bit-exact vs the shared batch core" -----------------------
        cell("serve.model_load", "io_error",
             "serve.model_load=io_error:1", "ok", serve=True,
             variant="swap_retry",
             note="one transient I/O error in the swap loader thread: "
                  "retried (utils/retry), the swap completes, the new "
                  "generation scores bit-exact"),
        cell("serve.model_load", "corrupt",
             "serve.model_load=corrupt:1", "ok", serve=True,
             variant="swap_refused",
             note="candidate coefficient bytes flipped on disk before "
                  "the load: the swap is REFUSED (load failure or "
                  "canary violation) and the service keeps serving "
                  "generation 1 bit-exact"),
        cell("serve.model_load", "slow", "serve.model_load=slow:1:3",
             "preempted", serve=True, variant="swap_drain_race",
             note="SIGTERM lands while the loader thread is stalled: "
                  "the drain refuses the in-flight swap and the "
                  "service still exits 75 cleanly"),
        cell("serve.swap", "io_error", "serve.swap=io_error:1", "ok",
             serve=True, variant="swap_flip_refused",
             note="I/O error at the atomic flip itself: the flip is "
                  "refused, the old generation keeps serving "
                  "bit-exact, and a RE-REQUESTED swap (budget spent) "
                  "completes"),
        cell("serve.swap", "kill",
             f"serve.swap=kill:1:{KILL_EXIT}", "killed", serve=True,
             note="killed mid-flip under photon_supervise --module: "
                  "the relaunch serves exactly one consistent "
                  "generation (the boot model) bit-exact; stop-file "
                  "drains the supervisor to done"),
        # --- scorer fleet: serve.route fires in the MEMBER process on
        # --- routed sub-requests (tag = fleet index), so what's
        # --- drilled is the ROUTER's machinery — bounded retry,
        # --- failover to the shard's fallback member, typed shed —
        # --- and its no-black-hole ledger --------------------------
        cell("serve.route", "io_error", "serve.route@1=io_error:1",
             "ok", serve=True, variant="fleet",
             note="member 1's routed sub-request EIOs once: retried "
                  "on the same member (budget spent), the request "
                  "answers bit-exact, no failover needed"),
        cell("serve.route", "flaky", "serve.route@1=flaky:6:0.5",
             "ok", serve=True, variant="fleet",
             note="seeded flaky member: flaky sub-requests retried "
                  "(or failed over), every request answered "
                  "bit-exact, zero typed errors"),
        cell("serve.route", "slow", "serve.route@1=slow:2:0.05",
             "ok", serve=True, variant="fleet",
             note="a slow member stalls well inside the router's "
                  "member timeout: requests complete bit-exact, "
                  "nothing sheds"),
        cell("serve.route", "kill",
             f"serve.route@1=kill:1:{KILL_EXIT}", "killed",
             serve=True, variant="fleet", smoke_cell=True,
             note="the no-black-hole drill: member 1 dies mid-request "
                  "under photon_supervise --fleet; every submitted "
                  "request is answered (request-id accounting — "
                  "scores or a typed error, zero silent drops), "
                  "answered scores bit-exact, and the relaunched "
                  "member re-admits onto the live generation"),
        # --- serve telemetry plane: fleet traffic with EVERY process
        # --- (members + router) pointed at a permanently dead
        # --- --telemetry-endpoint (a never-writable file: target —
        # --- the terminal mode past the dead-socket fallback) — no
        # --- fault spec, the dead consumer IS the chaos. Scores
        # --- bit-exact, ledger clean, the only evidence
        # --- telemetry_dropped{kind} counters ------------------------
        cell("serve.telemetry", "dead_consumer",
             "--telemetry-endpoint=<never-writable>", "ok",
             serve=True, variant="fleet_dead_telemetry",
             bit_exact=True, expect_drops=True,
             note="fleet traffic under a permanently dead telemetry "
                  "consumer: every request answers bit-exact, the "
                  "route ledger stays clean, and the only evidence is "
                  "telemetry_dropped counters in the run dirs"),
    ]
    if smoke:
        cells = [c for c in cells if c["smoke"]]
    return cells


# ---------------------------------------------------------------------------
# Invariant checks
# ---------------------------------------------------------------------------


def _run_driver(args, extra_env=None, timeout=240):
    env = dict(os.environ)
    env.pop("PHOTON_FAULTS", None)
    env.pop("PHOTON_FAULTS_STATE_DIR", None)
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-m", "photon_ml_tpu.cli.game_training_driver",
         *args],
        env=env, cwd=_REPO, text=True, capture_output=True,
        timeout=timeout)


def _final_objective(out_dir: str):
    with open(os.path.join(out_dir, "metrics.json")) as fh:
        record = json.load(fh)
    states = record["grid"][0]["states"]
    return record, (states[-1]["objective"] if states else None)


def _telemetry_dropped_total(trace_dir: str):
    """Sum of the telemetry_dropped counter's label sets in the run's
    final metrics snapshot (None when the stream is missing)."""
    path = os.path.join(trace_dir, "metrics.jsonl")
    if not os.path.exists(path):
        return None
    total = 0.0
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("kind") == "counter" \
                    and rec.get("name") == "telemetry_dropped":
                total += rec.get("value", 0.0)
    return total


def _check_no_traceback(proc, failures):
    if "Traceback (most recent call last)" in proc.stderr:
        failures.append("stack-trace crash:\n" + proc.stderr[-2000:])


def _check_checkpoint_restorable(ckpt_dir: str, failures):
    """Invariant 2: restore() returns or raises its DOCUMENTED
    exceptions; no stale .tmp dirs linger after a save/restore cycle."""
    from photon_ml_tpu.utils.checkpoint import (
        CheckpointCorruptionError,
        CheckpointManager,
    )

    if not os.path.isdir(ckpt_dir):
        return
    mgr = CheckpointManager(ckpt_dir)
    try:
        mgr.restore()
    except (FileNotFoundError, CheckpointCorruptionError):
        pass
    except Exception as e:  # noqa: BLE001 — the assertion is the point
        failures.append(
            f"checkpoint dir not restorable: restore() raised "
            f"undocumented {type(e).__name__}: {e}")
    stale = [n for n in os.listdir(ckpt_dir) if n.endswith(".tmp")]
    if stale:
        failures.append(f"stale tmp dirs survive restore(): {stale}")


def _check_trace_survives(trace_dir: str, failures):
    """Invariant 4: every COMPLETE line of the jsonl streams parses and
    the manifest exists (a mid-write kill may tear the last line)."""
    if not os.path.isdir(trace_dir):
        failures.append("trace dir missing entirely")
        return
    if not os.path.exists(os.path.join(trace_dir, "run_manifest.json")):
        failures.append("run_manifest.json missing")
    for name in ("metrics.jsonl", "spans.jsonl"):
        path = os.path.join(trace_dir, name)
        if not os.path.exists(path):
            continue
        with open(path, "rb") as fh:
            raw = fh.read()
        for line in raw.split(b"\n")[:-1]:  # complete lines only
            if not line.strip():
                continue
            try:
                json.loads(line)
            except ValueError:
                failures.append(f"{name}: complete line does not parse: "
                                f"{line[:120]!r}")
                break


def run_cell(c: CellDef, fixture: dict, workdir: str,
             reference_objective) -> dict:
    """One (point, mode) cell: arm via PHOTON_FAULTS, run the driver,
    assert the invariant matrix."""
    if c.get("serve"):
        return _run_serve_cell(c, workdir)
    name = f"{c['point']}={c['mode']}"
    if c.get("variant"):
        name += f"@{c['variant']}"
    cell_dir = os.path.join(
        workdir, "cells",
        name.replace("=", "_").replace(".", "_").replace("@", "_"))
    shutil.rmtree(cell_dir, ignore_errors=True)
    os.makedirs(cell_dir)
    # every cell gets its OWN copy of the input: corrupt/partial modes
    # mutate shards on disk and must not leak into other cells
    data_dir = os.path.join(cell_dir, "data")
    shutil.copytree(fixture["data_dir"], data_dir)
    out = os.path.join(cell_dir, "out")
    ckpt = os.path.join(cell_dir, "ckpt")
    tracked = os.path.join(cell_dir, "trace")
    args = driver_args(data_dir, fixture["fs_dir"], out, ckpt, tracked)
    args += c.get("extra_args") or []
    failures: list[str] = []
    t0 = time.monotonic()

    if c.get("extra_args"):
        # extra flags (e.g. --cd-block-size) change the training math,
        # so the shared fault-free reference no longer anchors the
        # bit-exact check — this cell runs its own
        ref_out = os.path.join(cell_dir, "ref_out")
        ref = _run_driver(driver_args(
            data_dir, fixture["fs_dir"], ref_out,
            os.path.join(cell_dir, "ref_ckpt"),
            os.path.join(cell_dir, "ref_trace")) + c["extra_args"])
        if ref.returncode != 0:
            failures.append(f"cell reference run failed "
                            f"rc={ref.returncode}:\n{ref.stderr[-1000:]}")
        else:
            _, reference_objective = _final_objective(ref_out)

    if c["pre_run"]:  # seed checkpoints for restore-path cells
        pre = _run_driver(args)
        if pre.returncode != 0:
            failures.append(f"pre-run failed rc={pre.returncode}:\n"
                            f"{pre.stderr[-1000:]}")

    if c.get("bridge"):
        return _run_bridge_cell(c, name, args, tracked, out,
                                reference_objective, ckpt, failures, t0)

    state_dir = os.path.join(cell_dir, "fault_state")
    proc = _run_driver(args, extra_env={
        "PHOTON_FAULTS": c["spec"],
        "PHOTON_FAULTS_STATE_DIR": state_dir,
        "PHOTON_FAULTS_SEED": "42",
    })
    rc = proc.returncode
    _check_no_traceback(proc, failures)

    expected = c["expected"]
    outcome = "?"
    if expected == "killed":
        if rc != KILL_EXIT:
            failures.append(f"expected injected kill rc={KILL_EXIT}, "
                            f"got rc={rc}:\n{proc.stderr[-1000:]}")
        else:
            # invariant 3: relaunch (same env minus faults) resumes and
            # lands on the fault-free reference objective, float-exact
            resume = _run_driver(args)
            _check_no_traceback(resume, failures)
            if resume.returncode != 0:
                failures.append(
                    f"resume run failed rc={resume.returncode}:\n"
                    f"{resume.stderr[-1000:]}")
            else:
                _, obj = _final_objective(out)
                if obj != reference_objective:
                    failures.append(
                        f"resume NOT bit-exact: final objective {obj!r} "
                        f"vs reference {reference_objective!r}")
        outcome = "killed+resumed"
    elif expected == "preempted":
        if rc != PREEMPTED_EXIT:
            failures.append(f"expected graceful preemption "
                            f"rc={PREEMPTED_EXIT}, got rc={rc}:\n"
                            f"{proc.stderr[-1000:]}")
        elif "PHOTON_PREEMPTED" not in proc.stderr:
            failures.append(f"rc={PREEMPTED_EXIT} without a "
                            f"PHOTON_PREEMPTED line:\n"
                            f"{proc.stderr[-1000:]}")
        else:
            # same resume-anywhere contract as an injected kill, but
            # from the SAFE-POINT snapshot the stop path took itself
            resume = _run_driver(args)
            _check_no_traceback(resume, failures)
            if resume.returncode != 0:
                failures.append(
                    f"resume after preemption failed "
                    f"rc={resume.returncode}:\n{resume.stderr[-1000:]}")
            else:
                _, obj = _final_objective(out)
                if obj != reference_objective:
                    failures.append(
                        f"preempted resume NOT bit-exact: final "
                        f"objective {obj!r} vs reference "
                        f"{reference_objective!r}")
        outcome = "preempted+resumed"
    elif expected == "abort":
        if rc != CLEAN_ABORT_EXIT or "PHOTON_ABORT" not in proc.stderr:
            failures.append(
                f"expected clean abort rc={CLEAN_ABORT_EXIT} with "
                f"PHOTON_ABORT line, got rc={rc}:\n"
                f"{proc.stderr[-1000:]}")
        outcome = "clean_abort"
    elif expected in ("ok", "degraded", "ok_or_abort"):
        allowed = {0, CLEAN_ABORT_EXIT} if expected == "ok_or_abort" \
            else {0}
        if rc not in allowed:
            failures.append(f"expected rc in {sorted(allowed)}, got "
                            f"rc={rc}:\n{proc.stderr[-1500:]}")
        if rc == CLEAN_ABORT_EXIT and "PHOTON_ABORT" not in proc.stderr:
            failures.append("rc=3 without a PHOTON_ABORT line")
        if rc == 0 and expected == "degraded":
            record, _ = _final_objective(out)
            cov = record.get("data_coverage")
            lost = (record.get("ingest") or {}).get("train", {})
            lost = (lost or {}).get("shards_quarantined", [])
            if not (cov is not None and cov < 1.0 and lost):
                failures.append(
                    f"expected quarantined shard + coverage < 1, got "
                    f"coverage={cov} quarantined={lost}")
            outcome = f"degraded(coverage={cov})"
        else:
            outcome = {0: "ok", CLEAN_ABORT_EXIT: "clean_abort"}.get(
                rc, f"rc={rc}")
        if rc == 0 and c.get("bit_exact"):
            # the telemetry-plane contract: a broken consumer changes
            # NOTHING about the training result, float-for-float
            _, obj = _final_objective(out)
            if obj != reference_objective:
                failures.append(
                    f"result NOT bit-exact under {name}: final "
                    f"objective {obj!r} vs reference "
                    f"{reference_objective!r}")
        if rc == 0 and c.get("expect_drops"):
            drops = _telemetry_dropped_total(tracked)
            if not drops:
                failures.append(
                    "expected telemetry_dropped > 0 in the final "
                    f"metrics snapshot, found {drops!r}")
            else:
                outcome += f"+dropped({int(drops)})"

    # universal invariants for every cell
    _check_checkpoint_restorable(ckpt, failures)
    _check_trace_survives(tracked, failures)

    return {"cell": name, "spec": c["spec"], "expected": expected,
            "rc": rc, "outcome": outcome, "note": c["note"],
            "seconds": round(time.monotonic() - t0, 1),
            "failures": failures, "passed": not failures}


def _run_bridge_cell(c: CellDef, name: str, args: list[str],
                     tracked: str, out: str, reference_objective,
                     ckpt: str, failures: list[str], t0: float) -> dict:
    """An ``obs.otlp`` cell: the fault point lives in the BRIDGE
    process, not the driver. Train fault-free, then run
    ``tools/otlp_bridge.py`` over the run dir with the fault armed AND
    a dead collector, and assert: bridge rc 0 with its batches
    dropped+counted, training rc 0 and bit-exact."""
    proc = _run_driver(args)
    rc = proc.returncode
    _check_no_traceback(proc, failures)
    if rc != 0:
        failures.append(f"fault-free training run under bridge cell "
                        f"must exit 0, got rc={rc}:\n"
                        f"{proc.stderr[-1500:]}")
    elif c.get("bit_exact"):
        _, obj = _final_objective(out)
        if obj != reference_objective:
            failures.append(
                f"training result NOT bit-exact under {name}: final "
                f"objective {obj!r} vs reference "
                f"{reference_objective!r}")

    env = dict(os.environ)
    env.update({"PHOTON_FAULTS": c["spec"], "PHOTON_FAULTS_SEED": "42"})
    bridge = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "otlp_bridge.py"),
         "--run-dir", tracked,
         # port 9 (discard) is closed on any sane host: the dead
         # collector every POST must survive
         "--collector", "http://127.0.0.1:9"],
        env=env, cwd=_REPO, text=True, capture_output=True, timeout=180)
    outcome = "bridge_survived"
    if bridge.returncode != 0:
        failures.append(
            f"bridge must exit 0 under {name} + dead collector, got "
            f"rc={bridge.returncode}:\n{bridge.stderr[-1500:]}")
    else:
        m = [w for w in bridge.stderr.split() if w.startswith("dropped=")]
        dropped = int(m[-1].split("=", 1)[1]) if m else None
        if not dropped:
            failures.append(
                f"bridge under a dead collector must report dropped "
                f"batches, stderr: {bridge.stderr[-400:]!r}")
        else:
            outcome += f"+dropped({dropped})"

    _check_checkpoint_restorable(ckpt, failures)
    _check_trace_survives(tracked, failures)
    return {"cell": name, "spec": c["spec"], "expected": c["expected"],
            "rc": rc, "outcome": outcome, "note": c["note"],
            "seconds": round(time.monotonic() - t0, 1),
            "failures": failures, "passed": not failures}


# ---------------------------------------------------------------------------
# Scoring-service cells
# ---------------------------------------------------------------------------

_SERVE_FIXTURE: dict = {}


def build_serve_fixture(workdir: str) -> dict:
    """Tiny GAME model on disk + request rows + the reference scores
    computed HERE through the shared batch scoring core
    (`serve.scoring`): the anchor every serve cell's bit-exactness
    check compares against. Also saves a second, "retrained" model
    (same structure, different coefficients) as the hot-swap
    candidate, with its own reference scores — the post-flip
    bit-exactness anchor."""
    if workdir in _SERVE_FIXTURE:
        return _SERVE_FIXTURE[workdir]
    import jax.numpy as jnp
    import numpy as np

    from photon_ml_tpu.game.models import (
        FixedEffectModel,
        GameModel,
        RandomEffectModel,
    )
    from photon_ml_tpu.io.data_format import game_dataset_from_records
    from photon_ml_tpu.io.index_map import IndexMap
    from photon_ml_tpu.io.model_io import save_game_model
    from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel
    from photon_ml_tpu.optimize.config import TaskType
    from photon_ml_tpu.serve.scoring import (
        load_scoring_model,
        score_game_dataset,
    )

    d_g, d_u, n_users = 4, 2, 6
    rng = np.random.default_rng(11)
    imaps = {
        "global": IndexMap.from_keys([f"g{j}" for j in range(d_g)],
                                     add_intercept=True),
        "user": IndexMap.from_keys([f"u{j}" for j in range(d_u)],
                                   add_intercept=True),
    }
    fixed = FixedEffectModel(GeneralizedLinearModel(
        Coefficients(jnp.asarray(rng.normal(size=len(imaps["global"])),
                                 jnp.float32)),
        TaskType.LINEAR_REGRESSION), "global")
    vocab = np.asarray([f"user{u}" for u in range(n_users)])
    re_model = RandomEffectModel(
        random_effect_type="userId", feature_shard_id="user",
        entity_codes=np.arange(n_users),
        coefficients=jnp.asarray(
            rng.normal(size=(n_users, len(imaps["user"]))), jnp.float32))
    model_dir = os.path.join(workdir, "serve_model")
    save_game_model(GameModel({"fixed": fixed, "per-user": re_model}),
                    model_dir, imaps, entity_vocabs={"userId": vocab})

    # the "retrained" candidate: identical structure/vocab, freshly
    # drawn coefficients (scores genuinely differ from the boot model)
    fixed_b = FixedEffectModel(GeneralizedLinearModel(
        Coefficients(jnp.asarray(rng.normal(size=len(imaps["global"])),
                                 jnp.float32)),
        TaskType.LINEAR_REGRESSION), "global")
    re_model_b = RandomEffectModel(
        random_effect_type="userId", feature_shard_id="user",
        entity_codes=np.arange(n_users),
        coefficients=jnp.asarray(
            rng.normal(size=(n_users, len(imaps["user"]))), jnp.float32))
    candidate_dir = os.path.join(workdir, "serve_model_retrained")
    save_game_model(
        GameModel({"fixed": fixed_b, "per-user": re_model_b}),
        candidate_dir, imaps, entity_vocabs={"userId": vocab})

    records = []
    for i in range(24):
        u = int(rng.integers(0, n_users))
        records.append({
            "uid": f"req_{i}",
            "metadataMap": {"userId": f"user{u}"},
            "globalFeatures": [
                {"name": f"g{j}", "term": "",
                 "value": float(rng.normal())} for j in range(d_g)],
            "userFeatures": [
                {"name": f"u{j}", "term": "",
                 "value": float(rng.normal())} for j in range(d_u)],
        })
    sections = {"global": ["globalFeatures"], "user": ["userFeatures"]}
    # reload model AND index maps from disk — the exact load the serve
    # subprocess performs, so the reference anchors the same mapping
    model, loaded_maps = load_scoring_model(model_dir, None)
    data = game_dataset_from_records(
        records, sections, loaded_maps, id_types=("userId",),
        response_required=False)
    ref = np.asarray(score_game_dataset(model, data), np.float64)
    model_b, maps_b = load_scoring_model(candidate_dir, None)
    data_b = game_dataset_from_records(
        records, sections, maps_b, id_types=("userId",),
        response_required=False)
    ref_b = np.asarray(score_game_dataset(model_b, data_b), np.float64)
    fix = {"model_dir": model_dir, "records": records, "ref": ref,
           "candidate_dir": candidate_dir, "ref_candidate": ref_b}
    _SERVE_FIXTURE[workdir] = fix
    return fix


def serve_args(model_dir: str, listen: str, trace_dir: str,
               extra: list[str] | None = None) -> list[str]:
    return [
        "--game-model-input-dir", model_dir,
        "--listen", listen,
        "--feature-shard-id-to-feature-section-keys-map",
        "global:globalFeatures|user:userFeatures",
        "--random-effect-id-set", "userId",
        "--max-batch-rows", "64",
        "--trace-dir", trace_dir,
        "--trace-heartbeat-seconds", "0.2",
        *(extra or []),
    ]


def _spawn_serve(args: list[str], extra_env: dict | None = None):
    """Start a real serve subprocess, wait for its ready line, return
    ``(proc, endpoint)``."""
    env = dict(os.environ)
    env.pop("PHOTON_FAULTS", None)
    env.pop("PHOTON_FAULTS_STATE_DIR", None)
    env.update(extra_env or {})
    proc = subprocess.Popen(
        [sys.executable, "-m", "photon_ml_tpu.serve.service", *args],
        env=env, cwd=_REPO, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    line = proc.stdout.readline().strip()  # blocks through model load
    if not line.startswith("PHOTON_SERVE ready endpoint="):
        proc.kill()
        _, err = proc.communicate()
        raise RuntimeError(
            f"serve subprocess never became ready: {line!r}\n{err[-2000:]}")
    return proc, line.split("endpoint=", 1)[1]


def _serve_score_once(endpoint: str, records) -> dict:
    from photon_ml_tpu.serve.protocol import ServeClient

    with ServeClient(endpoint) as client:
        return client.score(records)


def _serve_score_retry(endpoint: str, records, deadline_secs=120.0):
    """Score with reconnect retries — rides out a dead/relaunching
    service until the endpoint answers with real scores."""
    last: object = None
    deadline = time.monotonic() + deadline_secs
    while time.monotonic() < deadline:
        try:
            resp = _serve_score_once(endpoint, records)
            if resp.get("kind") == "scores":
                return resp
            last = resp
        except (ConnectionError, OSError) as e:
            last = e
        time.sleep(0.25)
    raise RuntimeError(f"service never answered with scores: {last!r}")


def _serve_metric_total(trace_dir: str, name: str):
    """The metric's value in the LAST ``metric_totals`` snapshot of the
    serve run's metrics stream (run_end preferred by position)."""
    path = os.path.join(trace_dir, "metrics.jsonl")
    if not os.path.exists(path):
        return None
    total = None
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("metric_totals") and name in rec["metric_totals"]:
                total = rec["metric_totals"][name]
    return total


def _run_serve_cell(c: CellDef, workdir: str) -> dict:
    """One scoring-service (point, mode) cell against a real
    photon_serve subprocess."""
    import numpy as np

    fix = build_serve_fixture(workdir)
    name = f"{c['point']}={c['mode']}"
    cell_dir = os.path.join(
        workdir, "cells", name.replace("=", "_").replace(".", "_"))
    shutil.rmtree(cell_dir, ignore_errors=True)
    os.makedirs(cell_dir)
    trace = os.path.join(cell_dir, "trace")
    sock = os.path.join(cell_dir, "serve.sock")
    failures: list[str] = []
    t0 = time.monotonic()
    ref = fix["ref"]
    records = fix["records"]
    expected = c["expected"]

    if c["point"] == "serve.route":
        if expected == "killed":
            return _run_fleet_kill_cell(c, name, fix, cell_dir,
                                        failures, t0)
        return _run_fleet_cell(c, name, fix, cell_dir, failures, t0)
    if c["point"] == "serve.telemetry":
        return _run_fleet_dead_telemetry_cell(c, name, fix, cell_dir,
                                              failures, t0)
    if c["point"] in ("serve.model_load", "serve.swap"):
        if expected == "killed":
            return _run_serve_swap_kill_cell(c, name, fix, cell_dir,
                                             trace, sock, failures, t0)
        return _run_serve_swap_cell(c, name, fix, cell_dir, trace, sock,
                                    failures, t0)
    if expected == "killed":
        return _run_serve_kill_cell(c, name, fix, cell_dir, trace, sock,
                                    failures, t0)

    env = {"PHOTON_FAULTS": c["spec"],
           "PHOTON_FAULTS_STATE_DIR": os.path.join(cell_dir, "fault_state"),
           "PHOTON_FAULTS_SEED": "42"}
    proc, endpoint = _spawn_serve(
        serve_args(fix["model_dir"], "unix:" + sock, trace), extra_env=env)
    rc = None
    outcome = "?"
    try:
        if expected == "preempted":
            # `signal` fires INSIDE the batch: the SIGTERM is latched,
            # the batch still completes and replies, then the service
            # drains and exits preempted
            resp = _serve_score_once(endpoint, records)
            if resp.get("kind") != "scores" or not np.array_equal(
                    np.asarray(resp["scores"], np.float64), ref):
                failures.append(
                    f"signal cell: the in-flight batch must complete "
                    f"bit-exact before the drain, got {str(resp)[:300]}")
            rc = proc.wait(timeout=90)
            if rc != PREEMPTED_EXIT:
                failures.append(f"expected drain to rc={PREEMPTED_EXIT}, "
                                f"got rc={rc}")
            outcome = "preempted(batch completed)"
        else:  # connection-scoped "ok" cells
            first = None
            try:
                first = _serve_score_once(endpoint, records)
            except (ConnectionError, OSError):
                pass  # the faulted connection may just drop
            if first is not None and first.get("kind") == "scores":
                failures.append(
                    f"fault {c['spec']} armed but the first score "
                    f"request succeeded")
            resp = _serve_score_retry(endpoint, records, deadline_secs=30)
            if not np.array_equal(
                    np.asarray(resp["scores"], np.float64), ref):
                failures.append(
                    "post-fault scores NOT bit-exact vs the shared "
                    "batch scoring core")
            proc.terminate()
            rc = proc.wait(timeout=90)
            if rc != PREEMPTED_EXIT:
                failures.append(f"SIGTERM drain must exit "
                                f"rc={PREEMPTED_EXIT}, got rc={rc}")
            outcome = "survived+bit_exact"
    except Exception as e:  # noqa: BLE001 — the report IS the handler
        failures.append(f"serve cell harness error: "
                        f"{type(e).__name__}: {e}")
    finally:
        if proc.poll() is None:
            proc.kill()
        _, err = proc.communicate()
    if "Traceback (most recent call last)" in err:
        failures.append("stack-trace crash:\n" + err[-2000:])
    if rc == PREEMPTED_EXIT and "PHOTON_PREEMPTED" not in err:
        failures.append(f"rc={PREEMPTED_EXIT} without a "
                        f"PHOTON_PREEMPTED line")
    _check_trace_survives(trace, failures)
    return {"cell": name, "spec": c["spec"], "expected": expected,
            "rc": rc, "outcome": outcome, "note": c["note"],
            "seconds": round(time.monotonic() - t0, 1),
            "failures": failures, "passed": not failures}


def _run_serve_kill_cell(c: CellDef, name: str, fix: dict, cell_dir: str,
                         trace: str, sock: str, failures: list[str],
                         t0: float) -> dict:
    """The supervisor-relaunch drill: photon_supervise --module runs the
    service; an injected kill lands mid-batch (budget claimed once via
    PHOTON_FAULTS_STATE_DIR, so the relaunch runs clean); the client
    rides the outage on reconnect retries; post-relaunch scores must be
    bit-exact; a stop file drains the supervisor to PHOTON_SUPERVISE_OK."""
    import numpy as np

    stop_file = os.path.join(cell_dir, "stop")
    args = serve_args(fix["model_dir"], "unix:" + sock, trace,
                      extra=["--stop-file", stop_file])
    env = dict(os.environ)
    env.pop("PHOTON_FAULTS", None)
    env.pop("PHOTON_FAULTS_STATE_DIR", None)
    env.update({
        "PHOTON_FAULTS": c["spec"],
        "PHOTON_FAULTS_STATE_DIR": os.path.join(cell_dir, "fault_state"),
        "PHOTON_FAULTS_SEED": "42",
    })
    sup = subprocess.Popen(
        [sys.executable, os.path.join(_REPO, "tools",
                                      "photon_supervise.py"),
         "--module", "photon_ml_tpu.serve.service",
         "--backoff-base", "0.2", "--run-dir", trace, "--", *args],
        env=env, cwd=_REPO, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    rc = None
    outcome = "?"
    try:
        # the first scored batch trips the kill; keep retrying through
        # the death + relaunch until the second incarnation answers
        resp = _serve_score_retry("unix:" + sock, fix["records"],
                                  deadline_secs=150)
        if not np.array_equal(np.asarray(resp["scores"], np.float64),
                              fix["ref"]):
            failures.append("post-relaunch scores NOT bit-exact vs the "
                            "shared batch scoring core")
        with open(stop_file, "w") as fh:
            fh.write("chaos cell done\n")
        rc = sup.wait(timeout=120)
        outcome = "killed+relaunched"
    except Exception as e:  # noqa: BLE001 — the report IS the handler
        failures.append(f"serve kill cell harness error: "
                        f"{type(e).__name__}: {e}")
    finally:
        if sup.poll() is None:
            sup.kill()
        out, err = sup.communicate()
    if rc != 0:
        failures.append(f"supervisor must finish rc=0 after the "
                        f"stop-file drain, got rc={rc}:\n{err[-1500:]}")
    elif "PHOTON_SUPERVISE_OK" not in out:
        failures.append(f"no PHOTON_SUPERVISE_OK line: {out[-400:]!r}")
    else:
        m = [w for w in out.split() if w.startswith("restarts=")]
        restarts = int(m[-1].split("=", 1)[1]) if m else 0
        if restarts < 1:
            failures.append(
                "supervisor reports restarts=0 — the injected kill "
                "never cost an incarnation")
        else:
            outcome += f"(restarts={restarts})"
    if "Traceback (most recent call last)" in err:
        failures.append("stack-trace crash:\n" + err[-2000:])
    _check_trace_survives(trace, failures)
    return {"cell": name, "spec": c["spec"], "expected": c["expected"],
            "rc": rc, "outcome": outcome, "note": c["note"],
            "seconds": round(time.monotonic() - t0, 1),
            "failures": failures, "passed": not failures}


def _spawn_fleet_router(members: list[str], listen: str, trace: str,
                        extra_env: dict | None = None,
                        extra_args: list | None = None):
    """Start the fleet router subprocess, wait for its ready line
    (printed only after every reachable member admitted)."""
    env = dict(os.environ)
    env.pop("PHOTON_FAULTS", None)
    env.pop("PHOTON_FAULTS_STATE_DIR", None)
    env.update(extra_env or {})
    proc = subprocess.Popen(
        [sys.executable, "-m", "photon_ml_tpu.serve.router",
         "--listen", listen, "--members", ",".join(members),
         "--route-id", "userId", "--heartbeat-seconds", "0.1",
         "--trace-dir", trace, "--trace-heartbeat-seconds", "0.2",
         *(extra_args or [])],
        env=env, cwd=_REPO, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    line = proc.stdout.readline().strip()
    if not line.startswith("PHOTON_SERVE ready endpoint="):
        proc.kill()
        _, err = proc.communicate()
        raise RuntimeError(
            f"fleet router never became ready: {line!r}\n{err[-2000:]}")
    return proc, line.split("endpoint=", 1)[1]


def _run_fleet_cell(c: CellDef, name: str, fix: dict, cell_dir: str,
                    failures: list[str], t0: float) -> dict:
    """serve.route ok-mode cells: a 2-member fleet behind the router;
    the fault fires in member 1 on routed sub-requests. The invariant
    is the no-black-hole ledger — every request answered with real
    scores (retry/failover absorb the fault), zero typed errors, zero
    sheds, bit-exact against the shared batch scoring core."""
    import numpy as np

    from photon_ml_tpu.serve.protocol import ServeClient

    env = {"PHOTON_FAULTS": c["spec"],
           "PHOTON_FAULTS_STATE_DIR": os.path.join(cell_dir,
                                                   "fault_state"),
           "PHOTON_FAULTS_SEED": "42"}
    members, endpoints = [], []
    router = None
    rc = None
    outcome = "?"
    try:
        for k in range(2):
            proc, ep = _spawn_serve(serve_args(
                fix["model_dir"],
                "unix:" + os.path.join(cell_dir, f"m{k}.sock"),
                os.path.join(cell_dir, f"member{k}")), extra_env=env)
            members.append(proc)
            endpoints.append(ep)
        router, endpoint = _spawn_fleet_router(
            endpoints, "unix:" + os.path.join(cell_dir, "router.sock"),
            os.path.join(cell_dir, "router"), extra_env=env)
        answered = 0
        with ServeClient(endpoint) as client:
            for i in range(6):
                resp = client.score(fix["records"])
                if resp.get("kind") != "scores":
                    failures.append(f"request {i} not answered with "
                                    f"scores: {str(resp)[:200]}")
                    continue
                answered += 1
                if not np.array_equal(
                        np.asarray(resp["scores"], np.float64),
                        fix["ref"]):
                    failures.append(f"request {i} NOT bit-exact vs "
                                    f"the shared batch scoring core")
            route = client.stats().get("route") or {}
        for bad in ("error", "shed"):
            if route.get(bad):
                failures.append(f"route ledger shows {bad}="
                                f"{route[bad]} — the fault must be "
                                f"absorbed by retry/failover")
        router.terminate()
        rc = router.wait(timeout=90)
        if rc != PREEMPTED_EXIT:
            failures.append(f"router SIGTERM drain must exit "
                            f"rc={PREEMPTED_EXIT}, got rc={rc}")
        outcome = f"absorbed(answered={answered}, route={route})"
    except Exception as e:  # noqa: BLE001 — the report IS the handler
        failures.append(f"fleet cell harness error: "
                        f"{type(e).__name__}: {e}")
    finally:
        err = ""
        if router is not None:
            if router.poll() is None:
                router.kill()
            _, err = router.communicate()
        for proc in members:
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
    if "Traceback (most recent call last)" in err:
        failures.append("router stack-trace crash:\n" + err[-2000:])
    _check_trace_survives(os.path.join(cell_dir, "router"), failures)
    return {"cell": name, "spec": c["spec"], "expected": c["expected"],
            "rc": rc, "outcome": outcome, "note": c["note"],
            "seconds": round(time.monotonic() - t0, 1),
            "failures": failures, "passed": not failures}


def _run_fleet_dead_telemetry_cell(c: CellDef, name: str, fix: dict,
                                   cell_dir: str, failures: list[str],
                                   t0: float) -> dict:
    """The serve-plane dead-consumer drill: a 2-member fleet plus the
    router, EVERY process pointed at a ``--telemetry-endpoint`` that
    can never accept a record. A dead SOCKET consumer diverts to the
    run-dir fallback stream (the training drill's standing posture),
    so this cell arms the terminal mode instead: a ``file:`` target
    whose parent is a regular file — every append fails ENOTDIR and
    every batch is drop-counted. No fault spec is armed — the dead
    consumer is the whole cell. Invariants: every request answers
    bit-exact against the shared batch scoring core, the route ledger
    shows zero errors/sheds, every process drains cleanly, and the
    only evidence anything was wrong is a non-zero
    ``telemetry_dropped`` total in each run dir."""
    import numpy as np

    from photon_ml_tpu.serve.protocol import ServeClient

    blocked = os.path.join(cell_dir, "blocked")
    with open(blocked, "w") as fh:
        fh.write("not a directory\n")
    dead = "file:" + os.path.join(blocked, "telemetry.jsonl")
    members, endpoints = [], []
    router = None
    rc = None
    outcome = "?"
    try:
        for k in range(2):
            proc, ep = _spawn_serve(serve_args(
                fix["model_dir"],
                "unix:" + os.path.join(cell_dir, f"m{k}.sock"),
                os.path.join(cell_dir, f"member{k}"),
                extra=["--telemetry-endpoint", dead]))
            members.append(proc)
            endpoints.append(ep)
        router, endpoint = _spawn_fleet_router(
            endpoints, "unix:" + os.path.join(cell_dir, "router.sock"),
            os.path.join(cell_dir, "router"),
            extra_args=["--telemetry-endpoint", dead])
        answered = 0
        with ServeClient(endpoint) as client:
            for i in range(6):
                resp = client.score(fix["records"])
                if resp.get("kind") != "scores":
                    failures.append(f"request {i} not answered with "
                                    f"scores: {str(resp)[:200]}")
                    continue
                answered += 1
                if not np.array_equal(
                        np.asarray(resp["scores"], np.float64),
                        fix["ref"]):
                    failures.append(f"request {i} NOT bit-exact vs "
                                    f"the shared batch scoring core "
                                    f"under the dead consumer")
            route = client.stats().get("route") or {}
        for bad in ("error", "shed"):
            if route.get(bad):
                failures.append(f"route ledger shows {bad}="
                                f"{route[bad]} — a dead telemetry "
                                f"consumer must not touch scoring")
        # let at least one sink flush interval elapse so the dropped
        # batches are counted and a heartbeat carries the totals out
        time.sleep(1.0)
        router.terminate()
        rc = router.wait(timeout=90)
        if rc != PREEMPTED_EXIT:
            failures.append(f"router SIGTERM drain must exit "
                            f"rc={PREEMPTED_EXIT}, got rc={rc}")
        for proc in members:
            proc.terminate()
        for proc in members:
            mrc = proc.wait(timeout=90)
            if mrc != PREEMPTED_EXIT:
                failures.append(f"member SIGTERM drain must exit "
                                f"rc={PREEMPTED_EXIT}, got rc={mrc}")
        dropped = {}
        for role in ("member0", "member1", "router"):
            dropped[role] = _serve_metric_total(
                os.path.join(cell_dir, role), "telemetry_dropped")
            if not dropped[role]:
                failures.append(
                    f"{role}: expected a non-zero telemetry_dropped "
                    f"total as the dead-consumer evidence, got "
                    f"{dropped[role]!r}")
        outcome = (f"contained(answered={answered}, "
                   f"dropped={dropped})")
    except Exception as e:  # noqa: BLE001 — the report IS the handler
        failures.append(f"dead-telemetry cell harness error: "
                        f"{type(e).__name__}: {e}")
    finally:
        err = ""
        if router is not None:
            if router.poll() is None:
                router.kill()
            _, err = router.communicate()
        for proc in members:
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
    if "Traceback (most recent call last)" in err:
        failures.append("router stack-trace crash:\n" + err[-2000:])
    for role in ("member0", "member1", "router"):
        _check_trace_survives(os.path.join(cell_dir, role), failures)
    return {"cell": name, "spec": c["spec"], "expected": c["expected"],
            "rc": rc, "outcome": outcome, "note": c["note"],
            "seconds": round(time.monotonic() - t0, 1),
            "failures": failures, "passed": not failures}


def _run_fleet_kill_cell(c: CellDef, name: str, fix: dict,
                         cell_dir: str, failures: list[str],
                         t0: float) -> dict:
    """The fleet no-black-hole drill: photon_supervise --fleet runs 4
    members + the router; the injected kill (budget claimed once via
    PHOTON_FAULTS_STATE_DIR) drops member 1 mid-request under
    concurrent load. Request-id accounting proves zero silent drops:
    every submitted request gets a reply carrying its own id — real
    scores (bit-exact) or a typed error. The relaunched member must
    re-admit onto the live generation, and a stop-file drains the
    supervisor to PHOTON_SUPERVISE_OK."""
    import threading

    import numpy as np

    from photon_ml_tpu.serve.protocol import ServeClient

    stop_file = os.path.join(cell_dir, "stop")
    fleet_dir = os.path.join(cell_dir, "fleet")
    rsock = os.path.join(cell_dir, "router.sock")
    env = dict(os.environ)
    env.pop("PHOTON_FAULTS", None)
    env.pop("PHOTON_FAULTS_STATE_DIR", None)
    env.update({
        "PHOTON_FAULTS": c["spec"],
        "PHOTON_FAULTS_STATE_DIR": os.path.join(cell_dir,
                                                "fault_state"),
        "PHOTON_FAULTS_SEED": "42",
    })
    sup = subprocess.Popen(
        [sys.executable, os.path.join(_REPO, "tools",
                                      "photon_supervise.py"),
         "--fleet", "4", "--fleet-dir", fleet_dir,
         "--router-listen", "unix:" + rsock,
         "--stop-file", stop_file,
         "--backoff-base", "0.2", "--poll-seconds", "0.1", "--",
         "--game-model-input-dir", fix["model_dir"],
         "--feature-shard-id-to-feature-section-keys-map",
         "global:globalFeatures|user:userFeatures",
         "--random-effect-id-set", "userId",
         "--max-batch-rows", "64",
         "--trace-heartbeat-seconds", "0.2"],
        env=env, cwd=_REPO, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    rc = None
    outcome = "?"
    ledger = {"submitted": 0, "scores": 0, "typed_errors": 0,
              "silent": 0, "not_bit_exact": 0}
    llock = threading.Lock()
    try:
        # wait for the router with rows member 1 does NOT own, so the
        # warm-up cannot consume the kill budget — member 1 dies later,
        # mid-request, under the concurrent load below
        from photon_ml_tpu.serve.fleet import entity_shard
        warm = [r for r in fix["records"]
                if entity_shard(r["metadataMap"]["userId"], 4) != 1]
        _serve_score_retry("unix:" + rsock, warm[:2],
                           deadline_secs=150)

        def load_loop(worker: int) -> None:
            with ServeClient("unix:" + rsock, timeout=60) as client:
                for i in range(8):
                    rid = f"w{worker}r{i}"
                    with llock:
                        ledger["submitted"] += 1
                    try:
                        resp = client.request(
                            {"kind": "score", "id": rid,
                             "rows": fix["records"]})
                    except (ConnectionError, OSError):
                        with llock:
                            ledger["silent"] += 1
                        return
                    with llock:
                        if resp.get("id") != rid:
                            ledger["silent"] += 1
                        elif resp.get("kind") == "scores":
                            ledger["scores"] += 1
                            if not np.array_equal(
                                    np.asarray(resp["scores"],
                                               np.float64),
                                    fix["ref"]):
                                ledger["not_bit_exact"] += 1
                        elif resp.get("error"):
                            ledger["typed_errors"] += 1
                        else:
                            ledger["silent"] += 1

        workers = [threading.Thread(target=load_loop, args=(w,))
                   for w in range(3)]
        for th in workers:
            th.start()
        for th in workers:
            th.join(timeout=120)
        if ledger["silent"]:
            failures.append(f"{ledger['silent']} request(s) "
                            f"black-holed: {ledger}")
        if ledger["scores"] + ledger["typed_errors"] \
                != ledger["submitted"]:
            failures.append(f"request-id accounting does not balance: "
                            f"{ledger}")
        if ledger["not_bit_exact"]:
            failures.append(f"{ledger['not_bit_exact']} answered "
                            f"request(s) NOT bit-exact vs the shared "
                            f"batch scoring core")

        # the relaunched member must RE-ADMIT onto the live generation
        deadline = time.monotonic() + 90
        states: dict = {}
        model_ids: set = set()
        while time.monotonic() < deadline:
            try:
                with ServeClient("unix:" + rsock, timeout=30) as cl:
                    fleet_stats = cl.stats().get("fleet") or {}
                ms = fleet_stats.get("members") or []
                states = {m["member"]: m["state"] for m in ms}
                model_ids = {m["model_id"] for m in ms
                             if m["model_id"] is not None}
                if ms and all(m["state"] == "healthy" for m in ms):
                    break
            except (ConnectionError, OSError):
                pass
            time.sleep(0.3)
        if not states or any(s != "healthy" for s in states.values()):
            failures.append(f"killed member never re-admitted: "
                            f"states={states}")
        if len(model_ids) > 1:
            failures.append(f"SPLIT FLEET: members serve "
                            f"{sorted(model_ids)}")
        with open(stop_file, "w") as fh:
            fh.write("chaos cell done\n")
        rc = sup.wait(timeout=120)
        outcome = (f"killed+relaunched(answered="
                   f"{ledger['scores']}+{ledger['typed_errors']}e"
                   f"/{ledger['submitted']})")
    except Exception as e:  # noqa: BLE001 — the report IS the handler
        failures.append(f"fleet kill cell harness error: "
                        f"{type(e).__name__}: {e}")
    finally:
        if sup.poll() is None:
            sup.kill()
        out, err = sup.communicate()
    if rc != 0:
        failures.append(f"fleet supervisor must finish rc=0 after the "
                        f"stop-file drain, got rc={rc}:\n{err[-1500:]}")
    elif "PHOTON_SUPERVISE_OK" not in out:
        failures.append(f"no PHOTON_SUPERVISE_OK line: {out[-400:]!r}")
    elif "relaunch_member" not in out:
        failures.append("supervisor log shows no member relaunch — "
                        "the injected kill never cost a member")
    if "Traceback (most recent call last)" in err:
        failures.append("stack-trace crash:\n" + err[-2000:])
    _check_trace_survives(os.path.join(fleet_dir, "router"), failures)
    return {"cell": name, "spec": c["spec"], "expected": c["expected"],
            "rc": rc, "outcome": outcome, "note": c["note"],
            "seconds": round(time.monotonic() - t0, 1),
            "failures": failures, "passed": not failures}


#: Hot-swap cells where the swap must COMPLETE open the canary gate —
#: the fixture candidate is a genuinely retrained model, so its scores
#: differ from the boot model's by design. Probation is kept short so
#: cells finish fast.
_SWAP_OPEN_GATE = ["--swap-canary-threshold-pct", "1e9",
                   "--swap-probation-seconds", "0.2"]

#: Refusal cells pair the fault with a TIGHT gate instead: a corrupt
#: candidate that still decodes to garbage coefficients must trip the
#: score-diff canary even when the load itself survives.
_SWAP_TIGHT_GATE = ["--swap-canary-threshold-pct", "5",
                    "--swap-canary-min-delta", "1e-4",
                    "--swap-probation-seconds", "0.2"]


def _serve_swap_once(endpoint: str, model_dir: str,
                     model_id: str = "retrained",
                     timeout: float = 120.0) -> dict:
    from photon_ml_tpu.serve.protocol import ServeClient

    with ServeClient(endpoint, timeout=timeout) as client:
        return client.swap(model_dir, model_id=model_id)


def _serve_stats_once(endpoint: str) -> dict:
    from photon_ml_tpu.serve.protocol import ServeClient

    with ServeClient(endpoint) as client:
        return client.stats()


def _run_serve_swap_cell(c: CellDef, name: str, fix: dict,
                         cell_dir: str, trace: str, sock: str,
                         failures: list[str], t0: float) -> dict:
    """Hot-swap (point, mode) cells: the fault fires somewhere in the
    load → canary → flip machine; the invariant is always that score
    traffic lands bit-exact on exactly ONE model — the boot model when
    the swap refuses, the candidate when it completes."""
    import threading

    import numpy as np

    # `corrupt` mutates the candidate ON DISK: every swap cell works
    # on a private copy so the shared fixture stays pristine
    candidate = os.path.join(cell_dir, "candidate_model")
    shutil.copytree(fix["candidate_dir"], candidate)
    env = {"PHOTON_FAULTS": c["spec"],
           "PHOTON_FAULTS_STATE_DIR": os.path.join(cell_dir,
                                                   "fault_state"),
           "PHOTON_FAULTS_SEED": "42"}
    variant = c["variant"]
    gate = (_SWAP_TIGHT_GATE if variant == "swap_refused"
            else _SWAP_OPEN_GATE)
    proc, endpoint = _spawn_serve(
        serve_args(fix["model_dir"], "unix:" + sock, trace, extra=gate),
        extra_env=env)
    rc = None
    outcome = "?"
    try:
        first = _serve_score_once(endpoint, fix["records"])
        if not np.array_equal(np.asarray(first["scores"], np.float64),
                              fix["ref"]):
            failures.append("pre-swap scores NOT bit-exact vs the "
                            "shared batch scoring core")
        if variant == "swap_drain_race":
            # the loader thread is stalled on the injected slow fault;
            # a SIGTERM during the stall must refuse the in-flight
            # swap and still drain to the documented exit
            result: dict = {}

            def _swap_in_background() -> None:
                try:
                    result["resp"] = _serve_swap_once(endpoint,
                                                      candidate)
                except (ConnectionError, OSError) as e:
                    result["error"] = e

            th = threading.Thread(target=_swap_in_background,
                                  daemon=True)
            th.start()
            time.sleep(0.8)  # well inside the 3 s injected stall
            proc.terminate()
            rc = proc.wait(timeout=90)
            th.join(timeout=30)
            resp = result.get("resp")
            if not isinstance(resp, dict) \
                    or resp.get("outcome") != "refused":
                failures.append(f"a swap racing the drain must resolve "
                                f"refused, got {result!r}")
            if rc != PREEMPTED_EXIT:
                failures.append(f"expected drain to "
                                f"rc={PREEMPTED_EXIT}, got rc={rc}")
            outcome = "preempted(swap refused on drain)"
        elif variant == "swap_refused":
            resp = _serve_swap_once(endpoint, candidate)
            if resp.get("outcome") != "refused":
                failures.append(f"corrupt candidate must be refused, "
                                f"got {str(resp)[:300]}")
            elif "ModelSwapRefusedError" not in resp.get("error", ""):
                failures.append(f"refusal carries no typed error: "
                                f"{str(resp)[:300]}")
            stats = _serve_stats_once(endpoint)
            if stats.get("generation") != 1:
                failures.append(f"refused swap must leave generation 1 "
                                f"current, got "
                                f"{stats.get('generation')!r}")
            after = _serve_score_once(endpoint, fix["records"])
            if not np.array_equal(
                    np.asarray(after["scores"], np.float64),
                    fix["ref"]):
                failures.append("scores after the refused swap NOT "
                                "bit-exact vs the boot model")
            proc.terminate()
            rc = proc.wait(timeout=90)
            if rc != PREEMPTED_EXIT:
                failures.append(f"SIGTERM drain must exit "
                                f"rc={PREEMPTED_EXIT}, got rc={rc}")
            outcome = f"refused({resp.get('reason', '')[:40]}...)"
        else:  # swap_retry / swap_flip_refused: the swap COMPLETES
            resp = _serve_swap_once(endpoint, candidate)
            if variant == "swap_flip_refused":
                # the injected flip fault refuses the FIRST attempt;
                # the re-request (budget spent) must complete
                if resp.get("outcome") != "refused" \
                        or "flip" not in resp.get("reason", ""):
                    failures.append(f"flip fault must refuse the first "
                                    f"swap, got {str(resp)[:300]}")
                mid = _serve_score_once(endpoint, fix["records"])
                if not np.array_equal(
                        np.asarray(mid["scores"], np.float64),
                        fix["ref"]):
                    failures.append("scores after the refused flip NOT "
                                    "bit-exact vs the boot model")
                resp = _serve_swap_once(endpoint, candidate)
            if resp.get("outcome") != "ok" \
                    or resp.get("generation") != 2:
                failures.append(f"swap must complete onto generation "
                                f"2, got {str(resp)[:300]}")
            after = _serve_score_once(endpoint, fix["records"])
            if not np.array_equal(
                    np.asarray(after["scores"], np.float64),
                    fix["ref_candidate"]):
                failures.append("post-swap scores NOT bit-exact vs the "
                                "candidate's batch reference")
            proc.terminate()
            rc = proc.wait(timeout=90)
            if rc != PREEMPTED_EXIT:
                failures.append(f"SIGTERM drain must exit "
                                f"rc={PREEMPTED_EXIT}, got rc={rc}")
            outcome = ("swapped(load retried)"
                       if variant == "swap_retry"
                       else "refused-then-swapped")
    except Exception as e:  # noqa: BLE001 — the report IS the handler
        failures.append(f"serve swap cell harness error: "
                        f"{type(e).__name__}: {e}")
    finally:
        if proc.poll() is None:
            proc.kill()
        _, err = proc.communicate()
    if "Traceback (most recent call last)" in err:
        failures.append("stack-trace crash:\n" + err[-2000:])
    if rc == PREEMPTED_EXIT and "PHOTON_PREEMPTED" not in err:
        failures.append(f"rc={PREEMPTED_EXIT} without a "
                        f"PHOTON_PREEMPTED line")
    if variant == "swap_retry" and not failures:
        retried = _serve_metric_total(trace, "retries")
        if not retried:
            failures.append(f"expected retries >= 1 in the final "
                            f"metric totals, found {retried!r}")
    _check_trace_survives(trace, failures)
    return {"cell": name, "spec": c["spec"], "expected": c["expected"],
            "rc": rc, "outcome": outcome, "note": c["note"],
            "seconds": round(time.monotonic() - t0, 1),
            "failures": failures, "passed": not failures}


def _run_serve_swap_kill_cell(c: CellDef, name: str, fix: dict,
                              cell_dir: str, trace: str, sock: str,
                              failures: list[str], t0: float) -> dict:
    """Killed mid-flip under photon_supervise: the injected kill fires
    at the atomic-flip fault point, the supervisor relaunches, and the
    relaunch must serve exactly ONE consistent generation — the boot
    model, bit-exact, reporting generation 1."""
    import numpy as np

    from photon_ml_tpu.serve.protocol import ServeClient

    candidate = os.path.join(cell_dir, "candidate_model")
    shutil.copytree(fix["candidate_dir"], candidate)
    stop_file = os.path.join(cell_dir, "stop")
    args = serve_args(fix["model_dir"], "unix:" + sock, trace,
                      extra=[*_SWAP_OPEN_GATE,
                             "--stop-file", stop_file])
    env = dict(os.environ)
    env.pop("PHOTON_FAULTS", None)
    env.pop("PHOTON_FAULTS_STATE_DIR", None)
    env.update({
        "PHOTON_FAULTS": c["spec"],
        "PHOTON_FAULTS_STATE_DIR": os.path.join(cell_dir,
                                                "fault_state"),
        "PHOTON_FAULTS_SEED": "42",
    })
    sup = subprocess.Popen(
        [sys.executable, os.path.join(_REPO, "tools",
                                      "photon_supervise.py"),
         "--module", "photon_ml_tpu.serve.service",
         "--backoff-base", "0.2", "--run-dir", trace, "--", *args],
        env=env, cwd=_REPO, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    rc = None
    outcome = "?"
    try:
        resp = _serve_score_retry("unix:" + sock, fix["records"],
                                  deadline_secs=150)
        if not np.array_equal(np.asarray(resp["scores"], np.float64),
                              fix["ref"]):
            failures.append("pre-swap scores NOT bit-exact")
        try:
            swap_resp = _serve_swap_once("unix:" + sock, candidate)
            # a reply at all means the kill never fired at the flip
            failures.append(f"injected kill at the flip never fired: "
                            f"swap resolved {str(swap_resp)[:200]}")
        except (ConnectionError, OSError):
            pass  # the process died mid-flip, as drilled
        # ride the relaunch: the second incarnation must come back on
        # the BOOT model — one consistent generation, bit-exact
        deadline = time.monotonic() + 150
        relaunch = None
        while time.monotonic() < deadline:
            try:
                with ServeClient("unix:" + sock) as client:
                    relaunch = (client.generation,
                                client.score(fix["records"]))
                break
            except (ConnectionError, OSError):
                time.sleep(0.25)
        if relaunch is None:
            failures.append("service never relaunched after the "
                            "mid-flip kill")
        else:
            gen, resp = relaunch
            if gen != 1:
                failures.append(f"relaunch must serve generation 1 "
                                f"(the boot model), got {gen!r}")
            if not np.array_equal(
                    np.asarray(resp["scores"], np.float64),
                    fix["ref"]):
                failures.append("post-relaunch scores NOT bit-exact vs "
                                "the boot model — the kill left a "
                                "mixed generation behind")
        with open(stop_file, "w") as fh:
            fh.write("chaos cell done\n")
        rc = sup.wait(timeout=120)
        outcome = "killed mid-flip+relaunched(gen 1)"
    except Exception as e:  # noqa: BLE001 — the report IS the handler
        failures.append(f"serve swap kill cell harness error: "
                        f"{type(e).__name__}: {e}")
    finally:
        if sup.poll() is None:
            sup.kill()
        out, err = sup.communicate()
    if rc != 0:
        failures.append(f"supervisor must finish rc=0 after the "
                        f"stop-file drain, got rc={rc}:\n{err[-1500:]}")
    elif "PHOTON_SUPERVISE_OK" not in out:
        failures.append(f"no PHOTON_SUPERVISE_OK line: {out[-400:]!r}")
    else:
        m = [w for w in out.split() if w.startswith("restarts=")]
        restarts = int(m[-1].split("=", 1)[1]) if m else 0
        if restarts < 1:
            failures.append("supervisor reports restarts=0 — the "
                            "injected kill never cost an incarnation")
        else:
            outcome += f"(restarts={restarts})"
    if "Traceback (most recent call last)" in err:
        failures.append("stack-trace crash:\n" + err[-2000:])
    _check_trace_survives(trace, failures)
    return {"cell": name, "spec": c["spec"], "expected": c["expected"],
            "rc": rc, "outcome": outcome, "note": c["note"],
            "seconds": round(time.monotonic() - t0, 1),
            "failures": failures, "passed": not failures}


def run_serve_canary_violation_scenario(workdir: str) -> dict:
    """No injection: a hot-swap to a genuinely different model under a
    TIGHT canary gate. The shadow-scoring canary must refuse the flip
    — the service never leaves generation 1, and keeps scoring the
    boot model bit-exact."""
    import numpy as np

    fix = build_serve_fixture(workdir)
    cell_dir = os.path.join(workdir, "cells",
                            "scenario_serve_canary_violation")
    shutil.rmtree(cell_dir, ignore_errors=True)
    os.makedirs(cell_dir)
    trace = os.path.join(cell_dir, "trace")
    sock = os.path.join(cell_dir, "serve.sock")
    failures: list[str] = []
    t0 = time.monotonic()
    proc, endpoint = _spawn_serve(
        serve_args(fix["model_dir"], "unix:" + sock, trace,
                   extra=_SWAP_TIGHT_GATE))
    rc = None
    reason = ""
    try:
        first = _serve_score_once(endpoint, fix["records"])
        if not np.array_equal(np.asarray(first["scores"], np.float64),
                              fix["ref"]):
            failures.append("pre-swap scores NOT bit-exact")
        resp = _serve_swap_once(endpoint, fix["candidate_dir"])
        reason = resp.get("reason", "")
        if resp.get("outcome") != "refused" or "canary" not in reason:
            failures.append(f"the canary gate must refuse the flip, "
                            f"got {str(resp)[:300]}")
        stats = _serve_stats_once(endpoint)
        if stats.get("generation") != 1:
            failures.append(f"a canary-refused service must stay on "
                            f"generation 1, got "
                            f"{stats.get('generation')!r}")
        if (stats.get("last_swap") or {}).get("outcome") != "refused":
            failures.append(f"last_swap must record the refusal, got "
                            f"{stats.get('last_swap')!r}")
        after = _serve_score_once(endpoint, fix["records"])
        if not np.array_equal(np.asarray(after["scores"], np.float64),
                              fix["ref"]):
            failures.append("scores after the refused swap NOT "
                            "bit-exact vs the boot model")
        proc.terminate()
        rc = proc.wait(timeout=90)
        if rc != PREEMPTED_EXIT:
            failures.append(f"SIGTERM drain must exit "
                            f"rc={PREEMPTED_EXIT}, got rc={rc}")
    except Exception as e:  # noqa: BLE001 — the report IS the handler
        failures.append(f"canary scenario harness error: "
                        f"{type(e).__name__}: {e}")
    finally:
        if proc.poll() is None:
            proc.kill()
        _, err = proc.communicate()
    if "Traceback (most recent call last)" in err:
        failures.append("stack-trace crash:\n" + err[-2000:])
    _check_trace_survives(trace, failures)
    return {"cell": "scenario.serve_canary_violation",
            "spec": "(retrained candidate under a tight canary gate — "
                    "no injection)",
            "expected": "refused", "rc": rc,
            "outcome": f"refused({reason[:48]})",
            "note": "ISSUE acceptance scenario: a seeded canary "
                    "violation never flips",
            "seconds": round(time.monotonic() - t0, 1),
            "failures": failures, "passed": not failures}


def run_serve_dead_client_scenario(workdir: str) -> dict:
    """No injection: a client sends a score request and vanishes without
    reading the reply. The service must count the dead client as shed
    (`serve_shed{reason=dead_client}`) and keep serving — the next
    connection scores bit-exact."""
    import socket

    import numpy as np

    fix = build_serve_fixture(workdir)
    cell_dir = os.path.join(workdir, "cells", "scenario_serve_dead_client")
    shutil.rmtree(cell_dir, ignore_errors=True)
    os.makedirs(cell_dir)
    trace = os.path.join(cell_dir, "trace")
    sock_path = os.path.join(cell_dir, "serve.sock")
    failures: list[str] = []
    t0 = time.monotonic()
    proc, endpoint = _spawn_serve(
        serve_args(fix["model_dir"], "unix:" + sock_path, trace))
    rc = None
    try:
        raw = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        raw.connect(sock_path)
        reader = raw.makefile("rb")
        reader.readline()  # server hello
        raw.sendall((json.dumps(
            {"kind": "score", "id": "doomed",
             "rows": fix["records"]}) + "\n").encode())
        # vanish before the reply: shutdown() severs the socket even
        # though the makefile() reader still holds a reference
        raw.shutdown(socket.SHUT_RDWR)
        reader.close()
        raw.close()
        resp = _serve_score_retry(endpoint, fix["records"],
                                  deadline_secs=30)
        if not np.array_equal(np.asarray(resp["scores"], np.float64),
                              fix["ref"]):
            failures.append("scores after the dead client NOT bit-exact "
                            "vs the shared batch scoring core")
        proc.terminate()
        rc = proc.wait(timeout=90)
        if rc != PREEMPTED_EXIT:
            failures.append(f"SIGTERM drain must exit "
                            f"rc={PREEMPTED_EXIT}, got rc={rc}")
    except Exception as e:  # noqa: BLE001 — the report IS the handler
        failures.append(f"dead-client scenario harness error: "
                        f"{type(e).__name__}: {e}")
    finally:
        if proc.poll() is None:
            proc.kill()
        _, err = proc.communicate()
    if "Traceback (most recent call last)" in err:
        failures.append("stack-trace crash:\n" + err[-2000:])
    shed = _serve_metric_total(trace, "serve_shed")
    if not shed:
        failures.append(f"expected serve_shed >= 1 in the final metric "
                        f"totals, found {shed!r}")
    _check_trace_survives(trace, failures)
    return {"cell": "scenario.serve_dead_client",
            "spec": "(client sends a score request and closes without "
                    "reading — no injection)",
            "expected": "ok", "rc": rc,
            "outcome": f"survived+shed({shed})",
            "note": "ISSUE acceptance scenario: the service outlives "
                    "its worst client",
            "seconds": round(time.monotonic() - t0, 1),
            "failures": failures, "passed": not failures}


def run_corrupt_shard_scenario(fixture: dict, workdir: str) -> dict:
    """The issue's acceptance scenario, with NO fault injection: one
    Avro shard's real bytes are flipped on disk; the training run must
    complete with the shard quarantined and coverage reported."""
    from photon_ml_tpu.utils.faults import corrupt_path

    cell_dir = os.path.join(workdir, "cells", "scenario_corrupt_shard")
    shutil.rmtree(cell_dir, ignore_errors=True)
    os.makedirs(cell_dir)
    data_dir = os.path.join(cell_dir, "data")
    shutil.copytree(fixture["data_dir"], data_dir)
    corrupt_path(os.path.join(data_dir, "part-00002.avro"))
    out = os.path.join(cell_dir, "out")
    args = driver_args(data_dir, fixture["fs_dir"], out,
                       os.path.join(cell_dir, "ckpt"),
                       os.path.join(cell_dir, "trace"))
    failures: list[str] = []
    t0 = time.monotonic()
    proc = _run_driver(args)
    _check_no_traceback(proc, failures)
    cov = None
    if proc.returncode != 0:
        failures.append(f"run with one corrupt shard must complete, "
                        f"got rc={proc.returncode}:\n"
                        f"{proc.stderr[-1500:]}")
    else:
        record, _ = _final_objective(out)
        cov = record.get("data_coverage")
        lost = [q["path"] for q in
                (record.get("ingest") or {}).get("train", {})
                .get("shards_quarantined", [])]
        if cov is None or cov >= 1.0 or not any(
                "part-00002" in p for p in lost):
            failures.append(
                f"corrupt shard not quarantined/reported: "
                f"coverage={cov} lost={lost}")
    return {"cell": "scenario.corrupt_shard", "spec": "(real bytes "
            "flipped in part-00002.avro — no injection)",
            "expected": "degraded", "rc": proc.returncode,
            "outcome": f"degraded(coverage={cov})",
            "note": "ISSUE acceptance scenario",
            "seconds": round(time.monotonic() - t0, 1),
            "failures": failures, "passed": not failures}


# ---------------------------------------------------------------------------
# Campaign driver
# ---------------------------------------------------------------------------


def run_campaign(workdir: str, smoke: bool,
                 points: list[str] | None = None,
                 report_path: str | None = None) -> int:
    from photon_ml_tpu.utils.faults import FAULT_POINTS

    os.makedirs(workdir, exist_ok=True)
    fixture = build_fixture(workdir)
    cells = build_cells(smoke)
    if points:
        cells = [c for c in cells if c["point"] in points]
    covered = {c["point"] for c in cells}
    skipped = [{"cell": f"{p}=*", "outcome": "skipped",
                "note": "multihost-only point: needs a multiprocess "
                        "backend this host lacks", "passed": True}
               for p, info in FAULT_POINTS.items()
               if info.multihost_only and (not points or p in points)]
    if not smoke and not points:
        uncovered = {p for p, i in FAULT_POINTS.items()
                     if not i.multihost_only} - covered
        assert not uncovered, \
            f"campaign has no cells for fault points: {sorted(uncovered)}"

    # fault-free reference: the resume bit-exactness anchor
    ref_dir = os.path.join(workdir, "reference")
    shutil.rmtree(ref_dir, ignore_errors=True)
    args = driver_args(fixture["data_dir"], fixture["fs_dir"],
                       os.path.join(ref_dir, "out"),
                       os.path.join(ref_dir, "ckpt"),
                       os.path.join(ref_dir, "trace"))
    t0 = time.monotonic()
    ref = _run_driver(args)
    assert ref.returncode == 0, \
        (f"fault-free reference run failed rc={ref.returncode}\n"
         f"{ref.stdout[-1000:]}\n{ref.stderr[-2000:]}")
    _, reference_objective = _final_objective(os.path.join(ref_dir, "out"))
    print(f"chaos: reference run ok ({time.monotonic() - t0:.1f}s, "
          f"final objective {reference_objective})", flush=True)

    results = []
    for c in cells:
        r = run_cell(c, fixture, workdir, reference_objective)
        results.append(r)
        status = "PASS" if r["passed"] else "FAIL"
        print(f"chaos: [{status}] {r['cell']:<28} -> {r['outcome']} "
              f"({r['seconds']}s)", flush=True)
        for f in r["failures"]:
            print(f"chaos:        {f}", flush=True)
    if not points:  # --points restricts to injection cells only
        scenarios = [run_corrupt_shard_scenario(fixture, workdir)]
        if not smoke:  # the serve scenarios need no training fixture
            scenarios.append(run_serve_dead_client_scenario(workdir))
            scenarios.append(
                run_serve_canary_violation_scenario(workdir))
        for r in scenarios:
            results.append(r)
            print(f"chaos: [{'PASS' if r['passed'] else 'FAIL'}] "
                  f"{r['cell']:<28} -> {r['outcome']} ({r['seconds']}s)",
                  flush=True)
            for f in r["failures"]:
                print(f"chaos:        {f}", flush=True)

    results.extend(skipped)
    failed = [r for r in results if not r["passed"]]
    report = {
        "kind": "chaos_report",
        "smoke": smoke,
        "reference_objective": reference_objective,
        "cells_run": len([r for r in results
                          if r.get("outcome") != "skipped"]),
        "cells_failed": len(failed),
        "invariants": [
            "documented exit semantics (0 / 3+PHOTON_ABORT / "
            "75+PHOTON_PREEMPTED / kill code; never a stack-trace "
            "crash)",
            "checkpoint dir restorable after every cell (no stale .tmp)",
            "bit-exact resume after every kill or signal cell",
            "trace/metrics streams parse line-complete after any cell",
            "corrupt shards quarantine with recorded coverage",
            "a dead/flaky/laggy telemetry consumer leaves training "
            "exit-0 and bit-exact, with only telemetry_dropped as "
            "evidence (obs.export cells)",
            "a dead collector leaves the OTLP bridge exit-0 with its "
            "batches dropped+counted, and the run it watches exit-0 "
            "and bit-exact (obs.otlp cells)",
            "a permanently dead --telemetry-endpoint under fleet "
            "traffic leaves every answer bit-exact and every process "
            "draining cleanly, with only telemetry_dropped counters "
            "as evidence (serve.telemetry cell)",
            "a scoring-service fault is connection-scoped: the service "
            "outlives its worst request/client, post-fault scores stay "
            "bit-identical to the shared batch core, and an injected "
            "kill costs one supervised incarnation (serve.* cells)",
            "a hot-swap lands on exactly one model: refused swaps "
            "(corrupt candidate, canary violation, flip fault, drain "
            "race) leave the current generation serving bit-exact, "
            "completed swaps serve the candidate bit-exact, and a "
            "kill mid-flip relaunches onto one consistent generation "
            "(serve.model_load / serve.swap cells)",
        ],
        "cells": results,
    }
    report_path = report_path or os.path.join(workdir,
                                              "chaos_report.json")
    with open(report_path, "w") as fh:
        json.dump(report, fh, indent=1)
    if failed:
        print(f"CHAOS_FAIL cells={len(results)} failed={len(failed)} "
              f"report={report_path}", flush=True)
        return 2
    print(f"CHAOS_OK cells={len(results)} "
          f"(skipped={len(skipped)}) report={report_path}", flush=True)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: fresh tempdir)")
    ap.add_argument("--smoke", action="store_true",
                    help="curated tier-1 subset (< 60 s)")
    ap.add_argument("--points", default="",
                    help="comma-separated fault points to restrict to")
    ap.add_argument("--report", default=None,
                    help="where to write chaos_report.json")
    args = ap.parse_args(argv)
    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_drill_")
    points = [p.strip() for p in args.points.split(",") if p.strip()]
    return run_campaign(workdir, smoke=args.smoke, points=points or None,
                        report_path=args.report)


if __name__ == "__main__":
    raise SystemExit(main())
