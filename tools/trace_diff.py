#!/usr/bin/env python
"""Per-span self-time regression diff between two traced runs.

"Did PR N slow down ``cd.epilogue_fetch``?" becomes a command: compare a
baseline and a candidate trace (both ``--trace-dir`` ``trace.json``
documents, or ``tools/trace_merge.py`` merged ones) span-name by
span-name on **self time per occurrence** — the same containment sweep
``tools/trace_report.py`` ranks by, so a child span getting slower is
charged to the child, not to every ancestor above it.

The verdict is noise-aware, not a raw comparison:

- a span only REGRESSES when its per-occurrence self time grew by more
  than ``--threshold-pct`` (relative) AND the absolute growth clears
  ``--min-delta-ms`` — timer jitter on a microsecond-scale span can be
  300% of nothing;
- spans whose TOTAL self time stays under ``--min-self-ms`` in both
  runs are ignored entirely (sub-noise either way);
- spans present in only one run are reported (``added`` / ``removed``)
  but never fail the verdict by themselves — a new feature legitimately
  adds spans.

Exit codes: 0 = PASS (no regression), 1 = FAIL (at least one span
regressed), 2 = unreadable/empty input.

Usage::

    python tools/trace_diff.py base/trace.json new/trace.json \
        [--threshold-pct 30] [--min-self-ms 5] [--min-delta-ms 2] \
        [--process 0] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from trace_report import load_events, self_times  # noqa: E402


def profile(path: str, process: int | None = None) -> dict[str, dict]:
    """``{span name: {count, total_us, self_us}}`` for one trace."""
    events = load_events(path)
    if process is not None:
        events = [e for e in events if int(e.get("pid", 0)) == process]
    if not events:
        raise ValueError("no complete span events"
                         + (f" for process {process}"
                            if process is not None else ""))
    return self_times(events)


def diff_profiles(base: dict[str, dict], new: dict[str, dict],
                  threshold_pct: float = 30.0,
                  min_self_ms: float = 5.0,
                  min_delta_ms: float = 2.0) -> dict:
    """Span-by-span comparison + verdict (see module docstring for the
    noise rules). Per-occurrence self time is the compared quantity, so
    a run with more sweeps is not 'slower' just for doing more work."""
    min_self_us = min_self_ms * 1e3
    min_delta_us = min_delta_ms * 1e3
    spans = []
    regressions = []
    for name in sorted(set(base) | set(new)):
        b, n = base.get(name), new.get(name)
        if b is None or n is None:
            spans.append({"span": name,
                          "status": "added" if b is None else "removed"})
            continue
        if b["self_us"] < min_self_us and n["self_us"] < min_self_us:
            spans.append({"span": name, "status": "sub-noise"})
            continue
        b_per = b["self_us"] / max(b["count"], 1)
        n_per = n["self_us"] / max(n["count"], 1)
        delta_pct = (100.0 * (n_per - b_per) / b_per if b_per > 0
                     else float("inf"))
        entry = {
            "span": name,
            "base": {"count": b["count"], "self_us": b["self_us"],
                     "self_per_occurrence_us": b_per},
            "new": {"count": n["count"], "self_us": n["self_us"],
                    "self_per_occurrence_us": n_per},
            "delta_pct": delta_pct,
        }
        if (delta_pct > threshold_pct
                and (n_per - b_per) * min(b["count"], n["count"])
                > min_delta_us):
            entry["status"] = "regressed"
            regressions.append(entry)
        elif delta_pct < -threshold_pct:
            entry["status"] = "improved"
        else:
            entry["status"] = "stable"
        spans.append(entry)
    return {
        "kind": "trace_diff",
        "verdict": "FAIL" if regressions else "PASS",
        "thresholds": {"threshold_pct": threshold_pct,
                       "min_self_ms": min_self_ms,
                       "min_delta_ms": min_delta_ms},
        "regressions": [e["span"] for e in regressions],
        "spans": spans,
    }


def format_diff(report: dict) -> str:
    lines = [f"{'span':<24} {'base ms/occ':>12} {'new ms/occ':>12} "
             f"{'Δ%':>8}  status", "-" * 72]
    for e in report["spans"]:
        if "base" not in e:
            lines.append(f"{e['span']:<24} {'—':>12} {'—':>12} {'—':>8}"
                         f"  {e['status']}")
            continue
        lines.append(
            f"{e['span']:<24} "
            f"{e['base']['self_per_occurrence_us'] / 1e3:>12.3f} "
            f"{e['new']['self_per_occurrence_us'] / 1e3:>12.3f} "
            f"{e['delta_pct']:>+7.1f}%  {e['status']}")
    lines.append("")
    if report["regressions"]:
        lines.append(f"TRACE_DIFF_FAIL regressed="
                     f"{','.join(report['regressions'])}")
    else:
        lines.append("TRACE_DIFF_PASS no span regressed past the "
                     "thresholds")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="noise-aware per-span self-time regression diff "
                    "between two --trace-dir traces")
    p.add_argument("base", help="baseline trace.json")
    p.add_argument("new", help="candidate trace.json")
    p.add_argument("--threshold-pct", type=float, default=30.0,
                   help="relative per-occurrence self-time growth that "
                        "counts as a regression (default 30%%)")
    p.add_argument("--min-self-ms", type=float, default=5.0,
                   help="ignore spans whose total self time stays under "
                        "this in BOTH runs (default 5 ms)")
    p.add_argument("--min-delta-ms", type=float, default=2.0,
                   help="absolute total-growth floor a regression must "
                        "also clear (default 2 ms)")
    p.add_argument("--process", type=int, default=None,
                   help="restrict merged multi-process documents to one "
                        "track (pid) on both sides")
    p.add_argument("--json", action="store_true",
                   help="emit the full diff document as JSON")
    ns = p.parse_args(argv)
    try:
        base = profile(ns.base, process=ns.process)
        new = profile(ns.new, process=ns.process)
    except (OSError, ValueError) as e:
        print(f"trace_diff: {e}", file=sys.stderr)
        return 2
    report = diff_profiles(base, new, threshold_pct=ns.threshold_pct,
                           min_self_ms=ns.min_self_ms,
                           min_delta_ms=ns.min_delta_ms)
    print(json.dumps(report, indent=1) if ns.json
          else format_diff(report))
    return 0 if report["verdict"] == "PASS" else 1


if __name__ == "__main__":
    raise SystemExit(main())
