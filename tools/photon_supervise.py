#!/usr/bin/env python
"""photon-supervise: a self-healing single-machine run supervisor.

Wraps one ``game_training_driver`` run and keeps it alive through the
failure modes the chaos campaign drills:

- **crash** (any nonzero exit: a scripted ``kill``, an OOM, a bug) —
  relaunch with resume (the driver restores its ``--checkpoint-dir``
  automatically) under the same bounded-exponential-backoff policy the
  multi-host :class:`WorkerSupervisor` uses;
- **preemption** (exit 75, the driver honored a SIGTERM/deadline/stop
  file at a commit barrier) — same relaunch path, no backoff penalty
  beyond the policy's;
- **stall** (the run's heartbeat flags ``stalled`` — a wedged I/O, a
  hung collective) — detected by tailing the run dir (or consuming the
  telemetry endpoint) through ``photon_status``'s exit-code contract,
  then SIGTERM (the graceful window) → ``--grace-seconds`` → SIGKILL →
  relaunch;
- **repeated failure at the same coordinate** — the degradation
  ladder: after ``--degrade-after`` failures pinned to one
  (sweep, coordinate) position, relaunch with CD pipelining disabled
  (``--cd-pipeline-depth 0``, bit-exact semantics, simpler execution);
  if it STILL fails there, force fully sequential semantics
  (``--cd-block-size 1``, the well-understood convergence baseline);
  if even sequential mode fails at that coordinate, abort clean — the
  failure is in the model/data, not the execution strategy.

Every action (launch, exit, stall_kill, degrade, abort, done) is
recorded as an NDJSON telemetry record in ``<run-dir>/supervisor.jsonl``
and echoed as a ``PHOTON_SUPERVISE`` line on stdout.

Exit codes: ``0`` — the run completed (possibly after restarts);
``3`` — clean abort (the driver hit a documented terminal condition,
or the degradation ladder exhausted); ``1`` — restart budget exhausted.

Everything after ``--`` is passed to the driver verbatim (give it a
``--checkpoint-dir`` or relaunches restart from scratch, and a
``--trace-dir`` or stalls go undetected)::

    python tools/photon_supervise.py --max-restarts 5 -- \
        --train-input-dirs data --output-dir out \
        --checkpoint-dir out/ckpt --trace-dir out/trace ...
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import signal
import subprocess
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _load_tool(filename: str, name: str):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_HERE, filename))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


photon_status = _load_tool("photon_status.py", "photon_status")

CLEAN_ABORT_EXIT = 3
PREEMPTED_EXIT = 75
#: The default supervised module. ``--module`` swaps in any entrypoint
#: that speaks the same exit-code contract (0/3/75/scripted-kill) — the
#: scoring service (``photon_ml_tpu.serve.service``) is the other
#: in-tree citizen.
TRAIN_MODULE = "photon_ml_tpu.cli.game_training_driver"
#: Fleet mode's members and front end (``--fleet N``).
SERVE_MODULE = "photon_ml_tpu.serve.service"
ROUTER_MODULE = "photon_ml_tpu.serve.router"
# the ladder: level 0 runs the operator's args untouched; each level
# appends flags (argparse last-occurrence-wins, so appending overrides).
# The flags are training-driver CD semantics — the ladder only engages
# when the supervised module IS the training driver.
DEGRADE_LADDER = (
    [],
    ["--cd-pipeline-depth", "0"],
    ["--cd-pipeline-depth", "0", "--cd-block-size", "1"],
)


def _flag_value(args: list[str], flag: str):
    """LAST occurrence of ``--flag value`` in the driver args (matching
    argparse's resolution), or None."""
    value = None
    for i, a in enumerate(args):
        if a == flag and i + 1 < len(args):
            value = args[i + 1]
        elif a.startswith(flag + "="):
            value = a.split("=", 1)[1]
    return value


class Recorder:
    """NDJSON supervisor-action log + the stdout echo. The file lives in
    the run dir next to the driver's telemetry streams (its name matches
    none of photon_status's tail patterns, so it never double-counts
    into the run's own status)."""

    def __init__(self, path: str | None):
        self.path = path
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def __call__(self, action: str, **fields) -> None:
        rec = {"kind": "supervisor", "action": action,
               "t": round(time.time(), 3), **fields}
        if self.path:
            try:
                with open(self.path, "a") as fh:
                    fh.write(json.dumps(rec) + "\n")
            except OSError:
                pass  # a dead disk must not take the supervisor down
        detail = " ".join(f"{k}={v}" for k, v in fields.items())
        print(f"PHOTON_SUPERVISE {action} {detail}".rstrip(), flush=True)


class StatusSource:
    """One incarnation's view of the run's telemetry: a fresh run-dir
    tailer (the driver rotates the previous incarnation's files to
    ``.prev`` on relaunch, so a fresh tailer sees only live evidence) or
    a slice of the listen collector's accumulated records."""

    def __init__(self, run_dir: str | None, collector=None):
        self._collector = collector
        self._offset = 0
        self._tailer = (photon_status.RunDirTailer(run_dir)
                        if run_dir else None)
        if collector is not None:
            self._offset = len(collector.records())

    def status(self) -> dict | None:
        if self._collector is not None:
            return photon_status.compute_status(
                self._collector.records()[self._offset:])
        if self._tailer is not None:
            return photon_status.compute_status(self._tailer.poll())
        return None


def _position(status: dict | None):
    """The run's (sweep, last_coordinate) — the degradation ladder's
    failure-locality key."""
    if not status:
        return None
    p0 = (status.get("processes") or {}).get(0)
    if not p0:
        return None
    if p0.get("sweep") is None and p0.get("last_coordinate") is None:
        return None
    return (p0.get("sweep"), p0.get("last_coordinate"))


def _terminate_gracefully(proc: subprocess.Popen, grace: float,
                          record: Recorder) -> None:
    """SIGTERM (the driver's graceful-stop window: it will snapshot at
    its next commit barrier and exit 75) → grace → SIGKILL (a wedged
    run never reaches a barrier; PEP 475 means even a sleeping run
    resumes its sleep after the handler)."""
    try:
        proc.send_signal(signal.SIGTERM)
    except OSError:
        return
    try:
        proc.wait(timeout=grace)
    except subprocess.TimeoutExpired:
        record("escalate_kill", pid=proc.pid, grace_seconds=grace)
        try:
            proc.kill()
        except OSError:
            pass
        proc.wait()


def supervise(driver_args: list[str], *, max_restarts: int = 5,
              backoff_base: float = 0.5, backoff_max: float = 15.0,
              grace_seconds: float = 10.0, poll_seconds: float = 0.5,
              startup_grace_seconds: float = 5.0, degrade_after: int = 2,
              listen: str | None = None, run_dir: str | None = None,
              python: str | None = None,
              module: str = TRAIN_MODULE) -> int:
    """Run the driver to completion through crashes, preemptions, and
    stalls. Returns the supervisor's exit code (see module docstring)."""
    from photon_ml_tpu.parallel.multihost import WorkerSupervisor

    run_dir = run_dir or _flag_value(driver_args, "--trace-dir")
    out_dir = _flag_value(driver_args, "--output-dir")
    log_dir = run_dir or out_dir
    record = Recorder(os.path.join(log_dir, "supervisor.jsonl")
                      if log_dir else None)
    # reuse the multi-host supervisor's backoff POLICY (exponential +
    # deterministic jitter) without its run loop — this loop also has
    # stall detection and the ladder to drive
    policy = WorkerSupervisor(
        spawn=lambda attempt: None, max_restarts=max_restarts,
        backoff_base_seconds=backoff_base,
        backoff_max_seconds=backoff_max, name="photon-supervise")

    collector = photon_status.ListenCollector(listen) if listen else None
    ladder_level = 0
    fail_position = None
    fails_at_position = 0
    restarts = 0
    attempt = 0
    try:
        while True:
            attempt += 1
            args = list(driver_args) + (DEGRADE_LADDER[ladder_level]
                                        if module == TRAIN_MODULE else [])
            env = dict(os.environ)
            env["PHOTON_GAME_SUPERVISED"] = "1"
            record("launch", attempt=attempt, ladder_level=ladder_level,
                   restarts=restarts)
            proc = subprocess.Popen(
                [python or sys.executable, "-m", module, *args],
                env=env)
            source = StatusSource(run_dir, collector)
            spawn_t = time.monotonic()
            stall_killed = False
            try:
                while proc.poll() is None:
                    time.sleep(poll_seconds)
                    status = source.status()
                    if (status is not None
                            and status["exit_code"]
                            == photon_status.EXIT_STALLED
                            and time.monotonic() - spawn_t
                            >= startup_grace_seconds):
                        record("stall_kill", pid=proc.pid,
                               sweep=status.get("sweep"),
                               position=str(_position(status)))
                        stall_killed = True
                        _terminate_gracefully(proc, grace_seconds,
                                              record)
                        break
                rc = proc.wait()
            except BaseException:
                # an interrupted supervisor must not orphan the driver
                try:
                    proc.kill()
                except OSError:
                    pass
                proc.wait()
                raise
            if rc == 0:
                record("done", restarts=restarts, attempts=attempt)
                print(f"PHOTON_SUPERVISE_OK restarts={restarts}",
                      flush=True)
                return 0
            status = source.status()
            position = _position(status)
            record("exit", rc=rc, attempt=attempt,
                   preempted=(rc == PREEMPTED_EXIT),
                   stall_killed=stall_killed, position=str(position))
            if rc == CLEAN_ABORT_EXIT:
                # a documented terminal condition (PHOTON_ABORT): the
                # driver told us retrying cannot help
                record("abort", reason="driver clean abort", rc=rc)
                return CLEAN_ABORT_EXIT
            # the degradation ladder tracks FAILURES pinned to one
            # coordinate; an honored preemption is progress, not
            # failure. Its rungs are training-only CD flags, so other
            # modules restart at level 0 forever instead of climbing.
            if rc != PREEMPTED_EXIT and module == TRAIN_MODULE:
                if position == fail_position:
                    fails_at_position += 1
                else:
                    fail_position, fails_at_position = position, 1
                if fails_at_position >= degrade_after:
                    if ladder_level + 1 < len(DEGRADE_LADDER):
                        ladder_level += 1
                        fails_at_position = 0
                        record("degrade", level=ladder_level,
                               flags=" ".join(
                                   DEGRADE_LADDER[ladder_level]),
                               position=str(fail_position))
                    else:
                        record("abort",
                               reason="degradation ladder exhausted",
                               position=str(fail_position))
                        print(f"PHOTON_ABORT "
                              f"kind=SupervisorDegradationExhausted: "
                              f"run keeps failing at {fail_position} "
                              f"even with sequential CD semantics",
                              file=sys.stderr, flush=True)
                        return CLEAN_ABORT_EXIT
            restarts += 1
            if restarts > max_restarts:
                record("abort", reason="restart budget exhausted",
                       restarts=restarts - 1, last_rc=rc)
                print(f"PHOTON_SUPERVISE_EXHAUSTED "
                      f"restarts={restarts - 1} last_rc={rc}",
                      file=sys.stderr, flush=True)
                return 1
            delay = policy.backoff_seconds(restarts)
            record("backoff", seconds=round(delay, 2), restart=restarts)
            time.sleep(delay)
    finally:
        if collector is not None:
            collector.close()


def supervise_fleet(member_args: list[str], *, fleet: int,
                    fleet_dir: str, router_listen: str | None = None,
                    max_restarts: int = 5, backoff_base: float = 0.5,
                    backoff_max: float = 15.0,
                    poll_seconds: float = 0.2,
                    grace_seconds: float = 10.0,
                    stop_file: str | None = None,
                    python: str | None = None,
                    module: str = SERVE_MODULE) -> int:
    """Fleet mode: keep N scorer members (and optionally the fleet
    router in front of them) alive. Member ``k`` listens on
    ``unix:<fleet-dir>/member<k>.sock`` with its telemetry under
    ``<fleet-dir>/member<k>/`` — the layout ``photon_status --fleet``
    aggregates. A dead member is relaunched with per-member bounded
    backoff; the router re-admits it only after a verified,
    generation-checked hello (``serve/fleet.py``) — the supervisor
    only supplies the process, never the trust. A ``--stop-file``
    reaches every child, so one touch drains the whole fleet to exit
    0. Exit codes match :func:`supervise`."""
    from photon_ml_tpu.parallel.multihost import WorkerSupervisor

    os.makedirs(fleet_dir, exist_ok=True)
    record = Recorder(os.path.join(fleet_dir, "supervisor.jsonl"))
    policy = WorkerSupervisor(
        spawn=lambda attempt: None, max_restarts=max_restarts,
        backoff_base_seconds=backoff_base,
        backoff_max_seconds=backoff_max, name="photon-supervise-fleet")
    env = dict(os.environ)
    env["PHOTON_GAME_SUPERVISED"] = "1"
    sockets = [os.path.join(fleet_dir, f"member{k}.sock")
               for k in range(fleet)]
    endpoints = [f"unix:{s}" for s in sockets]

    def spawn_member(k: int) -> subprocess.Popen:
        args = (list(member_args)
                + ["--listen", endpoints[k],
                   "--trace-dir", os.path.join(fleet_dir, f"member{k}")]
                + (["--stop-file", stop_file] if stop_file else []))
        record("launch_member", member=k, endpoint=endpoints[k])
        return subprocess.Popen(
            [python or sys.executable, "-m", module, *args], env=env)

    def spawn_router() -> subprocess.Popen:
        args = (["--listen", router_listen,
                 "--members", ",".join(endpoints),
                 "--trace-dir", os.path.join(fleet_dir, "router")]
                + (["--stop-file", stop_file] if stop_file else []))
        record("launch_router", endpoint=router_listen)
        return subprocess.Popen(
            [python or sys.executable, "-m", ROUTER_MODULE, *args],
            env=env)

    members: list[subprocess.Popen | None] = [spawn_member(k)
                                              for k in range(fleet)]
    router = spawn_router() if router_listen else None
    restarts = [0] * fleet
    router_restarts = 0
    relaunch_at: dict[int, float] = {}  # member → earliest relaunch

    def shutdown_all(procs) -> None:
        for proc in procs:
            if proc is not None and proc.poll() is None:
                _terminate_gracefully(proc, grace_seconds, record)

    try:
        while True:
            time.sleep(poll_seconds)
            now = time.monotonic()
            for k in range(fleet):
                proc = members[k]
                if proc is not None and proc.poll() is not None:
                    rc = proc.returncode
                    record("member_exit", member=k, rc=rc,
                           preempted=(rc == PREEMPTED_EXIT))
                    members[k] = None
                    if rc == 0:
                        continue  # scheduled stop: done, not dead
                    restarts[k] += 1
                    if restarts[k] > max_restarts:
                        record("abort", member=k,
                               reason="member restart budget exhausted",
                               restarts=restarts[k] - 1, last_rc=rc)
                        print(f"PHOTON_SUPERVISE_EXHAUSTED member={k} "
                              f"restarts={restarts[k] - 1} last_rc={rc}",
                              file=sys.stderr, flush=True)
                        shutdown_all(members + [router])
                        return 1
                    delay = policy.backoff_seconds(restarts[k])
                    record("backoff", member=k, seconds=round(delay, 2),
                           restart=restarts[k])
                    relaunch_at[k] = now + delay
                elif (proc is None and k in relaunch_at
                        and now >= relaunch_at[k]):
                    del relaunch_at[k]
                    record("relaunch_member", member=k,
                           restart=restarts[k])
                    members[k] = spawn_member(k)
            if router is not None and router.poll() is not None:
                rc = router.returncode
                record("router_exit", rc=rc,
                       preempted=(rc == PREEMPTED_EXIT))
                if rc == 0:
                    shutdown_all(members)
                    total = sum(restarts) + router_restarts
                    record("done", restarts=total)
                    print(f"PHOTON_SUPERVISE_OK restarts={total}",
                          flush=True)
                    return 0
                if rc == CLEAN_ABORT_EXIT:
                    record("abort", reason="router clean abort", rc=rc)
                    shutdown_all(members)
                    return CLEAN_ABORT_EXIT
                router_restarts += 1
                if router_restarts > max_restarts:
                    record("abort",
                           reason="router restart budget exhausted",
                           restarts=router_restarts - 1, last_rc=rc)
                    shutdown_all(members)
                    return 1
                delay = policy.backoff_seconds(router_restarts)
                record("backoff", seconds=round(delay, 2),
                       restart=router_restarts, member="router")
                time.sleep(delay)
                record("relaunch_router", restart=router_restarts)
                router = spawn_router()
            if (router is None and not relaunch_at
                    and all(m is None for m in members)):
                record("done", restarts=sum(restarts))
                print(f"PHOTON_SUPERVISE_OK restarts={sum(restarts)}",
                      flush=True)
                return 0
    except BaseException:
        # an interrupted supervisor must not orphan the fleet
        for proc in members + [router]:
            if proc is not None and proc.poll() is None:
                try:
                    proc.kill()
                except OSError:
                    pass
                proc.wait()
        raise


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="self-healing supervisor for a game_training_driver "
                    "run: relaunch-with-resume on crash/preemption, "
                    "SIGTERM+relaunch on stall, degradation ladder on "
                    "repeated same-coordinate failures",
        epilog="driver arguments go after `--`")
    p.add_argument("--max-restarts", type=int, default=5)
    p.add_argument("--backoff-base", type=float, default=0.5,
                   help="backoff base seconds (doubles per restart, "
                        "deterministic jitter)")
    p.add_argument("--backoff-max", type=float, default=15.0)
    p.add_argument("--grace-seconds", type=float, default=10.0,
                   help="SIGTERM→SIGKILL escalation window for a "
                        "stalled run")
    p.add_argument("--poll-seconds", type=float, default=0.5,
                   help="status poll cadence while the driver runs")
    p.add_argument("--startup-grace-seconds", type=float, default=5.0,
                   help="ignore stall verdicts this long after a "
                        "launch (the new incarnation has not rotated "
                        "the old telemetry yet)")
    p.add_argument("--degrade-after", type=int, default=2,
                   help="failures at the same (sweep, coordinate) "
                        "before climbing the degradation ladder")
    p.add_argument("--run-dir", default=None,
                   help="the run's --trace-dir (default: extracted "
                        "from the driver args) — tailed for stall "
                        "detection and failure positions")
    p.add_argument("--listen", default=None,
                   help="consume the run's --telemetry-endpoint stream "
                        "at HOST:PORT / unix:/path.sock instead of "
                        "tailing the run dir")
    p.add_argument("--module", default=TRAIN_MODULE,
                   help="the python -m entrypoint to supervise "
                        "(default: the GAME training driver; "
                        "photon_ml_tpu.serve.service keeps the scoring "
                        "service alive through the same contract)")
    p.add_argument("--fleet", type=int, default=0, metavar="N",
                   help="fleet mode: supervise N scorer members (the "
                        "driver args after `--` become EVERY member's "
                        "args — model flags, queue depths); implies "
                        "--module photon_ml_tpu.serve.service unless "
                        "overridden")
    p.add_argument("--fleet-dir", default=None,
                   help="fleet mode: directory for member sockets "
                        "(member<k>.sock), per-member telemetry dirs "
                        "(member<k>/), the router dir, and "
                        "supervisor.jsonl")
    p.add_argument("--router-listen", default=None,
                   help="fleet mode: also run the fleet router in "
                        "front of the members at this endpoint "
                        "(HOST:PORT or unix:/path.sock); its exit 0 "
                        "drains the whole fleet")
    p.add_argument("--stop-file", default=None,
                   help="fleet mode: forwarded to every member and the "
                        "router — touching it drains the fleet to "
                        "exit 0")
    ns, driver_args = p.parse_known_args(argv)
    if driver_args and driver_args[0] == "--":
        driver_args = driver_args[1:]
    if ns.fleet:
        if not ns.fleet_dir:
            p.error("--fleet requires --fleet-dir")
        module = (ns.module if ns.module != TRAIN_MODULE
                  else SERVE_MODULE)
        return supervise_fleet(
            driver_args, fleet=ns.fleet, fleet_dir=ns.fleet_dir,
            router_listen=ns.router_listen,
            max_restarts=ns.max_restarts, backoff_base=ns.backoff_base,
            backoff_max=ns.backoff_max, poll_seconds=ns.poll_seconds,
            grace_seconds=ns.grace_seconds, stop_file=ns.stop_file,
            module=module)
    if not driver_args:
        p.error("no driver arguments given (pass them after `--`)")
    return supervise(
        driver_args, max_restarts=ns.max_restarts,
        backoff_base=ns.backoff_base, backoff_max=ns.backoff_max,
        grace_seconds=ns.grace_seconds, poll_seconds=ns.poll_seconds,
        startup_grace_seconds=ns.startup_grace_seconds,
        degrade_after=ns.degrade_after, listen=ns.listen,
        run_dir=ns.run_dir, module=ns.module)


if __name__ == "__main__":
    raise SystemExit(main())
