#!/usr/bin/env python
"""Summarize a Chrome trace produced by ``--trace-dir``.

Reads a ``trace.json`` (or ``trace.<process_index>.json``, or a
``tools/trace_merge.py`` merged multi-process document) written by
``photon_ml_tpu/obs`` and prints:

1. the top-N span names by SELF time (total minus time spent in child
   spans on the same thread — timestamp containment defines nesting, so
   the report works on any Chrome trace with complete "X" events), and
2. per-coordinate sweep attribution: how much wall-clock each coordinate's
   ``cd.update`` spans cost per sweep — the "which coordinate ate the
   sweep" question the observability layer exists to answer.

``--process N`` restricts a merged multi-process document to one track;
``--json`` emits the same stats machine-readably (the format
``tools/trace_diff.py`` composes with). ``--device`` switches to the
device-plane view: per compile site, ``xla.compile`` span labels
(compiles, compile ms, ``cost_analysis()`` flops/bytes) joined with the
runtime span's self-time into a roofline-style achieved GF/s / GB/s
column, plus retrace counts and the last retrace cause.

``--request <trace_id>`` switches to the serve plane's request view:
every span carrying that propagated ``trace_id`` label — across every
process track of a ``trace_merge``'d fleet document — is stitched into
one waterfall via its ``span_id``/``parent`` labels (NOT containment:
the parent link crosses processes, client→router→member), with
per-stage self-times so "where did this request's latency go" reads
straight off the tree. Works on sampled spans and on the exemplar
trees ``trace_merge --fleet`` folds in, so the slowest requests
resolve regardless of the sample rate.

Exit codes: 0 = report printed, 2 = unreadable/empty/invalid trace
(or an unknown ``--request`` trace id).

Usage::

    python tools/trace_report.py out/trace/trace.json [--top 15]
                                 [--process 0] [--json]
    python tools/trace_report.py out/fleet/merged_trace.json \
                                 --request 1f00ab34c55d9e21
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict


def load_events(path: str) -> list[dict]:
    """Complete ("ph": "X") events from a Chrome trace file (object with
    ``traceEvents`` or a bare event array)."""
    with open(path) as fh:
        doc = json.load(fh)
    events = doc.get("traceEvents", doc) if isinstance(doc, dict) else doc
    if not isinstance(events, list):
        raise ValueError("not a Chrome trace: traceEvents is not a list")
    out = []
    for e in events:
        if (isinstance(e, dict) and e.get("ph") == "X"
                and "ts" in e and "name" in e):
            out.append(e)
    return out


def self_times(events: list[dict]) -> dict[str, dict]:
    """Per-name {count, total_us, self_us} via a containment sweep per
    (pid, tid): an event's self time is its duration minus its DIRECT
    children's durations."""
    stats: dict[str, dict] = defaultdict(
        lambda: {"count": 0, "total_us": 0.0, "self_us": 0.0})
    by_track: dict[tuple, list[dict]] = defaultdict(list)
    for e in events:
        by_track[(e.get("pid", 0), e.get("tid", 0))].append(e)
    for track in by_track.values():
        # sort by start asc, then duration desc so parents precede their
        # children that start at the identical timestamp
        track.sort(key=lambda e: (e["ts"], -e.get("dur", 0.0)))
        stack: list[tuple[float, float, list]] = []  # (end, dur, child_durs)
        for e in track:
            ts, dur = float(e["ts"]), float(e.get("dur", 0.0))
            while stack and ts >= stack[-1][0] - 1e-9:
                stack.pop()
            if stack:
                stack[-1][2].append(dur)
            child_durs: list = []
            stack.append((ts + dur, dur, child_durs))
            s = stats[e["name"]]
            s["count"] += 1
            s["total_us"] += dur
            # children are appended as later events arrive; record the
            # slot so self time resolves after the full sweep
            s.setdefault("_pending", []).append((dur, child_durs))
    for s in stats.values():
        for dur, child_durs in s.pop("_pending", []):
            s["self_us"] += max(0.0, dur - sum(child_durs))
    return dict(stats)


def sweep_attribution(events: list[dict]) -> dict[tuple, float]:
    """(sweep, coordinate) -> total cd.update microseconds."""
    out: dict[tuple, float] = defaultdict(float)
    for e in events:
        if e["name"] != "cd.update":
            continue
        args = e.get("args") or {}
        out[(args.get("sweep", "?"), args.get("coordinate", "?"))] += \
            float(e.get("dur", 0.0))
    return dict(out)


#: compile site -> the runtime span whose self-time its executables
#: spend (the --device join key). Sites without a mapping still report
#: their compile cost, just without a utilization column.
_SITE_RUNTIME_SPAN = {
    "optimizer.lbfgs": "optimizer.solve",
    "optimizer.owlqn": "optimizer.solve",
    "optimizer.tron": "optimizer.solve",
    "re.fit_blocks": "re.solve",
    "cd.epilogue": "cd.epilogue_fetch",
    "cd.canonical_total": "cd.epilogue_fetch",
}


def device_report(events: list[dict]) -> list[dict]:
    """The --device view: per compile site, the ``xla.compile`` span
    labels (compiles, compile seconds, cost_analysis flops/bytes)
    joined with the mapped runtime span's count and self-time — a
    roofline-style achieved-rate column (``gflops_per_sec`` /
    ``gbytes_per_sec`` over the span's self time) plus the site's
    retrace count and last recorded retrace cause."""
    sites: dict[str, dict] = {}
    for e in events:
        args = e.get("args") or {}
        site = args.get("site")
        if site is None:
            continue
        if e["name"] == "xla.compile":
            row = sites.setdefault(site, {
                "site": site, "compiles": 0, "compile_ms": 0.0,
                "flops": None, "bytes_accessed": None, "retraces": 0,
                "last_retrace": None})
            row["compiles"] += 1
            row["compile_ms"] += float(args.get("secs", 0.0)) * 1e3
            if args.get("flops") is not None:
                row["flops"] = float(args["flops"])
            if args.get("bytes_accessed") is not None:
                row["bytes_accessed"] = float(args["bytes_accessed"])
        elif e["name"] == "xla.retrace":
            row = sites.setdefault(site, {
                "site": site, "compiles": 0, "compile_ms": 0.0,
                "flops": None, "bytes_accessed": None, "retraces": 0,
                "last_retrace": None})
            row["retraces"] += 1
            row["last_retrace"] = {
                "arg": args.get("arg"), "field": args.get("field"),
                "old": args.get("old"), "new": args.get("new")}
    if not sites:
        return []
    stats = self_times(events)
    for site, row in sites.items():
        span = _SITE_RUNTIME_SPAN.get(site)
        s = stats.get(span) if span else None
        row["runtime_span"] = span
        row["span_count"] = s["count"] if s else None
        row["span_self_ms"] = round(s["self_us"] / 1e3, 3) if s else None
        row["gflops_per_sec"] = row["gbytes_per_sec"] = None
        if s and s["self_us"] > 0:
            secs = s["self_us"] / 1e6
            if row["flops"] is not None:
                row["gflops_per_sec"] = round(
                    row["flops"] * s["count"] / secs / 1e9, 3)
            if row["bytes_accessed"] is not None:
                row["gbytes_per_sec"] = round(
                    row["bytes_accessed"] * s["count"] / secs / 1e9, 3)
        row["compile_ms"] = round(row["compile_ms"], 3)
    return sorted(sites.values(), key=lambda r: r["site"])


def format_device_report(events: list[dict]) -> str:
    rows = device_report(events)
    if not rows:
        return ("no device-plane spans in this trace — run with "
                "--device-telemetry to record xla.compile/xla.retrace")
    lines = ["device plane (xla.compile ⋈ runtime span self-time):",
             f"{'site':<20} {'compiles':>8} {'compile_ms':>11} "
             f"{'retraces':>8} {'runtime_span':<18} {'self_ms':>9} "
             f"{'GF/s':>8} {'GB/s':>8}"]
    lines.append("-" * 97)
    for r in rows:
        lines.append(
            f"{r['site']:<20} {r['compiles']:>8} "
            f"{r['compile_ms']:>11.2f} {r['retraces']:>8} "
            f"{str(r['runtime_span'] or '—'):<18} "
            f"{r['span_self_ms'] if r['span_self_ms'] is not None else '—':>9} "
            f"{r['gflops_per_sec'] if r['gflops_per_sec'] is not None else '—':>8} "
            f"{r['gbytes_per_sec'] if r['gbytes_per_sec'] is not None else '—':>8}")
    causes = [(r["site"], r["last_retrace"]) for r in rows
              if r["last_retrace"]]
    if causes:
        lines.append("")
        lines.append("last retrace cause per site:")
        for site, c in causes:
            lines.append(f"  {site}: {c['arg']} {c['field']} changed "
                         f"{c['old']} -> {c['new']}")
    return "\n".join(lines)


def request_tree(events: list[dict], trace_id: str) -> list[dict]:
    """The one request's spans as nested nodes (roots first, children
    under ``"children"``, siblings by start time), stitched by the
    propagated ``span_id``/``parent`` labels rather than containment —
    the links cross process tracks in a merged fleet document.

    Each node: ``{name, pid, ts, dur_us, self_us, labels, children}``.
    Spans appearing twice (a sampled span AND its exemplar-tree copy)
    dedup by ``span_id``. Self time is duration minus DIRECT children's
    durations; a remote child (the member's ``serve.request`` under the
    router's ``route.dispatch``) subtracts like a local one, so the
    router's dispatch self-time reads as pure wire+routing overhead."""
    by_id: dict[str, dict] = {}
    for e in events:
        args = e.get("args") or {}
        if args.get("trace_id") != trace_id:
            continue
        sid = args.get("span_id")
        if not sid or sid in by_id:
            continue
        by_id[sid] = {"name": e.get("name", ""),
                      "pid": e.get("pid", 0),
                      "ts": float(e.get("ts", 0.0)),
                      "dur_us": float(e.get("dur", 0.0)),
                      "self_us": float(e.get("dur", 0.0)),
                      "labels": {k: v for k, v in args.items()
                                 if k not in ("trace_id", "span_id",
                                              "parent")},
                      "parent": args.get("parent"),
                      "children": []}
    roots: list[dict] = []
    for node in by_id.values():
        parent = by_id.get(node["parent"] or "")
        if parent is not None and parent is not node:
            parent["children"].append(node)
            parent["self_us"] = max(0.0,
                                    parent["self_us"] - node["dur_us"])
        else:
            roots.append(node)
    for node in by_id.values():
        node["children"].sort(key=lambda n: n["ts"])
        del node["parent"]
    roots.sort(key=lambda n: n["ts"])
    return roots


def format_request(events: list[dict], trace_id: str) -> str | None:
    """The --request waterfall (None when the trace id is unknown)."""
    roots = request_tree(events, trace_id)
    if not roots:
        return None
    t0 = min(n["ts"] for n in roots)
    lines = [f"request {trace_id}:",
             f"{'span':<40} {'pid':>4} {'start_ms':>9} {'dur_ms':>9} "
             f"{'self_ms':>9}  detail",
             "-" * 92]

    def walk(node: dict, depth: int) -> None:
        label = "  " * depth + node["name"]
        detail = " ".join(
            f"{k}={node['labels'][k]}"
            for k in ("outcome", "rows", "shard", "member", "hops")
            if k in node["labels"])
        lines.append(
            f"{label:<40} {node['pid']:>4} "
            f"{(node['ts'] - t0) / 1e3:>9.3f} "
            f"{node['dur_us'] / 1e3:>9.3f} "
            f"{node['self_us'] / 1e3:>9.3f}  {detail}".rstrip())
        for child in node["children"]:
            walk(child, depth + 1)

    for root in roots:
        walk(root, 0)
    return "\n".join(lines)


def format_report(events: list[dict], top: int) -> str:
    lines = []
    stats = self_times(events)
    ranked = sorted(stats.items(), key=lambda kv: -kv[1]["self_us"])[:top]
    lines.append(f"{'span':<24} {'count':>7} {'total_ms':>10} "
                 f"{'self_ms':>10} {'avg_ms':>9}")
    lines.append("-" * 64)
    for name, s in ranked:
        lines.append(
            f"{name:<24} {s['count']:>7} {s['total_us'] / 1e3:>10.2f} "
            f"{s['self_us'] / 1e3:>10.2f} "
            f"{s['total_us'] / s['count'] / 1e3:>9.3f}")
    attr = sweep_attribution(events)
    if attr:
        lines.append("")
        lines.append("per-coordinate sweep attribution (cd.update):")
        lines.append(f"{'sweep':>6} {'coordinate':<20} {'ms':>10} {'%':>6}")
        lines.append("-" * 46)
        by_sweep: dict = defaultdict(float)
        for (sweep, _), us in attr.items():
            by_sweep[sweep] += us

        def sweep_key(sweep):
            # numeric sweeps sort numerically (2 before 10); non-numeric
            # labels (the "?" fallback) sort after, lexicographically
            try:
                return (0, float(sweep), "")
            except (TypeError, ValueError):
                return (1, 0.0, str(sweep))

        for (sweep, coord), us in sorted(
                attr.items(),
                key=lambda kv: (sweep_key(kv[0][0]), -kv[1])):
            pct = 100.0 * us / by_sweep[sweep] if by_sweep[sweep] else 0.0
            lines.append(f"{str(sweep):>6} {str(coord):<20} "
                         f"{us / 1e3:>10.2f} {pct:>5.1f}%")
    return "\n".join(lines)


def json_report(events: list[dict], top: int) -> dict:
    """The machine-readable twin of :func:`format_report` — per-name
    self-time stats plus sweep attribution, the document
    ``tools/trace_diff.py`` and scripted perf checks consume."""
    stats = self_times(events)
    ranked = sorted(stats.items(), key=lambda kv: -kv[1]["self_us"])
    return {
        "kind": "trace_report",
        "processes": sorted({e.get("pid", 0) for e in events}),
        "span_count": len(events),
        "spans": {name: {"count": s["count"],
                         "total_us": s["total_us"],
                         "self_us": s["self_us"]}
                  for name, s in ranked[:top]},
        "sweep_attribution": [
            {"sweep": sweep, "coordinate": coord, "us": us}
            for (sweep, coord), us in sorted(
                sweep_attribution(events).items(),
                key=lambda kv: (str(kv[0][0]), str(kv[0][1])))],
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="top spans by self-time + per-coordinate sweep "
                    "attribution from a --trace-dir trace.json")
    p.add_argument("trace", help="path to trace.json")
    p.add_argument("--top", type=int, default=15,
                   help="span names to show (by self time)")
    p.add_argument("--process", type=int, default=None,
                   help="restrict a merged multi-process document to "
                        "this process's track (pid)")
    p.add_argument("--json", action="store_true",
                   help="emit the stats as JSON instead of the table")
    p.add_argument("--device", action="store_true",
                   help="device-plane view: join xla.compile "
                        "cost-analysis labels (flops/bytes) with runtime "
                        "span self-time for a roofline-style achieved "
                        "rate per compile site (needs a trace recorded "
                        "with --device-telemetry)")
    p.add_argument("--request", default=None, metavar="TRACE_ID",
                   help="serve-plane request view: the cross-process "
                        "waterfall of one propagated trace id (stitched "
                        "by span_id/parent labels across a merged fleet "
                        "document) with per-stage self-times")
    ns = p.parse_args(argv)
    try:
        events = load_events(ns.trace)
    except (OSError, ValueError) as e:
        print(f"trace_report: cannot read {ns.trace}: {e}",
              file=sys.stderr)
        return 2
    if ns.process is not None:
        events = [e for e in events
                  if int(e.get("pid", 0)) == ns.process]
    if not events:
        where = (f" for process {ns.process}"
                 if ns.process is not None else "")
        print(f"trace_report: {ns.trace} holds no complete span "
              f"events{where}", file=sys.stderr)
        return 2
    if ns.request is not None:
        roots = request_tree(events, ns.request)
        if not roots:
            print(f"trace_report: no spans labeled trace_id="
                  f"{ns.request} in {ns.trace}", file=sys.stderr)
            return 2
        if ns.json:
            print(json.dumps({"kind": "trace_report_request",
                              "trace_id": ns.request,
                              "spans": roots}, indent=1))
        else:
            print(format_request(events, ns.request))
        return 0
    if ns.json:
        doc = json_report(events, ns.top)
        if ns.device:
            # additive key: the base schema (pinned by the stability
            # test) is unchanged unless --device is asked for
            doc["device"] = device_report(events)
        print(json.dumps(doc, indent=1))
    elif ns.device:
        print(format_device_report(events))
    else:
        print(format_report(events, ns.top))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
