#!/usr/bin/env python
"""OTLP bridge: re-emit a photon run's telemetry as OTLP/HTTP JSON.

Attaches to a run the same two ways ``tools/photon_status.py`` does:

- ``--run-dir DIR`` — read (or, with ``--follow``, tail) the run's
  ``--trace-dir``: spans from ``spans[.i].jsonl``, heartbeat/run-end
  records from ``metrics[.i].jsonl``, manifests for resource
  attributes;
- ``--listen HOST:PORT`` (or ``unix:/path.sock``) — BE the run's
  ``--telemetry-endpoint`` consumer and convert the NDJSON stream as
  it arrives.

Converted documents go to ``--collector URL`` (POST to
``<URL>/v1/traces`` and ``<URL>/v1/metrics`` — any OTLP/HTTP collector:
Grafana Alloy, Jaeger, Tempo, the otel-collector) and/or ``--out
FILE`` (the combined JSON document, golden-fixture friendly).

The collector contract mirrors ``--telemetry-endpoint``'s: a dead,
slow, or flaky collector can only ever cause batches to be DROPPED
(counted, reported on stderr at exit) — the bridge always exits 0 once
it has read its input, and the run it watches is never affected (the
``obs.otlp`` chaos cell proves both). Conversion refuses a
``telemetry_proto`` it has never seen (exit 2) instead of mis-mapping
it.

Usage::

    python tools/otlp_bridge.py --run-dir out/trace \
        --collector http://127.0.0.1:4318
    python tools/otlp_bridge.py --run-dir out/trace --out run_otlp.json
    python tools/otlp_bridge.py --listen 127.0.0.1:9201 \
        --collector http://127.0.0.1:4318 --for-seconds 30
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from photon_ml_tpu.obs.otlp import (  # noqa: E402
    UnsupportedProtoError,
    load_run_dir,
    post_otlp,
    records_to_otlp,
)

EXIT_OK, EXIT_USAGE = 0, 2


def _listen_records(endpoint: str, for_seconds: float) -> list:
    """Bind the endpoint, accept every producer that connects within
    the window, and collect their NDJSON records (one connection at a
    time is enough: drivers connect once and stream)."""
    if endpoint.startswith("unix:"):
        server = socket.socket(socket.AF_UNIX)
        path = endpoint[len("unix:"):]
        if os.path.exists(path):
            os.unlink(path)
        server.bind(path)
    else:
        host, _, port = endpoint.rpartition(":")
        server = socket.socket()
        server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        server.bind((host or "127.0.0.1", int(port)))
    server.listen(4)
    server.settimeout(0.5)
    deadline = time.monotonic() + for_seconds
    records: list = []
    try:
        while time.monotonic() < deadline:
            try:
                conn, _ = server.accept()
            except socket.timeout:
                continue
            with conn:
                conn.settimeout(1.0)
                buf = b""
                while time.monotonic() < deadline:
                    try:
                        chunk = conn.recv(65536)
                    except socket.timeout:
                        continue
                    except OSError:
                        break
                    if not chunk:
                        break
                    buf += chunk
                    while b"\n" in buf:
                        line, buf = buf.split(b"\n", 1)
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue
                        if isinstance(rec, dict):
                            records.append(rec)
    finally:
        server.close()
    return records


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="convert photon telemetry to OTLP/HTTP JSON")
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--run-dir", help="a run's --trace-dir to convert")
    src.add_argument("--listen",
                     help="be the --telemetry-endpoint consumer "
                          "(HOST:PORT or unix:/path.sock)")
    ap.add_argument("--collector",
                    help="OTLP/HTTP collector base URL (POSTs to "
                         "<URL>/v1/traces and <URL>/v1/metrics)")
    ap.add_argument("--out", help="write the combined OTLP JSON document "
                                  "({traces, metrics}) to this file")
    ap.add_argument("--follow", action="store_true",
                    help="with --run-dir: keep re-reading and re-posting "
                         "until a run_end record appears (or "
                         "--for-seconds elapses)")
    ap.add_argument("--for-seconds", type=float, default=10.0,
                    help="--listen window / --follow deadline "
                         "(default 10)")
    ap.add_argument("--poll-seconds", type=float, default=1.0,
                    help="--follow re-read cadence (default 1)")
    ns = ap.parse_args(argv)
    if not ns.collector and not ns.out:
        ap.error("nothing to do: pass --collector and/or --out")

    stats = {"posted": 0, "dropped": 0}

    def convert_and_ship(records) -> dict:
        docs = records_to_otlp(records)
        if ns.collector:
            r = post_otlp(docs, ns.collector)
            stats["posted"] += r["posted"]
            stats["dropped"] += r["dropped"]
        return docs

    try:
        if ns.listen:
            records = _listen_records(ns.listen, ns.for_seconds)
            docs = convert_and_ship(records)
        elif ns.follow:
            deadline = time.monotonic() + ns.for_seconds
            docs = {}
            while True:
                records = load_run_dir(ns.run_dir)
                docs = convert_and_ship(records)
                ended = any(r.get("kind") == "run_end" for r in records)
                if ended or time.monotonic() >= deadline:
                    break
                time.sleep(ns.poll_seconds)
        else:
            docs = convert_and_ship(load_run_dir(ns.run_dir))
    except UnsupportedProtoError as e:
        print(f"otlp_bridge: {e}", file=sys.stderr)
        return EXIT_USAGE

    if ns.out:
        with open(ns.out, "w") as fh:
            json.dump(docs, fh, indent=1, sort_keys=True)
    spans = sum(len(ss["spans"])
                for rs in docs.get("traces", {}).get("resourceSpans", [])
                for ss in rs["scopeSpans"])
    metrics = sum(len(sm["metrics"])
                  for rm in docs.get("metrics", {}).get(
                      "resourceMetrics", [])
                  for sm in rm["scopeMetrics"])
    print(f"otlp_bridge: {spans} span(s), {metrics} metric(s), "
          f"posted={stats['posted']} dropped={stats['dropped']}",
          file=sys.stderr)
    return EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
