"""photon_ml_tpu — a TPU-native framework for GLMs and GAME mixed-effect models.

A ground-up JAX/XLA re-design with the capabilities of LinkedIn Photon-ML
(reference: jinyu0310/photon-ml, Spark/Scala): generalized linear models
(linear / logistic / Poisson regression, smoothed-hinge linear SVM) with
L1/L2/elastic-net regularization, box constraints, feature normalization,
offsets, feature summarization and diagnostics — plus GAME (Generalized
Additive Mixed Effects): coordinate descent over a fixed-effect GLM, many
per-entity random-effect GLMs, and factored random effects, sharded over a
TPU device mesh instead of Spark partitions.

Layer map (mirrors SURVEY.md §1, re-architected TPU-first):

- ``parallel/``   device mesh + sharding policy (replaces Spark runtime)
- ``data/``       columnar device batches, GAME datasets, entity blocking
- ``ops/``        pointwise losses + fused objective kernels (XLA-fused)
- ``optimize/``   L-BFGS / OWL-QN / TRON as jitted lax.while_loop kernels
- ``game/``       coordinate descent, fixed/random/factored coordinates
- ``models/``     coefficient containers + GLM / GAME model families
- ``evaluation/`` metrics and (sharded) evaluators
- ``projector/``  per-entity dimension reduction
- ``io/``         Avro object-container codec, model serialization, LibSVM
- ``cli/``        training / scoring / indexing drivers
- ``diagnostics/`` bootstrap, fitting, HL, importance, reporting
"""

__version__ = "0.1.0"
