"""GAME: Generalized Additive Mixed Effects on TPU.

The flagship subsystem (reference README.md:73-99): a coordinate-descent
outer loop over a global fixed-effect GLM, per-entity random-effect GLMs
(vmapped + entity-sharded), and optional factored random effects.
"""

from photon_ml_tpu.game.coordinate import (  # noqa: F401
    FactoredRandomEffectCoordinate,
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.game.coordinate_descent import (  # noqa: F401
    CoordinateDescentResult,
    run_coordinate_descent,
    training_loss_evaluator,
)
from photon_ml_tpu.game.dataset import (  # noqa: F401
    FixedEffectDataConfiguration,
    FixedEffectDataset,
    GameDataset,
    RandomEffectDataConfiguration,
    RandomEffectDataset,
    build_fixed_effect_dataset,
    build_random_effect_dataset,
)
from photon_ml_tpu.game.models import (  # noqa: F401
    FactoredRandomEffectModel,
    FixedEffectModel,
    GameModel,
    MatrixFactorizationModel,
    RandomEffectModel,
    RandomEffectModelInProjectedSpace,
)
from photon_ml_tpu.game.random_effect import (  # noqa: F401
    RandomEffectOptimizationProblem,
    score_random_effect,
)
