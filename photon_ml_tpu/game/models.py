"""GAME model family: fixed-effect, random-effect, factored, MF, composite.

TPU-native re-design of the reference's model layer
(reference paths under photon-ml/src/main/scala/com/linkedin/photon/ml/model/):

- ``DatumScoringModel.score`` (DatumScoringModel.scala:33) — score an RDD of
  GameDatum. Here every model scores a :class:`GameDataset` into a plain
  ``[N]`` sample-major array.
- ``GAMEModel`` (GAMEModel.scala:29-114) — coordinateId → model map; total
  score = Σ sub-scores.
- ``FixedEffectModel`` (FixedEffectModel.scala:29-103) — broadcast GLM + its
  feature shard. Broadcasting disappears: coefficients live in HBM.
- ``RandomEffectModel`` (RandomEffectModel.scala:33-165) — RDD[(entityId,
  GLM)]; scoring cogroups data with models. Here: a stacked coefficient block
  ``[E, D]`` + the entity vocabulary; scoring is a gather.
- ``RandomEffectModelInProjectedSpace`` — coefficients kept in each entity's
  reduced space with the projector retained for raw-space conversion.
- ``MatrixFactorizationModel`` (MatrixFactorizationModel.scala:50-179) — row/
  col latent factors; score = dot of the latent vectors.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Protocol, Union

import numpy as np

import jax
import jax.numpy as jnp
import scipy.sparse as sp

from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_ml_tpu.optimize.config import TaskType
from photon_ml_tpu.projector.projectors import (
    IndexMapProjectors,
    RandomProjector,
)

Array = jnp.ndarray


class DatumScoringModel(Protocol):
    """model/DatumScoringModel.scala:33 analog."""

    def score(self, data: GameDataset) -> Array: ...


def _match(keys: np.ndarray, queries: np.ndarray) -> np.ndarray:
    """Row in ``keys`` for each query (len(keys) where absent)."""
    e = len(keys)
    if e == 0 or len(queries) == 0:
        return np.full(len(queries), e, dtype=np.int64)
    order = np.argsort(keys, kind="stable")
    sorted_keys = keys[order]
    pos = np.clip(np.searchsorted(sorted_keys, queries), 0, e - 1)
    found = sorted_keys[pos] == queries
    return np.where(found, order[pos], e)


def _codes_via_ids(ids: np.ndarray, vocab: np.ndarray,
                   codes: np.ndarray) -> np.ndarray:
    """Match dataset rows (dictionary ``codes`` into ``vocab``) against a
    model's raw ``ids``: returns the model row per sample, len(ids) where the
    entity has no model. Both sides are compared as python strings — casting
    to the vocab's fixed-width unicode dtype would silently truncate longer
    model ids into false matches."""
    ids_s = np.asarray([str(x) for x in np.asarray(ids).ravel()],
                       dtype=object)
    vocab_s = np.asarray([str(x) for x in np.asarray(vocab).ravel()],
                         dtype=object)
    return _match(ids_s, vocab_s[np.asarray(codes)])


# ---------------------------------------------------------------------------


def rowwise_sparse_dot(mat, w_rows: np.ndarray) -> Array:
    """Per-row sparse-dense dot ``Σ_j x_ij w_ij`` for CSR ``mat`` [N, D]
    against dense per-row coefficient rows ``w_rows`` [N, D].

    Shared between :meth:`RandomEffectModel.score` and the serving
    path's tiered coefficient store (``photon_ml_tpu/serve``): both
    must produce bit-identical contributions for the same rows, so they
    run the same expression — scipy's f64 accumulation, cast to the
    array default dtype on the way into the Σ-coordinate fold."""
    prod = mat.multiply(w_rows).sum(axis=1)
    return jnp.asarray(np.asarray(prod).ravel())


@dataclasses.dataclass(frozen=True)
class FixedEffectModel:
    """GLM over one feature shard (model/FixedEffectModel.scala:29-103)."""

    model: GeneralizedLinearModel
    feature_shard_id: str

    def score(self, data: GameDataset) -> Array:
        mat = data.feature_shards[self.feature_shard_id]
        means = np.asarray(self.model.coefficients.means)
        # margin = x.w, on host via CSR for the full pass (scoring is
        # bandwidth-bound once; training uses the device batches).
        return jnp.asarray(mat @ means)

    @property
    def coefficients(self) -> Coefficients:
        return self.model.coefficients


@dataclasses.dataclass(frozen=True)
class RandomEffectModel:
    """Per-entity coefficient block in RAW shard space.

    ``coefficients[e]`` scores rows of entity ``entity_codes[e]``; rows whose
    entity is unseen (cold start) get 0 from this coordinate — matching the
    reference's cogroup semantics (RandomEffectModel.scala:137-165: no model ⇒
    no score contribution).
    """

    random_effect_type: str
    feature_shard_id: str
    entity_codes: np.ndarray  # [E] codes into the dataset vocab
    coefficients: Array  # [E, D_raw] (dense; raw space)
    # Raw entity id per block row (strings/ints). Set on models loaded from
    # disk so they can score datasets whose dictionary encoding differs from
    # the one they were trained against (the reference keys models by raw
    # entityId, model/RandomEffectModel.scala:33).
    entity_ids: Optional[np.ndarray] = None

    def _lookup(self, codes: np.ndarray, data: "GameDataset") -> np.ndarray:
        """Map dataset entity codes → local row in the coefficient block
        (or E, a zero discard row) — vectorized binary search."""
        if self.entity_ids is not None:
            # Standalone model: match by raw id through the dataset vocab.
            vocab = data.id_vocabs[self.random_effect_type]
            return _codes_via_ids(self.entity_ids, vocab, codes)
        return _match(self.entity_codes, codes)

    def score(self, data: GameDataset) -> Array:
        if self.coefficients.shape[0] == 0:
            # Empty coordinate (e.g. the checked-in GameIntegTest/gameModel
            # random effects): every row is cold-start ⇒ zero contribution.
            # Also avoids a (N,0)-vs-(N,D) scipy shape error when the block
            # width doesn't match the dataset shard.
            return jnp.zeros(data.num_samples)
        codes = data.id_columns[self.random_effect_type]
        local = self._lookup(codes, data)  # [N] in [0, E]
        mat = data.feature_shards[self.feature_shard_id]
        coefs = np.vstack([np.asarray(self.coefficients),
                           np.zeros((1, self.coefficients.shape[1]),
                                    dtype=np.asarray(self.coefficients).dtype)])
        w_rows = coefs[local]  # [N, D]
        return rowwise_sparse_dot(mat, w_rows)


@dataclasses.dataclass(frozen=True)
class RandomEffectModelInProjectedSpace:
    """Coefficients in per-entity reduced space + the projector to raw space.

    Reference: model/RandomEffectModelInProjectedSpace.scala — models stay
    projected for training; conversion to raw space happens for scoring/
    publishing (toRandomEffectModel analog: :meth:`to_raw`).
    """

    random_effect_type: str
    feature_shard_id: str
    entity_codes: np.ndarray
    coefficients_projected: Array  # [E, D_red]
    projectors: Optional[IndexMapProjectors] = None
    random_projector: Optional[RandomProjector] = None

    def to_raw(self) -> RandomEffectModel:
        if self.projectors is not None:
            dense = self.projectors.scatter_coefficients(
                np.asarray(self.coefficients_projected)).dense()
        elif self.random_projector is not None:
            dense = self.random_projector.project_back(
                np.asarray(self.coefficients_projected))
        else:
            dense = np.asarray(self.coefficients_projected)
        return RandomEffectModel(
            random_effect_type=self.random_effect_type,
            feature_shard_id=self.feature_shard_id,
            entity_codes=self.entity_codes,
            coefficients=jnp.asarray(dense),
        )

    def score(self, data: GameDataset) -> Array:
        return self.to_raw().score(data)


@dataclasses.dataclass(frozen=True)
class MatrixFactorizationModel:
    """Latent row/col factors; score = rowFactor . colFactor.

    Reference: model/MatrixFactorizationModel.scala:50,141 joins row and col
    factor RDDs by the datum's two entity ids; here both factor tables are
    dense blocks indexed by dictionary codes (unseen ids score 0).
    """

    row_effect_type: str
    col_effect_type: str
    row_factors: Array  # [R, K]
    col_factors: Array  # [C, K]
    # Raw ids per factor row (set on models loaded from disk; None means the
    # factors are aligned to the scoring dataset's dictionary codes).
    row_ids: Optional[np.ndarray] = None
    col_ids: Optional[np.ndarray] = None

    @property
    def num_latent_factors(self) -> int:
        return int(self.row_factors.shape[1])

    def score(self, data: GameDataset) -> Array:
        r_codes = np.asarray(data.id_columns[self.row_effect_type])
        c_codes = np.asarray(data.id_columns[self.col_effect_type])
        if self.row_ids is not None:
            r_codes = _codes_via_ids(self.row_ids,
                                     data.id_vocabs[self.row_effect_type],
                                     r_codes)
        if self.col_ids is not None:
            c_codes = _codes_via_ids(self.col_ids,
                                     data.id_vocabs[self.col_effect_type],
                                     c_codes)
        rf = np.vstack([np.asarray(self.row_factors),
                        np.zeros((1, self.num_latent_factors), np.float32)])
        cf = np.vstack([np.asarray(self.col_factors),
                        np.zeros((1, self.num_latent_factors), np.float32)])
        r = np.where(r_codes < len(self.row_factors), r_codes,
                     len(self.row_factors))
        c = np.where(c_codes < len(self.col_factors), c_codes,
                     len(self.col_factors))
        return jnp.asarray(np.sum(rf[r] * cf[c], axis=-1))


@dataclasses.dataclass(frozen=True)
class FactoredRandomEffectModel:
    """Per-entity models in latent space + the shared projection matrix.

    Reference: model/FactoredRandomEffectModel.scala — random effect solved in
    a learned latent space, with the projection matrix itself trained by the
    factored coordinate (algorithm/FactoredRandomEffectCoordinate.scala).
    """

    random_effect_type: str
    feature_shard_id: str
    entity_codes: np.ndarray
    coefficients_latent: Array  # [E, K]
    projection: Array  # [K, D_raw] latent → raw

    def to_raw(self) -> RandomEffectModel:
        dense = np.asarray(self.coefficients_latent) @ np.asarray(self.projection)
        return RandomEffectModel(
            random_effect_type=self.random_effect_type,
            feature_shard_id=self.feature_shard_id,
            entity_codes=self.entity_codes,
            coefficients=jnp.asarray(dense),
        )

    def score(self, data: GameDataset) -> Array:
        return self.to_raw().score(data)


CoordinateModel = Union[
    FixedEffectModel,
    RandomEffectModel,
    RandomEffectModelInProjectedSpace,
    FactoredRandomEffectModel,
    MatrixFactorizationModel,
]


@dataclasses.dataclass
class GameModel:
    """coordinateId → model; total score = Σ coordinate scores
    (model/GAMEModel.scala:29-114)."""

    models: dict[str, CoordinateModel]

    def score(self, data: GameDataset) -> Array:
        total = jnp.zeros(data.num_samples)
        for m in self.models.values():
            total = total + m.score(data)
        return total

    def get(self, coordinate_id: str) -> Optional[CoordinateModel]:
        return self.models.get(coordinate_id)

    def updated(self, coordinate_id: str, model: CoordinateModel
                ) -> "GameModel":
        out = dict(self.models)
        out[coordinate_id] = model
        return GameModel(out)

    @property
    def coordinate_ids(self) -> list[str]:
        return list(self.models)
