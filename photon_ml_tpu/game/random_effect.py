"""Random-effect solver: vmapped local optimizers over entity blocks.

TPU-native replacement for the reference's per-entity solve
(reference: photon-ml/src/main/scala/com/linkedin/photon/ml/algorithm/
RandomEffectCoordinate.scala:104-113 — a 3-way join of activeData ⋈ problems ⋈
models followed by ``mapValues(localProblem.run)``, i.e. one Breeze L-BFGS per
entity running data-local on a Spark executor).

Here every entity's subproblem lives in one padded tensor
``[E, N_max, D_red]`` and the *same* jitted solver kernels
(optimize/lbfgs.py, owlqn.py, tron.py) are ``vmap``ped over the entity
axis — XLA batches the two-loop recursion / line search / trust-region CG
across entities, so thousands of tiny solves become large MXU matmuls. Sharding the entity axis over the mesh
(``pjit``) reproduces Spark's embarrassing parallelism with zero communication
in the hot loop (SURVEY §2.2, §5.8).

Heterogeneous convergence (SURVEY §7 hard part 2) is handled by the batched
``lax.while_loop``: lanes that converged keep their state via the per-lane
convergence predicate in ``should_continue`` — the loop runs until every lane
is done, converged lanes' updates are masked out by the line-search failure
path costing only wasted FLOPs, never wrong results.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.batch import DenseBatch
from photon_ml_tpu.game.dataset import RandomEffectDataset
from photon_ml_tpu.ops.aggregators import GLMObjective
from photon_ml_tpu.ops.losses import get_loss
from photon_ml_tpu.optimize.common import solver_x0
from photon_ml_tpu.optimize.config import (
    GLMOptimizationConfiguration,
    OptimizerType,
    TASK_LOSS_NAME,
    TaskType,
)
from photon_ml_tpu.optimize.lbfgs import minimize_lbfgs
from photon_ml_tpu.optimize.owlqn import minimize_owlqn
from photon_ml_tpu.optimize.tron import minimize_tron

Array = jnp.ndarray

# Per-entity convergence codes (RandomEffectOptimizationTracker.
# countsByConvergence analog; names match ConvergenceReason values).
CONV_MAX_ITERATIONS = 0
CONV_FUNCTION_VALUES = 1
CONV_GRADIENT = 2
CONV_NOT_PROGRESSED = 3
CONVERGENCE_CODE_NAMES = {
    CONV_MAX_ITERATIONS: "MaxIterations",
    CONV_FUNCTION_VALUES: "FunctionValuesConverged",
    CONV_GRADIENT: "GradientConverged",
    CONV_NOT_PROGRESSED: "ObjectiveNotImproving",
}


def _vg(w, payload):
    obj, batch = payload
    return obj.calculate(w, batch)


def _hvp(w, v, payload):
    obj, batch = payload
    return obj.hessian_vector(w, v, batch)


@partial(jax.jit, static_argnames=("solver", "max_iter", "tolerance"))
def _fit_blocks(
    X: Array,
    labels: Array,
    offsets: Array,
    weights: Array,
    initial: Array,
    obj: GLMObjective,
    l1: Array,
    solver: str,
    max_iter: int,
    tolerance: float,
):
    """vmapped solve over entity blocks; returns (coefs [E,D], iters [E],
    final loss values [E], convergence codes [E] int8 — see
    CONVERGENCE_CODE_NAMES). ``solver`` is "lbfgs"/"owlqn"/"tron"."""

    def solve_one(Xe, ye, oe, we, x0):
        batch = DenseBatch(X=Xe, labels=ye, offsets=oe, weights=we)
        if solver == "owlqn":
            x, hist, progressed = minimize_owlqn(
                _vg, x0, (obj, batch), l1=l1,
                max_iter=max_iter, tolerance=tolerance)
        elif solver == "tron":
            x, hist, progressed = minimize_tron(
                _vg, _hvp, x0, (obj, batch),
                max_iter=max_iter, tolerance=tolerance)
        else:
            x, hist, progressed = minimize_lbfgs(
                _vg, x0, (obj, batch),
                max_iter=max_iter, tolerance=tolerance)
        k = hist.num_iterations
        final_value = hist.values[k]
        # Per-lane convergence classification mirroring the HOST ordering
        # of Optimizer.getConvergenceReason (Optimizer.scala:156-170 port,
        # optimize/common._convergence_reason): max-iterations, then
        # not-progressed, then function values, then gradient; the
        # total-function fallback is FunctionValuesConverged like the host.
        # A lane that stalls with an unchanged objective therefore reports
        # ObjectiveNotImproving, keeping tracker counts aligned with the
        # reference's countsByConvergence.
        fv = (k >= 1) & (
            jnp.abs(final_value - hist.values[jnp.maximum(k - 1, 0)])
            <= tolerance * jnp.abs(hist.values[0]))
        gv = hist.grad_norms[k] <= tolerance * hist.grad_norms[0]
        code = jnp.where(
            k >= max_iter, CONV_MAX_ITERATIONS,
            jnp.where(~progressed, CONV_NOT_PROGRESSED,
                      jnp.where(fv, CONV_FUNCTION_VALUES,
                                jnp.where(gv, CONV_GRADIENT,
                                          CONV_FUNCTION_VALUES))))
        return x, k, final_value, code.astype(jnp.int8)

    return jax.vmap(solve_one)(X, labels, offsets, weights, initial)


@dataclasses.dataclass(frozen=True)
class RandomEffectOptimizationProblem:
    """Per-entity GLM problems for one random-effect coordinate.

    Reference: optimization/game/RandomEffectOptimizationProblem.scala:41-130
    builds an RDD of SingleNodeOptimizationProblems co-partitioned with the
    data; here one config applies to all entities and the per-entity state is
    just the coefficient block.
    """

    config: GLMOptimizationConfiguration
    task: TaskType

    def objective(self) -> GLMObjective:
        cfg = self.config
        l2 = cfg.regularization_context.l2_weight(cfg.regularization_weight)
        return GLMObjective(
            loss=get_loss(TASK_LOSS_NAME[self.task]),
            l2_lambda=l2,
            has_hessian=self.task != TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
        )

    def run(
        self,
        dataset: RandomEffectDataset,
        offsets: Array,
        initial: Optional[Array] = None,
    ) -> tuple[Array, Array, Array, Array]:
        """Fit all entities; returns (coefficients [E, D_red], iterations [E],
        final losses [E], convergence codes [E] — CONVERGENCE_CODE_NAMES).

        ``offsets`` is the entity-major offset block (base offsets + other
        coordinates' scores). All three solvers run batched under ``vmap``:
        TRON's trust-region/CG loop nest is the same ``lax.while_loop``
        program per entity lane (OptimizerFactory.scala:69-77 allows TRON
        for single-node problems; TRON.scala:84-341). As in the reference,
        TRON requires a twice-differentiable loss, so smoothed-hinge + TRON
        is rejected (OptimizerFactory.scala:78-79).
        """
        cfg = self.config
        l1 = cfg.regularization_context.l1_weight(cfg.regularization_weight)
        if cfg.optimizer_type == OptimizerType.TRON:
            if self.task == TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM:
                raise ValueError(
                    "TRON requires a twice-differentiable loss; the smoothed "
                    "hinge (linear SVM) task has no usable Hessian "
                    "(OptimizerFactory.scala:78-79). Use LBFGS instead.")
            solver = "tron"
        elif l1 > 0.0:
            solver = "owlqn"
        else:
            solver = "lbfgs"

        if dataset.buckets is not None:
            return self._run_bucketed(dataset, offsets, initial, solver, l1)

        e, _, d = dataset.X.shape
        acc = jnp.promote_types(dataset.X.dtype, jnp.float32)
        x0 = solver_x0(acc, (e, d), initial)
        # solver state policy: blocks are f32, solver state >= f32; a
        # wider offset vector (e.g. f64 scores) must not poison the
        # jitted solver's carry dtypes
        offsets = jnp.asarray(offsets, acc)
        return _fit_blocks(
            dataset.X, dataset.labels, offsets, dataset.weights, x0,
            self.objective(), jnp.full(d, l1, x0.dtype),
            solver, cfg.max_iterations, float(cfg.tolerance))

    def _run_bucketed(self, dataset, offsets, initial, solver: str,
                      l1: float):
        """Per-bucket vmapped solves assembled into one compact global
        block ``[num_entities, reduced_dim]`` (entity order is bucket-major;
        pad lanes never leave the bucket).

        Compile-cost note: each distinct bucket shape (E_b, N_b, D_b)
        compiles its own ``_fit_blocks`` trace, so the first sweep pays one
        compile per bucket. The DP bucket plan is deterministic for a given
        dataset, so shapes are stable across sweeps/processes and the
        in-process jit cache plus the persistent XLA compile cache
        (utils/compile_cache.py) absorb every later sweep; keep bucket
        counts small (3-4) so the one-time cost stays bounded."""
        cfg = self.config
        e_tot, d_red = dataset.num_entities, dataset.reduced_dim
        acc = jnp.promote_types(dataset.buckets[0].X.dtype, jnp.float32)
        obj = self.objective()
        coefs = jnp.zeros((e_tot, d_red), acc)
        iters = jnp.zeros(e_tot, jnp.int32)
        values = jnp.zeros(e_tot, acc)
        codes = jnp.zeros(e_tot, jnp.int8)
        for bucket, off_b in zip(dataset.buckets, offsets):
            e_b, _, d_b = bucket.X.shape
            nr, start = bucket.num_real, bucket.entity_start
            # solver state policy: blocks are f32, solver state >= f32
            # (optimize/common.solver_x0); offsets join at the same dtype
            off_b = jnp.asarray(off_b, acc)
            x0_b = jnp.zeros((e_b, d_b), acc)
            if initial is not None:
                x0_b = x0_b.at[:nr].set(
                    jnp.asarray(initial, acc)[start:start + nr, :d_b])
            c_b, it_b, v_b, k_b = _fit_blocks(
                bucket.X, bucket.labels, off_b, bucket.weights, x0_b,
                obj, jnp.full(d_b, l1, acc),
                solver, cfg.max_iterations, float(cfg.tolerance))
            coefs = coefs.at[start:start + nr, :d_b].set(c_b[:nr])
            iters = iters.at[start:start + nr].set(it_b[:nr])
            values = values.at[start:start + nr].set(v_b[:nr])
            codes = codes.at[start:start + nr].set(k_b[:nr])
        return coefs, iters, values, codes

    def regularization_value(self, coefs: Array) -> float:
        """Σ over entities of the per-entity penalty
        (RandomEffectOptimizationProblem.getRegularizationTermValue)."""
        cfg = self.config
        l1 = cfg.regularization_context.l1_weight(cfg.regularization_weight)
        l2 = cfg.regularization_context.l2_weight(cfg.regularization_weight)
        val = 0.0
        if l1 > 0:
            val += l1 * float(jnp.sum(jnp.abs(coefs)))
        if l2 > 0:
            val += 0.5 * l2 * float(jnp.sum(coefs * coefs))
        return val


@partial(jax.jit, static_argnames=("num_samples",))
def score_active(dataset_X: Array, coefs: Array, row_ids: Array,
                 weights: Array, num_samples: int) -> Array:
    """Scatter per-entity active-row margins back to the sample axis.

    margins[e, n] = X[e, n] . coefs[e]; padded rows (weight 0) scatter to the
    discard slot ``num_samples``. This is the entity→sample resharding half of
    the score exchange (RandomEffectCoordinate.score :137-151 analog).
    """
    margins = jnp.einsum("end,ed->en", dataset_X, coefs,
                         preferred_element_type=jnp.float32)
    margins = jnp.where(weights > 0, margins, 0.0)
    flat = jax.ops.segment_sum(
        margins.reshape(-1), row_ids.reshape(-1).astype(jnp.int32),
        num_segments=num_samples + 1)
    return flat[:num_samples]


@partial(jax.jit, static_argnames=("num_samples",))
def score_passive(passive_X: Array, passive_entity: Array, coefs: Array,
                  passive_row_ids: Array, num_samples: int) -> Array:
    """Score passive rows with their entity's model (gather + rowwise dot).

    Reference: RandomEffectCoordinate.scala:153-199 collects the relevant
    models into a broadcast map; here it is a gather of coefficient rows.
    """
    w = coefs[passive_entity]  # [P, D_red]
    margins = jnp.sum(passive_X * w, axis=-1)
    return jax.ops.segment_sum(
        margins, passive_row_ids.astype(jnp.int32),
        num_segments=num_samples + 1)[:num_samples]


def score_random_effect(dataset: RandomEffectDataset, coefs: Array) -> Array:
    """Full sample-axis score vector (active + passive) for this coordinate.

    ``coefs`` is the compact global block ``[num_entities, reduced_dim]``;
    bucketed datasets score per bucket (row sets are disjoint, so the
    per-bucket scatters sum without overlap)."""
    if dataset.buckets is not None:
        s = jnp.zeros(dataset.num_samples, jnp.float32)
        for bucket in dataset.buckets:
            e_b, _, d_b = bucket.X.shape
            nr, start = bucket.num_real, bucket.entity_start
            c_b = jnp.zeros((e_b, d_b), coefs.dtype)
            c_b = c_b.at[:nr].set(coefs[start:start + nr, :d_b])
            s = s + score_active(bucket.X, c_b, bucket.row_ids,
                                 bucket.weights, dataset.num_samples)
    else:
        s = score_active(dataset.X, coefs, dataset.row_ids, dataset.weights,
                         dataset.num_samples)
    if dataset.num_passive:
        s = s + score_passive(dataset.passive_X, dataset.passive_entity,
                              coefs, dataset.passive_row_ids,
                              dataset.num_samples)
    return s
