"""Random-effect solver: vmapped local optimizers over entity blocks.

TPU-native replacement for the reference's per-entity solve
(reference: photon-ml/src/main/scala/com/linkedin/photon/ml/algorithm/
RandomEffectCoordinate.scala:104-113 — a 3-way join of activeData ⋈ problems ⋈
models followed by ``mapValues(localProblem.run)``, i.e. one Breeze L-BFGS per
entity running data-local on a Spark executor).

Here every entity's subproblem lives in one padded tensor
``[E, N_max, D_red]`` and the *same* jitted solver kernels
(optimize/lbfgs.py, owlqn.py, tron.py) are ``vmap``ped over the entity
axis — XLA batches the two-loop recursion / line search / trust-region CG
across entities, so thousands of tiny solves become large MXU matmuls. Sharding the entity axis over the mesh
(``pjit``) reproduces Spark's embarrassing parallelism with zero communication
in the hot loop (SURVEY §2.2, §5.8).

Heterogeneous convergence (SURVEY §7 hard part 2) is handled by the batched
``lax.while_loop``: lanes that converged keep their state via the per-lane
convergence predicate in ``should_continue`` — the loop runs until every lane
is done, converged lanes' updates are masked out by the line-search failure
path costing only wasted FLOPs, never wrong results.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from functools import lru_cache, partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from photon_ml_tpu.data.batch import DenseBatch
from photon_ml_tpu.game.dataset import RandomEffectDataset
from photon_ml_tpu.obs import compile as obs_compile
from photon_ml_tpu.obs import trace
from photon_ml_tpu.obs.metrics import REGISTRY
from photon_ml_tpu.ops.aggregators import GLMObjective
from photon_ml_tpu.ops.losses import get_loss
from photon_ml_tpu.optimize.common import (
    LaneCompactionState,
    padded_lane_count,
    solver_x0,
)
from photon_ml_tpu.optimize.config import (
    GLMOptimizationConfiguration,
    OptimizerType,
    TASK_LOSS_NAME,
    TaskType,
)
from photon_ml_tpu.optimize.lbfgs import minimize_lbfgs
from photon_ml_tpu.optimize.owlqn import minimize_owlqn
from photon_ml_tpu.optimize.tron import minimize_tron
from photon_ml_tpu.parallel.mesh import ENTITY_AXIS, get_default_mesh
from photon_ml_tpu.utils.faults import fault_point

Array = jnp.ndarray

logger = logging.getLogger(__name__)

# Per-entity convergence codes (RandomEffectOptimizationTracker.
# countsByConvergence analog; names match ConvergenceReason values).
CONV_MAX_ITERATIONS = 0
CONV_FUNCTION_VALUES = 1
CONV_GRADIENT = 2
CONV_NOT_PROGRESSED = 3
CONVERGENCE_CODE_NAMES = {
    CONV_MAX_ITERATIONS: "MaxIterations",
    CONV_FUNCTION_VALUES: "FunctionValuesConverged",
    CONV_GRADIENT: "GradientConverged",
    CONV_NOT_PROGRESSED: "ObjectiveNotImproving",
}


def _vg(w, payload):
    obj, batch = payload
    return obj.calculate(w, batch)


def _hvp(w, v, payload):
    obj, batch = payload
    return obj.hessian_vector(w, v, batch)


# Per-solve telemetry for bench.py's dispatch-vs-compute attribution:
# ``solve_secs`` is time blocked on chunk dispatch + the one unconverged-mask
# fetch per chunk, ``compact_secs`` is active-lane gather/re-pack time,
# ``lane_counts`` the still-active lane count entering each compacted chunk.
# The ``shard_*`` keys account the mesh-sharded path: real vs power-of-two
# padded lanes per sharded dispatch (their ratio is bench.py's
# ``re_shard_padding_frac``) and a rolling window of per-shard active-lane
# counts (the load-balance signal).
SOLVE_STATS = {"dispatches": 0, "chunks": 0, "solve_secs": 0.0,
               "compact_secs": 0.0, "lane_counts": [],
               "shard_real_lanes": 0, "shard_padded_lanes": 0,
               "shard_lane_counts": []}


def reset_solve_stats() -> None:
    SOLVE_STATS.update({"dispatches": 0, "chunks": 0, "solve_secs": 0.0,
                        "compact_secs": 0.0, "lane_counts": [],
                        "shard_real_lanes": 0, "shard_padded_lanes": 0,
                        "shard_lane_counts": []})


#: ``lane_compaction_chunk`` sentinel (driver flag value ``auto``): the
#: chunk size is chosen — and re-tuned between solves — by
#: :class:`ChunkAutoTuner` from the observed per-chunk active-lane decay.
AUTO_COMPACTION_CHUNK = -1

#: ``--re-entity-shards`` sentinel (flag value ``auto``): put EVERY local
#: device on the mesh entity axis (the driver resolves this to the device
#: count before building the mesh; kept an int so run-manifest flags stay
#: scalar).
AUTO_ENTITY_SHARDS = -1


def _pow2_at_most(x: int) -> int:
    return 1 << max(int(x).bit_length() - 1, 0)


class ChunkAutoTuner:
    """Feedback controller for the lane-compaction chunk size.

    The data source is the per-chunk active-lane sequence each compacted
    solve produces (the same counts the ``re_chunk_active_lanes``
    histogram aggregates — the ROADMAP item's promised signal): the
    fraction of lanes still active after a solve's FIRST chunk says
    whether the chunk budget was matched to the convergence profile.

    - survival > 0.75: the chunk is shedding too few lanes to pay for
      its per-chunk host fetch + re-pack → double it;
    - survival < 0.25: most lanes idled through the tail of the chunk
      before compaction could shed them → halve it;
    - in between: keep it.

    One tuner per coordinate problem (created lazily by
    :class:`RandomEffectOptimizationProblem` — the problem instance
    lives across sweeps, so feedback accumulates, while two coordinates
    with IDENTICAL configs but opposite convergence profiles still tune
    independently instead of ping-ponging one shared entry). State is
    keyed per (solver, max_iterations) within the instance and clamped
    to [4, max_iterations); a probe chunk of ``~max_iterations / 4``
    (power of two, for compile-shape reuse) seeds each key. Chunk sizes
    stay powers of two so re-tuning between sweeps revisits previously
    compiled shapes instead of growing the jit cache without bound.
    """

    MIN_CHUNK = 4

    def __init__(self):
        self._chunks: dict = {}

    def chunk_for(self, solver: str, max_iterations: int) -> int:
        if max_iterations <= self.MIN_CHUNK:
            return 0  # nothing to chunk: single dispatch
        key = (solver, max_iterations)
        c = self._chunks.get(key)
        if c is None:
            c = max(self.MIN_CHUNK, _pow2_at_most(max_iterations // 4))
            self._chunks[key] = c
        return c

    def update(self, solver: str, max_iterations: int,
               lane_counts: list) -> None:
        """Feed one solve's per-chunk active-lane sequence back."""
        if max_iterations <= self.MIN_CHUNK or not lane_counts:
            return
        key = (solver, max_iterations)
        c = self._chunks.get(key)
        if c is None or lane_counts[0] <= 0:
            return
        if len(lane_counts) == 1:
            # everything converged inside one chunk: the budget was
            # bigger than the straggler tail needed
            survival = 0.0
        else:
            survival = lane_counts[1] / lane_counts[0]
        if survival > 0.75:
            c *= 2
        elif survival < 0.25:
            c //= 2
        self._chunks[key] = min(max(c, self.MIN_CHUNK),
                                _pow2_at_most(max_iterations - 1))


def _fit_blocks_impl(
    X: Array,
    labels: Array,
    offsets: Array,
    weights: Array,
    initial: Array,
    obj: GLMObjective,
    l1: Array,
    solver: str,
    max_iter: int,
    tolerance: float,
    boundary_convergence: bool = False,
    resume=None,
    return_carry: bool = False,
):
    """vmapped solve over entity blocks; returns (coefs [E,D], iters [E],
    final loss values [E], convergence codes [E] int8 — see
    CONVERGENCE_CODE_NAMES), plus a per-lane solver carry when
    ``return_carry``. ``solver`` is "lbfgs"/"owlqn"/"tron".

    ``boundary_convergence`` is set by the lane-compaction driver on
    NON-final chunks: a lane that satisfies a convergence criterion on
    exactly its last budgeted iteration then reports that criterion
    instead of MaxIterations, so it leaves the active set with its true
    reason rather than being re-dispatched from its optimum. The
    default preserves the host-ordering classification
    (Optimizer.scala:156-170): max-iterations wins.

    ``resume`` is the previous chunk's per-lane carry (lane-compacted by
    the caller): the solvers continue their loop state verbatim and every
    convergence check stays anchored to the ORIGINAL dispatch's f₀/‖g₀‖,
    so a chunked solve is bit-identical to the single dispatch."""

    def solve_one(Xe, ye, oe, we, x0, res):
        batch = DenseBatch(X=Xe, labels=ye, offsets=oe, weights=we)
        if solver == "owlqn":
            out = minimize_owlqn(
                _vg, x0, (obj, batch), l1=l1,
                max_iter=max_iter, tolerance=tolerance,
                resume=res, return_carry=return_carry)
        elif solver == "tron":
            out = minimize_tron(
                _vg, _hvp, x0, (obj, batch),
                max_iter=max_iter, tolerance=tolerance,
                resume=res, return_carry=return_carry)
        else:
            out = minimize_lbfgs(
                _vg, x0, (obj, batch),
                max_iter=max_iter, tolerance=tolerance,
                resume=res, return_carry=return_carry)
        x, hist, progressed = out[:3]
        carry = out[3] if return_carry else None
        k = hist.num_iterations
        final_value = hist.values[k]
        # Per-lane convergence classification mirroring the HOST ordering
        # of Optimizer.getConvergenceReason (Optimizer.scala:156-170 port,
        # optimize/common._convergence_reason): max-iterations, then
        # not-progressed, then function values, then gradient; the
        # total-function fallback is FunctionValuesConverged like the host.
        # A lane that stalls with an unchanged objective therefore reports
        # ObjectiveNotImproving, keeping tracker counts aligned with the
        # reference's countsByConvergence. On a resumed chunk the
        # thresholds anchor to the ORIGINAL dispatch's f₀/‖g₀‖ and a
        # k==0 exit compares against the pre-boundary value — the checks
        # the uninterrupted loop would have run.
        if res is None:
            f0_anchor = hist.values[0]
            g0n_anchor = hist.grad_norms[0]
            prev_value = hist.values[jnp.maximum(k - 1, 0)]
            fv_gate = k >= 1
        else:
            f0_anchor = res.f0
            g0n_anchor = res.g0n
            prev_value = jnp.where(k >= 1,
                                   hist.values[jnp.maximum(k - 1, 0)],
                                   res.prev_f)
            fv_gate = True
        fv = fv_gate & (jnp.abs(final_value - prev_value)
                        <= tolerance * jnp.abs(f0_anchor))
        gv = hist.grad_norms[k] <= tolerance * g0n_anchor
        converged = jnp.where(~progressed, CONV_NOT_PROGRESSED,
                              jnp.where(fv, CONV_FUNCTION_VALUES,
                                        jnp.where(gv, CONV_GRADIENT,
                                                  CONV_FUNCTION_VALUES)))
        if boundary_convergence:
            # chunk boundary: an exhausted budget only means MaxIterations
            # when no criterion fired on the final iteration
            exhausted = jnp.where(
                ~progressed, CONV_NOT_PROGRESSED,
                jnp.where(fv, CONV_FUNCTION_VALUES,
                          jnp.where(gv, CONV_GRADIENT,
                                    CONV_MAX_ITERATIONS)))
        else:
            exhausted = CONV_MAX_ITERATIONS
        code = jnp.where(k >= max_iter, exhausted, converged)
        if return_carry:
            return x, k, final_value, code.astype(jnp.int8), carry
        return x, k, final_value, code.astype(jnp.int8)

    if resume is None:
        return jax.vmap(
            lambda Xe, ye, oe, we, x0: solve_one(Xe, ye, oe, we, x0, None)
        )(X, labels, offsets, weights, initial)
    return jax.vmap(solve_one)(X, labels, offsets, weights, initial, resume)


_STATIC = ("solver", "max_iter", "tolerance", "boundary_convergence",
           "return_carry")
_fit_blocks = partial(jax.jit, static_argnames=_STATIC)(_fit_blocks_impl)
# Donating variants, only engaged off-CPU (the CPU runtime can't alias and
# would warn per call) and only for callers that own the buffers:
# - offsets (arg 2) is rebuilt per update from the CD score vector, so the
#   coordinate-update path may always hand its buffer to XLA as scratch;
# - initial/x0 (arg 4) is donated ONLY by the compacted re-dispatch path,
#   whose x0 is a gather this module just created. The plain path's x0 can
#   BE the caller's live array (solver_x0 returns a matching-dtype warm
#   start unchanged — i.e. coordinate descent's states[cid] last-good
#   state, which retries/quarantine/checkpointing must still read), so
#   donating it there would delete state out from under the CD loop.
_fit_blocks_donate_offsets = partial(
    jax.jit, static_argnames=_STATIC, donate_argnums=(2,),
)(_fit_blocks_impl)
_fit_blocks_donate_offsets_x0 = partial(
    jax.jit, static_argnames=_STATIC, donate_argnums=(2, 4),
)(_fit_blocks_impl)


# (variant, shapes, dtypes, statics) signatures already dispatched: a key
# not seen before is about to pay an XLA trace+compile (the in-process jit
# cache misses exactly there), so the ``retraces{site="re.dispatch"}``
# counter tracks bucketed-dispatch compile pressure — host-side bookkeeping
# only, no device work.
_SEEN_DISPATCH_KEYS: set = set()


def _dispatch_fit(X, labels, offsets, weights, initial, obj, l1, solver,
                  max_iter, tolerance, donate: bool,
                  donate_x0: bool = False,
                  boundary_convergence: bool = False,
                  resume=None, return_carry: bool = False):
    SOLVE_STATS["dispatches"] += 1
    fn = _fit_blocks
    if donate and jax.default_backend() != "cpu":
        # the resumed-chunk path passes the gathered carry's x as BOTH
        # the x0 arg and a resume leaf — never donate x0 there (aliasing
        # a donated buffer with a live arg is a runtime error)
        fn = (_fit_blocks_donate_offsets_x0
              if donate_x0 and resume is None
              else _fit_blocks_donate_offsets)
    key = (id(fn), tuple(X.shape), str(X.dtype), tuple(initial.shape),
           str(initial.dtype), solver, max_iter, float(tolerance),
           boundary_convergence, resume is not None, return_carry)
    if key not in _SEEN_DISPATCH_KEYS:
        _SEEN_DISPATCH_KEYS.add(key)
        REGISTRY.counter("retraces").inc(site="re.dispatch")
    # statics by position in _fit_blocks_impl's signature (the _STATIC
    # names): solver=7, max_iter=8, tolerance=9, boundary_convergence=10,
    # return_carry=12 — obs.compile strips them for the AOT fastpath
    return obs_compile.call(
        "re.fit_blocks", fn,
        (X, labels, offsets, weights, initial, obj, l1, solver,
         max_iter, tolerance, boundary_convergence, resume, return_carry),
        static_argnums=(7, 8, 9, 10, 12),
        arg_names=("X", "labels", "offsets", "weights", "initial", "obj",
                   "l1", "solver", "max_iter", "tolerance",
                   "boundary_convergence", "resume", "return_carry"))


def _fit_blocks_compacted(X, labels, offsets, weights, x0, obj, l1,
                          solver, max_iter, tolerance, chunk: int,
                          donate: bool,
                          lane_seq: Optional[list] = None):
    """Chunked solve with active-lane compaction (Snap ML-style: don't pay
    straggler cost for converged subproblems).

    Runs the batched solver ``chunk`` iterations at a time; after each
    chunk the lanes that converged keep their results and only the
    still-active lanes are gathered into a dense (power-of-two padded)
    block and re-dispatched. A bucket where 90% of entities converge in 5
    iterations then costs ~10% of the lanes for the straggler tail instead
    of running every lane to the slowest lane's count. Each chunk costs
    one small device→host fetch (the unconverged mask).

    Restarts are EXACT: each non-final chunk also returns the solvers'
    per-lane carry (iterate, curvature history / trust region, previous
    objective, ORIGINAL f₀/‖g₀‖ anchors — LBFGSResume/TRONResume), which
    is gathered down to the still-active lanes and resumed, so the
    chunked solve runs bit-identically to the single dispatch instead of
    re-anchoring its relative tolerances at every boundary."""
    state = LaneCompactionState.initial(x0, x0.dtype)
    idx: Optional[np.ndarray] = None
    carry = None  # previous chunk's per-lane solver carry (device)
    cur = (X, labels, offsets, weights, x0)
    spent = 0
    chunk_index = 0
    while True:
        budget = min(chunk, max_iter - spent)
        final_chunk = spent + budget >= max_iter
        # span per chunk, labeled with the REAL active-lane count entering
        # it (not the power-of-two padded dispatch width): the shrinking
        # sequence IS the iteration histogram the ROADMAP chunk-size
        # auto-tuner needs, and the ``re_chunk_active_lanes`` histogram
        # aggregates it across the run
        active_lanes = int(X.shape[0]) if idx is None else int(len(idx))
        if lane_seq is not None:  # the auto-tuner's feedback signal
            lane_seq.append(active_lanes)
        t0 = time.perf_counter()
        with trace.span("re.compact_chunk", chunk=chunk_index,
                        active_lanes=active_lanes, budget=budget):
            # chunk 1 runs the caller's buffers (which later compactions
            # re-gather from: never donate them); compacted chunks run
            # gathered copies this loop owns outright — but x0 doubles as
            # the carry's live iterate on resumed chunks, so only the
            # offsets buffer is donated there. Non-final chunks classify
            # boundary convergence so a lane converging on its last
            # budgeted iteration leaves with its true reason instead of
            # a re-dispatch from its optimum.
            donate_chunk = donate and idx is not None
            out = _dispatch_fit(*cur, obj, l1, solver, budget,
                                tolerance, donate=donate_chunk,
                                donate_x0=donate_chunk,
                                boundary_convergence=not final_chunk,
                                resume=carry,
                                return_carry=not final_chunk)
            if final_chunk:
                c, it, v, k = out
                new_carry = None
            else:
                c, it, v, k, new_carry = out
            still, still_local = state.absorb(idx, c, it, v, k,
                                              CONV_MAX_ITERATIONS)
        REGISTRY.histogram("re_chunk_active_lanes").observe(active_lanes)
        SOLVE_STATS["solve_secs"] += time.perf_counter() - t0
        SOLVE_STATS["chunks"] += 1
        chunk_index += 1
        spent += budget
        if spent >= max_iter or len(still) == 0:
            break
        t0 = time.perf_counter()
        idx = still
        pad = padded_lane_count(len(still))
        idx_padded = np.concatenate(
            [still, np.full(pad - len(still), still[0], np.int32)])
        g = jax.device_put(idx_padded)
        # data tensors gather by GLOBAL lane id; the carry gathers by the
        # lanes' LOCAL positions within the chunk that produced it
        local_padded = np.concatenate(
            [still_local,
             np.full(pad - len(still_local), still_local[0], np.int32)])
        gl = jax.device_put(local_padded)
        carry = jax.tree_util.tree_map(
            lambda leaf: jnp.take(leaf, gl, axis=0), new_carry)
        cur = (jnp.take(X, g, axis=0), jnp.take(labels, g, axis=0),
               jnp.take(offsets, g, axis=0), jnp.take(weights, g, axis=0),
               carry.x)
        SOLVE_STATS["compact_secs"] += time.perf_counter() - t0
        # bounded telemetry: long training runs append per compaction and
        # only bench/tests ever reset, so keep a rolling window
        SOLVE_STATS["lane_counts"] = (
            SOLVE_STATS["lane_counts"][-63:] + [int(len(still))])
    return state.results()


# ---------------------------------------------------------------------------
# Mesh-sharded dispatch: the entity axis of a bucket is split over the mesh
# ENTITY_AXIS (parallel/mesh.py) via shard_map — every device runs the SAME
# vmapped solver kernel on its local lane slice, with ZERO collectives inside
# the solve loop (entity subproblems are independent; the reference's Spark
# embarrassing parallelism made explicit). Only the score exchange reduces
# across shards, with an on-device psum (see _sharded_score_fn).
# ---------------------------------------------------------------------------


@lru_cache(maxsize=64)
def _sharded_fit_fn(mesh, solver, max_iter, tolerance,
                    boundary_convergence, return_carry):
    """shard_map + jit of the block solve for a FULL (unpadded) dispatch:
    lane-leading arrays split over the entity axis, obj/l1 replicated.
    Cached per (mesh, statics) so repeat dispatches reuse the executable
    instead of re-tracing a fresh closure per call."""
    from photon_ml_tpu.parallel.distributed import _shard_map

    lane = P(ENTITY_AXIS)

    def impl(X, labels, offsets, weights, initial, obj, l1):
        return _fit_blocks_impl(X, labels, offsets, weights, initial, obj,
                                l1, solver, max_iter, tolerance,
                                boundary_convergence, None, return_carry)

    fit = _shard_map(impl, mesh,
                     in_specs=(lane, lane, lane, lane, lane, P(), P()),
                     out_specs=tuple([lane] * (5 if return_carry else 4)))
    return jax.jit(fit)


@lru_cache(maxsize=64)
def _sharded_resume_fit_fn(mesh, solver, max_iter, tolerance,
                           boundary_convergence, return_carry):
    """shard_map + jit of a RESUMED compacted dispatch. The still-active
    lane gather happens ON DEVICE inside the sharded program: each shard
    receives its ``[1, L]`` row of local data ids / carry positions,
    gathers its own lanes from its resident slice of the full block and
    the previous chunk's carry, and resumes — the host never re-packs
    data tensors, it only computes the tiny id arrays from the one
    unconverged-mask fetch per chunk."""
    from photon_ml_tpu.parallel.distributed import _shard_map

    lane = P(ENTITY_AXIS)

    def impl(X, labels, offsets, weights, idx_data, idx_carry, obj, l1,
             carry):
        idx_d = idx_data.reshape(-1)
        idx_c = idx_carry.reshape(-1)
        res = jax.tree_util.tree_map(
            lambda leaf: jnp.take(leaf, idx_c, axis=0), carry)
        return _fit_blocks_impl(
            jnp.take(X, idx_d, axis=0), jnp.take(labels, idx_d, axis=0),
            jnp.take(offsets, idx_d, axis=0),
            jnp.take(weights, idx_d, axis=0),
            res.x, obj, l1, solver, max_iter, tolerance,
            boundary_convergence, res, return_carry)

    fit = _shard_map(
        impl, mesh,
        in_specs=(lane, lane, lane, lane, lane, lane, P(), P(), lane),
        out_specs=tuple([lane] * (5 if return_carry else 4)))
    return jax.jit(fit)


def _note_shard_dispatch(kind: str, fn, X, extra=()) -> None:
    SOLVE_STATS["dispatches"] += 1
    key = (kind, id(fn), tuple(X.shape), str(X.dtype)) + tuple(extra)
    if key not in _SEEN_DISPATCH_KEYS:
        _SEEN_DISPATCH_KEYS.add(key)
        REGISTRY.counter("retraces").inc(site="re.shard_dispatch")


def _dispatch_fit_sharded(mesh, X, labels, offsets, weights, initial, obj,
                          l1, solver, max_iter, tolerance,
                          boundary_convergence: bool = False,
                          return_carry: bool = False):
    fn = _sharded_fit_fn(mesh, solver, max_iter, float(tolerance),
                         boundary_convergence, return_carry)
    _note_shard_dispatch("shard", fn, X)
    # a full dispatch has no pad lanes: real == padded
    SOLVE_STATS["shard_real_lanes"] += int(X.shape[0])
    SOLVE_STATS["shard_padded_lanes"] += int(X.shape[0])
    return obs_compile.call(
        "re.shard_fit_blocks", fn,
        (X, labels, offsets, weights, initial, obj, l1),
        arg_names=("X", "labels", "offsets", "weights", "initial", "obj",
                   "l1"))


def _dispatch_fit_sharded_resume(mesh, X, labels, offsets, weights,
                                 idx_data, idx_carry, obj, l1, carry,
                                 solver, max_iter, tolerance,
                                 boundary_convergence: bool,
                                 return_carry: bool):
    fn = _sharded_resume_fit_fn(mesh, solver, max_iter, float(tolerance),
                                boundary_convergence, return_carry)
    _note_shard_dispatch("shard_resume", fn, X,
                         extra=(tuple(idx_data.shape),))
    return obs_compile.call(
        "re.shard_fit_blocks", fn,
        (X, labels, offsets, weights, idx_data, idx_carry, obj, l1, carry),
        arg_names=("X", "labels", "offsets", "weights", "idx_data",
                   "idx_carry", "obj", "l1", "carry"))


def _fit_blocks_compacted_sharded(mesh, shards: int, X, labels, offsets,
                                  weights, x0, obj, l1, solver,
                                  max_iter, tolerance, chunk: int,
                                  lane_seq: Optional[list] = None):
    """Sharded variant of :func:`_fit_blocks_compacted`: lane compaction
    with PER-SHARD power-of-two padding. A lane's home shard never changes
    (global id // lanes_per_shard), so after each chunk the host partitions
    the still-active ids by owner, pads every shard's list to one shared
    power-of-two width L (a ragged per-shard width would be a different
    program shape per shard), and dispatches ``[K, L]`` local-id arrays —
    the data/carry gathers run on device inside the sharded program.

    Pad slots duplicate one of the shard's own carried lanes; a shard with
    NO active lanes re-resolves one of its converged lanes, which is an
    exact no-op (resuming a converged carry fails the loop predicate
    immediately and writes back the value it already holds). Results are
    folded with :meth:`LaneCompactionState.absorb_padded`, which masks pad
    slots out of the iteration scatter-add. Host cost per chunk is
    unchanged from the unsharded loop: ONE unconverged-mask fetch."""
    K = shards
    e = int(X.shape[0])
    e_shard = e // K
    state = LaneCompactionState.initial(x0, x0.dtype)
    idx: Optional[np.ndarray] = None  # flat [K*L] global ids (host)
    mask: Optional[np.ndarray] = None  # flat [K*L] real-slot flags (host)
    carry = None
    cur_idx = None  # ([K, L] local data ids, [K, L] carry positions)
    prev_width = e_shard  # lanes-per-shard width of the previous dispatch
    prev_global = np.arange(e, dtype=np.int32).reshape(K, e_shard)
    spent = 0
    chunk_index = 0
    while True:
        budget = min(chunk, max_iter - spent)
        final_chunk = spent + budget >= max_iter
        active_lanes = e if idx is None else int(mask.sum())
        if lane_seq is not None:
            lane_seq.append(active_lanes)
        t0 = time.perf_counter()
        with trace.span("re.shard_chunk", chunk=chunk_index,
                        active_lanes=active_lanes, budget=budget,
                        shards=K):
            if idx is None:
                out = _dispatch_fit_sharded(
                    mesh, X, labels, offsets, weights, x0, obj, l1,
                    solver, budget, tolerance,
                    boundary_convergence=not final_chunk,
                    return_carry=not final_chunk)
            else:
                out = _dispatch_fit_sharded_resume(
                    mesh, X, labels, offsets, weights, cur_idx[0],
                    cur_idx[1], obj, l1, carry, solver, budget, tolerance,
                    boundary_convergence=not final_chunk,
                    return_carry=not final_chunk)
            if final_chunk:
                c, it, v, k = out
                new_carry = None
            else:
                c, it, v, k, new_carry = out
            if idx is None:
                still, still_local = state.absorb(None, c, it, v, k,
                                                  CONV_MAX_ITERATIONS)
            else:
                still, still_local = state.absorb_padded(
                    idx, mask, c, it, v, k, CONV_MAX_ITERATIONS)
        REGISTRY.histogram("re_chunk_active_lanes").observe(active_lanes)
        SOLVE_STATS["solve_secs"] += time.perf_counter() - t0
        SOLVE_STATS["chunks"] += 1
        chunk_index += 1
        spent += budget
        if spent >= max_iter or len(still) == 0:
            break
        t0 = time.perf_counter()
        carry = new_carry
        owner = still_local // prev_width
        counts = np.bincount(owner, minlength=K)
        L = padded_lane_count(int(counts.max()), floor=min(8, e_shard))
        rows_global = np.empty((K, L), np.int32)
        rows_carry = np.zeros((K, L), np.int32)
        rows_mask = np.zeros((K, L), bool)
        for s in range(K):
            sel = owner == s
            g_ids = still[sel]
            l_pos = (still_local[sel] % prev_width).astype(np.int32)
            n = len(g_ids)
            if n:
                fill_g, fill_c = g_ids[0], l_pos[0]
            else:
                fill_g, fill_c = prev_global[s, 0], 0
            rows_global[s] = fill_g
            rows_carry[s] = fill_c
            rows_global[s, :n] = g_ids
            rows_carry[s, :n] = l_pos
            rows_mask[s, :n] = True
        idx = rows_global.reshape(-1)
        mask = rows_mask.reshape(-1)
        cur_idx = (rows_global
                   - np.arange(K, dtype=np.int32)[:, None] * e_shard,
                   rows_carry)
        prev_global = rows_global
        prev_width = L
        SOLVE_STATS["compact_secs"] += time.perf_counter() - t0
        SOLVE_STATS["shard_real_lanes"] += int(counts.sum())
        SOLVE_STATS["shard_padded_lanes"] += K * L
        SOLVE_STATS["shard_lane_counts"] = (
            SOLVE_STATS["shard_lane_counts"][-15:] + [counts.tolist()])
        SOLVE_STATS["lane_counts"] = (
            SOLVE_STATS["lane_counts"][-63:] + [int(len(still))])
    return state.results()


#: fallback reasons already logged (one warning per distinct cause, not
#: one per sweep — the sharded path is hit every CD sweep)
_SHARD_FALLBACK_WARNED: set = set()


def _resolve_entity_shards(entity_shards: int, num_lanes: int):
    """(mesh, K) when the mesh-sharded path engages for a block of
    ``num_lanes`` entity lanes, else (None, 1) — with one logged warning
    per distinct fallback cause. K is the DEFAULT mesh's entity-axis
    extent (the driver sizes both from the same flag; a mesh granted
    fewer shards than requested already warned in setup_default_mesh)."""
    if entity_shards <= 1:
        return None, 1
    mesh = get_default_mesh()
    K = int(mesh.shape.get(ENTITY_AXIS, 1)) if mesh is not None else 1
    if K <= 1:
        reason = ("no-mesh", entity_shards)
        if reason not in _SHARD_FALLBACK_WARNED:
            _SHARD_FALLBACK_WARNED.add(reason)
            logger.warning(
                "re-entity-shards=%d requested but no default mesh with an "
                "entity axis > 1 is installed; running unsharded",
                entity_shards)
        return None, 1
    if num_lanes % K != 0:
        reason = ("ragged", num_lanes, K)
        if reason not in _SHARD_FALLBACK_WARNED:
            _SHARD_FALLBACK_WARNED.add(reason)
            logger.warning(
                "entity block of %d lanes does not divide %d entity "
                "shards; running this block unsharded (build the dataset "
                "with entity_axis_size=%d to pad it)", num_lanes, K, K)
        return None, 1
    return mesh, K


@lru_cache(maxsize=64)
def _sharded_score_fn(mesh, num_samples, collective_quant="none"):
    """shard_map + jit of the active-score exchange: each shard scores its
    resident entity lanes and scatters into a full-length sample-axis
    partial, reduced ON DEVICE with a psum over the entity axis — the
    replicated result feeds the CD fused epilogue directly, no host-side
    assemble and no new device→host syncs. ``collective_quant`` selects
    the psum wire format (int8 ships blockwise-quantized partials and
    dequant-accumulates in f32); it is part of the cache key, so the two
    wire modes compile as distinct programs and never cross-hit."""
    from photon_ml_tpu.parallel.distributed import _shard_map
    from photon_ml_tpu.parallel.quantized_collectives import qpsum

    lane = P(ENTITY_AXIS)

    def impl(X, coefs, row_ids, weights):
        margins = jnp.einsum("end,ed->en", X, coefs,
                             preferred_element_type=jnp.float32)
        margins = jnp.where(weights > 0, margins, 0.0)
        flat = jax.ops.segment_sum(
            margins.reshape(-1), row_ids.reshape(-1).astype(jnp.int32),
            num_segments=num_samples + 1)
        return qpsum(flat[:num_samples], ENTITY_AXIS,
                     mode=collective_quant)

    fit = _shard_map(impl, mesh, in_specs=(lane, lane, lane, lane),
                     out_specs=P())
    return jax.jit(fit)


@dataclasses.dataclass(frozen=True)
class RandomEffectOptimizationProblem:
    """Per-entity GLM problems for one random-effect coordinate.

    Reference: optimization/game/RandomEffectOptimizationProblem.scala:41-130
    builds an RDD of SingleNodeOptimizationProblems co-partitioned with the
    data; here one config applies to all entities and the per-entity state is
    just the coefficient block.
    """

    config: GLMOptimizationConfiguration
    task: TaskType
    # > 0 engages chunked solving with active-lane compaction: the batched
    # solver runs ``lane_compaction_chunk`` iterations at a time and only
    # still-unconverged lanes re-dispatch (see _fit_blocks_compacted).
    # 0 keeps the single-dispatch all-lanes-to-max-lane-count behavior.
    # AUTO_COMPACTION_CHUNK (-1, driver flag value "auto") lets this
    # problem's own ChunkAutoTuner pick — and re-tune between solves —
    # from the observed per-chunk active-lane decay.
    lane_compaction_chunk: int = 0
    # > 1 engages the mesh-sharded dispatch (driver flag
    # --re-entity-shards): entity lanes split over the default mesh's
    # ENTITY_AXIS via shard_map, per-shard lane compaction, on-device
    # psum score exchange. Engages only when a default mesh with a
    # matching entity axis is installed AND the block's lane count
    # divides it (build_random_effect_dataset(entity_axis_size=K) pads
    # for this); otherwise one logged warning and the unsharded path.
    # 1 (the default) IS the unsharded path — bit-identical to before.
    entity_shards: int = 1
    # per-coordinate controller state (the problem instance lives
    # across sweeps, so auto-mode feedback persists; identical configs
    # on different coordinates still tune independently)
    chunk_tuner: ChunkAutoTuner = dataclasses.field(
        default_factory=ChunkAutoTuner, compare=False, repr=False)
    # Wire format of the sharded score exchange's entity-axis psum
    # ("none" | "int8", driver --collective-quant). The per-entity
    # solves themselves have no collectives — entities are independent —
    # so this only affects the score path.
    collective_quant: str = "none"

    def objective(self) -> GLMObjective:
        cfg = self.config
        l2 = cfg.regularization_context.l2_weight(cfg.regularization_weight)
        return GLMObjective(
            loss=get_loss(TASK_LOSS_NAME[self.task]),
            l2_lambda=l2,
            has_hessian=self.task != TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
        )

    def _fit(self, X, labels, offsets, weights, x0, obj, l1_arr,
             solver: str, donate: bool, fault_tag: Optional[str] = None):
        """One entity block through the solver — compacted in iteration
        chunks when ``lane_compaction_chunk`` engages (auto-tuned when
        it is AUTO_COMPACTION_CHUNK), one dispatch otherwise. With
        ``entity_shards`` > 1 and a matching default mesh, the block
        dispatches mesh-sharded instead (``donate`` is ignored there:
        the sharded program gathers on device from the caller's
        buffers, which therefore stay live)."""
        cfg = self.config
        chunk = self.lane_compaction_chunk
        auto = chunk == AUTO_COMPACTION_CHUNK
        if auto:
            chunk = self.chunk_tuner.chunk_for(solver, cfg.max_iterations)
        mesh, shards = _resolve_entity_shards(self.entity_shards,
                                              int(X.shape[0]))
        if shards > 1:
            e = int(X.shape[0])
            with trace.span("re.shard_solve", solver=solver, shards=shards,
                            lanes=e):
                if 0 < chunk < cfg.max_iterations and e // shards > 1:
                    lane_seq = [] if auto else None
                    out = _fit_blocks_compacted_sharded(
                        mesh, shards, X, labels, offsets, weights, x0,
                        obj, l1_arr, solver, cfg.max_iterations,
                        float(cfg.tolerance), chunk, lane_seq=lane_seq)
                    if auto:
                        self.chunk_tuner.update(solver, cfg.max_iterations,
                                                lane_seq)
                else:
                    out = _dispatch_fit_sharded(
                        mesh, X, labels, offsets, weights, x0, obj,
                        l1_arr, solver, cfg.max_iterations,
                        float(cfg.tolerance))
            # host-level chaos site (never traced): a drill here proves a
            # fault INSIDE a sharded solve rides the existing CD recovery
            # ladder — see utils/faults.FAULT_POINTS["re.shard_dispatch"]
            poisoned = fault_point("re.shard_dispatch", tag=fault_tag,
                                   arrays=out[0])
            return (poisoned,) + tuple(out[1:])
        if 0 < chunk < cfg.max_iterations and int(X.shape[0]) > 1:
            lane_seq: Optional[list] = [] if auto else None
            out = _fit_blocks_compacted(
                X, labels, offsets, weights, x0, obj, l1_arr, solver,
                cfg.max_iterations, float(cfg.tolerance), chunk, donate,
                lane_seq=lane_seq)
            if auto:
                self.chunk_tuner.update(solver, cfg.max_iterations,
                                        lane_seq)
            return out
        return _dispatch_fit(
            X, labels, offsets, weights, x0, obj, l1_arr, solver,
            cfg.max_iterations, float(cfg.tolerance), donate)

    def run(
        self,
        dataset: RandomEffectDataset,
        offsets: Array,
        initial: Optional[Array] = None,
        donate: bool = False,
    ) -> tuple[Array, Array, Array, Array]:
        """Fit all entities; returns (coefficients [E, D_red], iterations [E],
        final losses [E], convergence codes [E] — CONVERGENCE_CODE_NAMES).

        ``offsets`` is the entity-major offset block (base offsets + other
        coordinates' scores). All three solvers run batched under ``vmap``:
        TRON's trust-region/CG loop nest is the same ``lax.while_loop``
        program per entity lane (OptimizerFactory.scala:69-77 allows TRON
        for single-node problems; TRON.scala:84-341). As in the reference,
        TRON requires a twice-differentiable loss, so smoothed-hinge + TRON
        is rejected (OptimizerFactory.scala:78-79).
        """
        cfg = self.config
        l1 = cfg.regularization_context.l1_weight(cfg.regularization_weight)
        if cfg.optimizer_type == OptimizerType.TRON:
            if self.task == TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM:
                raise ValueError(
                    "TRON requires a twice-differentiable loss; the smoothed "
                    "hinge (linear SVM) task has no usable Hessian "
                    "(OptimizerFactory.scala:78-79). Use LBFGS instead.")
            solver = "tron"
        elif l1 > 0.0:
            solver = "owlqn"
        else:
            solver = "lbfgs"

        if dataset.buckets is not None:
            with trace.span("re.solve", solver=solver, bucketed=True,
                            entities=int(dataset.num_entities)):
                return self._run_bucketed(dataset, offsets, initial,
                                          solver, l1, donate)

        e, _, d = dataset.X.shape
        acc = jnp.promote_types(dataset.X.dtype, jnp.float32)
        x0 = solver_x0(acc, (e, d), initial)
        # solver state policy: blocks are f32, solver state >= f32; a
        # wider offset vector (e.g. f64 scores) must not poison the
        # jitted solver's carry dtypes
        offsets = jnp.asarray(offsets, acc)
        with trace.span("re.solve", solver=solver, bucketed=False,
                        entities=int(e)):
            return self._fit(
                dataset.X, dataset.labels, offsets, dataset.weights, x0,
                self.objective(), jnp.full(d, l1, x0.dtype), solver,
                donate and offsets is not dataset.base_offsets,
                fault_tag="0")

    def _run_bucketed(self, dataset, offsets, initial, solver: str,
                      l1: float, donate: bool = False):
        """Per-bucket vmapped solves assembled into one compact global
        block ``[num_entities, reduced_dim]`` (entity order is bucket-major;
        pad lanes never leave the bucket). With compaction off (the
        default) all buckets are DISPATCHED before any result is
        assembled and no blocking read happens here at all (the trackers
        fetch lazily, the CD epilogue fetches once); the compact global
        block is built with one concatenate per output instead of a
        per-bucket ``.at[].set`` copy chain over a presized zero block.
        With ``lane_compaction_chunk`` set, each bucket's chunked solve
        blocks on its small per-chunk unconverged-mask fetches before the
        next bucket dispatches — compaction trades that serialization for
        shedding converged lanes.

        Compile-cost note: each distinct bucket shape (E_b, N_b, D_b)
        compiles its own ``_fit_blocks`` trace, so the first sweep pays one
        compile per bucket. The DP bucket plan is deterministic for a given
        dataset, so shapes are stable across sweeps/processes and the
        in-process jit cache plus the persistent XLA compile cache
        (utils/compile_cache.py) absorb every later sweep; keep bucket
        counts small (3-4) so the one-time cost stays bounded."""
        d_red = dataset.reduced_dim
        acc = jnp.promote_types(dataset.buckets[0].X.dtype, jnp.float32)
        obj = self.objective()
        # solver state policy: blocks are f32, solver state >= f32
        # (optimize/common.solver_x0); the warm-start conversion is hoisted
        # out of the bucket loop (it used to re-convert per bucket/sweep)
        initial_acc = None if initial is None else jnp.asarray(initial, acc)
        outs = []
        for bi, (bucket, off_b) in enumerate(zip(dataset.buckets, offsets)):
            e_b, _, d_b = bucket.X.shape
            nr, start = bucket.num_real, bucket.entity_start
            off_b = jnp.asarray(off_b, acc)
            if initial_acc is None:
                x0_b = jnp.zeros((e_b, d_b), acc)
            else:
                # pad rows/columns in one op instead of zeros + .at[].set
                x0_b = jnp.pad(initial_acc[start:start + nr, :d_b],
                               ((0, e_b - nr), (0, 0)))
            outs.append(self._fit(
                bucket.X, bucket.labels, off_b, bucket.weights, x0_b,
                obj, jnp.full(d_b, l1, acc), solver, donate,
                fault_tag=str(bi)))
        # bucket-major concatenation IS the global entity order; pad each
        # bucket's D_b out to the global reduced_dim
        coefs = jnp.concatenate([
            jnp.pad(c[:b.num_real],
                    ((0, 0), (0, d_red - int(c.shape[1])))).astype(acc)
            for b, (c, _, _, _) in zip(dataset.buckets, outs)])
        iters = jnp.concatenate([
            it[:b.num_real]
            for b, (_, it, _, _) in zip(dataset.buckets, outs)])
        values = jnp.concatenate([
            v[:b.num_real].astype(acc)
            for b, (_, _, v, _) in zip(dataset.buckets, outs)])
        codes = jnp.concatenate([
            k[:b.num_real]
            for b, (_, _, _, k) in zip(dataset.buckets, outs)])
        return coefs, iters, values, codes

    def regularization_value_device(self, coefs: Array):
        """Σ over entities of the per-entity penalty as a device scalar
        (no host sync — feeds the CD fused epilogue's per-coordinate reg
        cache). Python ``0.0`` when the config has no penalty."""
        cfg = self.config
        l1 = cfg.regularization_context.l1_weight(cfg.regularization_weight)
        l2 = cfg.regularization_context.l2_weight(cfg.regularization_weight)
        val = 0.0
        if l1 > 0:
            val = val + l1 * jnp.sum(jnp.abs(coefs))
        if l2 > 0:
            val = val + 0.5 * l2 * jnp.sum(coefs * coefs)
        return val

    def regularization_value(self, coefs: Array) -> float:
        """Σ over entities of the per-entity penalty
        (RandomEffectOptimizationProblem.getRegularizationTermValue)."""
        val = self.regularization_value_device(coefs)
        # photonlint: allow-W101(this IS the host-scalar accessor: one guarded scalar sync per sweep-end objective, annotated -> float)
        return val if isinstance(val, float) else float(val)


@partial(jax.jit, static_argnames=("num_samples",))
def score_active(dataset_X: Array, coefs: Array, row_ids: Array,
                 weights: Array, num_samples: int) -> Array:
    """Scatter per-entity active-row margins back to the sample axis.

    margins[e, n] = X[e, n] . coefs[e]; padded rows (weight 0) scatter to the
    discard slot ``num_samples``. This is the entity→sample resharding half of
    the score exchange (RandomEffectCoordinate.score :137-151 analog).
    """
    margins = jnp.einsum("end,ed->en", dataset_X, coefs,
                         preferred_element_type=jnp.float32)
    margins = jnp.where(weights > 0, margins, 0.0)
    flat = jax.ops.segment_sum(
        margins.reshape(-1), row_ids.reshape(-1).astype(jnp.int32),
        num_segments=num_samples + 1)
    return flat[:num_samples]


@partial(jax.jit, static_argnames=("num_samples",))
def score_passive(passive_X: Array, passive_entity: Array, coefs: Array,
                  passive_row_ids: Array, num_samples: int) -> Array:
    """Score passive rows with their entity's model (gather + rowwise dot).

    Reference: RandomEffectCoordinate.scala:153-199 collects the relevant
    models into a broadcast map; here it is a gather of coefficient rows.
    """
    w = coefs[passive_entity]  # [P, D_red]
    margins = jnp.sum(passive_X * w, axis=-1)
    return jax.ops.segment_sum(
        margins, passive_row_ids.astype(jnp.int32),
        num_segments=num_samples + 1)[:num_samples]


def score_random_effect(dataset: RandomEffectDataset, coefs: Array,
                        entity_shards: int = 1,
                        collective_quant: str = "none") -> Array:
    """Full sample-axis score vector (active + passive) for this coordinate.

    ``coefs`` is the compact global block ``[num_entities, reduced_dim]``;
    bucketed datasets score per bucket (row sets are disjoint, so the
    per-bucket scatters sum without overlap). With ``entity_shards`` > 1
    (and the same engagement conditions as the sharded solve), each
    block's scoring runs shard-local and the per-shard partial score
    vectors reduce with an on-device psum over the entity axis — the
    replicated result feeds the CD fused epilogue with zero added host
    syncs; ``collective_quant="int8"`` ships that psum's partials
    blockwise-quantized (parallel/quantized_collectives.py) and counts
    the wire bytes on ``collective_bytes{site="re.score_psum"}``.
    Shard-count 1 is the unchanged single-program path."""
    from photon_ml_tpu.parallel.quantized_collectives import \
        record_collective_bytes

    def _score_block(X, c_b, row_ids, weights):
        mesh, K = _resolve_entity_shards(entity_shards, int(X.shape[0]))
        if K > 1:
            with trace.span("re.shard_score", shards=K,
                            lanes=int(X.shape[0])):
                out = _sharded_score_fn(mesh, int(dataset.num_samples),
                                        collective_quant)(
                    X, c_b, row_ids, weights)
                record_collective_bytes("re.score_psum", collective_quant,
                                        int(dataset.num_samples))
                return out
        return score_active(X, c_b, row_ids, weights, dataset.num_samples)

    if dataset.buckets is not None:
        s = jnp.zeros(dataset.num_samples, jnp.float32)
        for bucket in dataset.buckets:
            e_b, _, d_b = bucket.X.shape
            nr, start = bucket.num_real, bucket.entity_start
            c_b = jnp.zeros((e_b, d_b), coefs.dtype)
            c_b = c_b.at[:nr].set(coefs[start:start + nr, :d_b])
            s = s + _score_block(bucket.X, c_b, bucket.row_ids,
                                 bucket.weights)
    else:
        s = _score_block(dataset.X, coefs, dataset.row_ids, dataset.weights)
    if dataset.num_passive:
        s = s + score_passive(dataset.passive_X, dataset.passive_entity,
                              coefs, dataset.passive_row_ids,
                              dataset.num_samples)
    return s
