"""GAME data layer: columnar dataset, per-coordinate views, score exchange.

TPU-native re-design of the reference's GAME data structures
(reference paths under photon-ml/src/main/scala/com/linkedin/photon/ml/):

- ``GameDatum`` (data/GameDatum.scala:33-54) — one row with response/offset/
  weight, per-feature-shard sparse vectors, and an idType→entityId map. Here
  the whole dataset is **columnar**: response/offset/weight arrays, one CSR
  matrix per feature shard, and integer entity-code columns per id type.
- ``FixedEffectDataSet`` (data/FixedEffectDataSet.scala:29-103) — an RDD of
  rows for one shard. Here: a device batch (dense or ELL) whose rows ARE the
  sample axis, sharded over the mesh ``data`` axis.
- ``RandomEffectDataSet`` (data/RandomEffectDataSet.scala:40-317) — active
  data grouped per entity (reservoir-capped), passive overflow, projection.
  Here: padded entity-major blocks ``[E, N_max, D_red]`` plus sample-major
  passive arrays; the sample↔entity layout exchange is a gather/scatter by
  row id (the Spark-shuffle analog, SURVEY §5.7).
- ``KeyValueScore`` (data/KeyValueScore.scala:32-95) — score vector keyed by
  unique sample id. Here: a plain ``[N]`` array aligned to row order; the
  outer-join ``+``/``-`` becomes elementwise add/sub.

Ragged→static design (SURVEY §7 hard part 1): active rows per entity are
capped (reservoir), entity blocks are padded to one ``N_max`` and reduced
feature spaces padded to one ``D_red``; padded rows carry weight 0 and row id
``N`` (scores scattered there land in a discard slot).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import numpy as np
import scipy.sparse as sp

import jax.numpy as jnp

from photon_ml_tpu.data.batch import (
    Batch,
    DenseBatch,
    canonicalized_csr,
    ell_from_csr,
)
from photon_ml_tpu.io.native_loader import pack_projected_rows_native
from photon_ml_tpu.projector.projectors import (
    IndexMapProjectors,
    ProjectorConfig,
    ProjectorType,
    RandomProjector,
    build_random_projector,
)

Array = jnp.ndarray

# Densify a shard below this width; ELL above (mirrors the reference's
# representation switch around 200k features, cli/game/training/Driver.scala:
# 357-363 — ours trades dense MXU matmuls against gather/scatter cost).
DENSE_FEATURE_THRESHOLD = 4096


# ---------------------------------------------------------------------------
# Columnar GAME dataset (host side)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class GameDataset:
    """Columnar GAME dataset: the host-resident source of per-coordinate views.

    ``feature_shards[shard]`` is a scipy CSR ``[N, D_shard]``;
    ``id_columns[id_type]`` holds integer entity codes (`0..V-1`) with the
    original ids in ``id_vocabs[id_type]`` (GameDatum.scala:33-54's
    idTypeToValueMap, dictionary-encoded).
    """

    responses: np.ndarray  # [N] float
    feature_shards: dict[str, sp.csr_matrix]
    offsets: Optional[np.ndarray] = None  # [N]
    weights: Optional[np.ndarray] = None  # [N]
    id_columns: dict[str, np.ndarray] = dataclasses.field(default_factory=dict)
    id_vocabs: dict[str, np.ndarray] = dataclasses.field(default_factory=dict)
    uids: Optional[np.ndarray] = None  # [N] raw uid strings when present

    def __post_init__(self):
        n = len(self.responses)
        self.responses = np.asarray(self.responses, dtype=np.float64)
        if self.offsets is None:
            self.offsets = np.zeros(n)
        if self.weights is None:
            self.weights = np.ones(n)
        for name, mat in list(self.feature_shards.items()):
            if not sp.issparse(mat):
                mat = sp.csr_matrix(np.asarray(mat))
            else:
                mat = mat.tocsr()
            # downstream block fills scatter `mat.data` by (row, col) —
            # duplicate entries must be pre-summed or the scatter keeps
            # only the last write
            self.feature_shards[name] = canonicalized_csr(mat)

    @property
    def num_samples(self) -> int:
        return len(self.responses)

    def shard_dim(self, shard: str) -> int:
        return self.feature_shards[shard].shape[1]

    def encode_ids(self, id_type: str, raw_ids: np.ndarray) -> None:
        """Dictionary-encode a raw id column (strings or ints) into codes."""
        vocab, codes = np.unique(np.asarray(raw_ids), return_inverse=True)
        self.id_columns[id_type] = codes.astype(np.int64)
        self.id_vocabs[id_type] = vocab


# ---------------------------------------------------------------------------
# Scores (KeyValueScore analog)
# ---------------------------------------------------------------------------


def zero_scores(n: int) -> np.ndarray:
    return np.zeros(n)


# ---------------------------------------------------------------------------
# Fixed-effect view
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class FixedEffectDataset:
    """Device batch over the full sample axis for one feature shard.

    Reference: data/FixedEffectDataSet.scala:29-103. ``batch`` rows align
    with GameDataset row order, so coordinate-descent offset injection
    (addScoresToOffsets, :55-74 analog) is a plain array swap — see
    ``with_offsets``.
    """

    shard_id: str
    batch: Batch
    base_offsets: Array  # original data offsets (before CD score injection)

    @property
    def num_samples(self) -> int:
        return int(self.batch.labels.shape[0])

    def with_offsets(self, extra_scores: Array) -> Batch:
        """Batch whose offsets = data offsets + other coordinates' scores."""
        return self.batch._replace(offsets=self.base_offsets + extra_scores)


def csr_to_batch(
    mat: sp.csr_matrix,
    labels: np.ndarray,
    offsets: np.ndarray,
    weights: np.ndarray,
    dtype=jnp.float32,
    dense_threshold: int = DENSE_FEATURE_THRESHOLD,
) -> Batch:
    if mat.shape[1] <= dense_threshold:
        return DenseBatch(
            X=jnp.asarray(mat.toarray(), dtype),
            labels=jnp.asarray(labels, jnp.float32),
            offsets=jnp.asarray(offsets, jnp.float32),
            weights=jnp.asarray(weights, jnp.float32),
        )
    # the ELL layout would split a duplicated cell across slots and
    # corrupt Hessian-diagonal terms (sum(x^2) vs (sum x)^2); toarray()
    # above sums implicitly so only this branch needs the canonical form
    return ell_from_csr(canonicalized_csr(mat), labels, offsets, weights,
                        dtype=dtype)


def build_fixed_effect_dataset(
    data: GameDataset,
    shard_id: str,
    dtype=jnp.float32,
    dense_threshold: int = DENSE_FEATURE_THRESHOLD,
) -> FixedEffectDataset:
    mat = data.feature_shards[shard_id]
    batch = csr_to_batch(mat, data.responses, data.offsets, data.weights,
                         dtype=dtype, dense_threshold=dense_threshold)
    return FixedEffectDataset(shard_id=shard_id, batch=batch,
                              base_offsets=batch.offsets)


# ---------------------------------------------------------------------------
# Load-balanced entity partitioning
# ---------------------------------------------------------------------------


def balanced_entity_order(counts: np.ndarray, num_bins: int,
                          capacity: int = 10000) -> np.ndarray:
    """Greedy bin-pack entities by sample count; return a permutation whose
    contiguous ``num_bins`` slices are load-balanced.

    Mirrors data/RandomEffectDataSetPartitioner.scala:31-108: the heaviest
    ``capacity`` entities are placed greedily onto the lightest bin (min-heap
    by assigned samples); the long tail is hashed. Two changes for the mesh
    layout: bins become contiguous index ranges (sharding = slicing), and bin
    cardinality is capped at ⌈E/num_bins⌉ so equal-size slices line up with
    the bins (padded entity blocks all cost the same compute anyway — load
    balance here equalizes *active sample mass* per shard for build/IO).
    """
    import heapq

    e = len(counts)
    if e == 0:
        return np.zeros(0, dtype=np.int64)
    order = np.argsort(-counts, kind="stable")
    heavy = order[: min(capacity, e)]
    tail = order[min(capacity, e):]
    cap = -(-e // num_bins)
    bins: list[list[int]] = [[] for _ in range(num_bins)]
    heap = [(0, b) for b in range(num_bins)]
    heapq.heapify(heap)
    for ent in heavy:
        spill = []
        while True:
            load, b = heapq.heappop(heap)
            if len(bins[b]) < cap:
                break
            spill.append((load, b))
        bins[b].append(int(ent))
        heapq.heappush(heap, (load + int(counts[ent]), b))
        for item in spill:
            heapq.heappush(heap, item)
    for ent in tail:
        b = int(ent) % num_bins
        if len(bins[b]) >= cap:
            b = min(range(num_bins), key=lambda i: len(bins[i]))
        bins[b].append(int(ent))
    return np.concatenate([np.asarray(b, dtype=np.int64) for b in bins])


# ---------------------------------------------------------------------------
# Random-effect view
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RandomEffectDataConfiguration:
    """Per-coordinate data knobs (data/RandomEffectDataConfiguration.scala:80).

    String format (parity with the reference's CLI):
    ``idType,featureShardId,numPartitions[,activeBound[,passiveBound
    [,numFeaturesToKeep[,projector]]]]`` with ``-`` / ``none`` meaning unset.
    """

    random_effect_type: str
    feature_shard_id: str
    num_partitions: int = 1
    num_active_data_points_upper_bound: Optional[int] = None
    num_passive_data_points_lower_bound: Optional[int] = None
    # CLI field 5 is a features-to-samples RATIO (double) in the reference
    # (RandomEffectDataConfiguration.scala:104-109); the per-entity keep
    # count is ceil(ratio * num_entity_samples) (RandomEffectDataSet.scala:
    # 384-390). The absolute count is a direct-API knob, not CLI-parsed.
    num_features_to_samples_ratio_upper_bound: Optional[float] = None
    num_features_to_keep_upper_bound: Optional[int] = None
    projector: ProjectorConfig = ProjectorConfig(ProjectorType.INDEX_MAP)

    def features_to_keep(self, num_entity_samples: int) -> Optional[int]:
        """Per-entity feature cap: the absolute bound if set, else
        ceil(ratio * samples) (RandomEffectDataSet.scala:386)."""
        if self.num_features_to_keep_upper_bound is not None:
            return self.num_features_to_keep_upper_bound
        if self.num_features_to_samples_ratio_upper_bound is not None:
            return int(math.ceil(
                self.num_features_to_samples_ratio_upper_bound
                * num_entity_samples))
        return None

    @staticmethod
    def parse(s: str) -> "RandomEffectDataConfiguration":
        parts = [p.strip() for p in s.split(",")]
        if len(parts) < 3:
            raise ValueError(
                f"random-effect data config needs at least idType,shard,"
                f"numPartitions: {s!r}")

        def _unset(i):
            return i >= len(parts) or parts[i] in ("", "-", "none", "None")

        def opt_int(i):
            # Negative raw values mean "no bound" (the reference maps them
            # to Int.MaxValue, RandomEffectDataConfiguration.scala:92-95).
            if _unset(i):
                return None
            v = int(parts[i])
            return None if v < 0 else v

        def opt_ratio(i):
            # Field 5 is a double (features-to-samples ratio); negative
            # means unbounded (RandomEffectDataConfiguration.scala:104-109).
            if _unset(i):
                return None
            v = float(parts[i])
            return None if v < 0 else v

        proj = ProjectorConfig(ProjectorType.INDEX_MAP)
        if len(parts) > 6 and parts[6] not in ("", "-", "none"):
            proj = ProjectorConfig.parse(parts[6])
        return RandomEffectDataConfiguration(
            random_effect_type=parts[0],
            feature_shard_id=parts[1],
            num_partitions=int(parts[2]),
            num_active_data_points_upper_bound=opt_int(3),
            num_passive_data_points_lower_bound=opt_int(4),
            num_features_to_samples_ratio_upper_bound=opt_ratio(5),
            projector=proj,
        )


@dataclasses.dataclass(frozen=True)
class FixedEffectDataConfiguration:
    """data/FixedEffectDataConfiguration.scala:23 — ``shardId[,minPartitions]``."""

    feature_shard_id: str
    min_num_partitions: int = 1

    @staticmethod
    def parse(s: str) -> "FixedEffectDataConfiguration":
        parts = [p.strip() for p in s.split(",")]
        return FixedEffectDataConfiguration(
            feature_shard_id=parts[0],
            min_num_partitions=int(parts[1]) if len(parts) > 1 else 1,
        )


@dataclasses.dataclass
class EntityBucket:
    """One (N, D)-homogeneous slice of the entity axis.

    SURVEY §7 hard part 1: padding every entity to a single global
    (N_max, D_red) wastes FLOPs and HBM when entity sizes are skewed (the
    MovieLens per-user block pads the median user ~20x). Entities are
    grouped into a few size buckets; each bucket is padded only to ITS
    (N_b, D_b), and the vmapped solver runs per bucket. Reference analog:
    exactly-sized per-entity local datasets (data/LocalDataSet.scala:34-155).

    ``entity_start``: first global (compact) entity index of this bucket;
    bucket row ``i < num_real`` is global entity ``entity_start + i``; rows
    beyond ``num_real`` are padding lanes for even mesh sharding.
    """

    entity_start: int
    num_real: int
    X: Array  # [E_b, N_b, D_b]
    labels: Array  # [E_b, N_b]
    base_offsets: Array  # [E_b, N_b]
    weights: Array  # [E_b, N_b] (0 = padding)
    row_ids: Array  # [E_b, N_b] int32 (num_samples = discard)
    # When built with entity_shard=(k, K): arrays hold only rows
    # [local_entity_offset, local_entity_offset + E_b/K) of the bucket's
    # padded entity axis; 0 for full builds.
    local_entity_offset: int = 0


@dataclasses.dataclass
class RandomEffectDataset:
    """Entity-major active blocks + sample-major passive rows for one coordinate.

    Active data (trained on): padded dense blocks in each entity's reduced
    feature space —
      ``X [E, N_max, D_red]``, ``labels/offsets/weights [E, N_max]``,
      ``row_ids [E, N_max]`` int32 (pad → ``num_samples``: scatters to a
      discard slot).
    Passive data (scored only, RandomEffectDataSet.scala:328+):
      ``passive_X [P, D_red]`` already projected per its entity,
      ``passive_entity [P]`` local entity index, ``passive_row_ids [P]``.

    ``entity_codes`` maps local entity index → dataset entity code;
    ``projectors`` maps reduced columns back to raw feature ids.

    When built with ``num_buckets > 1`` the single global block is replaced
    by ``buckets`` (each padded to its own (N_b, D_b) — see EntityBucket)
    and ``X/labels/base_offsets/weights/row_ids`` are ``None``; global
    coefficient blocks stay compact ``[num_entities, reduced_dim]`` with
    entity order bucket-major.
    """

    config: RandomEffectDataConfiguration
    entity_codes: np.ndarray  # [E] codes into GameDataset vocab
    X: Optional[Array]  # [E, N_max, D_red] (None when bucketed)
    labels: Optional[Array]  # [E, N_max]
    base_offsets: Optional[Array]  # [E, N_max]
    weights: Optional[Array]  # [E, N_max] (0 = padding)
    row_ids: Optional[Array]  # [E, N_max] int32 (num_samples = discard)
    num_samples: int  # N of the parent GameDataset
    projectors: Optional[IndexMapProjectors] = None
    random_projector: Optional[RandomProjector] = None
    # passive side (may be empty)
    passive_X: Optional[Array] = None  # [P, D_red]
    passive_entity: Optional[Array] = None  # [P] int32
    passive_row_ids: Optional[Array] = None  # [P] int32
    passive_offsets: Optional[Array] = None  # [P]
    # (N, D)-bucketed active blocks (replaces X... when present)
    buckets: Optional[list[EntityBucket]] = None
    _reduced_dim: Optional[int] = None  # set when bucketed

    @property
    def num_entities(self) -> int:
        if self.buckets is not None:
            return sum(b.num_real for b in self.buckets)
        return int(self.X.shape[0])

    @property
    def max_rows_per_entity(self) -> int:
        if self.buckets is not None:
            return max(int(b.X.shape[1]) for b in self.buckets)
        return int(self.X.shape[1])

    @property
    def reduced_dim(self) -> int:
        if self.buckets is not None:
            return int(self._reduced_dim)
        return int(self.X.shape[2])

    @property
    def num_passive(self) -> int:
        return 0 if self.passive_X is None else int(self.passive_X.shape[0])

    def gather_offsets(self, scores: Array) -> Array:
        """Entity-major view of a sample-major score vector (CD offset
        injection — the all-to-all resharding analog of
        RandomEffectDataSet.addScoresToOffsets :55-74)."""
        padded = jnp.concatenate([scores, jnp.zeros(1, scores.dtype)])
        return padded[self.row_ids]

    def offsets_with(self, extra_scores: Array):
        """Per-block training offsets (base + other coordinates' scores):
        one ``[E, N_max]`` array, or a list per bucket when bucketed."""
        if self.buckets is None:
            return self.base_offsets + self.gather_offsets(extra_scores)
        padded = jnp.concatenate(
            [extra_scores, jnp.zeros(1, extra_scores.dtype)])
        return [b.base_offsets + padded[b.row_ids] for b in self.buckets]

    def gather_passive_offsets(self, scores: Array) -> Array:
        if self.passive_row_ids is None:
            return jnp.zeros(0)
        return scores[self.passive_row_ids]


def _topk_per_segment(seg: np.ndarray, score: np.ndarray,
                      limit: np.ndarray) -> np.ndarray:
    """Boolean mask keeping the ``limit[seg]`` highest-``score`` items of
    each segment (stable; vectorized — no per-segment loop)."""
    order = np.lexsort((-score, seg))
    seg_sorted = seg[order]
    # rank within segment along the sorted layout
    boundaries = np.flatnonzero(np.diff(seg_sorted)) + 1
    starts = np.concatenate([[0], boundaries])
    seg_sizes = np.diff(np.concatenate([starts, [len(seg)]]))
    rank = np.arange(len(seg)) - np.repeat(starts, seg_sizes)
    keep_sorted = rank < limit[seg_sorted]
    mask = np.zeros(len(seg), dtype=bool)
    mask[order] = keep_sorted
    return mask


def _densify_chunked(sub: sp.csr_matrix, chunk: int = 1 << 16) -> np.ndarray:
    """``sub.toarray()`` in bounded-memory row chunks (identity projection
    on a wide shard would otherwise materialize one giant temporary on top
    of the destination block)."""
    r, d = sub.shape
    out = np.zeros((r, d), dtype=np.float32)
    for lo in range(0, r, chunk):
        out[lo:lo + chunk] = sub[lo:lo + chunk].toarray()
    return out


def _project_nnz(sub: sp.csr_matrix, entity_of_row: np.ndarray,
                 projectors: IndexMapProjectors
                 ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Reduced column of every stored element of ``sub``, batched.

    Row ``r`` of ``sub`` belongs to entity ``entity_of_row[r]``; each nnz's
    raw column is looked up in that entity's sorted index map with ONE
    ``searchsorted`` over a flattened (entity, raw_col) key table — the
    vectorized inverse of ``IndexMapProjectors.project_row``. Returns
    ``(row_of_nnz, reduced_col, valid)``; invalid elements (features the
    entity's map dropped) must be discarded by the caller.
    """
    lens = np.diff(sub.indptr)
    row_of = np.repeat(np.arange(sub.shape[0]), lens)
    ent = np.asarray(entity_of_row, dtype=np.int64)[row_of]
    d_red = projectors.max_reduced_dim
    stride = projectors.raw_dim + 1
    e = projectors.num_entities
    table = (np.arange(e, dtype=np.int64)[:, None] * stride
             + projectors.raw_indices.astype(np.int64)).ravel()
    keys = ent * stride + sub.indices
    pos = np.searchsorted(table, keys)
    pos_clip = np.minimum(pos, len(table) - 1)
    valid = table[pos_clip] == keys
    j = pos_clip - ent * d_red
    return row_of, j, valid


class _PairStatsAccumulator:
    """Streaming per-(entity, feature) moment accumulation for projector
    construction. Feed any number of active-row chunks through ``add``; the
    running state is the sorted unique (entity, feature) key set with summed
    moments (s1=Σx, s2=Σx², sxy=Σxy) plus per-entity label sums — memory is
    bounded by the number of DISTINCT pairs (the eventual projector table),
    never the total nnz, which is what lets the entity-block build stream
    past host RAM (RandomEffectDataSet.scala:169-206's shuffle-side
    combine)."""

    def __init__(self, raw_dim: int, e_real: int, with_moments: bool):
        self.raw_dim = raw_dim
        self.e_real = e_real
        self.with_moments = with_moments
        self.keys = np.zeros(0, np.int64)
        self.s1 = np.zeros(0)
        self.s2 = np.zeros(0)
        self.sxy = np.zeros(0)
        self.sy1 = np.zeros(e_real)
        self.sy2 = np.zeros(e_real)

    def add(self, sub: sp.csr_matrix, entity_of_row: np.ndarray,
            labels: np.ndarray) -> None:
        """Absorb one chunk of ACTIVE rows (CSR + their entity indices +
        labels)."""
        lens = np.diff(sub.indptr)
        row_of = np.repeat(np.arange(sub.shape[0]), lens)
        ent = np.asarray(entity_of_row, dtype=np.int64)[row_of]
        keys = ent * self.raw_dim + sub.indices
        pairs, inv = np.unique(keys, return_inverse=True)
        if self.with_moments:
            v = sub.data.astype(np.float64)
            y = np.asarray(labels, dtype=np.float64)
            # bincount-with-weights, not np.add.at: the buffered ufunc.at
            # path is ~10-30x slower on the 80M-element ingest bench.
            s1 = np.bincount(inv, weights=v, minlength=len(pairs))
            s2 = np.bincount(inv, weights=v * v, minlength=len(pairs))
            sxy = np.bincount(inv, weights=v * y[row_of],
                              minlength=len(pairs))
            ent_rows = np.asarray(entity_of_row, dtype=np.int64)
            self.sy1 += np.bincount(ent_rows, weights=y,
                                    minlength=self.e_real)
            self.sy2 += np.bincount(ent_rows, weights=y * y,
                                    minlength=self.e_real)
        else:
            s1 = s2 = sxy = np.zeros(len(pairs))
        # merge-compact into the running sorted key set
        if len(self.keys):
            merged, minv = np.unique(np.concatenate([self.keys, pairs]),
                                     return_inverse=True)
            ms1 = np.bincount(minv, weights=np.concatenate([self.s1, s1]),
                              minlength=len(merged))
            ms2 = np.bincount(minv, weights=np.concatenate([self.s2, s2]),
                              minlength=len(merged))
            msxy = np.bincount(minv, weights=np.concatenate([self.sxy, sxy]),
                               minlength=len(merged))
            self.keys, self.s1, self.s2, self.sxy = merged, ms1, ms2, msxy
        else:
            self.keys, self.s1, self.s2, self.sxy = pairs, s1, s2, sxy

    def finalize(self, act_counts: np.ndarray,
                 config: RandomEffectDataConfiguration,
                 pad_to_multiple: int = 8) -> IndexMapProjectors:
        """Per-entity feature unions + optional |Pearson| top-k selection
        (LocalDataSet.scala:202-248) over the accumulated pair stats."""
        e_real = self.e_real
        raw_dim = self.raw_dim
        pair_ent = (self.keys // raw_dim).astype(np.int64)
        pair_col = (self.keys % raw_dim).astype(np.int32)

        # Per-entity keep limits (None -> no cap anywhere).
        if config.num_features_to_keep_upper_bound is not None:
            limits = np.full(e_real,
                             config.num_features_to_keep_upper_bound,
                             dtype=np.int64)
        elif config.num_features_to_samples_ratio_upper_bound is not None:
            limits = np.ceil(
                config.num_features_to_samples_ratio_upper_bound
                * act_counts).astype(np.int64)
        else:
            limits = None

        if limits is not None:
            # |Pearson(feature, label)| per pair from the sparse moments:
            # cov = E[xy] - E[x]E[y], var = E[x^2] - E[x]^2 (zeros
            # contribute only through the entity's row count).
            k_e = np.maximum(act_counts, 1).astype(np.float64)
            ym = self.sy1 / k_e
            y_sd = np.sqrt(np.maximum(self.sy2 / k_e - ym * ym, 0.0))
            ke_p = k_e[pair_ent]
            xm = self.s1 / ke_p
            cov = self.sxy / ke_p - xm * ym[pair_ent]
            var_x = np.maximum(self.s2 / ke_p - xm * xm, 0.0)
            denom = np.sqrt(var_x) * y_sd[pair_ent]
            corr = np.where(denom > 0,
                            np.abs(cov) / np.where(denom > 0, denom, 1.0),
                            0.0)
            keep = _topk_per_segment(pair_ent, corr, limits)
            pair_ent, pair_col = pair_ent[keep], pair_col[keep]
            # restore (entity, column) order after the ranked selection
            reorder = np.lexsort((pair_col, pair_ent))
            pair_ent, pair_col = pair_ent[reorder], pair_col[reorder]

        reduced_dims = np.bincount(pair_ent,
                                   minlength=e_real).astype(np.int32)
        d_red = int(reduced_dims.max()) if e_real else 1
        d_red = max(1, -(-max(d_red, 1) // pad_to_multiple)
                    * pad_to_multiple)
        raw_indices = np.full((e_real, d_red), raw_dim, dtype=np.int32)
        starts = np.concatenate([[0], np.cumsum(reduced_dims)[:-1]])
        slot = np.arange(len(pair_ent)) - starts[pair_ent]
        raw_indices[pair_ent, slot] = pair_col
        return IndexMapProjectors(raw_indices, reduced_dims, raw_dim)


def _build_projectors_from_active(
    sub: sp.csr_matrix,
    entity_of_row: np.ndarray,
    act_counts: np.ndarray,
    labels: np.ndarray,
    raw_dim: int,
    config: RandomEffectDataConfiguration,
    pad_to_multiple: int = 8,
) -> IndexMapProjectors:
    """One-shot (single-chunk) projector build — the in-RAM entry to the
    same accumulate+finalize path the streamed builder uses chunk-wise."""
    need_moments = (
        config.num_features_to_keep_upper_bound is not None
        or config.num_features_to_samples_ratio_upper_bound is not None)
    acc = _PairStatsAccumulator(raw_dim, len(act_counts), need_moments)
    acc.add(sub, entity_of_row, labels)
    return acc.finalize(act_counts, config, pad_to_multiple)


def _bucket_plan(counts: np.ndarray, num_buckets: int, multiple: int
                 ) -> tuple[np.ndarray, np.ndarray]:
    """Optimal (N-threshold) bucketing of entities by active-row count.

    Quantizes counts up to ``multiple`` (rows are padded to that multiple
    anyway), then a small exact DP over the distinct quantized sizes picks
    ≤ ``num_buckets`` contiguous groups minimizing the padded area
    Σ_b E_b · N_b — the FLOP/HBM cost of the vmapped solve. Returns
    ``(bucket_n_max desc [K], bucket_of [E])``.
    """
    counts = np.asarray(counts, dtype=np.int64)
    q = np.maximum(multiple, -(-counts // multiple) * multiple)
    uniq, w = np.unique(q, return_counts=True)
    uniq, w = uniq[::-1], w[::-1].astype(np.int64)  # descending sizes
    m = len(uniq)
    k = min(num_buckets, m)
    if k >= m:
        n_max = uniq
        bucket_of = np.searchsorted(-uniq, -q)
        return n_max, bucket_of
    prefix = np.concatenate([[0], np.cumsum(w)])
    inf = np.iinfo(np.int64).max // 4
    # f[j, t] = min padded area covering the j largest sizes with t buckets
    f = np.full((m + 1, k + 1), inf, dtype=np.int64)
    arg = np.zeros((m + 1, k + 1), dtype=np.int64)
    f[0, 0] = 0
    for t in range(1, k + 1):
        for j in range(t, m + 1):
            # bucket (i..j] has N = uniq[i] (largest member)
            cand = f[:j, t - 1] + uniq[:j] * (prefix[j] - prefix[:j])
            i = int(np.argmin(cand))
            f[j, t], arg[j, t] = cand[i], i
    cuts = []
    j = m
    for t in range(k, 0, -1):
        i = int(arg[j, t])
        cuts.append(i)
        j = i
    cuts = cuts[::-1]  # ascending segment starts into uniq
    n_max = uniq[np.asarray(cuts)]
    # entity -> bucket: the segment its quantized size falls in
    seg_of_size = np.zeros(m, dtype=np.int64)
    for b, start in enumerate(cuts):
        seg_of_size[start:] = b
    size_rank = np.searchsorted(-uniq, -q)
    return n_max, seg_of_size[size_rank]


def _fill_feature_rows(
    sub: sp.csr_matrix,
    out: np.ndarray,
    flat_pos: np.ndarray,
    projectors: Optional[IndexMapProjectors],
    random_projector: Optional[RandomProjector],
    table_ent: Optional[np.ndarray] = None,
    global_ent: Optional[np.ndarray] = None,
    raw_indices: Optional[np.ndarray] = None,
) -> None:
    """ONE per-block feature fill shared by the single-block, bucketed, and
    passive builders: native pack (block_packer.cpp), numpy ``_project_nnz``
    scatter fallback, random-projector matmul, or chunked densify.

    ``out`` is a zeroed C-contiguous f32 array whose flat row view receives
    row ``r`` of ``sub`` at ``flat_pos[r]``. For index-map projection,
    ``table_ent[r]`` indexes ``raw_indices`` (which may be a bucket slice of
    the global table) and ``global_ent[r]`` is the row's GLOBAL entity index
    for the numpy fallback's searchsorted over the full projector table.
    """
    flat = out.reshape(-1, out.shape[-1])
    if projectors is not None:
        if not pack_projected_rows_native(sub, table_ent, flat_pos,
                                          raw_indices, out):
            nnz_row, nnz_j, nnz_ok = _project_nnz(sub, global_ent,
                                                  projectors)
            flat[flat_pos[nnz_row[nnz_ok]],
                 nnz_j[nnz_ok]] = sub.data[nnz_ok]
    elif random_projector is not None:
        flat[flat_pos] = (sub @ random_projector.matrix).astype(np.float32)
    else:
        flat[flat_pos] = _densify_chunked(sub)


def _pack_entity_buckets(
    sub: sp.csr_matrix,
    ent_of_act: np.ndarray,
    slot_of_act: np.ndarray,
    act_labels: np.ndarray,
    act_offsets: np.ndarray,
    act_weights: np.ndarray,
    rows_act: np.ndarray,
    n_samples: int,
    bucket_sizes: np.ndarray,
    bucket_n_max: np.ndarray,
    entity_axis_size: int,
    projectors: Optional[IndexMapProjectors],
    random_projector: Optional[RandomProjector],
    d_red: int,
    dtype,
    pad_dim_multiple: int = 8,
) -> list[EntityBucket]:
    """Pack active rows into per-bucket (N_b, D_b) blocks.

    ``ent_of_act`` are GLOBAL compact entity indices (bucket-major order);
    bucket b owns entities [starts[b], starts[b] + bucket_sizes[b]). Each
    bucket's D_b is the max per-entity reduced dim within it (index-map
    projection narrows tall-entity buckets too — that is the D half of the
    (N, D) bucketing), padded for lane alignment.
    """
    starts = np.concatenate([[0], np.cumsum(bucket_sizes)])
    bucket_of_act = np.searchsorted(starts, ent_of_act, side="right") - 1
    buckets: list[EntityBucket] = []
    for b in range(len(bucket_sizes)):
        nr = int(bucket_sizes[b])
        start = int(starts[b])
        n_b = int(bucket_n_max[b])
        if projectors is not None:
            d_b = int(projectors.reduced_dims[start:start + nr].max())
            d_b = max(1, -(-max(d_b, 1) // pad_dim_multiple)
                      * pad_dim_multiple)
            d_b = min(d_b, d_red)
        else:
            d_b = d_red
        e_b = max(1, -(-nr // entity_axis_size) * entity_axis_size)

        mask = bucket_of_act == b
        loc = ent_of_act[mask] - start
        slots = slot_of_act[mask]
        X = np.zeros((e_b, n_b, d_b), dtype=np.float32)
        labels = np.zeros((e_b, n_b), dtype=np.float32)
        offsets = np.zeros((e_b, n_b), dtype=np.float32)
        weights = np.zeros((e_b, n_b), dtype=np.float32)
        row_ids = np.full((e_b, n_b), n_samples, dtype=np.int32)
        labels[loc, slots] = act_labels[mask]
        offsets[loc, slots] = act_offsets[mask]
        weights[loc, slots] = act_weights[mask]
        row_ids[loc, slots] = rows_act[mask]

        # Per-bucket table slice: every entity's valid columns sit in the
        # first reduced_dims[e] <= D_b positions, so truncating to D_b only
        # drops pad sentinels.
        _fill_feature_rows(
            sub[mask], X, loc * n_b + slots,
            projectors, random_projector,
            table_ent=loc, global_ent=ent_of_act[mask],
            raw_indices=None if projectors is None
            else projectors.raw_indices[start:start + nr, :d_b])

        buckets.append(EntityBucket(
            entity_start=start, num_real=nr,
            X=jnp.asarray(X, dtype),
            labels=jnp.asarray(labels),
            base_offsets=jnp.asarray(offsets),
            weights=jnp.asarray(weights),
            row_ids=jnp.asarray(row_ids),
        ))
    return buckets


def build_random_effect_dataset(
    data: GameDataset,
    config: RandomEffectDataConfiguration,
    seed: int = 0,
    pad_rows_multiple: int = 8,
    dtype=jnp.float32,
    entity_axis_size: int = 1,
    num_buckets: int = 1,
) -> RandomEffectDataset:
    """Group rows per entity, cap/split, project, pad into device blocks.

    ``entity_axis_size``: the entity mesh-axis extent — E is padded to a
    multiple so the blocks shard evenly; entities are pre-permuted by the
    greedy load balancer (balanced_entity_order) so contiguous shards carry
    similar sample mass.

    ``num_buckets > 1`` activates (N, D) size bucketing (SURVEY §7 hard
    part 1): entities are grouped by active-row count into at most that
    many buckets, each padded only to its own (N_b, D_b) — see
    EntityBucket. Entity order becomes bucket-major (balanced within each
    bucket) and the returned dataset carries ``buckets`` instead of one
    global block.
    """
    id_type = config.random_effect_type
    if id_type not in data.id_columns:
        raise KeyError(f"id type {id_type!r} not in dataset (have "
                       f"{list(data.id_columns)})")
    codes = np.asarray(data.id_columns[id_type])
    mat = data.feature_shards[config.feature_shard_id].tocsr()
    n, raw_dim = mat.shape
    rng = np.random.default_rng(seed)

    # --- group + reservoir split in one lexsort: rows ordered by
    # (entity, random key), so the first `cap` rows of each group ARE a
    # uniform sample (RandomEffectDataSet.scala:254-317's reservoir,
    # vectorized). No per-entity Python loop anywhere below.
    order = np.lexsort((rng.random(n), codes))
    sorted_codes = codes[order]
    uniq, starts, group_sizes = np.unique(
        sorted_codes, return_index=True, return_counts=True)
    e_real = len(uniq)
    grp_of_sorted = np.repeat(np.arange(e_real), group_sizes)
    pos_in_group = np.arange(n) - starts[grp_of_sorted]

    cap = config.num_active_data_points_upper_bound
    if cap is None:
        active_mask = np.ones(n, dtype=bool)
        act_counts = group_sizes
    else:
        active_mask = pos_in_group < cap
        act_counts = np.minimum(group_sizes, cap)
    # weight rescale count/cap preserves expected total weight per entity
    group_scale = group_sizes / np.maximum(act_counts, 1)

    lo = config.num_passive_data_points_lower_bound
    pas_counts = group_sizes - act_counts
    keep_passive_group = (pas_counts > 0 if lo is None
                          else pas_counts >= lo)
    passive_mask = ~active_mask & keep_passive_group[grp_of_sorted]

    # --- load-balanced entity ordering for contiguous sharding. With
    # bucketing the order is bucket-major (balanced within each bucket:
    # members are within one padding quantum of each other, so contiguous
    # entity-axis shards stay balanced).
    bucket_sizes = bucket_n_max = None
    if num_buckets > 1 and e_real > 1:
        bucket_n_max, bucket_of = _bucket_plan(
            act_counts, num_buckets, pad_rows_multiple)
        parts = []
        for b in range(len(bucket_n_max)):
            idx = np.flatnonzero(bucket_of == b)
            parts.append(idx[balanced_entity_order(
                act_counts[idx], num_bins=max(1, entity_axis_size))])
        kept = [(n, p) for n, p in zip(bucket_n_max, parts) if len(p)]
        bucket_n_max = np.array([n for n, _ in kept], dtype=np.int64)
        parts = [p for _, p in kept]
        perm = np.concatenate(parts)
        bucket_sizes = np.array([len(p) for p in parts], dtype=np.int64)
    else:
        perm = balanced_entity_order(act_counts,
                                     num_bins=max(1, entity_axis_size))
    ent_codes = uniq[perm].astype(np.int64)
    inv_perm = np.empty(e_real, dtype=np.int64)
    inv_perm[perm] = np.arange(e_real)

    rows_act = order[active_mask]  # dataset row ids of active rows
    ent_of_act = inv_perm[grp_of_sorted[active_mask]]  # local entity index
    slot_of_act = pos_in_group[active_mask]
    counts = act_counts[perm]  # active rows per local entity

    # --- per-entity feature space (projection).
    proj_cfg = config.projector
    projectors = None
    random_projector = None
    sub = mat[rows_act]  # one bulk CSR row gather, row r <-> active row r
    if proj_cfg.kind == ProjectorType.INDEX_MAP:
        projectors = _build_projectors_from_active(
            sub, ent_of_act, counts, data.responses[rows_act], raw_dim,
            config)
        d_red = projectors.max_reduced_dim
    elif proj_cfg.kind == ProjectorType.RANDOM:
        random_projector = build_random_projector(
            raw_dim, proj_cfg.projected_dim, seed=proj_cfg.seed)
        d_red = proj_cfg.projected_dim
    else:  # IDENTITY
        d_red = raw_dim

    act_weights = (data.weights[rows_act]
                   * group_scale[grp_of_sorted[active_mask]])

    if bucket_sizes is not None:
        buckets = _pack_entity_buckets(
            sub, ent_of_act, slot_of_act,
            act_labels=data.responses[rows_act],
            act_offsets=data.offsets[rows_act],
            act_weights=act_weights,
            rows_act=rows_act, n_samples=n,
            bucket_sizes=bucket_sizes, bucket_n_max=bucket_n_max,
            entity_axis_size=entity_axis_size,
            projectors=projectors, random_projector=random_projector,
            d_red=d_red, dtype=dtype)
        X = None
    else:
        buckets = None
        # --- pad E to the entity axis and N to a stable multiple.
        e_pad = max(1,
                    -(-max(e_real, 1) // entity_axis_size) * entity_axis_size)
        n_max = int(counts.max()) if e_real else 1
        n_max = max(1, -(-n_max // pad_rows_multiple) * pad_rows_multiple)

        X = np.zeros((e_pad, n_max, d_red), dtype=np.float32)
        labels = np.zeros((e_pad, n_max), dtype=np.float32)
        offsets = np.zeros((e_pad, n_max), dtype=np.float32)
        weights = np.zeros((e_pad, n_max), dtype=np.float32)
        row_ids = np.full((e_pad, n_max), n, dtype=np.int32)

        labels[ent_of_act, slot_of_act] = data.responses[rows_act]
        offsets[ent_of_act, slot_of_act] = data.offsets[rows_act]
        weights[ent_of_act, slot_of_act] = act_weights
        row_ids[ent_of_act, slot_of_act] = rows_act

        _fill_feature_rows(
            sub, X, ent_of_act * n_max + slot_of_act,
            projectors, random_projector,
            table_ent=ent_of_act, global_ent=ent_of_act,
            raw_indices=None if projectors is None
            else projectors.raw_indices)

    # --- passive side (sample-major, already projected per entity).
    p_X = p_ent = p_rows = p_off = None
    if passive_mask.any():
        pr = order[passive_mask]
        local = inv_perm[grp_of_sorted[passive_mask]].astype(np.int32)
        sub_p = mat[pr]
        dense = np.zeros((len(pr), d_red), dtype=np.float32)
        _fill_feature_rows(
            sub_p, dense, np.arange(len(pr), dtype=np.int64),
            projectors, random_projector,
            table_ent=local.astype(np.int64), global_ent=local,
            raw_indices=None if projectors is None
            else projectors.raw_indices)
        p_X = jnp.asarray(dense)
        p_ent = jnp.asarray(local)
        p_rows = jnp.asarray(pr.astype(np.int32))
        p_off = jnp.asarray(data.offsets[pr].astype(np.float32))

    return RandomEffectDataset(
        config=config,
        entity_codes=ent_codes,
        X=None if buckets is not None else jnp.asarray(X, dtype),
        labels=None if buckets is not None else jnp.asarray(labels),
        base_offsets=None if buckets is not None else jnp.asarray(offsets),
        weights=None if buckets is not None else jnp.asarray(weights),
        row_ids=None if buckets is not None else jnp.asarray(row_ids),
        num_samples=n,
        projectors=projectors,
        random_projector=random_projector,
        passive_X=p_X,
        passive_entity=p_ent,
        passive_row_ids=p_rows,
        passive_offsets=p_off,
        buckets=buckets,
        _reduced_dim=d_red if buckets is not None else None,
    )


def _alloc_rows(shape, blocks_dir: Optional[str], name: str) -> np.ndarray:
    """Zeroed f32 destination: RAM array, or a disk-backed ``np.memmap``
    under ``blocks_dir`` (never resident all at once — the OS pages it)."""
    if blocks_dir is None:
        return np.zeros(shape, dtype=np.float32)
    import os

    os.makedirs(blocks_dir, exist_ok=True)
    return np.memmap(os.path.join(blocks_dir, name + ".f32"),
                     dtype=np.float32, mode="w+", shape=shape)


def build_random_effect_dataset_streamed(
    stream_factory,
    config: RandomEffectDataConfiguration,
    raw_dim: int,
    seed: int = 0,
    pad_rows_multiple: int = 8,
    entity_axis_size: int = 1,
    num_buckets: int = 1,
    blocks_dir: Optional[str] = None,
    pad_dim_multiple: int = 8,
    keep_host_blocks: bool = False,
    entity_shard: Optional[tuple[int, int]] = None,
    dtype=jnp.float32,
) -> RandomEffectDataset:
    """Random-effect blocks from STREAMED parts, optionally memmap-backed.

    The in-RAM builder (``build_random_effect_dataset``) holds the full
    feature CSR plus every padded block simultaneously; the reference
    instead streams partitioned parts through a distributed shuffle into
    entity-major layout (data/RandomEffectDataSet.scala:169-206) and never
    materializes the whole dataset on one host. This builder is that
    shuffle's single-host analog:

    - ``stream_factory()`` returns a FRESH iterator over parts, each part
      ``(csr_chunk [M, raw_dim], entity_codes [M], labels [M], offsets [M],
      weights [M])`` in a deterministic order (the iterator is consumed 2-3
      times; identical content each time).
    - Pass 1 holds only O(N) scalar columns (codes/labels/offsets/weights)
      — never features — and computes the reservoir split, the
      load-balanced entity order, and the (N, D) bucket plan.
    - For INDEX_MAP projection a stats pass accumulates per-(entity,
      feature) moments bounded by the projector-table size
      (``_PairStatsAccumulator``).
    - Pass 2 scatters each part's active/passive rows straight into their
      destination blocks; with ``blocks_dir`` those are ``np.memmap`` files
      (bucket blocks + passive rows), so peak RSS is one part + the scalar
      columns, not CSR + all blocks.

    Always returns the bucketed representation (``num_buckets=1`` → one
    bucket). Host-side staging is always float32; ``dtype`` applies at
    the device commit (the --precision bf16 storage mode), matching the
    in-RAM builder. With ``blocks_dir`` the blocks are f32 numpy memmaps
    that JAX copies to device per-bucket at solve time — the memmap
    files themselves stay f32 regardless of ``dtype`` (the on-disk
    format is the spill contract, and the paging path converts on
    device commit) — and the caller owns the directory's lifetime. ``keep_host_blocks=True`` keeps
    RAM-built blocks as plain numpy too (no device commit) — for callers
    that re-shard them onto a global mesh themselves (the multi-host
    worker must not materialize the full block set on one device first).

    ``entity_shard=(k, K)`` builds ONLY the k-th of K contiguous
    entity-axis slices of every bucket (the grouping/plan stays global,
    computed from the O(N) scalar columns): bucket arrays come back with
    leading dim ``E_b/K`` and ``EntityBucket.local_entity_offset`` set to
    the slice start, so a multi-host worker allocates and fills just its
    own entity range — no host ever holds another host's blocks, the
    per-host-sharded analog of RandomEffectDataSet.scala:169-206's
    partitioned shuffle output. Requires ``entity_axis_size`` divisible
    by K (every bucket's padded E_b then splits evenly). Passive arrays
    remain global.
    """
    # ---- pass 1: scalar columns only ------------------------------------
    codes_parts, y_parts, off_parts, wt_parts = [], [], [], []
    for chunk in stream_factory():
        _, c, y, o, w = chunk
        codes_parts.append(np.asarray(c, np.int64))
        y_parts.append(np.asarray(y, np.float64))
        off_parts.append(np.asarray(o, np.float32))
        # f64 so the reservoir rescale product below is bit-identical to
        # the in-RAM builder's (f64 weights x f64 scale, then one f32 cast)
        wt_parts.append(np.asarray(w, np.float64))
    if not codes_parts:
        raise ValueError("empty random-effect stream")
    codes = np.concatenate(codes_parts)
    resp = np.concatenate(y_parts)
    offs = np.concatenate(off_parts)
    wts = np.concatenate(wt_parts)
    del codes_parts, y_parts, off_parts, wt_parts
    n = len(codes)
    rng = np.random.default_rng(seed)

    # identical reservoir/grouping math to the in-RAM builder (same seed →
    # identical active sets, so the two paths are parity-testable)
    order = np.lexsort((rng.random(n), codes))
    sorted_codes = codes[order]
    uniq, starts, group_sizes = np.unique(
        sorted_codes, return_index=True, return_counts=True)
    e_real = len(uniq)
    grp_of_sorted = np.repeat(np.arange(e_real), group_sizes)
    pos_in_group = np.arange(n) - starts[grp_of_sorted]

    cap = config.num_active_data_points_upper_bound
    if cap is None:
        active_mask = np.ones(n, dtype=bool)
        act_counts = group_sizes
    else:
        active_mask = pos_in_group < cap
        act_counts = np.minimum(group_sizes, cap)
    group_scale = group_sizes / np.maximum(act_counts, 1)

    lo_b = config.num_passive_data_points_lower_bound
    pas_counts = group_sizes - act_counts
    keep_passive_group = (pas_counts > 0 if lo_b is None
                          else pas_counts >= lo_b)
    passive_mask = ~active_mask & keep_passive_group[grp_of_sorted]

    # bucket plan + bucket-major balanced entity order
    bucket_n_max, bucket_of = _bucket_plan(
        act_counts, max(1, num_buckets), pad_rows_multiple)
    parts = []
    for b in range(len(bucket_n_max)):
        idx = np.flatnonzero(bucket_of == b)
        parts.append(idx[balanced_entity_order(
            act_counts[idx], num_bins=max(1, entity_axis_size))])
    kept = [(nm, p) for nm, p in zip(bucket_n_max, parts) if len(p)]
    bucket_n_max = np.array([nm for nm, _ in kept], dtype=np.int64)
    parts = [p for _, p in kept]
    perm = np.concatenate(parts)
    bucket_sizes = np.array([len(p) for p in parts], dtype=np.int64)
    ent_codes = uniq[perm].astype(np.int64)
    inv_perm = np.empty(e_real, dtype=np.int64)
    inv_perm[perm] = np.arange(e_real)
    counts = act_counts[perm]

    # per-dataset-row assignments (row-indexed views of the sorted layout)
    row_ent = np.empty(n, np.int64)
    row_ent[order] = inv_perm[grp_of_sorted]
    row_slot = np.empty(n, np.int32)
    row_slot[order] = pos_in_group.astype(np.int32)
    row_active = np.empty(n, bool)
    row_active[order] = active_mask
    row_passive = np.empty(n, bool)
    row_passive[order] = passive_mask
    n_passive = int(passive_mask.sum())
    ppos = np.full(n, -1, np.int64)
    ppos[order[passive_mask]] = np.arange(n_passive)
    group_scale_perm = group_scale[perm]
    del (order, sorted_codes, grp_of_sorted, pos_in_group, active_mask,
         passive_mask, codes)

    # ---- projector (streamed stats pass for INDEX_MAP) -------------------
    proj_cfg = config.projector
    projectors = None
    random_projector = None
    if proj_cfg.kind == ProjectorType.INDEX_MAP:
        need_moments = (
            config.num_features_to_keep_upper_bound is not None
            or config.num_features_to_samples_ratio_upper_bound is not None)
        acc = _PairStatsAccumulator(raw_dim, e_real, need_moments)
        lo = 0
        for chunk in stream_factory():
            mat_c = chunk[0].tocsr()
            m = mat_c.shape[0]
            a = row_active[lo:lo + m]
            acc.add(mat_c[a], row_ent[lo:lo + m][a], resp[lo:lo + m][a])
            lo += m
        projectors = acc.finalize(counts, config, pad_dim_multiple)
        d_red = projectors.max_reduced_dim
    elif proj_cfg.kind == ProjectorType.RANDOM:
        random_projector = build_random_projector(
            raw_dim, proj_cfg.projected_dim, seed=proj_cfg.seed)
        d_red = proj_cfg.projected_dim
    else:  # IDENTITY
        d_red = raw_dim

    # ---- allocate destination blocks ------------------------------------
    if entity_shard is not None:
        shard_k, shard_count = entity_shard
        if not 0 <= shard_k < shard_count:
            raise ValueError(
                f"entity_shard index {shard_k} out of range for "
                f"{shard_count} shards")
        if entity_axis_size % shard_count != 0:
            raise ValueError(
                f"entity_shard needs entity_axis_size divisible by "
                f"{shard_count}, got {entity_axis_size}")
    else:
        shard_k, shard_count = 0, 1
    b_starts = np.concatenate([[0], np.cumsum(bucket_sizes)])
    Xs, labs, offsb, wtsb, rids, dims = [], [], [], [], [], []
    # local (this shard's) entity range of each bucket: [sl_lo, sl_hi)
    slice_lo, slice_hi = [], []
    for b in range(len(bucket_sizes)):
        nr, n_b = int(bucket_sizes[b]), int(bucket_n_max[b])
        start = int(b_starts[b])
        if projectors is not None:
            d_b = int(projectors.reduced_dims[start:start + nr].max())
            d_b = max(1, -(-max(d_b, 1) // pad_dim_multiple)
                      * pad_dim_multiple)
            d_b = min(d_b, d_red)
        else:
            d_b = d_red
        e_b = max(1, -(-nr // entity_axis_size) * entity_axis_size)
        e_loc = e_b // shard_count
        slice_lo.append(shard_k * e_loc)
        slice_hi.append((shard_k + 1) * e_loc)
        Xs.append(_alloc_rows((e_loc, n_b, d_b), blocks_dir,
                              f"bucket{b}_X"))
        labs.append(np.zeros((e_loc, n_b), np.float32))
        offsb.append(np.zeros((e_loc, n_b), np.float32))
        wtsb.append(np.zeros((e_loc, n_b), np.float32))
        rids.append(np.full((e_loc, n_b), n, np.int32))
        dims.append(d_b)
    p_X = (_alloc_rows((n_passive, d_red), blocks_dir, "passive_X")
           if n_passive else None)
    p_ent = np.zeros(n_passive, np.int32)
    p_rows = np.zeros(n_passive, np.int32)
    p_off = np.zeros(n_passive, np.float32)

    # ---- pass 2: scatter each part into its blocks -----------------------
    lo = 0
    for chunk in stream_factory():
        mat_c = chunk[0].tocsr()
        m = mat_c.shape[0]
        hi = lo + m
        a = np.flatnonzero(row_active[lo:hi])
        if len(a):
            rows_g = (lo + a).astype(np.int64)
            ent = row_ent[lo:hi][a]
            slot = row_slot[lo:hi][a]
            b_of = np.searchsorted(b_starts, ent, side="right") - 1
            sub_a = mat_c[a]
            for b in np.unique(b_of):
                mask = b_of == b
                start = int(b_starts[b])
                nr = int(bucket_sizes[b])
                if shard_count > 1:
                    # only this shard's entity range of the bucket
                    loc_all = ent - start
                    mask &= ((loc_all >= slice_lo[b])
                             & (loc_all < slice_hi[b]))
                    if not mask.any():
                        continue
                loc = ent[mask] - start - slice_lo[b]
                sl = slot[mask]
                n_b = int(bucket_n_max[b])
                # projector-table slice aligned with the slice-local loc
                # (real entities only: rows past nr are pure padding)
                tbl_lo = start + slice_lo[b]
                tbl_hi = start + min(nr, slice_hi[b])
                _fill_feature_rows(
                    sub_a[mask], Xs[b], loc * n_b + sl,
                    projectors, random_projector,
                    table_ent=loc, global_ent=ent[mask],
                    raw_indices=None if projectors is None
                    else projectors.raw_indices[tbl_lo:tbl_hi, :dims[b]])
                labs[b][loc, sl] = resp[rows_g[mask]].astype(np.float32)
                offsb[b][loc, sl] = offs[rows_g[mask]]
                wtsb[b][loc, sl] = (wts[rows_g[mask]]
                                    * group_scale_perm[ent[mask]]
                                    ).astype(np.float32)
                rids[b][loc, sl] = rows_g[mask].astype(np.int32)
        p = np.flatnonzero(row_passive[lo:hi])
        if len(p):
            rows_g = (lo + p).astype(np.int64)
            pp = ppos[rows_g]
            ent_p = row_ent[lo:hi][p]
            _fill_feature_rows(
                mat_c[p], p_X, pp,
                projectors, random_projector,
                table_ent=ent_p, global_ent=ent_p,
                raw_indices=None if projectors is None
                else projectors.raw_indices)
            p_ent[pp] = ent_p.astype(np.int32)
            p_rows[pp] = rows_g.astype(np.int32)
            p_off[pp] = offs[rows_g]
        lo = hi

    host_blocks = blocks_dir is not None or keep_host_blocks
    buckets = []
    for b in range(len(bucket_sizes)):
        if host_blocks and hasattr(Xs[b], "flush"):
            Xs[b].flush()
        buckets.append(EntityBucket(
            entity_start=int(b_starts[b]), num_real=int(bucket_sizes[b]),
            X=Xs[b] if host_blocks else jnp.asarray(Xs[b], dtype),
            labels=labs[b] if host_blocks else jnp.asarray(labs[b]),
            base_offsets=offsb[b] if host_blocks else jnp.asarray(offsb[b]),
            weights=wtsb[b] if host_blocks else jnp.asarray(wtsb[b]),
            row_ids=rids[b] if host_blocks else jnp.asarray(rids[b]),
            local_entity_offset=int(slice_lo[b]),
        ))
    if p_X is not None and host_blocks and hasattr(p_X, "flush"):
        p_X.flush()
    return RandomEffectDataset(
        config=config,
        entity_codes=ent_codes,
        X=None, labels=None, base_offsets=None, weights=None, row_ids=None,
        num_samples=n,
        projectors=projectors,
        random_projector=random_projector,
        passive_X=(None if p_X is None
                   else (p_X if host_blocks else jnp.asarray(p_X, dtype))),
        passive_entity=(None if p_X is None
                        else (p_ent if host_blocks else jnp.asarray(p_ent))),
        passive_row_ids=(None if p_X is None
                         else (p_rows if host_blocks else jnp.asarray(p_rows))),
        passive_offsets=(None if p_X is None
                         else (p_off if host_blocks else jnp.asarray(p_off))),
        buckets=buckets,
        _reduced_dim=d_red,
    )


def dataset_row_stream(data: GameDataset, config:
                       RandomEffectDataConfiguration,
                       chunk_rows: int = 500_000):
    """Stream factory over an in-RAM GameDataset (row chunks) — lets the
    streamed/memmap builder run on datasets that already fit in RAM, and
    defines the part contract for loaders that stream from disk."""
    id_type = config.random_effect_type
    if id_type not in data.id_columns:
        raise KeyError(f"id type {id_type!r} not in dataset (have "
                       f"{list(data.id_columns)})")

    def factory():
        mat = data.feature_shards[config.feature_shard_id].tocsr()
        codes = np.asarray(data.id_columns[id_type])
        for lo in range(0, data.num_samples, chunk_rows):
            hi = min(lo + chunk_rows, data.num_samples)
            yield (mat[lo:hi], codes[lo:hi], data.responses[lo:hi],
                   data.offsets[lo:hi], data.weights[lo:hi])

    return factory
