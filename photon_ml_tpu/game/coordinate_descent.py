"""Coordinate descent: the GAME outer loop.

TPU-native re-design of the reference's CoordinateDescent
(reference: photon-ml/src/main/scala/com/linkedin/photon/ml/algorithm/
CoordinateDescent.scala:50-263): initialize per-coordinate models and score
vectors; per (iteration, coordinate in updating sequence) — sum the *other*
coordinates' scores and inject them as offsets (:143-151), re-optimize the
coordinate, re-score it, log the global objective
``trainingLossEvaluator(Σ scores) + Σ regularization`` (:199-205), optionally
evaluate on validation data and keep the best full model by the first
validation evaluator (:245-255).

The reference's per-step RDD joins/unpersists become array adds and gathers;
all score vectors are sample-major ``[N]`` device arrays.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Callable, Optional

import numpy as np

import jax.numpy as jnp

from photon_ml_tpu.game.coordinate import Coordinate, Tracker
from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.game.models import GameModel
from photon_ml_tpu.ops.losses import get_loss
from photon_ml_tpu.optimize.config import TASK_LOSS_NAME, TaskType
from photon_ml_tpu.utils.events import (
    EventEmitter,
    FaultEvent,
    RecoveryEvent,
)
from photon_ml_tpu.utils.faults import InjectedFault, fault_point

Array = jnp.ndarray


class CoordinateDivergenceError(RuntimeError):
    """A coordinate update produced a non-finite state or objective."""


@dataclasses.dataclass(frozen=True)
class RecoveryPolicy:
    """What to do when a coordinate update diverges (non-finite state or
    objective) or raises an injected fault.

    The reference never needed this — Spark re-ran lost lineage for free
    but had no answer to numeric divergence either (SURVEY §5.4); here
    both are handled by one policy:

    - retry the update up to ``max_retries`` times from the last-good
      state, damping the accepted step by ``damping**attempt``. Damping
      rescues transient faults and finite-state overflows (an Inf
      objective from an over-long step); a DETERMINISTIC NaN solve will
      reproduce itself and exhaust the retries — the skip/abort action
      below is what bounds that cost;
    - when retries are exhausted, either ``skip`` the coordinate for this
      sweep (keep the last-good state, continue degraded) or ``abort``;
    - abort anyway after ``max_consecutive_failures`` consecutive skipped
      updates — a run that skips every sweep is not making progress.
    """

    max_retries: int = 2
    on_exhausted: str = "abort"  # "skip" | "abort"
    damping: float = 0.5
    max_consecutive_failures: int = 3

    def __post_init__(self):
        if self.on_exhausted not in ("skip", "abort"):
            raise ValueError(
                f"on_exhausted must be 'skip' or 'abort', "
                f"got {self.on_exhausted!r}")


def _state_leaves(state):
    return state if isinstance(state, tuple) else (state,)


def _state_is_finite(state) -> bool:
    # device-side reduction: one scalar comes back per leaf instead of a
    # full state copy (per-entity matrices can be millions of rows)
    return all(bool(jnp.all(jnp.isfinite(jnp.asarray(leaf))))
               for leaf in _state_leaves(state))


def _damp_toward(good, candidate, factor: float):
    """last_good + factor * (candidate - last_good), leaf-wise."""
    def blend(g, c):
        return g + factor * (jnp.asarray(c) - g)
    if isinstance(candidate, tuple):
        return tuple(blend(g, c) for g, c in zip(good, candidate))
    return blend(jnp.asarray(good), candidate)


def training_loss_evaluator(task: TaskType, labels: Array, weights: Array,
                            offsets: Array) -> Callable[[Array], float]:
    """Σ_i w_i l(score_i + offset_i, y_i) over the training data
    (prepareTrainingLossEvaluator, cli/game/training/Driver.scala:191)."""
    loss = get_loss(TASK_LOSS_NAME[task])

    def evaluate(scores: Array) -> float:
        l, _ = loss.loss_and_d1(scores + offsets, labels)
        return float(jnp.sum(weights * l))

    return evaluate


@dataclasses.dataclass
class CoordinateDescentState:
    """Per-iteration record (OptimizationStatesTracker + CD logging analog)."""

    iteration: int
    coordinate_id: str
    objective: float
    seconds: float
    tracker: Tracker
    validation_metrics: Optional[dict[str, float]] = None


@dataclasses.dataclass
class CoordinateDescentResult:
    model: GameModel
    states: list[CoordinateDescentState]
    best_model: Optional[GameModel] = None
    best_metric: Optional[float] = None


def run_coordinate_descent(
    coordinates: dict[str, Coordinate],
    num_iterations: int,
    task: TaskType,
    labels: Array,
    weights: Array,
    offsets: Array,
    validation_data: Optional[GameDataset] = None,
    validation_evaluator: Optional[Callable[[Array], dict[str, float]]] = None,
    validation_metric: Optional[str] = None,
    higher_is_better: bool = True,
    initial_states: Optional[dict] = None,
    logger: Optional[Callable[[str], None]] = None,
    checkpoint_manager=None,
    start_iteration: int = 0,
    initial_best: Optional[tuple] = None,
    recovery: Optional[RecoveryPolicy] = None,
    events: Optional[EventEmitter] = None,
) -> CoordinateDescentResult:
    """Run GAME coordinate descent over ``coordinates`` in dict order.

    ``coordinates`` iteration order IS the updating sequence
    (cli/game/training/Params updatingSequence). ``labels/weights/offsets``
    describe the training samples (sample-major). Single-coordinate runs skip
    the partial-score machinery exactly like CoordinateDescent.scala:82-120's
    special case.

    With a :class:`RecoveryPolicy`, every coordinate update is guarded for
    non-finite states/objectives and injected faults; detected faults emit
    :class:`FaultEvent`/:class:`RecoveryEvent` on ``events`` and follow the
    policy (retry damped / skip degraded / abort). Without one, behavior
    is the legacy fail-through (a NaN propagates to the caller).
    """
    log = logger or (lambda s: None)
    emit = events.send_event if events is not None else (lambda e: None)
    ids = list(coordinates)
    n = {cid: coordinates[cid].num_samples for cid in ids}
    num_samples = next(iter(n.values()))
    assert all(v == num_samples for v in n.values()), \
        "all coordinates must cover the same sample axis"

    loss_eval = training_loss_evaluator(task, labels, weights, offsets)

    # Init: zero states, zero scores (CoordinateDescent.scala:93-101).
    states = dict(initial_states or {})
    resumed = set(states)
    for cid in ids:
        if cid not in states:
            states[cid] = coordinates[cid].initial_state()
    # Restored coordinates must contribute their scores from the start —
    # zeros would make the first resumed sweep optimize against offsets
    # that pretend the other coordinates' models don't exist.
    scores = {cid: (coordinates[cid].score(states[cid])
                    if cid in resumed else jnp.zeros(num_samples))
              for cid in ids}
    total = jnp.zeros(num_samples)
    for cid in ids:
        total = total + scores[cid]

    history: list[CoordinateDescentState] = []
    best_model = None
    best_metric = None
    best_states = None
    if initial_best is not None:
        best_metric, restored_states = initial_best
        best_states = dict(restored_states)
        best_model = publish_game_model(coordinates, best_states)

    def attempt_update(cid, it, attempt):
        """One (possibly damped) coordinate update from last-good state;
        raises CoordinateDivergenceError on a non-finite result."""
        coord = coordinates[cid]
        partial = total - scores[cid]  # Σ other coordinates (:143-151)
        cand, tracker = coord.update(states[cid], partial)
        cand = fault_point("cd.update", arrays=cand)
        if attempt > 0:
            cand = _damp_toward(states[cid], cand,
                                recovery.damping ** attempt)
        new_score = coord.score(cand)
        new_total = partial + new_score
        reg = sum(coordinates[c].regularization_value(states[c])
                  for c in ids if c != cid)
        reg += coord.regularization_value(cand)
        objective = loss_eval(new_total) + reg  # (:199-205)
        if recovery is not None and (
                not math.isfinite(objective) or not _state_is_finite(cand)):
            raise CoordinateDivergenceError(
                f"iter {it} coordinate {cid}: non-finite "
                f"{'objective' if not math.isfinite(objective) else 'state'}"
                f" (attempt {attempt})")
        return cand, tracker, new_score, new_total, objective

    consecutive_failures = 0
    for it in range(start_iteration, num_iterations):
        for cid in ids:
            t0 = time.time()
            attempt = 0
            skipped = False
            while True:
                try:
                    (cand, tracker, new_score, new_total,
                     objective) = attempt_update(cid, it, attempt)
                    break
                except (InjectedFault, CoordinateDivergenceError,
                        FloatingPointError) as e:
                    if recovery is None:
                        raise
                    # an InjectedFault knows its origin site (e.g.
                    # "optimizer.gradient"); label divergence detected
                    # here as cd.update
                    emit(FaultEvent(point=getattr(e, "point", "cd.update"),
                                    coordinate_id=cid,
                                    iteration=it, message=str(e)))
                    log(f"iter {it} coordinate {cid}: FAULT "
                        f"(attempt {attempt}): {e}")
                    attempt += 1
                    if attempt <= recovery.max_retries:
                        emit(RecoveryEvent(action="retried",
                                           coordinate_id=cid, iteration=it,
                                           attempts=attempt))
                        continue
                    if recovery.on_exhausted == "skip":
                        skipped = True
                        break
                    raise RuntimeError(
                        f"coordinate descent aborted: coordinate {cid} "
                        f"failed {attempt} attempt(s) at iteration {it} "
                        f"(RecoveryPolicy on_exhausted='abort')") from e
            dt = time.time() - t0
            if skipped:
                # Keep the last-good state and its score; continue degraded
                # (the reference's closest analog: a failed Spark stage
                # retried elsewhere — here the coordinate just sits out).
                consecutive_failures += 1
                emit(RecoveryEvent(action="skipped", coordinate_id=cid,
                                   iteration=it, attempts=attempt))
                log(f"iter {it} coordinate {cid}: SKIPPED after "
                    f"{attempt} failed attempt(s) — keeping last-good "
                    f"state ({dt:.2f}s)")
                if consecutive_failures >= recovery.max_consecutive_failures:
                    emit(RecoveryEvent(action="aborted", coordinate_id=cid,
                                       iteration=it, attempts=attempt))
                    raise RuntimeError(
                        f"coordinate descent aborted: "
                        f"{consecutive_failures} consecutive coordinate "
                        f"updates failed (RecoveryPolicy "
                        f"max_consecutive_failures="
                        f"{recovery.max_consecutive_failures})")
                continue
            if attempt > 0:
                emit(RecoveryEvent(action="recovered", coordinate_id=cid,
                                   iteration=it, attempts=attempt))
                log(f"iter {it} coordinate {cid}: recovered after "
                    f"{attempt} retry(ies)")
            consecutive_failures = 0
            states[cid] = cand
            total = new_total
            scores[cid] = new_score
            log(f"iter {it} coordinate {cid}: objective={objective:.6f} "
                f"({dt:.2f}s) — {tracker.summary()}")

            metrics = None
            if validation_data is not None and validation_evaluator:
                model = publish_game_model(coordinates, states)
                val_scores = model.score(validation_data)
                metrics = validation_evaluator(val_scores)
                log(f"iter {it} coordinate {cid}: validation {metrics}")
                if validation_metric is not None:
                    m = metrics[validation_metric]
                    better = (best_metric is None
                              or (m > best_metric if higher_is_better
                                  else m < best_metric))
                    if better:  # (:245-255)
                        best_metric, best_model = m, model
                        best_states = dict(states)

            history.append(CoordinateDescentState(
                iteration=it, coordinate_id=cid, objective=objective,
                seconds=dt, tracker=tracker, validation_metrics=metrics))

        if checkpoint_manager is not None:
            def _np_states(d):
                return {
                    cid: (tuple(np.asarray(s) for s in d[cid])
                          if isinstance(d[cid], tuple)
                          else np.asarray(d[cid]))
                    for cid in d}

            checkpoint_manager.save(it + 1, {
                "iteration": it + 1,
                "states": _np_states(states),
                "best_metric": (None if best_metric is None
                                else float(best_metric)),
                "best_states": (None if best_states is None
                                else _np_states(best_states)),
            })

    final = publish_game_model(coordinates, states)
    return CoordinateDescentResult(model=final, states=history,
                                   best_model=best_model,
                                   best_metric=best_metric)


def publish_game_model(coordinates: dict[str, Coordinate], states: dict
                       ) -> GameModel:
    return GameModel({cid: coordinates[cid].publish(states[cid])
                      for cid in coordinates})
