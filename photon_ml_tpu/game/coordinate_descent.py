"""Coordinate descent: the GAME outer loop.

TPU-native re-design of the reference's CoordinateDescent
(reference: photon-ml/src/main/scala/com/linkedin/photon/ml/algorithm/
CoordinateDescent.scala:50-263): initialize per-coordinate models and score
vectors; per (iteration, coordinate in updating sequence) — sum the *other*
coordinates' scores and inject them as offsets (:143-151), re-optimize the
coordinate, re-score it, log the global objective
``trainingLossEvaluator(Σ scores) + Σ regularization`` (:199-205), optionally
evaluate on validation data and keep the best full model by the first
validation evaluator (:245-255).

The reference's per-step RDD joins/unpersists become array adds and gathers;
all score vectors are sample-major ``[N]`` device arrays.

Hot-loop sync discipline: one coordinate update costs AT MOST one device
round-trip. The update, its score, the changed coordinate's regularization
scalar, and the fused epilogue (:func:`make_update_epilogue`) dispatch
asynchronously; the single blocking read is a ``jax.device_get`` of the
epilogue's small scalar pytree. Everything sample-sized — the canonical
score total included — stays device-resident between updates, and the
per-coordinate trackers/optimizer histories materialize lazily at
log/metrics/checkpoint time. ``tests/test_sync_discipline.py`` enforces
this under ``jax.transfer_guard("disallow")``.

Two sweep-level optimizations attack the dispatch critical path that the
one-fetch-per-update work exposed:

- **Double-buffered updates** (``pipeline_depth=1``, the default): the
  next coordinate's solve is DISPATCHED against the previous epilogue's
  device-resident outputs (its corrected total and new score — the very
  arrays the previous commit will install) before the previous fetch
  blocks, so host dispatch work overlaps device compute. The committed
  floats are bit-identical to the sequential sweep — only host ordering
  changes — and the recovery/quarantine ladder tolerates acting one
  update late: a divergence discovered at fetch time rolls the
  speculative dispatch back (RNG stream positions included) and replays
  from last-good state.
- **Block-parallel sweeps** (``block_size=B``): B coordinates solve
  concurrently against the SAME stale score total, then ONE fused
  correction epilogue re-canonicalizes the ids-order total with all B
  new scores substituted — one fetch per block (1/B amortized
  syncs/update). Block updates use stale partial scores, so trajectories
  match the sequential sweep within tolerance, not bitwise; block
  boundaries are commit barriers, so checkpoint bit-exactness and
  ``tools/crash_resume_drill.py`` semantics are preserved (a snapshot
  never lands mid-block).

The pipeline-depth discipline (an epilogue fetch is consumed at most ONE
dispatch later) is structural: photonlint W105 flags a deferred handle
that survives two dispatches.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.game.coordinate import Coordinate, Tracker
from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.game.models import GameModel
from photon_ml_tpu.ops.losses import get_loss
from photon_ml_tpu.optimize.config import TASK_LOSS_NAME, TaskType
from photon_ml_tpu.obs import compile as obs_compile
from photon_ml_tpu.obs import devicemem, trace
from photon_ml_tpu.obs.metrics import REGISTRY
from photon_ml_tpu.utils.events import (
    CoordinateQuarantinedEvent,
    EventEmitter,
    FaultEvent,
    RecoveryEvent,
)
from photon_ml_tpu.utils.faults import InjectedFault, fault_point
from photon_ml_tpu.utils.preempt import PreemptionRequested
from photon_ml_tpu.utils.sync_telemetry import record_host_fetch

Array = jnp.ndarray


class CoordinateDivergenceError(RuntimeError):
    """A coordinate update produced a non-finite state or objective."""


# Hot-loop sync telemetry for bench.py / the transfer-guard test: the
# one-round-trip contract says every non-validation coordinate update
# performs AT MOST ONE blocking device→host fetch (the fused epilogue's
# small scalar pytree; a block of B updates shares ONE fetch, so the
# amortized rate is 1/B). ``update_dispatch_secs`` is host time spent
# dispatching the update + epilogue (async), ``epilogue_wait_secs`` the
# blocking wait inside the single fetch. The pipelining keys:
# ``max_inflight`` is the most dispatched-but-unfetched updates alive at
# once (2 with double-buffering at block size 1), ``pipelined_resolves``
# counts fetches that happened AFTER a later dispatch had already been
# issued, and ``overlap_secs`` is the host time that elapsed between a
# block's dispatch completing and its fetch starting — work the host did
# while the device was still computing, i.e. the hidden dispatch cost.
HOT_LOOP_STATS = {"updates": 0, "epilogue_fetches": 0,
                  "update_dispatch_secs": 0.0, "epilogue_wait_secs": 0.0,
                  "max_inflight": 0, "pipelined_resolves": 0,
                  "overlap_secs": 0.0}


def reset_hot_loop_stats() -> None:
    HOT_LOOP_STATS.update({"updates": 0, "epilogue_fetches": 0,
                           "update_dispatch_secs": 0.0,
                           "epilogue_wait_secs": 0.0,
                           "max_inflight": 0, "pipelined_resolves": 0,
                           "overlap_secs": 0.0})


def _sample_live_bytes(sweep: int) -> None:
    """Sample Σ nbytes over ``jax.live_arrays()`` into the
    ``hbm_live_bytes`` gauge and a ``cd.hbm_sample`` span at the
    sweep-boundary drain, so pipeline depth and the drain policy can be
    tuned from a trace (are deferred buffers accumulating between
    drains?). Metadata-only — enumerating live arrays never syncs the
    device — and skipped entirely when tracing is off (the enumeration
    is O(#arrays) host work that the untraced hot path must not pay)."""
    if trace.get_tracer() is None:
        return
    try:
        total_bytes = sum(int(getattr(a, "nbytes", 0) or 0)
                          for a in jax.live_arrays())
    except Exception:  # pragma: no cover - backend without live_arrays
        return
    REGISTRY.gauge("hbm_live_bytes").set(total_bytes, site="cd.sweep_drain")
    # mesh-sharded runs: attribute live bytes to each DEVICE holding a
    # shard (addressable_shards metadata — still no device sync), so a
    # lopsided entity partition shows up as a lopsided per-shard gauge
    try:
        per_device: dict = {}
        for a in jax.live_arrays():
            shards = getattr(a, "addressable_shards", None) or []
            if len(shards) > 1:
                for s in shards:
                    d = s.device.id
                    per_device[d] = (per_device.get(d, 0)
                                     + int(getattr(s.data, "nbytes", 0)
                                           or 0))
        for d, b in sorted(per_device.items()):
            REGISTRY.gauge("re_shard_hbm_live_bytes").set(b, shard=str(d))
    except Exception:  # pragma: no cover - backend without shard metadata
        pass
    with trace.span("cd.hbm_sample", sweep=sweep, live_bytes=total_bytes):
        pass
    # --device-telemetry: attribute the sweep's per-coordinate commit
    # watermarks at the same boundary (no-op unless armed)
    devicemem.drain_coordinate_watermarks(sweep)


@dataclasses.dataclass
class _InFlight:
    """One dispatched-but-unfetched block of coordinate updates: the
    fused epilogue's device handles plus everything the host needs to
    commit the block at fetch time — or discard it (``update_counts_
    before`` restores the RNG stream positions ``coord.update`` advanced,
    so a rolled-back speculative dispatch leaves no trace in a
    down-sampling coordinate's key sequence)."""

    it: int
    block: list  # [(ci, cid), ...] in dispatch order
    attempt: int
    cands: dict
    trackers: dict
    new_scores: dict
    new_regs: dict
    new_total: object  # device [N]: the re-canonicalized score total
    objective_d: object
    train_loss_d: object
    finite_d: object
    state_finite_d: object
    update_counts_before: dict
    snapshot_due: bool
    # resume point of the enclosing RAW block ("about to run this
    # coordinate"): quarantine-filtered members still count toward the
    # boundary, or a resumed run would re-partition the sweep's blocks
    snapshot_next_ci: int
    t_wall: float
    t_dispatched: float
    pipelined: bool = False  # a later dispatch was issued before this fetch


def _canonical_sum(score_list, num_samples: int):
    """Σ scores in updating-sequence order from zero — the ONE summation
    order used everywhere (init, resume, and INSIDE the fused epilogue), so
    a resumed run reproduces the uninterrupted run's floats exactly."""
    t = jnp.zeros(num_samples)
    for s in score_list:
        t = t + s
    return t


@functools.lru_cache(maxsize=32)
def _canonical_total_jit(num_samples: int):
    """Jitted canonical summation, cached per sample count so repeated
    runs (and the warm bench pass) reuse the executable."""
    return jax.jit(lambda score_list: _canonical_sum(score_list,
                                                     num_samples))


@functools.lru_cache(maxsize=32)
def make_update_epilogue(task: TaskType, num_samples: int):
    """Build the fused, jitted update epilogue (cached per task/sample
    count: repeated runs share one compiled executable per shape).

    One compiled call computes everything the host needs after a candidate
    coordinate update, replacing what used to be O(K) blocking syncs per
    update (a ``float()`` per coordinate's regularization term, a
    ``bool()`` per state leaf for the finiteness guard, a ``float()`` for
    the objective) with a single device program whose small outputs are
    fetched as ONE pytree:

    - the canonical ids-order score total (kept ON DEVICE — it feeds the
      next update's partial-score offsets without a round-trip); summation
      order is preserved inside the fused op so crash/resume stays
      bit-exact,
    - the training loss Σᵢ wᵢ·l(totalᵢ + offsetᵢ, yᵢ) (:199-205),
    - Σ regularization from the per-coordinate reg-scalar cache (updated
      only for the changed coordinate, summed in ids order),
    - the global objective (training loss + Σ reg),
    - one all-leaves finiteness flag over the candidate state + objective.

    ``score_list``/``reg_list`` arrive in updating-sequence order with the
    changed coordinates' entries already substituted — ONE changed entry
    for a sequential update, B entries for a block-parallel update (the
    canonical re-summation then IS the block's staleness-correction step:
    every member solved against the stale block-start total, and this op
    rebuilds the ids-order total with all members' new scores in one
    fused program). ``state_leaves`` concatenates every changed
    coordinate's state leaves, so the finiteness flag covers the whole
    block.
    """
    # this body runs only on an lru_cache MISS — i.e. a new (task, N)
    # shape is about to pay an XLA compile; the counter makes retrace
    # regressions visible in metrics.jsonl and the bench record
    REGISTRY.counter("retraces").inc(site="cd.epilogue")
    loss = get_loss(TASK_LOSS_NAME[task])

    @jax.jit
    def epilogue(score_list, reg_list, state_leaves, labels, weights,
                 offsets):
        total = _canonical_sum(score_list, num_samples)
        l, _ = loss.loss_and_d1(total + offsets, labels)
        train_loss = jnp.sum(weights * l)
        reg_total = 0.0
        for r in reg_list:  # ids order (python floats stay op-free)
            reg_total = reg_total + r
        objective = train_loss + reg_total
        state_finite = jnp.asarray(True)
        for leaf in state_leaves:
            state_finite = state_finite & jnp.all(jnp.isfinite(leaf))
        finite = state_finite & jnp.isfinite(objective)
        return total, objective, train_loss, reg_total, finite, state_finite

    return epilogue


@dataclasses.dataclass(frozen=True)
class RecoveryPolicy:
    """What to do when a coordinate update diverges (non-finite state or
    objective) or raises an injected fault.

    The reference never needed this — Spark re-ran lost lineage for free
    but had no answer to numeric divergence either (SURVEY §5.4); here
    both are handled by one policy:

    - retry the update up to ``max_retries`` times from the last-good
      state, damping the accepted step by ``damping**attempt``. Damping
      rescues transient faults and finite-state overflows (an Inf
      objective from an over-long step); a DETERMINISTIC NaN solve will
      reproduce itself and exhaust the retries — the skip/abort action
      below is what bounds that cost;
    - when retries are exhausted, either ``skip`` the coordinate for this
      sweep (keep the last-good state, continue degraded) or ``abort``;
    - abort anyway after ``max_consecutive_failures`` consecutive skipped
      updates — a run that skips every sweep is not making progress.

    ``quarantine_after`` adds a PER-COORDINATE failure budget on top of
    the global policy: when > 0, a coordinate whose retries exhaust is
    skipped for the sweep (degraded, regardless of ``on_exhausted``)
    until it has accumulated ``quarantine_after`` exhausted updates
    across the run, at which point it is QUARANTINED — frozen at its
    last-good state, announced with a
    :class:`~photon_ml_tpu.utils.events.CoordinateQuarantinedEvent`, and
    excluded from further sweeps while the rest of the descent continues.
    One chronically-diverging coordinate then costs its own bounded
    budget instead of burning the global retry/consecutive-failure
    budgets or aborting the whole run.

    Under double-buffering the policy acts ONE UPDATE LATE: a divergence
    surfaces at the fetch, after the next update has already been
    dispatched against the diverged outputs. The ladder then rolls the
    speculative dispatch back (its device work is never fetched, its RNG
    stream positions are restored) and re-runs it from the re-committed
    last-good state, so every retry/skip/quarantine decision is made
    against exactly the states the sequential sweep would have used.
    """

    max_retries: int = 2
    on_exhausted: str = "abort"  # "skip" | "abort"
    damping: float = 0.5
    max_consecutive_failures: int = 3
    quarantine_after: int = 0  # 0 = per-coordinate budget disabled

    def __post_init__(self):
        if self.on_exhausted not in ("skip", "abort"):
            raise ValueError(
                f"on_exhausted must be 'skip' or 'abort', "
                f"got {self.on_exhausted!r}")
        if self.quarantine_after < 0:
            raise ValueError(
                f"quarantine_after must be >= 0, "
                f"got {self.quarantine_after}")


def _state_leaves(state):
    return state if isinstance(state, tuple) else (state,)


def _state_is_finite(state) -> bool:
    # device-side reduction: one scalar comes back per leaf instead of a
    # full state copy (per-entity matrices can be millions of rows);
    # all leaves' flags return in a single instrumented fetch
    flags = jax.device_get(tuple(
        jnp.all(jnp.isfinite(jnp.asarray(leaf)))
        for leaf in _state_leaves(state)))
    record_host_fetch(site="cd.state_finite")
    return all(bool(f) for f in flags)


def _damp_toward(good, candidate, factor: float):
    """last_good + factor * (candidate - last_good), leaf-wise."""
    def blend(g, c):
        return g + factor * (jnp.asarray(c) - g)
    if isinstance(candidate, tuple):
        return tuple(blend(g, c) for g, c in zip(good, candidate))
    return blend(jnp.asarray(good), candidate)


def training_loss_evaluator(task: TaskType, labels: Array, weights: Array,
                            offsets: Array) -> Callable[[Array], float]:
    """Σ_i w_i l(score_i + offset_i, y_i) over the training data
    (prepareTrainingLossEvaluator, cli/game/training/Driver.scala:191)."""
    loss = get_loss(TASK_LOSS_NAME[task])

    def evaluate(scores: Array) -> float:
        l, _ = loss.loss_and_d1(scores + offsets, labels)
        value = jax.device_get(jnp.sum(weights * l))
        record_host_fetch(site="cd.training_loss")
        return float(value)

    return evaluate


@dataclasses.dataclass
class CoordinateDescentState:
    """Per-iteration record (OptimizationStatesTracker + CD logging analog)."""

    iteration: int
    coordinate_id: str
    objective: float
    seconds: float
    tracker: Tracker
    validation_metrics: Optional[dict[str, float]] = None


@dataclasses.dataclass
class CoordinateDescentResult:
    model: GameModel
    states: list[CoordinateDescentState]
    best_model: Optional[GameModel] = None
    best_metric: Optional[float] = None
    # Coordinates frozen at last-good state by the per-coordinate failure
    # budget (RecoveryPolicy.quarantine_after) — surfaced in the driver
    # summary and metrics.json.
    quarantined: list[str] = dataclasses.field(default_factory=list)


def _to_jnp_states(d: dict) -> dict:
    return {cid: (tuple(jnp.asarray(s) for s in v)
                  if isinstance(v, tuple) else jnp.asarray(v))
            for cid, v in d.items()}


def _checkpoint_save_contained(manager, step: int, snapshot: dict,
                               log, emit) -> bool:
    """Save a snapshot, CONTAINING a persistently-unwritable disk
    (CheckpointWriteError after the write-side retries): training state
    is intact and the next cadence point tries again, so a full
    checkpoint volume degrades durability instead of killing a
    multi-hour run. The failure is logged, counted
    (``ckpt_save_failures``), and announced as a FaultEvent."""
    from photon_ml_tpu.utils.checkpoint import CheckpointWriteError

    try:
        manager.save(step, snapshot)
        return True
    except CheckpointWriteError as e:
        REGISTRY.counter("ckpt_save_failures").inc()
        emit(FaultEvent(point="ckpt.write_bytes", message=str(e)))
        log(lambda: f"checkpoint step {step} NOT saved (degraded, "
            f"training continues): {e}")
        return False


def run_coordinate_descent(
    coordinates: dict[str, Coordinate],
    num_iterations: int,
    task: TaskType,
    labels: Array,
    weights: Array,
    offsets: Array,
    validation_data: Optional[GameDataset] = None,
    validation_evaluator: Optional[Callable[[Array], dict[str, float]]] = None,
    validation_metric: Optional[str] = None,
    higher_is_better: bool = True,
    initial_states: Optional[dict] = None,
    logger: Optional[Callable[[str], None]] = None,
    checkpoint_manager=None,
    start_iteration: int = 0,
    initial_best: Optional[tuple] = None,
    recovery: Optional[RecoveryPolicy] = None,
    events: Optional[EventEmitter] = None,
    checkpoint_every_coordinates: int = 0,
    start_coordinate: int = 0,
    resume_snapshot: Optional[dict] = None,
    block_size: int = 1,
    pipeline_depth: int = 1,
    stop=None,
) -> CoordinateDescentResult:
    """Run GAME coordinate descent over ``coordinates`` in dict order.

    ``coordinates`` iteration order IS the updating sequence
    (cli/game/training/Params updatingSequence). ``labels/weights/offsets``
    describe the training samples (sample-major). Single-coordinate runs skip
    the partial-score machinery exactly like CoordinateDescent.scala:82-120's
    special case.

    With a :class:`RecoveryPolicy`, every coordinate update is guarded for
    non-finite states/objectives and injected faults; detected faults emit
    :class:`FaultEvent`/:class:`RecoveryEvent` on ``events`` and follow the
    policy (retry damped / skip degraded / abort, plus per-coordinate
    quarantine when ``quarantine_after`` is set). Without one, behavior
    is the legacy fail-through (a NaN propagates to the caller).

    ``pipeline_depth=1`` (the default) DOUBLE-BUFFERS the sweep: the next
    block's solve dispatches against the previous epilogue's
    device-resident outputs before the previous fetch blocks, overlapping
    host dispatch with device compute. The committed floats are
    bit-identical to ``pipeline_depth=0`` (the epilogue consumes the same
    device arrays either way); a divergence discovered at the late fetch
    rolls the speculative dispatch back and replays it from last-good
    state. Depth > 1 is refused — an epilogue fetch must never age more
    than one dispatch (photonlint W105's structural contract).
    Pipelining turns itself off when a validation evaluator runs per
    update (validation needs the committed model) and pauses across
    checkpoint-cadence points (a snapshot is a commit barrier).

    ``block_size=B`` partitions each sweep into disjoint blocks of B
    coordinates solved CONCURRENTLY against the stale block-start score
    total, followed by one fused correction epilogue that
    re-canonicalizes the ids-order total with all B new scores — one
    fetch per block. Trajectories match the sequential sweep within
    tolerance (stale partials), and block boundaries are commit/snapshot
    barriers so crash→resume stays bit-exact for a given block size.
    B=1 is exactly today's sequential semantics.

    Checkpointing: with a ``checkpoint_manager`` a snapshot lands after
    every completed sweep, and — when ``checkpoint_every_coordinates``
    = N > 0 — additionally after every Nth coordinate update, so a crash
    inside a long sweep replays at most N updates instead of the whole
    sweep (with blocks, at the enclosing block boundary). A snapshot
    carries everything a BIT-EXACT resume needs: ``(sweep,
    coordinate_index, per-coordinate states AND scores, RNG stream
    positions, recovery counters, the quarantine set, the running
    best)``. Resume by passing the restored dict as ``resume_snapshot``
    (preferred — it repopulates all of the above; the legacy
    ``initial_states``/``start_iteration``/``initial_best`` trio still
    works for sweep-boundary snapshots). The score total is recomputed
    canonically (ids order, from zero) after every update rather than
    maintained incrementally, so a resumed run sees float-identical
    partial scores to the uninterrupted one.

    Graceful stop: ``stop`` is any object with a ``should_stop() ->
    str | None`` method (a :class:`~photon_ml_tpu.utils.preempt.
    StopController` in the drivers). It is polled ONLY at raw block
    boundaries — the existing commit/snapshot barriers — so a stop can
    never tear a block or race the pipeline. When it returns a reason,
    the in-flight pipelined handle is resolved first (the same settle-
    before-snapshot rule the checkpoint barrier follows), a final
    snapshot lands at the barrier (when checkpointing is on), and
    :class:`~photon_ml_tpu.utils.preempt.PreemptionRequested` is raised
    carrying the exact resume position. Resuming from that snapshot is
    bit-exact vs the uninterrupted run, exactly like crash resume.
    """
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    if pipeline_depth not in (0, 1):
        raise ValueError(
            f"pipeline_depth must be 0 (sequential) or 1 (double-"
            f"buffered), got {pipeline_depth}: a deeper pipeline would "
            f"let an epilogue fetch age more than one dispatch "
            f"(photonlint W105's structural contract)")

    def log(fn: Callable[[], str]):
        # Lazy formatting: log lines materialize lazy trackers (a device
        # fetch), so a run without a logger must never even BUILD them.
        if logger is not None:
            logger(fn())

    emit = events.send_event if events is not None else (lambda e: None)
    ids = list(coordinates)
    n = {cid: coordinates[cid].num_samples for cid in ids}
    num_samples = next(iter(n.values()))
    assert all(v == num_samples for v in n.values()), \
        "all coordinates must cover the same sample axis"

    epilogue = make_update_epilogue(task, num_samples)
    # The canonical total is computed by the SAME jitted summation the
    # epilogue runs, so the init/resume total is bit-identical to the
    # fused op's (XLA executes the identical add sequence).
    canonical_total_fn = _canonical_total_jit(num_samples)

    consecutive_failures = 0
    coordinate_failures: dict[str, int] = {}
    quarantined: set[str] = set()
    restored_scores = None
    if resume_snapshot is not None:
        snap = resume_snapshot
        initial_states = _to_jnp_states(snap["states"])
        start_iteration = int(snap.get("sweep", snap.get("iteration", 0)))
        start_coordinate = int(snap.get("coordinate_index", 0))
        if snap.get("best_states") is not None:
            initial_best = (snap.get("best_metric"),
                            _to_jnp_states(snap["best_states"]))
        if snap.get("scores") is not None:
            restored_scores = {cid: jnp.asarray(v)
                               for cid, v in snap["scores"].items()}
        # RNG stream positions: a down-sampling coordinate's PRNG key is
        # seed + update count, so the counter IS the key state
        for cid, cnt in (snap.get("update_counts") or {}).items():
            if cid in coordinates and hasattr(coordinates[cid],
                                              "_update_count"):
                coordinates[cid]._update_count = int(cnt)
        consecutive_failures = int(snap.get("consecutive_failures", 0))
        coordinate_failures = {k: int(v) for k, v in
                               (snap.get("coordinate_failures")
                                or {}).items()}
        quarantined = set(snap.get("quarantined") or [])

    # Init: zero states, zero scores (CoordinateDescent.scala:93-101).
    states = dict(initial_states or {})
    resumed = set(states)
    for cid in ids:
        if cid not in states:
            states[cid] = coordinates[cid].initial_state()

    def canonical_total(score_map):
        """Σ scores in ids order from zero — the ONE summation order used
        everywhere (shared with the fused epilogue), so a resume that
        rebuilds the total from restored scores reproduces the
        uninterrupted run's floats exactly."""
        return obs_compile.call(
            "cd.canonical_total", canonical_total_fn,
            (tuple(score_map[c] for c in ids),),
            arg_names=("score_list",))

    if restored_scores is not None:
        # Mid-sweep resume: scores come back verbatim from the snapshot
        # (recomputing them from states would be wrong for coordinates
        # that have never been updated — score(initial_state) need not be
        # zero under normalization shifts).
        scores = {cid: (restored_scores[cid] if cid in restored_scores
                        else jnp.zeros(num_samples)) for cid in ids}
    else:
        # Restored coordinates must contribute their scores from the
        # start — zeros would make the first resumed sweep optimize
        # against offsets that pretend the other coordinates' models
        # don't exist.
        scores = {cid: (coordinates[cid].score(states[cid])
                        if cid in resumed else jnp.zeros(num_samples))
                  for cid in ids}
    total = canonical_total(scores)

    # Device-resident per-coordinate regularization scalar cache: the fused
    # epilogue sums these in ids order; only the CHANGED coordinates'
    # entries are recomputed per update (the old path re-evaluated all K
    # penalties with a blocking float() each — O(K²) syncs per sweep).
    # Deterministic on resume: recomputed from the restored states by the
    # same ops.
    def _reg_device(cid, state):
        coord = coordinates[cid]
        fn = getattr(coord, "regularization_value_device",
                     coord.regularization_value)
        return fn(state)

    reg_cache = {cid: _reg_device(cid, states[cid]) for cid in ids}

    history: list[CoordinateDescentState] = []
    best_model = None
    best_metric = None
    best_states = None
    if initial_best is not None:
        best_metric, restored_states = initial_best
        best_states = dict(restored_states)
        best_model = publish_game_model(coordinates, best_states)

    # Per-update validation needs the committed model after EVERY update,
    # so it forces the sequential resolve order (no overlap to exploit).
    validate = (validation_data is not None
                and validation_evaluator is not None)
    use_pipeline = pipeline_depth > 0 and not validate

    last_saved_step = None

    def save_snapshot(sweep, next_ci):
        """Persist the full resume state as of 'about to run coordinate
        ``next_ci`` of ``sweep``'; a completed sweep normalizes to the
        next sweep's coordinate 0. Step number = global update count, so
        mid-sweep and sweep-end snapshots share one monotone sequence."""
        nonlocal last_saved_step
        if next_ci >= len(ids):
            sweep, next_ci = sweep + 1, 0
        step = sweep * len(ids) + next_ci
        if step == last_saved_step:
            return
        # THE fetch point: the whole snapshot (per-coordinate states AND
        # scores, still device-resident from the hot loop) comes back in
        # one explicit jax.device_get of the payload pytree instead of a
        # per-leaf np.asarray chain.
        payload = jax.device_get({
            "states": states,
            "scores": {cid: scores[cid] for cid in ids},
            "best_states": best_states,
        })
        record_host_fetch(site="ckpt.snapshot")
        saved = _checkpoint_save_contained(checkpoint_manager, step, {
            "sweep": sweep,
            "coordinate_index": next_ci,
            # legacy field: completed sweeps (pre-mid-sweep readers)
            "iteration": sweep,
            "states": payload["states"],
            "scores": payload["scores"],
            "best_metric": (None if best_metric is None
                            else float(best_metric)),
            "best_states": payload["best_states"],
            "update_counts": {
                cid: int(getattr(coordinates[cid], "_update_count"))
                for cid in ids
                if hasattr(coordinates[cid], "_update_count")},
            "consecutive_failures": int(consecutive_failures),
            "coordinate_failures": dict(coordinate_failures),
            "quarantined": sorted(quarantined),
        }, log=log, emit=emit)
        if saved:  # a failed save retries at the next cadence point
            last_saved_step = step

    def snapshot_cadence_due(block, it):
        """Does this (raw) block cross a ``checkpoint_every_coordinates``
        cadence point? ONE definition — the success path and every
        fault-replay path must snapshot on the same schedule."""
        return (checkpoint_manager is not None
                and checkpoint_every_coordinates > 0
                and any((it * len(ids) + ci + 1)
                        % checkpoint_every_coordinates == 0
                        for ci, _ in block))

    def dispatch_update(block, it, attempt, base_total, overlay,
                        snapshot_due=False, snapshot_next_ci=0):
        """Dispatch one block of candidate updates + ONE fused epilogue
        WITHOUT blocking; returns the :class:`_InFlight` handle whose
        single device→host read happens in ``fetch_update`` — possibly
        one block later (double-buffering).

        ``base_total``/``overlay`` carry the still-uncommitted previous
        block's device outputs (its corrected total and per-coordinate
        new scores/regs), so a pipelined dispatch optimistically sees
        EXACTLY the arrays the previous commit will install — which is
        why the block-size-1 pipelined sweep is bit-identical to the
        sequential one. Block members all read ``base_total`` (the stale
        block-start total); the epilogue's canonical re-summation is the
        correction step.

        A fault raised MID-DISPATCH of a multi-member block restores
        every member's RNG stream position before propagating: the
        block replay re-runs each member as its own fresh attempt 0, so
        members dispatched before the fault must not stay advanced (a
        down-sampling coordinate would draw a different key than the
        sequential ladder's). A SINGLETON dispatch keeps its advance —
        the seeded ladder treats it as attempt 0, exactly like the
        sequential retry loop."""
        t_wall = time.time()
        t0 = time.perf_counter()
        counts_before = {
            cid: getattr(coordinates[cid], "_update_count", None)
            for _, cid in block}
        cands: dict = {}
        trackers: dict = {}
        new_scores: dict = {}
        new_regs: dict = {}
        cids = ",".join(cid for _, cid in block)
        try:
            with trace.span("cd.dispatch", sweep=it, size=len(block),
                            coordinates=cids):
                for ci, cid in block:
                    coord = coordinates[cid]
                    partial = base_total - (
                        overlay[cid][0] if cid in overlay else scores[cid]
                    )  # Σ other coordinates (:143-151)
                    cand, tracker = coord.update(states[cid], partial)
                    cand = fault_point("cd.update", tag=f"{it}.{ci}",
                                       arrays=cand)
                    if attempt > 0:
                        cand = _damp_toward(states[cid], cand,
                                            recovery.damping ** attempt)
                    cands[cid] = cand
                    trackers[cid] = tracker
                    new_scores[cid] = coord.score(cand)
                    new_regs[cid] = _reg_device(cid, cand)
                score_list = tuple(
                    new_scores[c] if c in new_scores
                    else (overlay[c][0] if c in overlay else scores[c])
                    for c in ids)
                reg_list = tuple(
                    new_regs[c] if c in new_regs
                    else (overlay[c][1] if c in overlay else reg_cache[c])
                    for c in ids)
                leaves = tuple(jnp.asarray(leaf) for _, cid in block
                               for leaf in _state_leaves(cands[cid]))
                (new_total, objective_d, train_loss_d, _reg_total_d,
                 finite_d, state_finite_d) = obs_compile.call(
                    "cd.epilogue", epilogue,
                    (score_list, reg_list, leaves, labels, weights,
                     offsets),
                    arg_names=("score_list", "reg_list", "state_leaves",
                               "labels", "weights",
                               "offsets"))  # (:199-205)
        except Exception:
            if len(block) > 1:
                for _, cid in block:
                    before = counts_before.get(cid)
                    if before is not None:
                        coordinates[cid]._update_count = before
            raise
        HOT_LOOP_STATS["update_dispatch_secs"] += time.perf_counter() - t0
        return _InFlight(
            it=it, block=list(block), attempt=attempt, cands=cands,
            trackers=trackers, new_scores=new_scores, new_regs=new_regs,
            new_total=new_total, objective_d=objective_d,
            train_loss_d=train_loss_d, finite_d=finite_d,
            state_finite_d=state_finite_d,
            update_counts_before=counts_before,
            snapshot_due=snapshot_due,
            snapshot_next_ci=snapshot_next_ci,
            t_wall=t_wall, t_dispatched=time.perf_counter())

    def _set_update_counts(block, counts):
        for _, cid in block:
            v = counts.get(cid)
            if v is not None:
                coordinates[cid]._update_count = v

    def _snap_update_counts(block):
        return {cid: getattr(coordinates[cid], "_update_count", None)
                for _, cid in block}

    def rollback_update(p):
        """Discard a speculative dispatch: its device work is simply
        never fetched; the only HOST state it mutated is the
        down-sampling RNG stream position, which is restored here so the
        re-dispatch draws the keys the sequential sweep would have."""
        _set_update_counts(p.block, p.update_counts_before)

    def fetch_update(p):
        """THE blocking read: one ``jax.device_get`` of the fused
        epilogue's scalar pytree for the whole block. Raises
        :class:`CoordinateDivergenceError` (recovery mode only) when the
        block's states/objective are non-finite."""
        t0 = time.perf_counter()
        if p.pipelined:
            HOT_LOOP_STATS["pipelined_resolves"] += 1
            HOT_LOOP_STATS["overlap_secs"] += max(0.0,
                                                  t0 - p.t_dispatched)
        span_labels = {"sweep": p.it}
        if len(p.block) == 1:
            span_labels["coordinate"] = p.block[0][1]
        else:
            span_labels["coordinates"] = ",".join(
                cid for _, cid in p.block)
        with contextlib.ExitStack() as stack:
            if p.pipelined:
                # the residual wait AFTER the overlap window — the part
                # of the epilogue latency double-buffering couldn't hide
                stack.enter_context(
                    trace.span("cd.pipeline_wait", **span_labels))
            stack.enter_context(
                trace.span("cd.epilogue_fetch", **span_labels))
            objective, train_loss, finite, state_finite = jax.device_get(
                (p.objective_d, p.train_loss_d, p.finite_d,
                 p.state_finite_d))
        record_host_fetch(site="cd.epilogue")
        HOT_LOOP_STATS["epilogue_wait_secs"] += time.perf_counter() - t0
        HOT_LOOP_STATS["epilogue_fetches"] += 1
        HOT_LOOP_STATS["updates"] += len(p.block)
        objective = float(objective)
        if recovery is not None and not bool(finite):
            what = "state" if not bool(state_finite) else "objective"
            if len(p.block) == 1:
                raise CoordinateDivergenceError(
                    f"iter {p.it} coordinate {p.block[0][1]}: non-finite "
                    f"{what} (attempt {p.attempt})")
            raise CoordinateDivergenceError(
                f"iter {p.it} block "
                f"{[cid for _, cid in p.block]}: non-finite {what}")
        return objective, float(train_loss)

    def commit_update(p, objective, train_loss, seconds=None,
                      recovered_attempts=0, allow_snapshot=True):
        """Install an accepted block: states/scores/regs + the corrected
        canonical total, then the per-member bookkeeping (objective log,
        optional validation, history, checkpoint cadence).
        ``allow_snapshot=False`` defers the cadence snapshot to the
        caller — a multi-member block replaying its members one at a
        time must snapshot once at the BLOCK boundary, never after an
        individual member (a mid-block snapshot would re-partition the
        sweep's blocks on resume)."""
        nonlocal total, consecutive_failures
        nonlocal best_metric, best_model, best_states
        if recovered_attempts > 0:
            cid0 = p.block[0][1]
            emit(RecoveryEvent(action="recovered", coordinate_id=cid0,
                               iteration=p.it,
                               attempts=recovered_attempts))
            log(lambda: f"iter {p.it} coordinate {cid0}: recovered "
                f"after {recovered_attempts} retry(ies)")
        consecutive_failures = 0
        for _, cid in p.block:
            states[cid] = p.cands[cid]
            scores[cid] = p.new_scores[cid]
            reg_cache[cid] = p.new_regs[cid]
            # --device-telemetry: per-coordinate HBM watermark at the
            # moment this coordinate's buffers land (no-op unless armed;
            # metadata-only — never a device sync)
            devicemem.note_coordinate(cid)
        # canonical (ids order from zero), computed INSIDE the fused
        # epilogue — never incrementally drifted: resume parity
        total = p.new_total
        dt = seconds if seconds is not None else time.time() - p.t_wall
        per = dt / len(p.block)
        for _, cid in p.block:
            log(lambda cid=cid: f"iter {p.it} coordinate {cid}: "
                f"objective={objective:.6f} "
                f"({per:.2f}s) — {p.trackers[cid].summary()}")

        metrics = None
        if validate:
            with trace.span("cd.validation", sweep=p.it,
                            coordinates=",".join(c for _, c in p.block)):
                model = publish_game_model(coordinates, states)
                val_scores = model.score(validation_data)
                metrics = validation_evaluator(val_scores)
            log(lambda: f"iter {p.it} block "
                f"{[cid for _, cid in p.block]}: validation {metrics}")
            if validation_metric is not None:
                m = metrics[validation_metric]
                better = (best_metric is None
                          or (m > best_metric if higher_is_better
                              else m < best_metric))
                if better:  # (:245-255)
                    best_metric, best_model = m, model
                    best_states = dict(states)

        for _, cid in p.block:
            history.append(CoordinateDescentState(
                iteration=p.it, coordinate_id=cid, objective=objective,
                seconds=per, tracker=p.trackers[cid],
                validation_metrics=metrics))

        if p.snapshot_due and allow_snapshot:
            # snapshot at the RAW block boundary (quarantine-filtered
            # members included): state is committed through the block,
            # and resume re-partitions the sweep identically
            save_snapshot(p.it, p.snapshot_next_ci)

    def run_member(ci, cid, it, first_error=None, allow_snapshots=True,
                   snapshot_due=None, snapshot_next_ci=None):
        """One guarded coordinate update: the sequential retry / skip /
        quarantine ladder (dispatch + fetch inline, no overlap).
        ``first_error`` seeds the ladder with an attempt-0 failure
        already caught by the pipelined path — the ladder then proceeds
        exactly as if it had run that attempt itself.
        ``allow_snapshots=False`` marks a member replayed INSIDE a
        multi-coordinate block: snapshots (cadence and quarantine alike)
        are deferred to the enclosing block's boundary, preserving the
        never-mid-block invariant a blocked resume depends on.
        ``snapshot_due``/``snapshot_next_ci`` carry the enclosing RAW
        block's cadence flag and boundary (defaults: this member alone
        IS the block)."""
        nonlocal consecutive_failures
        if snapshot_due is None:
            snapshot_due = snapshot_cadence_due([(ci, cid)], it)
        if snapshot_next_ci is None:
            snapshot_next_ci = ci + 1
        with trace.span("cd.update", coordinate=cid, sweep=it):
            t0 = time.time()
            attempt = 0
            skipped = False
            budgeted_skip = False
            quarantine_now = False
            outcome = None
            error = first_error
            while True:
                if error is None:
                    try:
                        p = dispatch_update(
                            [(ci, cid)], it, attempt, total, {},
                            snapshot_due=snapshot_due,
                            snapshot_next_ci=snapshot_next_ci)
                        objective, train_loss = fetch_update(p)
                        outcome = (p, objective, train_loss)
                        break
                    except (InjectedFault, CoordinateDivergenceError,
                            FloatingPointError) as e:
                        if recovery is None:
                            raise
                        error = e
                        continue
                e, error = error, None
                # an InjectedFault knows its origin site (e.g.
                # "optimizer.gradient"); label divergence detected
                # here as cd.update
                emit(FaultEvent(point=getattr(e, "point", "cd.update"),
                                coordinate_id=cid,
                                iteration=it, message=str(e)))
                log(lambda: f"iter {it} coordinate {cid}: FAULT "
                    f"(attempt {attempt}): {e}")
                attempt += 1
                if attempt <= recovery.max_retries:
                    emit(RecoveryEvent(action="retried",
                                       coordinate_id=cid, iteration=it,
                                       attempts=attempt))
                    continue
                if recovery.quarantine_after > 0:
                    # per-coordinate budget: skip degraded until THIS
                    # coordinate's own budget exhausts, then freeze it
                    # (the global on_exhausted action never fires for
                    # budgeted coordinates — that is the point, and
                    # budgeted skips don't count toward the global
                    # consecutive-failure abort either)
                    coordinate_failures[cid] = (
                        coordinate_failures.get(cid, 0) + 1)
                    if (coordinate_failures[cid]
                            >= recovery.quarantine_after):
                        quarantine_now = True
                    else:
                        skipped = True
                        budgeted_skip = True
                    break
                if recovery.on_exhausted == "skip":
                    skipped = True
                    break
                raise RuntimeError(
                    f"coordinate descent aborted: coordinate {cid} "
                    f"failed {attempt} attempt(s) at iteration {it} "
                    f"(RecoveryPolicy on_exhausted='abort')") from e
            dt = time.time() - t0
            if quarantine_now:
                quarantined.add(cid)
                emit(CoordinateQuarantinedEvent(
                    coordinate_id=cid, iteration=it,
                    failures=coordinate_failures[cid],
                    message=(f"{coordinate_failures[cid]} exhausted "
                             f"update(s); frozen at last-good state")))
                log(lambda: f"iter {it} coordinate {cid}: QUARANTINED "
                    f"after {coordinate_failures[cid]} exhausted "
                    f"update(s) — frozen at last-good state, descent "
                    f"continues ({dt:.2f}s)")
                if checkpoint_manager is not None and allow_snapshots:
                    save_snapshot(it, snapshot_next_ci)
                return
            if skipped:
                # Keep the last-good state and its score; continue
                # degraded (the reference's closest analog: a failed
                # Spark stage retried elsewhere — here the coordinate
                # just sits out). A BUDGETED skip is bounded by the
                # coordinate's own quarantine budget, so it must not
                # also burn the global consecutive-failure budget (it
                # would abort the run before the quarantine ever
                # triggered).
                if not budgeted_skip:
                    consecutive_failures += 1
                emit(RecoveryEvent(action="skipped", coordinate_id=cid,
                                   iteration=it, attempts=attempt))
                log(lambda: f"iter {it} coordinate {cid}: SKIPPED after "
                    f"{attempt} failed attempt(s) — keeping last-good "
                    f"state ({dt:.2f}s)")
                if (not budgeted_skip and consecutive_failures
                        >= recovery.max_consecutive_failures):
                    emit(RecoveryEvent(action="aborted",
                                       coordinate_id=cid,
                                       iteration=it, attempts=attempt))
                    raise RuntimeError(
                        f"coordinate descent aborted: "
                        f"{consecutive_failures} consecutive coordinate "
                        f"updates failed (RecoveryPolicy "
                        f"max_consecutive_failures="
                        f"{recovery.max_consecutive_failures})")
                return
            p, objective, train_loss = outcome
            commit_update(p, objective, train_loss, seconds=dt,
                          recovered_attempts=attempt,
                          allow_snapshot=allow_snapshots)

    def replay_block_members(block, it, due_snapshot, next_ci):
        """Walk each block member through its own sequential ladder with
        snapshots DEFERRED, then save once at the RAW block boundary if
        the block crossed a cadence point — or if the replay quarantined
        a member (the sequential path persists quarantines promptly; the
        blocked path does so at its boundary). A mid-replay snapshot
        would land inside the block and re-partition the sweep on
        resume."""
        q_before = len(quarantined)
        for ci, cid in block:
            if cid not in quarantined:
                run_member(ci, cid, it, allow_snapshots=False)
        if (checkpoint_manager is not None
                and (due_snapshot or len(quarantined) > q_before)):
            save_snapshot(it, next_ci)

    def resolve_update(p, speculative=None):
        """Resolve one in-flight block: fetch its fused epilogue and
        commit — or, on divergence/fault, drop into the sequential
        recovery ladder from the last-good committed state. Returns True
        iff the block committed exactly as dispatched (the pipelined
        loop's signal that a speculative successor dispatch is still
        valid). ``speculative`` is that successor: on failure it is
        rolled back FIRST, before the ladder runs — the ladder's
        quarantine/cadence snapshots must never persist the speculative
        dispatch's advanced RNG stream positions (state the live run is
        about to discard)."""
        try:
            if len(p.block) == 1:
                with trace.span("cd.update", coordinate=p.block[0][1],
                                sweep=p.it):
                    objective, train_loss = fetch_update(p)
                    commit_update(p, objective, train_loss)
            else:
                with trace.span("cd.block", sweep=p.it,
                                size=len(p.block),
                                coordinates=",".join(
                                    cid for _, cid in p.block)):
                    objective, train_loss = fetch_update(p)
                    commit_update(p, objective, train_loss)
            return True
        except (CoordinateDivergenceError, FloatingPointError) as e:
            if recovery is None:
                raise
            if speculative is not None:
                rollback_update(speculative)
            if len(p.block) == 1:
                # the failed fetch WAS this coordinate's attempt 0: seed
                # the ladder with it (no rollback of p itself —
                # sequential retries advance the RNG stream per attempt,
                # and so must we)
                ci, cid = p.block[0]
                run_member(ci, cid, p.it, first_error=e,
                           snapshot_due=p.snapshot_due,
                           snapshot_next_ci=p.snapshot_next_ci)
            else:
                # the epilogue's finiteness flag covers the whole block:
                # discard the block (restoring RNG positions) and replay
                # members one at a time from the committed state —
                # innocents commit cleanly, the culprit walks its ladder
                emit(FaultEvent(point="cd.block", iteration=p.it,
                                message=str(e)))
                log(lambda: f"iter {p.it}: block "
                    f"{[cid for _, cid in p.block]} FAULT — replaying "
                    f"members sequentially: {e}")
                rollback_update(p)
                replay_block_members(p.block, p.it, p.snapshot_due,
                                     p.snapshot_next_ci)
            return False

    def run_block(raw_block, it, first_error=None):
        """Process one RAW block sequentially (dispatch + resolve
        inline): the unpipelined path, and the fallback every pipelined
        failure drops into. ``first_error`` carries a dispatch-time
        failure the pipelined loop already caught. Quarantined members
        are filtered here, but the snapshot boundary and cadence stay
        those of the RAW block — resume must re-partition the sweep
        identically."""
        block = [(ci, cid) for ci, cid in raw_block
                 if cid not in quarantined]
        if not block:
            return
        due = snapshot_cadence_due(raw_block, it)
        next_ci = raw_block[-1][0] + 1
        if first_error is None:
            try:
                p = dispatch_update(block, it, 0, total, {},
                                    snapshot_due=due,
                                    snapshot_next_ci=next_ci)
            except (InjectedFault, FloatingPointError) as e:
                if recovery is None:
                    raise
                first_error = e
            else:
                resolve_update(p)
                return
        # dispatch-time failure: straight to the ladder
        if len(block) > 1:
            emit(FaultEvent(point="cd.block", iteration=it,
                            message=str(first_error)))
            log(lambda: f"iter {it}: block "
                f"{[cid for _, cid in block]} FAULT at dispatch — "
                f"replaying members sequentially: {first_error}")
            replay_block_members(block, it, due, next_ci)
        else:
            run_member(block[0][0], block[0][1], it,
                       first_error=first_error,
                       snapshot_due=due, snapshot_next_ci=next_ci)

    for it in range(start_iteration, num_iterations):
        with trace.span("cd.sweep", sweep=it):
            fault_point("cd.sweep", tag=str(it))
            sweep_history_start = len(history)
            eligible = [(ci, cid) for ci, cid in enumerate(ids)
                        if not (it == start_iteration
                                and ci < start_coordinate)]
            blocks = [eligible[i:i + block_size]
                      for i in range(0, len(eligible), block_size)]

            pending: Optional[_InFlight] = None
            for raw_block in blocks:
                if stop is not None:
                    reason = stop.should_stop()
                    if reason is not None:
                        # Commit barrier: settle the in-flight pipelined
                        # handle first (the snapshot must read committed
                        # state, same rule as the checkpoint barrier),
                        # write the final "about to run this block"
                        # snapshot, and hand the exact resume position
                        # to the driver. Never tears a block.
                        if pending is not None:
                            resolve_update(pending)
                            pending = None
                        if checkpoint_manager is not None:
                            save_snapshot(it, raw_block[0][0])
                        raise PreemptionRequested(reason, it,
                                                  raw_block[0][0])
                block = [(ci, cid) for ci, cid in raw_block
                         if cid not in quarantined]
                if not block:
                    continue
                if not use_pipeline:
                    run_block(raw_block, it)
                    continue
                if pending is not None and pending.snapshot_due:
                    # checkpoint barrier: the pending block snapshots
                    # when it resolves, and a snapshot must never race a
                    # speculative in-flight successor — settle first
                    resolve_update(pending)
                    pending = None
                if pending is not None:
                    base_total = pending.new_total
                    overlay = {cid: (pending.new_scores[cid],
                                     pending.new_regs[cid])
                               for _, cid in pending.block}
                else:
                    base_total, overlay = total, {}
                counts0 = _snap_update_counts(block)
                try:
                    cur = dispatch_update(
                        block, it, 0, base_total, overlay,
                        snapshot_due=snapshot_cadence_due(raw_block, it),
                        snapshot_next_ci=raw_block[-1][0] + 1)
                except (InjectedFault, CoordinateDivergenceError,
                        FloatingPointError) as e:
                    # the dispatch itself failed (injected fault): settle
                    # the pending block first — its events and commit
                    # precede this block's ladder, as in the sequential
                    # order — then walk this block through the ladder
                    if pending is not None:
                        pending.pipelined = True
                        # pending's ladder may snapshot; a snapshot's
                        # "about to run this block" must carry PRE-
                        # dispatch RNG positions (what a sequential
                        # run's snapshot would hold), while the seeded
                        # ladder below still owns the failed dispatch's
                        # advance as its attempt 0 — swap the counters
                        # around the resolution
                        counts_adv = _snap_update_counts(block)
                        _set_update_counts(block, counts0)
                        resolve_update(pending)
                        pending = None
                        _set_update_counts(block, counts_adv)
                    if recovery is None:
                        raise
                    run_block(raw_block, it, first_error=e)
                    continue
                inflight = len(cur.block) + (
                    len(pending.block) if pending is not None else 0)
                REGISTRY.gauge("cd_inflight_updates").set(inflight)
                if inflight > HOT_LOOP_STATS["max_inflight"]:
                    HOT_LOOP_STATS["max_inflight"] = inflight
                if pending is not None:
                    pending.pipelined = True
                    ok = resolve_update(pending, speculative=cur)
                    pending = None
                    if not ok:
                        # the commit diverged from the overlay this
                        # dispatch speculated on (retry/skip/quarantine
                        # changed the state): resolve_update already
                        # rolled it back (BEFORE the ladder could
                        # snapshot its speculative RNG positions) — just
                        # re-run from the re-committed last-good state
                        run_block(raw_block, it)
                        continue
                pending = cur
            if pending is not None:
                # sweep drain: the last block resolves before the
                # tracker drain / sweep snapshot read committed state
                resolve_update(pending)
                pending = None

            # Sweep boundary: drain this sweep's lazy trackers (one
            # batched explicit fetch each, amortized over the whole
            # sweep) so their device-resident per-entity arrays and
            # solver histories don't accumulate in HBM across a long
            # run. The per-update hot path stays at one fetch per block;
            # this drain is the off-hot-path counterpart, like the
            # checkpoint below.
            with trace.span("cd.tracker_drain", sweep=it):
                for h in history[sweep_history_start:]:
                    mat = getattr(h.tracker, "materialize", None)
                    if mat is not None:
                        mat()
            # live-buffer watermark AFTER the drain: the signal that
            # tunes pipeline depth and the drain policy from a trace
            _sample_live_bytes(it)

            if checkpoint_manager is not None:
                save_snapshot(it, len(ids))

    final = publish_game_model(coordinates, states)
    return CoordinateDescentResult(model=final, states=history,
                                   best_model=best_model,
                                   best_metric=best_metric,
                                   quarantined=sorted(quarantined))


def publish_game_model(coordinates: dict[str, Coordinate], states: dict
                       ) -> GameModel:
    return GameModel({cid: coordinates[cid].publish(states[cid])
                      for cid in coordinates})
