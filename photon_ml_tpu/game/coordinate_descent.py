"""Coordinate descent: the GAME outer loop.

TPU-native re-design of the reference's CoordinateDescent
(reference: photon-ml/src/main/scala/com/linkedin/photon/ml/algorithm/
CoordinateDescent.scala:50-263): initialize per-coordinate models and score
vectors; per (iteration, coordinate in updating sequence) — sum the *other*
coordinates' scores and inject them as offsets (:143-151), re-optimize the
coordinate, re-score it, log the global objective
``trainingLossEvaluator(Σ scores) + Σ regularization`` (:199-205), optionally
evaluate on validation data and keep the best full model by the first
validation evaluator (:245-255).

The reference's per-step RDD joins/unpersists become array adds and gathers;
all score vectors are sample-major ``[N]`` device arrays.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

import numpy as np

import jax.numpy as jnp

from photon_ml_tpu.game.coordinate import Coordinate, Tracker
from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.game.models import GameModel
from photon_ml_tpu.ops.losses import get_loss
from photon_ml_tpu.optimize.config import TASK_LOSS_NAME, TaskType

Array = jnp.ndarray


def training_loss_evaluator(task: TaskType, labels: Array, weights: Array,
                            offsets: Array) -> Callable[[Array], float]:
    """Σ_i w_i l(score_i + offset_i, y_i) over the training data
    (prepareTrainingLossEvaluator, cli/game/training/Driver.scala:191)."""
    loss = get_loss(TASK_LOSS_NAME[task])

    def evaluate(scores: Array) -> float:
        l, _ = loss.loss_and_d1(scores + offsets, labels)
        return float(jnp.sum(weights * l))

    return evaluate


@dataclasses.dataclass
class CoordinateDescentState:
    """Per-iteration record (OptimizationStatesTracker + CD logging analog)."""

    iteration: int
    coordinate_id: str
    objective: float
    seconds: float
    tracker: Tracker
    validation_metrics: Optional[dict[str, float]] = None


@dataclasses.dataclass
class CoordinateDescentResult:
    model: GameModel
    states: list[CoordinateDescentState]
    best_model: Optional[GameModel] = None
    best_metric: Optional[float] = None


def run_coordinate_descent(
    coordinates: dict[str, Coordinate],
    num_iterations: int,
    task: TaskType,
    labels: Array,
    weights: Array,
    offsets: Array,
    validation_data: Optional[GameDataset] = None,
    validation_evaluator: Optional[Callable[[Array], dict[str, float]]] = None,
    validation_metric: Optional[str] = None,
    higher_is_better: bool = True,
    initial_states: Optional[dict] = None,
    logger: Optional[Callable[[str], None]] = None,
    checkpoint_manager=None,
    start_iteration: int = 0,
    initial_best: Optional[tuple] = None,
) -> CoordinateDescentResult:
    """Run GAME coordinate descent over ``coordinates`` in dict order.

    ``coordinates`` iteration order IS the updating sequence
    (cli/game/training/Params updatingSequence). ``labels/weights/offsets``
    describe the training samples (sample-major). Single-coordinate runs skip
    the partial-score machinery exactly like CoordinateDescent.scala:82-120's
    special case.
    """
    log = logger or (lambda s: None)
    ids = list(coordinates)
    n = {cid: coordinates[cid].num_samples for cid in ids}
    num_samples = next(iter(n.values()))
    assert all(v == num_samples for v in n.values()), \
        "all coordinates must cover the same sample axis"

    loss_eval = training_loss_evaluator(task, labels, weights, offsets)

    # Init: zero states, zero scores (CoordinateDescent.scala:93-101).
    states = dict(initial_states or {})
    resumed = set(states)
    for cid in ids:
        if cid not in states:
            states[cid] = coordinates[cid].initial_state()
    # Restored coordinates must contribute their scores from the start —
    # zeros would make the first resumed sweep optimize against offsets
    # that pretend the other coordinates' models don't exist.
    scores = {cid: (coordinates[cid].score(states[cid])
                    if cid in resumed else jnp.zeros(num_samples))
              for cid in ids}
    total = jnp.zeros(num_samples)
    for cid in ids:
        total = total + scores[cid]

    history: list[CoordinateDescentState] = []
    best_model = None
    best_metric = None
    best_states = None
    if initial_best is not None:
        best_metric, restored_states = initial_best
        best_states = dict(restored_states)
        best_model = publish_game_model(coordinates, best_states)

    for it in range(start_iteration, num_iterations):
        for cid in ids:
            t0 = time.time()
            coord = coordinates[cid]
            partial = total - scores[cid]  # Σ other coordinates (:143-151)
            states[cid], tracker = coord.update(states[cid], partial)
            new_score = coord.score(states[cid])
            total = partial + new_score
            scores[cid] = new_score

            reg = sum(coordinates[c].regularization_value(states[c])
                      for c in ids)
            objective = loss_eval(total) + reg  # (:199-205)
            dt = time.time() - t0
            log(f"iter {it} coordinate {cid}: objective={objective:.6f} "
                f"({dt:.2f}s) — {tracker.summary()}")

            metrics = None
            if validation_data is not None and validation_evaluator:
                model = publish_game_model(coordinates, states)
                val_scores = model.score(validation_data)
                metrics = validation_evaluator(val_scores)
                log(f"iter {it} coordinate {cid}: validation {metrics}")
                if validation_metric is not None:
                    m = metrics[validation_metric]
                    better = (best_metric is None
                              or (m > best_metric if higher_is_better
                                  else m < best_metric))
                    if better:  # (:245-255)
                        best_metric, best_model = m, model
                        best_states = dict(states)

            history.append(CoordinateDescentState(
                iteration=it, coordinate_id=cid, objective=objective,
                seconds=dt, tracker=tracker, validation_metrics=metrics))

        if checkpoint_manager is not None:
            def _np_states(d):
                return {
                    cid: (tuple(np.asarray(s) for s in d[cid])
                          if isinstance(d[cid], tuple)
                          else np.asarray(d[cid]))
                    for cid in d}

            checkpoint_manager.save(it + 1, {
                "iteration": it + 1,
                "states": _np_states(states),
                "best_metric": (None if best_metric is None
                                else float(best_metric)),
                "best_states": (None if best_states is None
                                else _np_states(best_states)),
            })

    final = publish_game_model(coordinates, states)
    return CoordinateDescentResult(model=final, states=history,
                                   best_model=best_model,
                                   best_metric=best_metric)


def publish_game_model(coordinates: dict[str, Coordinate], states: dict
                       ) -> GameModel:
    return GameModel({cid: coordinates[cid].publish(states[cid])
                      for cid in coordinates})
