"""Coordinate descent: the GAME outer loop.

TPU-native re-design of the reference's CoordinateDescent
(reference: photon-ml/src/main/scala/com/linkedin/photon/ml/algorithm/
CoordinateDescent.scala:50-263): initialize per-coordinate models and score
vectors; per (iteration, coordinate in updating sequence) — sum the *other*
coordinates' scores and inject them as offsets (:143-151), re-optimize the
coordinate, re-score it, log the global objective
``trainingLossEvaluator(Σ scores) + Σ regularization`` (:199-205), optionally
evaluate on validation data and keep the best full model by the first
validation evaluator (:245-255).

The reference's per-step RDD joins/unpersists become array adds and gathers;
all score vectors are sample-major ``[N]`` device arrays.

Hot-loop sync discipline: one coordinate update costs exactly ONE device
round-trip. The update, its score, the changed coordinate's regularization
scalar, and the fused epilogue (:func:`make_update_epilogue`) dispatch
asynchronously; the single blocking read is a ``jax.device_get`` of the
epilogue's small scalar pytree. Everything sample-sized — the canonical
score total included — stays device-resident between updates, and the
per-coordinate trackers/optimizer histories materialize lazily at
log/metrics/checkpoint time. ``tests/test_sync_discipline.py`` enforces
this under ``jax.transfer_guard("disallow")``.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.game.coordinate import Coordinate, Tracker
from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.game.models import GameModel
from photon_ml_tpu.ops.losses import get_loss
from photon_ml_tpu.optimize.config import TASK_LOSS_NAME, TaskType
from photon_ml_tpu.obs import trace
from photon_ml_tpu.obs.metrics import REGISTRY
from photon_ml_tpu.utils.events import (
    CoordinateQuarantinedEvent,
    EventEmitter,
    FaultEvent,
    RecoveryEvent,
)
from photon_ml_tpu.utils.faults import InjectedFault, fault_point
from photon_ml_tpu.utils.sync_telemetry import record_host_fetch

Array = jnp.ndarray


class CoordinateDivergenceError(RuntimeError):
    """A coordinate update produced a non-finite state or objective."""


# Hot-loop sync telemetry for bench.py / the transfer-guard test: the
# one-round-trip contract says every non-validation coordinate update
# performs EXACTLY ONE blocking device→host fetch (the fused epilogue's
# small scalar pytree). ``update_dispatch_secs`` is host time spent
# dispatching the update + epilogue (async), ``epilogue_wait_secs`` the
# blocking wait inside the single fetch.
HOT_LOOP_STATS = {"updates": 0, "epilogue_fetches": 0,
                  "update_dispatch_secs": 0.0, "epilogue_wait_secs": 0.0}


def reset_hot_loop_stats() -> None:
    HOT_LOOP_STATS.update({"updates": 0, "epilogue_fetches": 0,
                           "update_dispatch_secs": 0.0,
                           "epilogue_wait_secs": 0.0})


def _canonical_sum(score_list, num_samples: int):
    """Σ scores in updating-sequence order from zero — the ONE summation
    order used everywhere (init, resume, and INSIDE the fused epilogue), so
    a resumed run reproduces the uninterrupted run's floats exactly."""
    t = jnp.zeros(num_samples)
    for s in score_list:
        t = t + s
    return t


@functools.lru_cache(maxsize=32)
def _canonical_total_jit(num_samples: int):
    """Jitted canonical summation, cached per sample count so repeated
    runs (and the warm bench pass) reuse the executable."""
    return jax.jit(lambda score_list: _canonical_sum(score_list,
                                                     num_samples))


@functools.lru_cache(maxsize=32)
def make_update_epilogue(task: TaskType, num_samples: int):
    """Build the fused, jitted update epilogue (cached per task/sample
    count: repeated runs share one compiled executable per shape).

    One compiled call computes everything the host needs after a candidate
    coordinate update, replacing what used to be O(K) blocking syncs per
    update (a ``float()`` per coordinate's regularization term, a
    ``bool()`` per state leaf for the finiteness guard, a ``float()`` for
    the objective) with a single device program whose small outputs are
    fetched as ONE pytree:

    - the canonical ids-order score total (kept ON DEVICE — it feeds the
      next update's partial-score offsets without a round-trip); summation
      order is preserved inside the fused op so crash/resume stays
      bit-exact,
    - the training loss Σᵢ wᵢ·l(totalᵢ + offsetᵢ, yᵢ) (:199-205),
    - Σ regularization from the per-coordinate reg-scalar cache (updated
      only for the changed coordinate, summed in ids order),
    - the global objective (training loss + Σ reg),
    - one all-leaves finiteness flag over the candidate state + objective.

    ``score_list``/``reg_list`` arrive in updating-sequence order with the
    changed coordinate's entries already substituted.
    """
    # this body runs only on an lru_cache MISS — i.e. a new (task, N)
    # shape is about to pay an XLA compile; the counter makes retrace
    # regressions visible in metrics.jsonl and the bench record
    REGISTRY.counter("retraces").inc(site="cd.epilogue")
    loss = get_loss(TASK_LOSS_NAME[task])

    @jax.jit
    def epilogue(score_list, reg_list, state_leaves, labels, weights,
                 offsets):
        total = _canonical_sum(score_list, num_samples)
        l, _ = loss.loss_and_d1(total + offsets, labels)
        train_loss = jnp.sum(weights * l)
        reg_total = 0.0
        for r in reg_list:  # ids order (python floats stay op-free)
            reg_total = reg_total + r
        objective = train_loss + reg_total
        state_finite = jnp.asarray(True)
        for leaf in state_leaves:
            state_finite = state_finite & jnp.all(jnp.isfinite(leaf))
        finite = state_finite & jnp.isfinite(objective)
        return total, objective, train_loss, reg_total, finite, state_finite

    return epilogue


@dataclasses.dataclass(frozen=True)
class RecoveryPolicy:
    """What to do when a coordinate update diverges (non-finite state or
    objective) or raises an injected fault.

    The reference never needed this — Spark re-ran lost lineage for free
    but had no answer to numeric divergence either (SURVEY §5.4); here
    both are handled by one policy:

    - retry the update up to ``max_retries`` times from the last-good
      state, damping the accepted step by ``damping**attempt``. Damping
      rescues transient faults and finite-state overflows (an Inf
      objective from an over-long step); a DETERMINISTIC NaN solve will
      reproduce itself and exhaust the retries — the skip/abort action
      below is what bounds that cost;
    - when retries are exhausted, either ``skip`` the coordinate for this
      sweep (keep the last-good state, continue degraded) or ``abort``;
    - abort anyway after ``max_consecutive_failures`` consecutive skipped
      updates — a run that skips every sweep is not making progress.

    ``quarantine_after`` adds a PER-COORDINATE failure budget on top of
    the global policy: when > 0, a coordinate whose retries exhaust is
    skipped for the sweep (degraded, regardless of ``on_exhausted``)
    until it has accumulated ``quarantine_after`` exhausted updates
    across the run, at which point it is QUARANTINED — frozen at its
    last-good state, announced with a
    :class:`~photon_ml_tpu.utils.events.CoordinateQuarantinedEvent`, and
    excluded from further sweeps while the rest of the descent continues.
    One chronically-diverging coordinate then costs its own bounded
    budget instead of burning the global retry/consecutive-failure
    budgets or aborting the whole run.
    """

    max_retries: int = 2
    on_exhausted: str = "abort"  # "skip" | "abort"
    damping: float = 0.5
    max_consecutive_failures: int = 3
    quarantine_after: int = 0  # 0 = per-coordinate budget disabled

    def __post_init__(self):
        if self.on_exhausted not in ("skip", "abort"):
            raise ValueError(
                f"on_exhausted must be 'skip' or 'abort', "
                f"got {self.on_exhausted!r}")
        if self.quarantine_after < 0:
            raise ValueError(
                f"quarantine_after must be >= 0, "
                f"got {self.quarantine_after}")


def _state_leaves(state):
    return state if isinstance(state, tuple) else (state,)


def _state_is_finite(state) -> bool:
    # device-side reduction: one scalar comes back per leaf instead of a
    # full state copy (per-entity matrices can be millions of rows);
    # all leaves' flags return in a single instrumented fetch
    flags = jax.device_get(tuple(
        jnp.all(jnp.isfinite(jnp.asarray(leaf)))
        for leaf in _state_leaves(state)))
    record_host_fetch(site="cd.state_finite")
    return all(bool(f) for f in flags)


def _damp_toward(good, candidate, factor: float):
    """last_good + factor * (candidate - last_good), leaf-wise."""
    def blend(g, c):
        return g + factor * (jnp.asarray(c) - g)
    if isinstance(candidate, tuple):
        return tuple(blend(g, c) for g, c in zip(good, candidate))
    return blend(jnp.asarray(good), candidate)


def training_loss_evaluator(task: TaskType, labels: Array, weights: Array,
                            offsets: Array) -> Callable[[Array], float]:
    """Σ_i w_i l(score_i + offset_i, y_i) over the training data
    (prepareTrainingLossEvaluator, cli/game/training/Driver.scala:191)."""
    loss = get_loss(TASK_LOSS_NAME[task])

    def evaluate(scores: Array) -> float:
        l, _ = loss.loss_and_d1(scores + offsets, labels)
        value = jax.device_get(jnp.sum(weights * l))
        record_host_fetch(site="cd.training_loss")
        return float(value)

    return evaluate


@dataclasses.dataclass
class CoordinateDescentState:
    """Per-iteration record (OptimizationStatesTracker + CD logging analog)."""

    iteration: int
    coordinate_id: str
    objective: float
    seconds: float
    tracker: Tracker
    validation_metrics: Optional[dict[str, float]] = None


@dataclasses.dataclass
class CoordinateDescentResult:
    model: GameModel
    states: list[CoordinateDescentState]
    best_model: Optional[GameModel] = None
    best_metric: Optional[float] = None
    # Coordinates frozen at last-good state by the per-coordinate failure
    # budget (RecoveryPolicy.quarantine_after) — surfaced in the driver
    # summary and metrics.json.
    quarantined: list[str] = dataclasses.field(default_factory=list)


def _to_jnp_states(d: dict) -> dict:
    return {cid: (tuple(jnp.asarray(s) for s in v)
                  if isinstance(v, tuple) else jnp.asarray(v))
            for cid, v in d.items()}


def _checkpoint_save_contained(manager, step: int, snapshot: dict,
                               log, emit) -> bool:
    """Save a snapshot, CONTAINING a persistently-unwritable disk
    (CheckpointWriteError after the write-side retries): training state
    is intact and the next cadence point tries again, so a full
    checkpoint volume degrades durability instead of killing a
    multi-hour run. The failure is logged, counted
    (``ckpt_save_failures``), and announced as a FaultEvent."""
    from photon_ml_tpu.utils.checkpoint import CheckpointWriteError

    try:
        manager.save(step, snapshot)
        return True
    except CheckpointWriteError as e:
        REGISTRY.counter("ckpt_save_failures").inc()
        emit(FaultEvent(point="ckpt.write_bytes", message=str(e)))
        log(lambda: f"checkpoint step {step} NOT saved (degraded, "
            f"training continues): {e}")
        return False


def run_coordinate_descent(
    coordinates: dict[str, Coordinate],
    num_iterations: int,
    task: TaskType,
    labels: Array,
    weights: Array,
    offsets: Array,
    validation_data: Optional[GameDataset] = None,
    validation_evaluator: Optional[Callable[[Array], dict[str, float]]] = None,
    validation_metric: Optional[str] = None,
    higher_is_better: bool = True,
    initial_states: Optional[dict] = None,
    logger: Optional[Callable[[str], None]] = None,
    checkpoint_manager=None,
    start_iteration: int = 0,
    initial_best: Optional[tuple] = None,
    recovery: Optional[RecoveryPolicy] = None,
    events: Optional[EventEmitter] = None,
    checkpoint_every_coordinates: int = 0,
    start_coordinate: int = 0,
    resume_snapshot: Optional[dict] = None,
) -> CoordinateDescentResult:
    """Run GAME coordinate descent over ``coordinates`` in dict order.

    ``coordinates`` iteration order IS the updating sequence
    (cli/game/training/Params updatingSequence). ``labels/weights/offsets``
    describe the training samples (sample-major). Single-coordinate runs skip
    the partial-score machinery exactly like CoordinateDescent.scala:82-120's
    special case.

    With a :class:`RecoveryPolicy`, every coordinate update is guarded for
    non-finite states/objectives and injected faults; detected faults emit
    :class:`FaultEvent`/:class:`RecoveryEvent` on ``events`` and follow the
    policy (retry damped / skip degraded / abort, plus per-coordinate
    quarantine when ``quarantine_after`` is set). Without one, behavior
    is the legacy fail-through (a NaN propagates to the caller).

    Checkpointing: with a ``checkpoint_manager`` a snapshot lands after
    every completed sweep, and — when ``checkpoint_every_coordinates``
    = N > 0 — additionally after every Nth coordinate update, so a crash
    inside a long sweep replays at most N updates instead of the whole
    sweep. A snapshot carries everything a BIT-EXACT resume needs:
    ``(sweep, coordinate_index, per-coordinate states AND scores, RNG
    stream positions, recovery counters, the quarantine set, the running
    best)``. Resume by passing the restored dict as ``resume_snapshot``
    (preferred — it repopulates all of the above; the legacy
    ``initial_states``/``start_iteration``/``initial_best`` trio still
    works for sweep-boundary snapshots). The score total is recomputed
    canonically (ids order, from zero) after every update rather than
    maintained incrementally, so a resumed run sees float-identical
    partial scores to the uninterrupted one.
    """
    def log(fn: Callable[[], str]):
        # Lazy formatting: log lines materialize lazy trackers (a device
        # fetch), so a run without a logger must never even BUILD them.
        if logger is not None:
            logger(fn())

    emit = events.send_event if events is not None else (lambda e: None)
    ids = list(coordinates)
    n = {cid: coordinates[cid].num_samples for cid in ids}
    num_samples = next(iter(n.values()))
    assert all(v == num_samples for v in n.values()), \
        "all coordinates must cover the same sample axis"

    epilogue = make_update_epilogue(task, num_samples)
    # The canonical total is computed by the SAME jitted summation the
    # epilogue runs, so the init/resume total is bit-identical to the
    # fused op's (XLA executes the identical add sequence).
    canonical_total_fn = _canonical_total_jit(num_samples)

    consecutive_failures = 0
    coordinate_failures: dict[str, int] = {}
    quarantined: set[str] = set()
    restored_scores = None
    if resume_snapshot is not None:
        snap = resume_snapshot
        initial_states = _to_jnp_states(snap["states"])
        start_iteration = int(snap.get("sweep", snap.get("iteration", 0)))
        start_coordinate = int(snap.get("coordinate_index", 0))
        if snap.get("best_states") is not None:
            initial_best = (snap.get("best_metric"),
                            _to_jnp_states(snap["best_states"]))
        if snap.get("scores") is not None:
            restored_scores = {cid: jnp.asarray(v)
                               for cid, v in snap["scores"].items()}
        # RNG stream positions: a down-sampling coordinate's PRNG key is
        # seed + update count, so the counter IS the key state
        for cid, cnt in (snap.get("update_counts") or {}).items():
            if cid in coordinates and hasattr(coordinates[cid],
                                              "_update_count"):
                coordinates[cid]._update_count = int(cnt)
        consecutive_failures = int(snap.get("consecutive_failures", 0))
        coordinate_failures = {k: int(v) for k, v in
                               (snap.get("coordinate_failures")
                                or {}).items()}
        quarantined = set(snap.get("quarantined") or [])

    # Init: zero states, zero scores (CoordinateDescent.scala:93-101).
    states = dict(initial_states or {})
    resumed = set(states)
    for cid in ids:
        if cid not in states:
            states[cid] = coordinates[cid].initial_state()

    def canonical_total(score_map):
        """Σ scores in ids order from zero — the ONE summation order used
        everywhere (shared with the fused epilogue), so a resume that
        rebuilds the total from restored scores reproduces the
        uninterrupted run's floats exactly."""
        return canonical_total_fn(tuple(score_map[c] for c in ids))

    if restored_scores is not None:
        # Mid-sweep resume: scores come back verbatim from the snapshot
        # (recomputing them from states would be wrong for coordinates
        # that have never been updated — score(initial_state) need not be
        # zero under normalization shifts).
        scores = {cid: (restored_scores[cid] if cid in restored_scores
                        else jnp.zeros(num_samples)) for cid in ids}
    else:
        # Restored coordinates must contribute their scores from the
        # start — zeros would make the first resumed sweep optimize
        # against offsets that pretend the other coordinates' models
        # don't exist.
        scores = {cid: (coordinates[cid].score(states[cid])
                        if cid in resumed else jnp.zeros(num_samples))
                  for cid in ids}
    total = canonical_total(scores)

    # Device-resident per-coordinate regularization scalar cache: the fused
    # epilogue sums these in ids order; only the CHANGED coordinate's entry
    # is recomputed per update (the old path re-evaluated all K penalties
    # with a blocking float() each — O(K²) syncs per sweep). Deterministic
    # on resume: recomputed from the restored states by the same ops.
    def _reg_device(cid, state):
        coord = coordinates[cid]
        fn = getattr(coord, "regularization_value_device",
                     coord.regularization_value)
        return fn(state)

    reg_cache = {cid: _reg_device(cid, states[cid]) for cid in ids}

    history: list[CoordinateDescentState] = []
    best_model = None
    best_metric = None
    best_states = None
    if initial_best is not None:
        best_metric, restored_states = initial_best
        best_states = dict(restored_states)
        best_model = publish_game_model(coordinates, best_states)

    def attempt_update(cid, ci, it, attempt):
        """One (possibly damped) coordinate update from last-good state;
        raises CoordinateDivergenceError on a non-finite result.

        ONE device round-trip: the update, its score, the changed
        coordinate's regularization scalar, and the fused epilogue are all
        dispatched asynchronously; the only blocking device→host read is
        the single ``jax.device_get`` of the epilogue's small scalar
        pytree (objective, training loss, reg total, finiteness flags).
        The canonical score total stays on device for the next update."""
        coord = coordinates[cid]
        t0 = time.perf_counter()
        partial = total - scores[cid]  # Σ other coordinates (:143-151)
        cand, tracker = coord.update(states[cid], partial)
        cand = fault_point("cd.update", tag=f"{it}.{ci}", arrays=cand)
        if attempt > 0:
            cand = _damp_toward(states[cid], cand,
                                recovery.damping ** attempt)
        new_score = coord.score(cand)
        new_reg = _reg_device(cid, cand)
        (new_total, objective_d, train_loss_d, _reg_total_d, finite_d,
         state_finite_d) = epilogue(
            tuple(new_score if c == cid else scores[c] for c in ids),
            tuple(new_reg if c == cid else reg_cache[c] for c in ids),
            tuple(jnp.asarray(leaf) for leaf in _state_leaves(cand)),
            labels, weights, offsets)  # (:199-205)
        HOT_LOOP_STATS["update_dispatch_secs"] += time.perf_counter() - t0
        t0 = time.perf_counter()
        with trace.span("cd.epilogue_fetch", coordinate=cid, sweep=it):
            objective, train_loss, finite, state_finite = jax.device_get(
                (objective_d, train_loss_d, finite_d, state_finite_d))
        record_host_fetch(site="cd.epilogue")
        HOT_LOOP_STATS["epilogue_wait_secs"] += time.perf_counter() - t0
        HOT_LOOP_STATS["epilogue_fetches"] += 1
        HOT_LOOP_STATS["updates"] += 1
        objective = float(objective)
        if recovery is not None and not bool(finite):
            raise CoordinateDivergenceError(
                f"iter {it} coordinate {cid}: non-finite "
                f"{'state' if not bool(state_finite) else 'objective'}"
                f" (attempt {attempt})")
        return (cand, tracker, new_score, new_reg, new_total, objective,
                float(train_loss))

    last_saved_step = None

    def save_snapshot(sweep, next_ci):
        """Persist the full resume state as of 'about to run coordinate
        ``next_ci`` of ``sweep``'; a completed sweep normalizes to the
        next sweep's coordinate 0. Step number = global update count, so
        mid-sweep and sweep-end snapshots share one monotone sequence."""
        nonlocal last_saved_step
        if next_ci >= len(ids):
            sweep, next_ci = sweep + 1, 0
        step = sweep * len(ids) + next_ci
        if step == last_saved_step:
            return
        # THE fetch point: the whole snapshot (per-coordinate states AND
        # scores, still device-resident from the hot loop) comes back in
        # one explicit jax.device_get of the payload pytree instead of a
        # per-leaf np.asarray chain.
        payload = jax.device_get({
            "states": states,
            "scores": {cid: scores[cid] for cid in ids},
            "best_states": best_states,
        })
        record_host_fetch(site="ckpt.snapshot")
        saved = _checkpoint_save_contained(checkpoint_manager, step, {
            "sweep": sweep,
            "coordinate_index": next_ci,
            # legacy field: completed sweeps (pre-mid-sweep readers)
            "iteration": sweep,
            "states": payload["states"],
            "scores": payload["scores"],
            "best_metric": (None if best_metric is None
                            else float(best_metric)),
            "best_states": payload["best_states"],
            "update_counts": {
                cid: int(getattr(coordinates[cid], "_update_count"))
                for cid in ids
                if hasattr(coordinates[cid], "_update_count")},
            "consecutive_failures": int(consecutive_failures),
            "coordinate_failures": dict(coordinate_failures),
            "quarantined": sorted(quarantined),
        }, log=log, emit=emit)
        if saved:  # a failed save retries at the next cadence point
            last_saved_step = step

    def run_update(ci, cid, it):
        """One guarded coordinate update (retry loop + bookkeeping +
        optional validation) under its ``cd.update`` span."""
        nonlocal total, consecutive_failures
        nonlocal best_metric, best_model, best_states
        t0 = time.time()
        attempt = 0
        skipped = False
        budgeted_skip = False
        quarantine_now = False
        while True:
            try:
                (cand, tracker, new_score, new_reg, new_total,
                 objective, _train_loss) = attempt_update(
                    cid, ci, it, attempt)
                break
            except (InjectedFault, CoordinateDivergenceError,
                    FloatingPointError) as e:
                if recovery is None:
                    raise
                # an InjectedFault knows its origin site (e.g.
                # "optimizer.gradient"); label divergence detected
                # here as cd.update
                emit(FaultEvent(point=getattr(e, "point", "cd.update"),
                                coordinate_id=cid,
                                iteration=it, message=str(e)))
                log(lambda: f"iter {it} coordinate {cid}: FAULT "
                    f"(attempt {attempt}): {e}")
                attempt += 1
                if attempt <= recovery.max_retries:
                    emit(RecoveryEvent(action="retried",
                                       coordinate_id=cid, iteration=it,
                                       attempts=attempt))
                    continue
                if recovery.quarantine_after > 0:
                    # per-coordinate budget: skip degraded until THIS
                    # coordinate's own budget exhausts, then freeze it
                    # (the global on_exhausted action never fires for
                    # budgeted coordinates — that is the point, and
                    # budgeted skips don't count toward the global
                    # consecutive-failure abort either)
                    coordinate_failures[cid] = (
                        coordinate_failures.get(cid, 0) + 1)
                    if (coordinate_failures[cid]
                            >= recovery.quarantine_after):
                        quarantine_now = True
                    else:
                        skipped = True
                        budgeted_skip = True
                    break
                if recovery.on_exhausted == "skip":
                    skipped = True
                    break
                raise RuntimeError(
                    f"coordinate descent aborted: coordinate {cid} "
                    f"failed {attempt} attempt(s) at iteration {it} "
                    f"(RecoveryPolicy on_exhausted='abort')") from e
        dt = time.time() - t0
        if quarantine_now:
            quarantined.add(cid)
            emit(CoordinateQuarantinedEvent(
                coordinate_id=cid, iteration=it,
                failures=coordinate_failures[cid],
                message=(f"{coordinate_failures[cid]} exhausted "
                         f"update(s); frozen at last-good state")))
            log(lambda: f"iter {it} coordinate {cid}: QUARANTINED after "
                f"{coordinate_failures[cid]} exhausted update(s) — "
                f"frozen at last-good state, descent continues "
                f"({dt:.2f}s)")
            if checkpoint_manager is not None:
                save_snapshot(it, ci + 1)
            return
        if skipped:
            # Keep the last-good state and its score; continue degraded
            # (the reference's closest analog: a failed Spark stage
            # retried elsewhere — here the coordinate just sits out).
            # A BUDGETED skip is bounded by the coordinate's own
            # quarantine budget, so it must not also burn the global
            # consecutive-failure budget (it would abort the run
            # before the quarantine ever triggered).
            if not budgeted_skip:
                consecutive_failures += 1
            emit(RecoveryEvent(action="skipped", coordinate_id=cid,
                               iteration=it, attempts=attempt))
            log(lambda: f"iter {it} coordinate {cid}: SKIPPED after "
                f"{attempt} failed attempt(s) — keeping last-good "
                f"state ({dt:.2f}s)")
            if (not budgeted_skip and consecutive_failures
                    >= recovery.max_consecutive_failures):
                emit(RecoveryEvent(action="aborted", coordinate_id=cid,
                                   iteration=it, attempts=attempt))
                raise RuntimeError(
                    f"coordinate descent aborted: "
                    f"{consecutive_failures} consecutive coordinate "
                    f"updates failed (RecoveryPolicy "
                    f"max_consecutive_failures="
                    f"{recovery.max_consecutive_failures})")
            return
        if attempt > 0:
            emit(RecoveryEvent(action="recovered", coordinate_id=cid,
                               iteration=it, attempts=attempt))
            log(lambda: f"iter {it} coordinate {cid}: recovered after "
                f"{attempt} retry(ies)")
        consecutive_failures = 0
        states[cid] = cand
        scores[cid] = new_score
        reg_cache[cid] = new_reg
        # canonical (ids order from zero), computed INSIDE the fused
        # epilogue — never incrementally drifted: resume parity
        total = new_total
        log(lambda: f"iter {it} coordinate {cid}: "
            f"objective={objective:.6f} "
            f"({dt:.2f}s) — {tracker.summary()}")

        metrics = None
        if validation_data is not None and validation_evaluator:
            with trace.span("cd.validation", coordinate=cid, sweep=it):
                model = publish_game_model(coordinates, states)
                val_scores = model.score(validation_data)
                metrics = validation_evaluator(val_scores)
            log(lambda: f"iter {it} coordinate {cid}: "
                f"validation {metrics}")
            if validation_metric is not None:
                m = metrics[validation_metric]
                better = (best_metric is None
                          or (m > best_metric if higher_is_better
                              else m < best_metric))
                if better:  # (:245-255)
                    best_metric, best_model = m, model
                    best_states = dict(states)

        history.append(CoordinateDescentState(
            iteration=it, coordinate_id=cid, objective=objective,
            seconds=dt, tracker=tracker, validation_metrics=metrics))

        if (checkpoint_manager is not None
                and checkpoint_every_coordinates > 0
                and (it * len(ids) + ci + 1)
                % checkpoint_every_coordinates == 0):
            save_snapshot(it, ci + 1)

    for it in range(start_iteration, num_iterations):
        with trace.span("cd.sweep", sweep=it):
            fault_point("cd.sweep", tag=str(it))
            sweep_history_start = len(history)
            for ci, cid in enumerate(ids):
                if it == start_iteration and ci < start_coordinate:
                    continue  # mid-sweep resume: these updates already ran
                if cid in quarantined:
                    continue  # frozen at last-good state
                with trace.span("cd.update", coordinate=cid, sweep=it):
                    run_update(ci, cid, it)

            # Sweep boundary: drain this sweep's lazy trackers (one
            # batched explicit fetch each, amortized over the whole
            # sweep) so their device-resident per-entity arrays and
            # solver histories don't accumulate in HBM across a long
            # run. The per-update hot path stays at exactly one fetch;
            # this drain is the off-hot-path counterpart, like the
            # checkpoint below.
            with trace.span("cd.tracker_drain", sweep=it):
                for h in history[sweep_history_start:]:
                    mat = getattr(h.tracker, "materialize", None)
                    if mat is not None:
                        mat()

            if checkpoint_manager is not None:
                save_snapshot(it, len(ids))

    final = publish_game_model(coordinates, states)
    return CoordinateDescentResult(model=final, states=history,
                                   best_model=best_model,
                                   best_metric=best_metric,
                                   quarantined=sorted(quarantined))


def publish_game_model(coordinates: dict[str, Coordinate], states: dict
                       ) -> GameModel:
    return GameModel({cid: coordinates[cid].publish(states[cid])
                      for cid in coordinates})
