"""GAME coordinates: per-coordinate update/score units for coordinate descent.

TPU-native re-design of the reference's coordinate family
(reference paths under photon-ml/src/main/scala/com/linkedin/photon/ml/
algorithm/):

- ``Coordinate`` (Coordinate.scala:26-82): updateModel(model, partialScore →
  offsets), score(model), regularization value.
- ``FixedEffectCoordinate`` (FixedEffectCoordinate.scala:34-165): optimize a
  GLM on the offset-adjusted full batch via
  DistributedOptimizationProblem.runWithSampling (down-sampling per update).
- ``RandomEffectCoordinate`` (RandomEffectCoordinate.scala:99-199): per-entity
  local solves (here: the vmapped block solver) + active/passive scoring.
- ``RandomEffectCoordinateInProjectedSpace``
  (RandomEffectCoordinateInProjectedSpace.scala:25-149): models live in
  projected space — here that is the *native* representation; raw-space
  conversion happens when the model is published.
- ``FactoredRandomEffectCoordinate`` (FactoredRandomEffectCoordinate.scala:
  39-257): alternate per-entity latent fits with a distributed fit of the
  latent→raw projection on Kronecker-product features (:228-271) — the
  Kronecker expansion is one einsum on TPU.

Every coordinate's state is (model arrays, sample-axis score vector); the
partial-score offset injection is a gather along the stored row ids.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Union

import numpy as np

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.batch import DenseBatch
from photon_ml_tpu.game.dataset import (
    FixedEffectDataset,
    RandomEffectDataset,
)
from photon_ml_tpu.game.models import (
    FactoredRandomEffectModel,
    FixedEffectModel,
    RandomEffectModelInProjectedSpace,
)
from photon_ml_tpu.parallel.mesh import ensure_addressable
from photon_ml_tpu.game.random_effect import (
    RandomEffectOptimizationProblem,
    score_random_effect,
)
from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_ml_tpu.optimize.common import OptimizationResult
from photon_ml_tpu.optimize.config import TaskType
from photon_ml_tpu.optimize.problem import GLMOptimizationProblem
from photon_ml_tpu.sampler.samplers import down_sample

Array = jnp.ndarray

_CLASSIFICATION_TASKS = (
    TaskType.LOGISTIC_REGRESSION,
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
)


@dataclasses.dataclass
class FixedEffectTracker:
    """optimization/game/FixedEffectOptimizationTracker analog."""

    result: OptimizationResult

    def materialize(self) -> "FixedEffectTracker":
        """Force a deferred result's device-resident history host-side
        (one batched fetch) — the CD loop drains trackers at sweep
        boundaries so device buffers don't accumulate across the run."""
        force = getattr(self.result, "_force", None)
        if force is not None:
            force()
        return self

    def summary(self) -> str:
        return (f"fixed effect: {self.result.convergence_reason.name}, "
                f"{self.result.iterations} iterations")


@dataclasses.dataclass
class RandomEffectTracker:
    """optimization/game/RandomEffectOptimizationTracker analog: iteration
    counts + per-entity convergence-reason counts across the vmapped
    solves (countsByConvergence — the operator's only view into thousands
    of per-entity fits).

    LAZY: construction accepts device arrays and performs no host fetch —
    the CD hot loop creates one of these per update without blocking. The
    per-entity arrays materialize with a SINGLE ``jax.device_get`` of the
    whole tuple on first use (``summary()``/``counts_by_convergence()``,
    i.e. log or metrics time), where they are also sliced to ``num_real``
    entities (the single-block solver returns entity-axis pad lanes)."""

    iterations: np.ndarray  # [E] (device array until materialized)
    final_values: np.ndarray  # [E]
    convergence_codes: Optional[np.ndarray] = None  # [E] int8
    # lazy slice bound: real entity count (None = already compact)
    num_real: Optional[int] = None

    def materialize(self) -> "RandomEffectTracker":
        """Fetch the per-entity arrays host-side (one explicit
        ``jax.device_get`` of the tuple, multi-host safe) — idempotent."""
        if not isinstance(self.iterations, np.ndarray):
            from photon_ml_tpu.utils.sync_telemetry import record_host_fetch

            it, v, c = jax.device_get(tuple(
                None if a is None else ensure_addressable(a)
                for a in (self.iterations, self.final_values,
                          self.convergence_codes)))
            record_host_fetch(site="tracker.materialize")
            nr = self.num_real
            if nr is not None:
                it, v = it[:nr], v[:nr]
                c = None if c is None else c[:nr]
            self.iterations, self.final_values = np.asarray(it), np.asarray(v)
            self.convergence_codes = None if c is None else np.asarray(c)
            self.num_real = None
        return self

    def counts_by_convergence(self) -> dict[str, int]:
        """reason name -> entity count
        (RandomEffectOptimizationTracker.countsByConvergence)."""
        from photon_ml_tpu.game.random_effect import CONVERGENCE_CODE_NAMES

        self.materialize()
        if self.convergence_codes is None:
            return {}
        codes, counts = np.unique(self.convergence_codes,
                                  return_counts=True)
        return {CONVERGENCE_CODE_NAMES[int(c)]: int(n)
                for c, n in zip(codes, counts)}

    def summary(self) -> str:
        it = self.materialize().iterations
        base = (f"random effect: {len(it)} entities, iterations "
                f"min/mean/max = {it.min()}/{it.mean():.1f}/{it.max()}")
        counts = self.counts_by_convergence()
        if counts:
            base += ", convergence " + "/".join(
                f"{k}={v}" for k, v in sorted(counts.items()))
        return base


@dataclasses.dataclass
class FactoredRandomEffectTracker:
    inner: list[tuple[RandomEffectTracker, FixedEffectTracker]]

    def materialize(self) -> "FactoredRandomEffectTracker":
        for re_tracker, fe_tracker in self.inner:
            re_tracker.materialize()
            fe_tracker.materialize()
        return self

    def summary(self) -> str:
        return (f"factored random effect: {len(self.inner)} inner iterations")


Tracker = Union[FixedEffectTracker, RandomEffectTracker,
                FactoredRandomEffectTracker]


# ---------------------------------------------------------------------------


@dataclasses.dataclass
class FixedEffectCoordinate:
    """Global GLM coordinate over the mesh-sharded sample batch."""

    dataset: FixedEffectDataset
    problem: GLMOptimizationProblem
    seed: int = 0
    _update_count: int = 0

    @property
    def num_samples(self) -> int:
        return self.dataset.num_samples

    def initial_state(self) -> Array:
        """Zero coefficients in normalized space."""
        return jnp.zeros(self.dataset.batch.num_features)

    def update(self, coefs: Optional[Array], extra_scores: Array
               ) -> tuple[Array, Tracker]:
        """Re-optimize on the offset-adjusted batch
        (FixedEffectCoordinate.updateModel :137-148 + runWithSampling).
        Device-resident: ``run_lazy`` keeps the solve history on device, so
        the returned coefficients/tracker carry no blocking host read — the
        CD fused epilogue owns the update's single device→host fetch."""
        batch = self.dataset.with_offsets(extra_scores)
        rate = self.problem.config.down_sampling_rate
        if rate < 1.0:
            key = jax.random.PRNGKey(self.seed + self._update_count)
            batch = down_sample(
                batch, rate, key,
                is_classification=self.problem.task in _CLASSIFICATION_TASKS)
        self._update_count += 1
        result = self.problem.run_lazy(batch, initial=coefs)
        return result.coefficients, FixedEffectTracker(result)

    def score(self, coefs: Array) -> Array:
        """Sample-axis margins x.w (normalized-space coefficients are scored
        through the normalization's effective-coefficient algebra)."""
        w_eff, shift = self.problem.normalization.effective_coefficients(coefs)
        zero_off = self.dataset.batch._replace(
            offsets=jnp.zeros_like(self.dataset.base_offsets))
        return zero_off.margins(w_eff, shift)

    def regularization_value(self, coefs: Array) -> float:
        return self.problem.regularization_value(coefs)

    def regularization_value_device(self, coefs: Array):
        """Penalty as a device scalar (no sync) for the CD epilogue."""
        return self.problem.regularization_value_device(coefs)

    def publish(self, coefs: Array) -> FixedEffectModel:
        means = self.problem.normalization.transform_model_coefficients(coefs)
        model = GeneralizedLinearModel(Coefficients(means=means),
                                       self.problem.task)
        return FixedEffectModel(model=model,
                                feature_shard_id=self.dataset.shard_id)


# ---------------------------------------------------------------------------


@dataclasses.dataclass
class RandomEffectCoordinate:
    """Per-entity GLM coordinate, vmapped over the entity axis.

    Combines the reference's RandomEffectCoordinate and its projected-space
    wrapper: the dataset is already in each entity's reduced space, so the
    coordinate state (``[E, D_red]``) is the projected model
    (RandomEffectCoordinateInProjectedSpace.scala:25-149).
    """

    dataset: RandomEffectDataset
    problem: RandomEffectOptimizationProblem

    @property
    def num_samples(self) -> int:
        return self.dataset.num_samples

    def initial_state(self) -> Array:
        return jnp.zeros((self.dataset.num_entities, self.dataset.reduced_dim))

    def update(self, coefs: Optional[Array], extra_scores: Array
               ) -> tuple[Array, Tracker]:
        offsets = self.dataset.offsets_with(extra_scores)
        # ``donate=True``: the per-update offset block is rebuilt from the
        # CD score vector every update, so the solver may reuse its device
        # buffer in place (no-op on CPU; ``coefs`` — the CD loop's live
        # last-good state — is never donated, see _dispatch_fit)
        new_coefs, iters, values, codes = self.problem.run(
            self.dataset, offsets, initial=coefs, donate=True)
        # lazy tracker: arrays stay on device until log/metrics time; the
        # num_real bound trims the single-block path's entity-axis PAD
        # lanes at materialization (the bucketed path is already compact)
        tracker = RandomEffectTracker(
            iters, values, codes, num_real=len(self.dataset.entity_codes))
        return new_coefs, tracker

    def score(self, coefs: Array) -> Array:
        return score_random_effect(
            self.dataset, coefs,
            entity_shards=self.problem.entity_shards,
            collective_quant=self.problem.collective_quant)

    def regularization_value(self, coefs: Array) -> float:
        return self.problem.regularization_value(coefs)

    def regularization_value_device(self, coefs: Array):
        """Penalty as a device scalar (no sync) for the CD epilogue."""
        return self.problem.regularization_value_device(coefs)

    def publish(self, coefs: Array) -> RandomEffectModelInProjectedSpace:
        return RandomEffectModelInProjectedSpace(
            random_effect_type=self.dataset.config.random_effect_type,
            feature_shard_id=self.dataset.config.feature_shard_id,
            entity_codes=self.dataset.entity_codes,
            coefficients_projected=coefs,
            projectors=self.dataset.projectors,
            random_projector=self.dataset.random_projector,
        )


# ---------------------------------------------------------------------------


@dataclasses.dataclass
class FactoredRandomEffectCoordinate:
    """Alternating latent-space random effect + projection-matrix fit.

    The dataset must be built with IDENTITY projection (raw-space blocks
    ``[E, N, D]``). Each update runs ``num_inner_iterations`` of:

    1. project actives into the current latent space
       (``X_lat = X · Bᵀ``, one einsum) and solve per-entity latent
       coefficients with the vmapped block solver
       (FactoredRandomEffectCoordinate.scala:228-257's random-effect step);
    2. refit B on Kronecker-product features ``c_e ⊗ x`` with a single
       GLM whose coefficient vector is vec(B)
       (kroneckerProductFeaturesAndCoefficients :271) — the expansion is an
       einsum producing ``[E·N, K·D]``.
    """

    dataset: RandomEffectDataset  # identity-projected (raw blocks)
    problem: RandomEffectOptimizationProblem  # latent per-entity fits
    latent_problem: GLMOptimizationProblem  # projection-matrix fit
    latent_dim: int
    num_inner_iterations: int = 2
    seed: int = 0

    def __post_init__(self):
        if self.dataset.projectors is not None or \
                self.dataset.random_projector is not None:
            raise ValueError(
                "factored coordinate needs an identity-projected dataset")
        if self.dataset.buckets is not None:
            raise ValueError(
                "factored coordinate needs a single-block dataset "
                "(build with num_buckets=1): the latent refit shares one "
                "projection matrix across all entities")

    @property
    def num_samples(self) -> int:
        return self.dataset.num_samples

    def initial_state(self) -> tuple[Array, Array]:
        k = self.latent_dim
        e = self.dataset.num_entities
        d = self.dataset.reduced_dim
        # Random projection init (MFOptimizationConfiguration analog).
        # Explicit f32: under x64 the default dtype would draw DIFFERENT
        # random bits, and the bilinear alternation amplifies an init
        # difference into a different local optimum — the init must not
        # depend on the precision mode (blocks are f32 regardless).
        b0 = jax.random.normal(jax.random.PRNGKey(self.seed), (k, d),
                               dtype=jnp.float32) / jnp.sqrt(k)
        return jnp.zeros((e, k), jnp.float32), b0

    def update(self, state: Optional[tuple[Array, Array]],
               extra_scores: Array) -> tuple[tuple[Array, Array], Tracker]:
        coefs, B = state if state is not None else self.initial_state()
        ds = self.dataset
        offsets = ds.offsets_with(extra_scores)
        # The init is drawn in f32 so its BITS don't depend on the x64
        # mode; the running state then promotes to the ambient dtype (x64
        # runs keep solving in f64, with the identical starting values).
        acc = jnp.promote_types(jnp.promote_types(coefs.dtype, jnp.float32),
                                offsets.dtype)
        coefs, B = coefs.astype(acc), B.astype(acc)
        inner: list = []
        for _ in range(self.num_inner_iterations):
            # (1) latent-space per-entity fits on projected blocks.
            X_lat = jnp.einsum("end,kd->enk", ds.X, B,
                               preferred_element_type=jnp.float32)
            lat_ds = dataclasses.replace(ds, X=X_lat, projectors=None,
                                         random_projector=None)
            # donate=False: ``offsets`` is reused across inner iterations
            # and by the Kronecker refit below — its buffer must survive
            coefs, iters, values, codes = self.problem.run(
                lat_ds, offsets, initial=coefs, donate=False)
            re_tracker = RandomEffectTracker(
                iters, values, codes, num_real=len(ds.entity_codes))
            # (2) projection-matrix fit on Kronecker features c_e ⊗ x.
            e, n, d = ds.X.shape
            k = self.latent_dim
            kron = jnp.einsum("ek,end->enkd", coefs, ds.X,
                              preferred_element_type=jnp.float32)
            flat = DenseBatch(
                X=kron.reshape(e * n, k * d),
                labels=ds.labels.reshape(-1),
                offsets=offsets.reshape(-1),
                weights=ds.weights.reshape(-1),
            )
            _, result = self.latent_problem.run(
                flat, initial=B.reshape(-1))
            B = result.coefficients.reshape(k, d)
            inner.append((re_tracker, FixedEffectTracker(result)))
        return (coefs, B), FactoredRandomEffectTracker(inner)

    def score(self, state: tuple[Array, Array]) -> Array:
        coefs, B = state
        X_lat = jnp.einsum("end,kd->enk", self.dataset.X, B,
                           preferred_element_type=jnp.float32)
        # Passive rows project through the same latent map for scoring.
        lat_passive = (None if self.dataset.passive_X is None
                       else self.dataset.passive_X @ B.T)
        lat_ds = dataclasses.replace(self.dataset, X=X_lat,
                                     passive_X=lat_passive,
                                     projectors=None, random_projector=None)
        return score_random_effect(
            lat_ds, coefs,
            entity_shards=self.problem.entity_shards,
            collective_quant=self.problem.collective_quant)

    def regularization_value(self, state: tuple[Array, Array]) -> float:
        coefs, B = state
        return (self.problem.regularization_value(coefs)
                + self.latent_problem.regularization_value(B.reshape(-1)))

    def regularization_value_device(self, state: tuple[Array, Array]):
        """Penalty as a device scalar (no sync) for the CD epilogue."""
        coefs, B = state
        return (self.problem.regularization_value_device(coefs)
                + self.latent_problem.regularization_value_device(
                    B.reshape(-1)))

    def publish(self, state: tuple[Array, Array]) -> FactoredRandomEffectModel:
        coefs, B = state
        return FactoredRandomEffectModel(
            random_effect_type=self.dataset.config.random_effect_type,
            feature_shard_id=self.dataset.config.feature_shard_id,
            entity_codes=self.dataset.entity_codes,
            coefficients_latent=coefs,
            projection=B,
        )


Coordinate = Union[FixedEffectCoordinate, RandomEffectCoordinate,
                   FactoredRandomEffectCoordinate]
