"""SARIF 2.1.0 output for photonlint — editor and CI consumption.

One run, one tool, one result per *new* finding (baselined and
suppressed findings are deliberately omitted: SARIF consumers gate on
what's actionable, and the baseline already owns the grandfathered
set). The rule catalog is generated from ``core.RULES`` so the SARIF
``rules`` array, ``--list-rules`` and the README table can never
drift apart.
"""

from __future__ import annotations

import json

from photon_ml_tpu.analysis.core import Finding, LintReport, RULES

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")
_INFO_URI = "https://github.com/photon-ml-tpu"  # repo docs anchor
# Every rule row lives in the README "Rule catalog" table; SARIF viewers
# surface helpUri as the rule's "more info" link.
_CATALOG_URI = _INFO_URI + "/blob/main/README.md#rule-catalog"


def _result(f: Finding) -> dict:
    return {
        "ruleId": f.rule,
        "level": "warning",
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path,
                                     "uriBaseId": "SRCROOT"},
                "region": {"startLine": f.line,
                           "startColumn": f.col + 1},
            },
        }],
    }


def to_sarif(report: LintReport) -> dict:
    rules = [
        {
            "id": rule,
            "name": rule,
            "shortDescription": {"text": text},
            "helpUri": _CATALOG_URI,
            "defaultConfiguration": {"level": "warning"},
        }
        for rule, text in sorted(RULES.items())
    ]
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "photonlint",
                    "informationUri": _INFO_URI,
                    "rules": rules,
                },
            },
            "results": [_result(f) for f in report.new],
        }],
    }


def format_sarif(report: LintReport) -> str:
    return json.dumps(to_sarif(report), indent=2, sort_keys=True)
