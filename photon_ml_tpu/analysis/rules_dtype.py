"""W8xx — precision dtype-flow: bf16/f16 accumulation discipline.

A second abstract interpreter rides the same module ASTs as the jax
dataflow, but tracks *dtypes* instead of device placement. The lattice:

- ``f64``/``f32``/``bf16``/``f16`` — concrete float dtypes, from
  ``jnp.float32``-style tokens, ``"float32"`` strings, dtype kwargs and
  ``astype`` casts;
- ``weak`` — python float literals (weakly typed: they inherit the
  other operand's dtype under jax promotion);
- ``dyn`` — a *runtime-selected* dtype: the result of reading
  ``x.dtype`` or of ``astype(some_dtype_variable)``. A ``dyn`` value may
  be bf16/f16 at runtime — this is exactly the dtype-generic kernel
  pattern (``ops/pallas_kernels.py`` casts operands to ``x_ref.dtype``),
  so reductions over it need an explicit f32 accumulator;
- ``None`` — unknown; unknown is clean everywhere (precision over
  recall, same bias as the jax dataflow).

Cross-module: a top-level function whose every return joins to one
concrete tag exports it, fixpoint-style, so ``scale(x)`` returning bf16
in another module taints its callers.

Rules:

- **W801** a reduction (``sum``/``mean``/``dot``/``matmul``/``einsum``/
  ``dot_general``/``psum``/``pmean``/``segment_sum``/…) over a
  may-low-precision operand (bf16/f16/dyn) with no explicit accumulator
  — no ``preferred_element_type=``/``dtype=`` kwarg and no upcast. Any
  explicit accumulator kwarg clears the taint (a deliberate low-
  precision accumulator is a choice, not an accident).
- **W802** float64 construction (f64 dtype kwarg, ``astype(float64)``,
  ``jnp.float64(...)``) inside jit-reachable code in a module with no
  ``jax_enable_x64`` guard: under default config this silently truncates
  to f32; with x64 on it doubles memory — either way it should be
  deliberate and guarded.
- **W803** a jax value round-tripped through ``np.asarray``/``np.array``
  and fed back into a jitted callable — the round trip erases weak-type
  and committed-device information and re-traces on the promoted dtype
  (complements W701's shape-driven retrace rule).
- **W804** arithmetic mixing a concrete low dtype (bf16/f16) with a
  concrete high one (f32/f64) inside a loss/gradient-named function,
  relying on implicit promotion — make the promotion explicit where it
  decides gradient precision.
"""

from __future__ import annotations

import ast
from typing import Optional

from photon_ml_tpu.analysis.core import Finding
from photon_ml_tpu.analysis.dataflow import Dataflow, JAXFN, is_jax
from photon_ml_tpu.analysis.package import ModuleInfo, PackageIndex

F64, F32, BF16, F16, WEAK, DYN = "f64", "f32", "bf16", "f16", "weak", "dyn"
_LOW_CONCRETE = {BF16, F16}
_LOW = {BF16, F16, DYN}
_HIGH = {F32, F64}
_RANK = {WEAK: 0, F16: 1, BF16: 1, F32: 2, F64: 3}

# Trailing-component dtype tokens: jnp.float32, np.bfloat16, "float16".
_DTYPE_TOKENS = {
    "float64": F64, "double": F64, "float32": F32, "single": F32,
    "bfloat16": BF16, "float16": F16, "half": F16,
}

# jax reductions that accumulate in the operand dtype unless told not to.
_REDUCTIONS = {
    "jax.numpy.sum", "jax.numpy.mean", "jax.numpy.prod", "jax.numpy.dot",
    "jax.numpy.matmul", "jax.numpy.einsum", "jax.numpy.tensordot",
    "jax.numpy.cumsum", "jax.lax.dot", "jax.lax.dot_general",
    "jax.lax.psum", "jax.lax.pmean", "jax.ops.segment_sum",
}
_REDUCE_METHODS = {"sum", "mean", "prod", "dot"}
_ACC_KWARGS = ("preferred_element_type", "dtype", "acc_dtype")
# dtype-preserving methods worth following through chains.
_KEEP_METHODS = {"reshape", "ravel", "transpose", "squeeze", "copy",
                 "flatten", "block_until_ready", "conj", "clip"}
# dtype-preserving/promoting jnp calls: name -> index of first VALUE arg
# (where's condition arg carries no dtype).
_ELEMENTWISE = {
    "where": 1, "maximum": 0, "minimum": 0, "clip": 0, "abs": 0,
    "exp": 0, "log": 0, "log1p": 0, "expm1": 0, "sqrt": 0, "tanh": 0,
    "negative": 0, "transpose": 0, "reshape": 0, "ravel": 0,
    "squeeze": 0, "broadcast_to": 0, "concatenate": 0, "stack": 0,
    "add": 0, "subtract": 0, "multiply": 0, "divide": 0,
}
# array makers: name -> positional index of the dtype argument.
_MAKER_DTYPE_POS = {
    "asarray": 1, "array": 1, "zeros": 1, "ones": 1, "empty": 1,
    "full": 2, "zeros_like": 1, "ones_like": 1, "full_like": 2,
    "arange": None, "linspace": None, "eye": None,
}
# makers whose no-dtype default is the jnp float default (f32).
_F32_DEFAULT_MAKERS = {"zeros", "ones", "empty", "full", "linspace", "eye"}
_NP_CONVERTERS = {"numpy.asarray", "numpy.array", "numpy.ascontiguousarray"}
_LOSS_PATH_MARKERS = ("loss", "grad", "objective")


def _promote(a: Optional[str], b: Optional[str]) -> Optional[str]:
    """May-join under jax promotion: unknown defers to the known side,
    dyn stays dyn against weak/low (it may BE low) but a concrete f32/f64
    operand dominates the runtime result."""
    if a is None:
        return b
    if b is None:
        return a
    if a == b:
        return a
    if DYN in (a, b):
        other = b if a == DYN else a
        return other if other in _HIGH else DYN
    if {a, b} == {F16, BF16}:
        return F32  # jax promotes mixed half types to f32
    return a if _RANK[a] >= _RANK[b] else b


def _elt_tags(tag):
    if isinstance(tag, tuple) and tag and tag[0] == "tuple":
        return tag[1]
    return None


def _scalar(tag):
    """Collapse a tuple tag to the join of its elements."""
    elts = _elt_tags(tag)
    if elts is None:
        return tag
    out = None
    for t in elts:
        out = _promote(out, _scalar(t))
    return out


def _tail(dotted: str) -> str:
    return dotted.rsplit(".", 1)[-1]


def _x64_guarded(mod: ModuleInfo) -> bool:
    """True when the module visibly engages the x64 config switch."""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Constant) and node.value == "jax_enable_x64":
            return True
        if isinstance(node, ast.Attribute) and node.attr in (
                "jax_enable_x64", "x64_enabled", "enable_x64"):
            return True
    return False


class _DtypeInterp:
    """Per-module dtype walker; mirrors the jax dataflow's statement
    coverage (may-merge branches, two loop passes, nested defs at their
    definition point with the enclosing env as closure)."""

    def __init__(self, mod: ModuleInfo, index: PackageIndex,
                 flow: Dataflow, fn_dtypes: dict[str, str],
                 jit_reachable: set[str], emit: bool,
                 findings: Optional[list] = None):
        self.mod = mod
        self.index = index
        self.flow = flow
        self.fn_dtypes = fn_dtypes
        self.jit_reachable = jit_reachable
        self.emit = emit
        self.findings = findings if findings is not None else []
        self.fn_returns: dict[int, list] = {}
        self._ret_stack: list[list] = []
        self._fn_stack: list[str] = []
        self._x64_guard = _x64_guarded(mod)

    def run_module(self) -> None:
        self.run_block(self.mod.tree.body, {})

    # -- statements --------------------------------------------------------

    def run_block(self, body, env: dict) -> dict:
        for stmt in body:
            env = self.stmt(stmt, env)
        return env

    def stmt(self, s: ast.stmt, env: dict) -> dict:
        if isinstance(s, ast.Assign):
            t = self.expr(s.value, env)
            for tgt in s.targets:
                self.bind(tgt, t, env)
        elif isinstance(s, ast.AnnAssign):
            if s.value is not None:
                self.bind(s.target, self.expr(s.value, env), env)
        elif isinstance(s, ast.AugAssign):
            t = self.expr(s.value, env)
            if isinstance(s.target, ast.Name):
                env[s.target.id] = _promote(
                    _scalar(env.get(s.target.id)), _scalar(t))
        elif isinstance(s, ast.Return):
            t = self.expr(s.value, env) if s.value is not None else None
            if self._ret_stack:
                self._ret_stack[-1].append(t)
        elif isinstance(s, ast.Expr):
            self.expr(s.value, env)
        elif isinstance(s, ast.If):
            self.expr(s.test, env)
            env_a = self.run_block(s.body, dict(env))
            env_b = self.run_block(s.orelse, dict(env))
            env = _merge(env_a, env_b)
        elif isinstance(s, ast.For):
            self.expr(s.iter, env)
            self.bind(s.target, None, env)
            for _ in range(2):
                env = _merge(env, self.run_block(s.body, dict(env)))
            env = self.run_block(s.orelse, env)
        elif isinstance(s, ast.While):
            self.expr(s.test, env)
            for _ in range(2):
                env = _merge(env, self.run_block(s.body, dict(env)))
            env = self.run_block(s.orelse, env)
        elif isinstance(s, ast.With):
            for item in s.items:
                t = self.expr(item.context_expr, env)
                if item.optional_vars is not None:
                    self.bind(item.optional_vars, t, env)
            env = self.run_block(s.body, env)
        elif isinstance(s, ast.Try):
            env = self.run_block(s.body, env)
            base = dict(env)
            for h in s.handlers:
                env = _merge(env, self.run_block(h.body, dict(base)))
            env = self.run_block(s.orelse, env)
            env = self.run_block(s.finalbody, env)
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._enter_function(s, env)
        elif isinstance(s, ast.ClassDef):
            for sub in s.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._enter_function(sub, dict(env))
        elif isinstance(s, (ast.Raise, ast.Assert, ast.Delete)):
            for child in ast.iter_child_nodes(s):
                if isinstance(child, ast.expr):
                    self.expr(child, env)
        return env

    def _enter_function(self, fdef, closure_env: dict) -> None:
        env = dict(closure_env)
        a = fdef.args
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            env.pop(p.arg, None)
        if a.vararg:
            env.pop(a.vararg.arg, None)
        if a.kwarg:
            env.pop(a.kwarg.arg, None)
        for d in fdef.args.defaults + fdef.args.kw_defaults:
            if d is not None:
                self.expr(d, closure_env)
        self._ret_stack.append([])
        self._fn_stack.append(fdef.name)
        self.run_block(fdef.body, env)
        self._fn_stack.pop()
        self.fn_returns[id(fdef)] = self._ret_stack.pop()

    def bind(self, target, tag, env: dict) -> None:
        if isinstance(target, ast.Name):
            if tag is None:
                env.pop(target.id, None)
            else:
                env[target.id] = tag
        elif isinstance(target, (ast.Tuple, ast.List)):
            elts = _elt_tags(tag)
            if elts is not None and len(elts) == len(target.elts) \
                    and not any(isinstance(e, ast.Starred)
                                for e in target.elts):
                for elt, t in zip(target.elts, elts):
                    self.bind(elt, t, env)
            else:
                for elt in target.elts:
                    self.bind(elt, None, env)
        elif isinstance(target, ast.Starred):
            self.bind(target.value, tag, env)

    # -- expressions -------------------------------------------------------

    def expr(self, e: Optional[ast.expr], env: dict):
        if e is None:
            return None
        if isinstance(e, ast.Constant):
            if isinstance(e.value, float):
                return WEAK
            if isinstance(e.value, str):
                return _DTYPE_TOKENS.get(e.value)
            return None
        if isinstance(e, ast.Name):
            tok = self._token(e)
            return tok if tok is not None else env.get(e.id)
        if isinstance(e, ast.Attribute):
            if e.attr == "dtype":
                self.expr(e.value, env)
                return DYN
            tok = self._token(e)
            if tok is not None:
                return tok
            base = self.expr(e.value, env)
            if e.attr in ("T", "mT", "real", "imag", "at"):
                return _scalar(base)
            return None
        if isinstance(e, ast.Call):
            return self._call(e, env)
        if isinstance(e, ast.BinOp):
            left = _scalar(self.expr(e.left, env))
            right = _scalar(self.expr(e.right, env))
            if isinstance(e.op, ast.MatMult):
                self._check_reduction(e, [left, right], has_acc=False)
            elif self.emit and isinstance(
                    e.op, (ast.Add, ast.Sub, ast.Mult, ast.Div)):
                pair = {left, right}
                if pair & _LOW_CONCRETE and pair & _HIGH and any(
                        m in name for name in self._fn_stack
                        for m in _LOSS_PATH_MARKERS):
                    low = (pair & _LOW_CONCRETE).pop()
                    high = (pair & _HIGH).pop()
                    self.findings.append(Finding(
                        "W804", self.mod.relpath, e.lineno, e.col_offset,
                        f"{low} and {high} mixed by implicit promotion "
                        f"in a loss/gradient path — cast explicitly so "
                        f"the gradient precision is a decision, not a "
                        f"promotion-rule accident"))
            return _promote(left, right)
        if isinstance(e, ast.UnaryOp):
            return self.expr(e.operand, env)
        if isinstance(e, (ast.Compare, ast.BoolOp)):
            for child in ast.iter_child_nodes(e):
                if isinstance(child, ast.expr):
                    self.expr(child, env)
            return None  # boolean results
        if isinstance(e, ast.IfExp):
            self.expr(e.test, env)
            return _promote(_scalar(self.expr(e.body, env)),
                            _scalar(self.expr(e.orelse, env)))
        if isinstance(e, ast.Subscript):
            t = self.expr(e.value, env)
            self.expr(e.slice, env)
            elts = _elt_tags(t)
            if elts is not None and isinstance(e.slice, ast.Constant) \
                    and isinstance(e.slice.value, int) \
                    and -len(elts) <= e.slice.value < len(elts):
                return elts[e.slice.value]
            return _scalar(t)
        if isinstance(e, (ast.Tuple, ast.List)) and not any(
                isinstance(v, ast.Starred) for v in e.elts):
            tags = tuple(self.expr(v, env) for v in e.elts)
            return ("tuple", tags) if any(t is not None for t in tags) \
                else None
        if isinstance(e, ast.NamedExpr):
            t = self.expr(e.value, env)
            self.bind(e.target, t, env)
            return t
        if isinstance(e, ast.Lambda):
            inner = dict(env)
            a = e.args
            for p in a.posonlyargs + a.args + a.kwonlyargs:
                inner.pop(p.arg, None)
            self.expr(e.body, inner)
            return None
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                          ast.DictComp)):
            inner = dict(env)
            for gen in e.generators:
                self.expr(gen.iter, inner)
                self.bind(gen.target, None, inner)
                for cond in gen.ifs:
                    self.expr(cond, inner)
            if isinstance(e, ast.DictComp):
                self.expr(e.key, inner)
                self.expr(e.value, inner)
            else:
                self.expr(e.elt, inner)
            return None
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.expr):
                self.expr(child, env)
        return None

    def _token(self, node) -> Optional[str]:
        d = self.mod.resolve(node)
        if d is not None:
            return _DTYPE_TOKENS.get(_tail(d))
        return None

    def _dtype_arg(self, node, env) -> Optional[str]:
        """Dtype tag of an expression used *as a dtype* (kwarg/astype)."""
        if node is None:
            return None
        tok = None
        if isinstance(node, (ast.Name, ast.Attribute)):
            tok = self._token(node)
        if tok is not None:
            return tok
        return _scalar(self.expr(node, env))

    def _acc_kwarg(self, call: ast.Call, env):
        """(present, tag) for an explicit accumulator kwarg."""
        for kw in call.keywords:
            if kw.arg in _ACC_KWARGS:
                return True, self._dtype_arg(kw.value, env)
        return False, None

    def _check_reduction(self, node, operand_tags, has_acc: bool,
                         name: str = "@") -> None:
        if has_acc or not self.emit:
            return
        low = [t for t in operand_tags if t in _LOW]
        if not low:
            return
        tag = DYN if DYN in low else low[0]
        what = ("a value whose dtype is selected at runtime (propagated "
                "from a .dtype read)" if tag == DYN
                else f"a {tag} value")
        self.findings.append(Finding(
            "W801", self.mod.relpath, node.lineno, node.col_offset,
            f"{name} reduces {what} without an f32 accumulator — pass "
            f"preferred_element_type=jnp.float32 (or an explicit f32 "
            f"dtype/upcast) so bf16/f16 inputs do not accumulate in low "
            f"precision"))

    def _call(self, e: ast.Call, env):
        arg_tags = [_scalar(self.expr(a, env)) for a in e.args]
        kw_tags = {kw.arg: self.expr(kw.value, env) for kw in e.keywords}
        d = self.mod.resolve(e.func)

        # method calls on VALUES: x.astype(...), x.sum(), x.reshape(...)
        # — a resolvable dotted func (jnp.mean, np.sum) is a module call
        # and is classified below, not here
        if isinstance(e.func, ast.Attribute) and d is None:
            base = _scalar(self.expr(e.func.value, env))
            attr = e.func.attr
            if attr == "astype":
                target = e.args[0] if e.args else None
                for kw in e.keywords:
                    if kw.arg == "dtype":
                        target = kw.value
                return self._dtype_arg(target, env)
            if attr in _REDUCE_METHODS:
                jax_base = is_jax(self.flow.tag(e.func.value))
                has_acc, acc = self._acc_kwarg(e, env)
                if jax_base:
                    self._check_reduction(e, [base], has_acc,
                                          name=f".{attr}()")
                return acc if has_acc else base
            if attr in _KEEP_METHODS:
                return base

        if d is None:
            return None
        tail = _tail(d)
        is_jnp = d.startswith(("jax.numpy.", "jax.lax.", "jax.ops.",
                               "jax.nn.", "jax.scipy."))
        if d in _REDUCTIONS:
            has_acc, acc = self._acc_kwarg(e, env)
            self._check_reduction(e, arg_tags, has_acc, name=tail)
            if has_acc:
                return acc
            out = None
            for t in arg_tags:
                out = _promote(out, t)
            return out
        if is_jnp or d.startswith("numpy."):
            if tail in _MAKER_DTYPE_POS:
                dt = None
                pos = _MAKER_DTYPE_POS[tail]
                if "dtype" in kw_tags:
                    dt = self._dtype_arg(
                        next(kw.value for kw in e.keywords
                             if kw.arg == "dtype"), env)
                elif pos is not None and len(e.args) > pos:
                    dt = self._dtype_arg(e.args[pos], env)
                if dt is not None:
                    self._check_f64_construction(e, dt, d)
                    return dt
                if tail in ("asarray", "array", "zeros_like", "ones_like",
                            "full_like") and arg_tags:
                    return arg_tags[0]
                if is_jnp and tail in _F32_DEFAULT_MAKERS:
                    return F32
                return None
            if _DTYPE_TOKENS.get(tail) is not None:
                dt = _DTYPE_TOKENS[tail]
                self._check_f64_construction(e, dt, d)
                return dt
            if is_jnp and tail in _ELEMENTWISE:
                out = None
                for t in arg_tags[_ELEMENTWISE[tail]:]:
                    out = _promote(out, t)
                return out
            return None
        # cross-module: a package function with a known return dtype
        if d in self.fn_dtypes:
            return self.fn_dtypes[d]
        if d == "dataclasses.replace" and arg_tags:
            return arg_tags[0]
        return None

    def _check_f64_construction(self, node, dt, dotted) -> None:
        if not self.emit or dt != F64 or self._x64_guard:
            return
        fn = self._fn_stack[-1] if self._fn_stack else None
        if fn is None:
            return
        dotted_fn = f"{self.mod.module_name}.{fn}"
        if dotted_fn not in self.jit_reachable:
            return
        self.findings.append(Finding(
            "W802", self.mod.relpath, node.lineno, node.col_offset,
            f"float64 constructed via {dotted} in jit-reachable code "
            f"with no jax_enable_x64 guard in the module — under the "
            f"default config this silently truncates to float32; guard "
            f"the x64 config or use an explicit float32 dtype"))


def _merge(a: dict, b: dict) -> dict:
    out = dict(a)
    for k, v in b.items():
        cur = out.get(k)
        out[k] = v if cur is None else (
            cur if cur == v else _promote(_scalar(cur), _scalar(v)))
    return out


def _w803(mod: ModuleInfo, flow: Dataflow, jit_names: set[str],
          findings: list) -> None:
    """np.asarray(jax) results fed back into a jitted callable."""
    from photon_ml_tpu.analysis.rules_sync import build_scope_map

    scope_of = build_scope_map(mod.tree)
    # (scope id, name) -> line of the erasing conversion
    erased: dict[tuple, int] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call):
            d = mod.resolve(node.value.func)
            if d in _NP_CONVERTERS and node.value.args \
                    and is_jax(flow.tag(node.value.args[0])):
                scope = scope_of.get(id(node.value))
                key = (None if scope is None else id(scope),
                       node.targets[0].id)
                erased[key] = node.lineno
    if not erased:
        return
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        d = mod.resolve(node.func)
        jitted = (d in jit_names) or flow.tag(node.func) == JAXFN
        if not jitted:
            continue
        scope = scope_of.get(id(node))
        sid = None if scope is None else id(scope)
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Name) and (sid, arg.id) in erased:
                findings.append(Finding(
                    "W803", mod.relpath, node.lineno, node.col_offset,
                    f"{arg.id!r} is a jax value round-tripped through "
                    f"np.asarray (line {erased[(sid, arg.id)]}) and fed "
                    f"back into a jitted callable — the round trip "
                    f"erases weak-type/committed-device info and "
                    f"retraces on the promoted dtype; keep the value on "
                    f"device or device_get once at the boundary"))


def check(modules: list[ModuleInfo], index: PackageIndex,
          flows: dict[str, Dataflow], ctx) -> list[Finding]:
    jit_reachable = set(index.jit_reachable())
    jit_names = {b.impl for b in index.jit_bindings}
    jit_names.update(b.mod.module_name + "." + b.bound_name
                     for b in index.jit_bindings if b.bound_name)

    # fixpoint: export concrete return dtypes of top-level functions
    fn_dtypes: dict[str, str] = {}
    for _ in range(3):
        grew = False
        for mod in modules:
            interp = _DtypeInterp(mod, index, flows[mod.relpath],
                                  fn_dtypes, jit_reachable, emit=False)
            interp.run_module()
            for name, node in mod.toplevel_defs.items():
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                rets = interp.fn_returns.get(id(node), [])
                if not rets or any(not isinstance(t, str) or t == WEAK
                                   for t in (_scalar(r) for r in rets)):
                    continue
                tag = None
                for r in rets:
                    tag = _promote(tag, _scalar(r))
                dotted = f"{mod.module_name}.{name}"
                if tag is not None and fn_dtypes.get(dotted) != tag:
                    fn_dtypes[dotted] = tag
                    grew = True
        if not grew:
            break

    findings: list[Finding] = []
    for mod in modules:
        interp = _DtypeInterp(mod, index, flows[mod.relpath], fn_dtypes,
                              jit_reachable, emit=True, findings=findings)
        interp.run_module()
        _w803(mod, flows[mod.relpath], jit_names, findings)
    # loop bodies run twice and both If arms run — dedupe repeat visits
    seen: set[tuple] = set()
    out: list[Finding] = []
    for f in findings:
        key = (f.rule, f.path, f.line, f.col, f.message)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out
