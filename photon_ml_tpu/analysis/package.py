"""Module loading and whole-package name resolution for photonlint.

Everything here is syntactic: modules are parsed with the stdlib ``ast``
(never imported — linting must not execute package code or require jax),
and names are resolved through each module's import aliases. Resolution
returns *dotted* names (``jax.numpy.where``,
``photon_ml_tpu.evaluation.metrics.peak_f1``) that the rule modules
classify; a name that cannot be resolved resolves to ``None`` and every
downstream consumer treats unknown as "not mine" (lint stays precise
rather than noisy).
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Optional

from photon_ml_tpu.analysis.core import Finding, parse_suppressions

PACKAGE_PREFIX = "photon_ml_tpu."

# jax.jit-alikes whose call wraps a function for tracing.
JIT_WRAPPERS = {
    "jax.jit", "jax.pjit", "jax.experimental.pjit.pjit",
    "jax.experimental.pjit", "pjit",
}


@dataclasses.dataclass
class ModuleInfo:
    """One parsed source file plus its resolution tables."""

    path: Path
    relpath: str  # posix, relative to the lint root
    module_name: str  # dotted guess from relpath ("tools.photonlint")
    source: str
    lines: list[str]
    tree: ast.Module
    imports: dict[str, str]  # local alias -> dotted target
    toplevel_defs: dict[str, ast.AST]  # name -> FunctionDef/ClassDef
    constants: dict[str, ast.expr]  # name -> module-level literal expr
    suppressions: dict[int, list[tuple[str, str]]]
    malformed: list[Finding]

    @classmethod
    def load(cls, path: Path, root: Path) -> "ModuleInfo":
        source = path.read_text()
        tree = ast.parse(source, filename=str(path))
        relpath = path.relative_to(root).as_posix()
        module_name = relpath[:-3].replace("/", ".") \
            if relpath.endswith(".py") else relpath.replace("/", ".")
        if module_name.endswith(".__init__"):
            module_name = module_name[: -len(".__init__")]
        imports = _collect_imports(tree, module_name)
        toplevel_defs = {}
        constants = {}
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                toplevel_defs[node.name] = node
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                constants[node.targets[0].id] = node.value
        lines = source.splitlines()
        suppressions, malformed = parse_suppressions(lines, relpath)
        return cls(path=path, relpath=relpath, module_name=module_name,
                   source=source, lines=lines, tree=tree, imports=imports,
                   toplevel_defs=toplevel_defs, constants=constants,
                   suppressions=suppressions, malformed=malformed)

    # -- name resolution ---------------------------------------------------

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted name for a Name/Attribute chain, through import aliases.

        A module-local top-level def resolves to
        ``<module_name>.<name>`` so cross-module call edges line up with
        the other side's definition index.
        """
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        base = self.imports.get(node.id)
        if base is None:
            if node.id in self.toplevel_defs or node.id in self.constants:
                base = f"{self.module_name}.{node.id}"
            else:
                return None
        parts.append(base)
        return ".".join(reversed(parts))

    def literal(self, node: ast.AST):
        """Best-effort literal value: direct literal or a one-hop
        module-level constant (``static_argnames=_STATIC``). Returns
        ``None`` when unresolvable."""
        if isinstance(node, ast.Name) and node.id in self.constants:
            node = self.constants[node.id]
        try:
            return ast.literal_eval(node)
        except (ValueError, TypeError, SyntaxError):
            return None


def _collect_imports(tree: ast.Module, module_name: str) -> dict[str, str]:
    imports: dict[str, str] = {}
    pkg_parts = module_name.split(".")[:-1]
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                local = alias.asname or alias.name.split(".")[0]
                target = alias.name if alias.asname else \
                    alias.name.split(".")[0]
                imports[local] = target
        elif isinstance(node, ast.ImportFrom):
            if node.level:  # relative import: anchor to this package
                anchor = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                base = ".".join(anchor + ([node.module] if node.module
                                          else []))
            else:
                base = node.module or ""
            for alias in node.names:
                if alias.name == "*":
                    continue
                local = alias.asname or alias.name
                imports[local] = f"{base}.{alias.name}" if base \
                    else alias.name
    return imports


# -- jit bindings ----------------------------------------------------------


@dataclasses.dataclass
class JitBinding:
    """One traced entry point: the impl function plus its jit options."""

    impl: str  # dotted name of the traced python function
    fdef: Optional[ast.AST]  # its FunctionDef when module-local
    mod: ModuleInfo
    static_names: Optional[set[str]]  # None = could not resolve statics
    donate_idx: set[int]
    bound_name: Optional[str]  # module-level name the wrapper is bound to


def _jit_options(mod: ModuleInfo, call: ast.Call,
                 fdef: Optional[ast.AST]) -> tuple[Optional[set[str]],
                                                   set[int]]:
    """Extract (static param names, donated arg indices) from a
    jax.jit/pjit call's keywords. Unresolvable statics → None (the
    dataflow then treats NO param as a tracer, biasing away from false
    positives)."""
    static_names: set[str] = set()
    donate_idx: set[int] = set()
    unknown = False
    params = []
    if fdef is not None:
        a = fdef.args
        params = [p.arg for p in a.posonlyargs + a.args]
    for kw in call.keywords:
        if kw.arg in ("static_argnames", "static_argnums",
                      "donate_argnums", "donate_argnames"):
            value = mod.literal(kw.value)
            if value is None:
                unknown = True
                continue
            if isinstance(value, (str, int)):
                value = (value,)
            if kw.arg == "static_argnames":
                static_names.update(value)
            elif kw.arg == "static_argnums":
                if params:
                    static_names.update(
                        params[i] for i in value if i < len(params))
                else:
                    unknown = True
            elif kw.arg == "donate_argnums":
                donate_idx.update(int(i) for i in value)
            elif kw.arg == "donate_argnames":
                if params:
                    donate_idx.update(
                        params.index(n) for n in value if n in params)
    return (None if unknown else static_names), donate_idx


def jit_wrapping_call(mod: ModuleInfo, node: ast.AST) -> Optional[ast.Call]:
    """Return the jax.jit/pjit Call carrying the options when ``node``
    is a jit-wrapping expression, else None. Recognized shapes::

        jax.jit                       (bare decorator)
        jax.jit(f, ...) / pjit(f)     (direct wrap)
        partial(jax.jit, ...)         (decorator factory)
        partial(jax.jit, ...)(f)      (module-level binding)
    """
    if isinstance(node, ast.Call):
        d = mod.resolve(node.func)
        if d in JIT_WRAPPERS:
            return node
        if d == "functools.partial" and node.args:
            inner = mod.resolve(node.args[0])
            if inner in JIT_WRAPPERS:
                return node
        # partial(jax.jit, ...)(impl): options live on the inner call
        if isinstance(node.func, ast.Call):
            return jit_wrapping_call(mod, node.func)
    return None


def find_jit_bindings(mod: ModuleInfo) -> list[JitBinding]:
    """All traced entry points defined in one module: decorated defs and
    module-level ``name = jax.jit(...)/partial(jax.jit, ...)(impl)``."""
    out: list[JitBinding] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                is_bare = mod.resolve(dec) in JIT_WRAPPERS
                call = None if is_bare else jit_wrapping_call(mod, dec)
                if is_bare or call is not None:
                    static, donate = (set(), set()) if is_bare else \
                        _jit_options(mod, call, node)
                    out.append(JitBinding(
                        impl=f"{mod.module_name}.{node.name}",
                        fdef=node, mod=mod, static_names=static,
                        donate_idx=donate, bound_name=node.name))
    for node in mod.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)):
            continue
        call = node.value
        wrap = jit_wrapping_call(mod, call)
        if wrap is None:
            continue
        # the traced impl is the wrapped callable: jax.jit(IMPL, ...) or
        # partial(jax.jit, ...)(IMPL)
        impl_node = None
        if call is wrap and call.args:  # jax.jit(impl, ...)
            d = mod.resolve(call.args[0])
            if d != "functools.partial":
                impl_node = call.args[0]
        elif call.args:  # partial(jax.jit, ...)(impl)
            impl_node = call.args[0]
        impl = mod.resolve(impl_node) if impl_node is not None else None
        if impl is None:
            continue
        fdef = None
        local = impl.rsplit(".", 1)[-1]
        if impl == f"{mod.module_name}.{local}":
            cand = mod.toplevel_defs.get(local)
            if isinstance(cand, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fdef = cand
        static, donate = _jit_options(mod, wrap, fdef)
        out.append(JitBinding(
            impl=impl, fdef=fdef, mod=mod, static_names=static,
            donate_idx=donate, bound_name=node.targets[0].id))
    return out


# -- class index -----------------------------------------------------------


@dataclasses.dataclass
class ClassInfo:
    """One top-level class: its methods plus what its attributes hold.

    ``attr_classes`` maps an attribute name to the dotted name of the
    package class its value is known to be — from dataclass/field
    annotations and from ``self.x = SomeClass(...)`` assignments in
    ``__init__``. This is what lets the dataflow follow
    ``coord.score(...)`` through ``self.opt.step(...)`` chains.
    """

    dotted: str
    mod: ModuleInfo
    node: ast.ClassDef
    methods: dict[str, ast.AST]  # name -> FunctionDef
    attr_classes: dict[str, str]  # attribute -> dotted package class
    bases: list[str]  # resolved dotted base classes


def _class_info(mod: ModuleInfo, node: ast.ClassDef,
                class_names: set[str]) -> ClassInfo:
    dotted = f"{mod.module_name}.{node.name}"
    methods: dict[str, ast.AST] = {}
    attr_classes: dict[str, str] = {}
    for item in node.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            methods[item.name] = item
        elif isinstance(item, ast.AnnAssign) and \
                isinstance(item.target, ast.Name):
            # dataclass-style field: x: SomeClass
            ann = mod.resolve(item.annotation)
            if ann in class_names:
                attr_classes[item.target.id] = ann
    init = methods.get("__init__")
    if init is not None:
        for stmt in ast.walk(init):
            target = value = None
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                target, value = stmt.targets[0], stmt.value
            elif isinstance(stmt, ast.AnnAssign) and \
                    stmt.value is not None:
                target, value = stmt.target, stmt.value
            if not (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                continue
            if isinstance(value, ast.Call):
                callee = mod.resolve(value.func)
                if callee in class_names:
                    attr_classes.setdefault(target.attr, callee)
    bases = [b for b in (mod.resolve(b) for b in node.bases)
             if b in class_names]
    return ClassInfo(dotted=dotted, mod=mod, node=node, methods=methods,
                     attr_classes=attr_classes, bases=bases)


# -- mesh-axis universe ----------------------------------------------------

# Calls that *define* a named device axis. Collectives never add to the
# universe — otherwise a typo'd psum axis would define itself and W601
# could not fire.
_MESH_CTORS = {"jax.sharding.Mesh", "jax.experimental.maps.Mesh",
               "jax.interpreters.pxla.Mesh", "Mesh"}


def literal_in(mod: ModuleInfo, index: "PackageIndex", node: ast.AST):
    """Like ``ModuleInfo.literal`` but resolves Name/Attribute chains
    through the whole-package constant table and evaluates tuples/lists
    elementwise — e.g. ``(DATA_AXIS, ENTITY_AXIS)`` where both names are
    imported from another module. Returns None when unresolvable."""
    if isinstance(node, (ast.Tuple, ast.List)):
        elts = [literal_in(mod, index, e) for e in node.elts]
        if any(e is None for e in elts):
            return None
        return tuple(elts)
    if isinstance(node, (ast.Name, ast.Attribute)):
        dotted = mod.resolve(node)
        if dotted is not None:
            value = index.resolve_constant(dotted)
            if value is not None:
                return value
        if isinstance(node, ast.Name) and node.id in mod.constants:
            return mod.literal(node)
        return None
    try:
        return ast.literal_eval(node)
    except (ValueError, TypeError, SyntaxError):
        return None


def name_value(mod: ModuleInfo, index: "PackageIndex",
               node: ast.AST) -> tuple[str, Optional[str]]:
    """Bounded string abstraction for protocol/telemetry names.

    Returns ``("literal", s)`` for a statically known string (direct
    literal or constant resolved through the package),
    ``("prefix", head)`` for an f-string with a non-empty literal head
    (``f"serve.combine[b{n}]"`` → prefix ``serve.combine[b``), and
    ``("dynamic", None)`` for everything else — the WA00/WB00
    "unauditable name" bucket.
    """
    if isinstance(node, ast.JoinedStr):
        head = node.values[0] if node.values else None
        if isinstance(head, ast.Constant) and isinstance(head.value, str) \
                and head.value:
            return ("prefix", head.value)
        return ("dynamic", None)
    value = literal_in(mod, index, node)
    if isinstance(value, str):
        return ("literal", value)
    return ("dynamic", None)


def collect_mesh_axes(index: "PackageIndex") -> set[str]:
    """Every axis name the program can legitimately collective over:
    Mesh(..., axis_names) construction sites, ``jax.pmap(axis_name=...)``
    definitions, and ``*_AXIS`` string module constants (the package's
    naming convention for mesh axes)."""
    axes: set[str] = set()
    for mod in index.modules:
        for name, value in mod.constants.items():
            if name.endswith("_AXIS") and isinstance(value, ast.Constant) \
                    and isinstance(value.value, str):
                axes.add(value.value)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = mod.resolve(node.func)
            if dotted is not None and (
                    dotted in _MESH_CTORS or dotted.endswith(".Mesh")):
                spec = None
                if len(node.args) >= 2:
                    spec = node.args[1]
                for kw in node.keywords:
                    if kw.arg == "axis_names":
                        spec = kw.value
                value = literal_in(mod, index, spec) \
                    if spec is not None else None
                if isinstance(value, str):
                    axes.add(value)
                elif isinstance(value, tuple):
                    axes.update(v for v in value if isinstance(v, str))
            elif dotted in ("jax.pmap", "pmap"):
                for kw in node.keywords:
                    if kw.arg == "axis_name":
                        value = literal_in(mod, index, kw.value)
                        if isinstance(value, str):
                            axes.add(value)
    return axes


# -- package index ---------------------------------------------------------


@dataclasses.dataclass
class PackageIndex:
    """Cross-module facts the rules share."""

    modules: list[ModuleInfo]
    functions: dict[str, tuple[ModuleInfo, ast.AST]]  # top-level defs
    jit_bindings: list[JitBinding]
    jax_fns: set[str]  # dotted names known to return jax values
    call_graph: dict[str, set[str]]  # dotted fn -> called package fns
    classes: dict[str, ClassInfo] = dataclasses.field(default_factory=dict)
    by_module_name: dict[str, ModuleInfo] = dataclasses.field(
        default_factory=dict)
    jax_methods: set[str] = dataclasses.field(default_factory=set)
    mesh_axes: set[str] = dataclasses.field(default_factory=set)

    def resolve_constant(self, dotted: str):
        """Literal value of a fully-qualified module constant, following
        the definition across modules (``pkg.parallel.mesh.ENTITY_AXIS``
        → ``"entity"``). None when the module is outside the lint run or
        the value is not a literal."""
        if "." not in dotted:
            return None
        mod_name, attr = dotted.rsplit(".", 1)
        mod = self.by_module_name.get(mod_name)
        if mod is None or attr not in mod.constants:
            return None
        return mod.literal(mod.constants[attr])

    def resolve_method(
        self, class_dotted: str, method: str
    ) -> Optional[tuple[ClassInfo, ast.AST]]:
        """Look ``method`` up on a class, walking package base classes."""
        seen: set[str] = set()
        stack = [class_dotted]
        while stack:
            current = stack.pop(0)
            if current in seen:
                continue
            seen.add(current)
            info = self.classes.get(current)
            if info is None:
                continue
            if method in info.methods:
                return info, info.methods[method]
            stack.extend(info.bases)
        return None

    def attr_class(self, class_dotted: str,
                   attr: str) -> Optional[str]:
        """Dotted class of ``<instance of class_dotted>.<attr>``, walking
        package base classes."""
        seen: set[str] = set()
        stack = [class_dotted]
        while stack:
            current = stack.pop(0)
            if current in seen:
                continue
            seen.add(current)
            info = self.classes.get(current)
            if info is None:
                continue
            if attr in info.attr_classes:
                return info.attr_classes[attr]
            stack.extend(info.bases)
        return None

    def jit_reachable(self) -> dict[str, str]:
        """Package functions reachable from any jit entry point, mapped
        to the dotted name of (one of) the jit root(s) that reaches
        them. Roots map to themselves."""
        reached: dict[str, str] = {}
        stack = [(b.impl, b.impl) for b in self.jit_bindings
                 if b.impl in self.functions]
        while stack:
            fn, root = stack.pop()
            if fn in reached:
                continue
            reached[fn] = root
            for callee in self.call_graph.get(fn, ()):
                if callee not in reached and callee in self.functions:
                    stack.append((callee, root))
        return reached


def build_index(modules: list[ModuleInfo]) -> PackageIndex:
    functions: dict[str, tuple[ModuleInfo, ast.AST]] = {}
    class_names: set[str] = set()
    for mod in modules:
        for name, node in mod.toplevel_defs.items():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                functions[f"{mod.module_name}.{name}"] = (mod, node)
            elif isinstance(node, ast.ClassDef):
                class_names.add(f"{mod.module_name}.{name}")
    classes: dict[str, ClassInfo] = {}
    for mod in modules:
        for name, node in mod.toplevel_defs.items():
            if isinstance(node, ast.ClassDef):
                info = _class_info(mod, node, class_names)
                classes[info.dotted] = info
    jit_bindings = [b for mod in modules for b in find_jit_bindings(mod)]
    jax_fns = {b.impl for b in jit_bindings}
    jax_fns.update(b.mod.module_name + "." + b.bound_name
                   for b in jit_bindings if b.bound_name)
    call_graph: dict[str, set[str]] = {}
    for dotted, (mod, fdef) in functions.items():
        callees = set()
        for node in ast.walk(fdef):
            if isinstance(node, ast.Call):
                d = mod.resolve(node.func)
                if d is not None and d in functions:
                    callees.add(d)
        call_graph[dotted] = callees
    index = PackageIndex(modules=modules, functions=functions,
                         jit_bindings=jit_bindings, jax_fns=jax_fns,
                         call_graph=call_graph, classes=classes,
                         by_module_name={m.module_name: m
                                         for m in modules})
    index.mesh_axes = collect_mesh_axes(index)
    return index
