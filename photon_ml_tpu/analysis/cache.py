"""Incremental lint cache: content-addressed per-file artifacts plus a
whole-program replay artifact.

photonlint is whole-program — a W801 needs the accumulator two calls
away, WA01 needs every client send site — so it cannot simply skip
unchanged files. What it CAN skip is re-deriving per-file state
(:class:`~photon_ml_tpu.analysis.package.ModuleInfo`: parse, import
map, constant table, suppression scan) for files whose bytes are
unchanged, and, when *nothing* changed, re-running the rules at all:

- **file artifact** — a pickled ``ModuleInfo`` keyed on
  ``sha256(relpath, file bytes, analyzer signature)``. A hit replaces
  parse + four AST visits with one unpickle.
- **program artifact** — the raw (pre-suppression, pre-baseline)
  findings plus the per-file suppression maps, keyed on the sorted
  file keys, the README bytes and the enabled families. A hit replays
  the whole fixpoint without loading a single module; suppression and
  baseline filtering still run live, so a baseline edit or
  ``--changed-files`` restriction is honored against cached findings.

Keys contain no mtimes: ``touch`` without an edit is still a full hit.
The *analyzer signature* — a digest of every ``analysis/*.py`` source —
folds the linter's own code into every key, so editing a rule, the
dataflow engine, or this file invalidates everything (the classic
stale-lint-cache bug class). Any unpickle failure (corrupt file,
pickle-protocol drift) is treated as a miss, never an error.

Runs that read external evidence (``--trace-evidence`` drives W702 off
trace files this key scheme does not see) bypass the program artifact;
per-file artifacts are still safe and still used.
"""

from __future__ import annotations

import hashlib
import pickle
from pathlib import Path
from typing import Any, Optional

from photon_ml_tpu.analysis.package import ModuleInfo

CACHE_VERSION = 1

_analyzer_sig: Optional[str] = None


def analyzer_signature() -> str:
    """Digest of the analysis package's own sources (computed once)."""
    global _analyzer_sig
    if _analyzer_sig is None:
        h = hashlib.sha256()
        pkg = Path(__file__).parent
        for src in sorted(pkg.glob("*.py")):
            h.update(src.name.encode())
            h.update(b"\0")
            h.update(src.read_bytes())
            h.update(b"\0")
        _analyzer_sig = h.hexdigest()
    return _analyzer_sig


class LintCache:
    """Content-addressed artifact store under ``cache_dir``.

    Layout: ``files/<key>.pkl`` (one ``ModuleInfo`` each) and
    ``program/<key>.pkl`` (one findings replay each). Hit/miss counts
    accumulate on the instance; ``stats()`` snapshots them for
    ``LintReport.cache_stats``.
    """

    def __init__(self, cache_dir) -> None:
        self.dir = Path(cache_dir)
        self.file_hits = 0
        self.file_misses = 0
        self.program_hit = False

    # -- keys --------------------------------------------------------------

    def file_key(self, relpath: str, source: bytes) -> str:
        h = hashlib.sha256()
        h.update(f"photonlint-file-v{CACHE_VERSION}\0".encode())
        h.update(analyzer_signature().encode())
        h.update(b"\0")
        h.update(relpath.encode())
        h.update(b"\0")
        h.update(source)
        return h.hexdigest()

    def program_key(self, file_keys: list[str],
                    readme_bytes: Optional[bytes],
                    families: Optional[set[str]]) -> str:
        h = hashlib.sha256()
        h.update(f"photonlint-program-v{CACHE_VERSION}\0".encode())
        for k in sorted(file_keys):
            h.update(k.encode())
            h.update(b"\0")
        h.update(b"readme\0")
        h.update(readme_bytes if readme_bytes is not None else b"<none>")
        h.update(b"\0families\0")
        fams = "all" if families is None else ",".join(sorted(families))
        h.update(fams.encode())
        return h.hexdigest()

    # -- artifacts ---------------------------------------------------------

    def _read(self, kind: str, key: str) -> Any:
        path = self.dir / kind / f"{key}.pkl"
        try:
            with open(path, "rb") as fh:
                return pickle.load(fh)
        except Exception:
            return None

    def _write(self, kind: str, key: str, payload: Any) -> None:
        folder = self.dir / kind
        try:
            folder.mkdir(parents=True, exist_ok=True)
            tmp = folder / f".{key}.tmp"
            with open(tmp, "wb") as fh:
                pickle.dump(payload, fh, protocol=pickle.HIGHEST_PROTOCOL)
            tmp.replace(folder / f"{key}.pkl")
        except Exception:
            # A read-only or full cache dir degrades to a cold run.
            pass

    def load_module(self, path: Path, root: Path) -> tuple[ModuleInfo, str]:
        """ModuleInfo for ``path`` — unpickled on a content hit, built
        fresh (and stored) on a miss. Returns ``(module, file_key)``."""
        source = Path(path).read_bytes()
        try:
            relpath = Path(path).relative_to(root).as_posix()
        except ValueError:
            relpath = Path(path).as_posix()
        key = self.file_key(relpath, source)
        mod = self._read("files", key)
        if isinstance(mod, ModuleInfo):
            self.file_hits += 1
            return mod, key
        self.file_misses += 1
        mod = ModuleInfo.load(path, root)
        self._write("files", key, mod)
        return mod, key

    def load_program(self, key: str) -> Optional[dict]:
        payload = self._read("program", key)
        if isinstance(payload, dict) and "findings" in payload:
            self.program_hit = True
            return payload
        return None

    def store_program(self, key: str, payload: dict) -> None:
        self._write("program", key, payload)

    def stats(self) -> dict:
        return {
            "file_hits": self.file_hits,
            "file_misses": self.file_misses,
            "program_hit": self.program_hit,
        }
