"""W1xx — sync discipline: blocking device→host conversions.

The hot-loop contract (one blocking fetch per coordinate update, every
intentional fetch instrumented through
``utils/sync_telemetry.record_host_fetch``) is enforced dynamically only
on the paths the transfer-guard test executes. These rules check the
whole package statically:

- **W101** ``float()``/``int()``/``bool()`` on a jax-valued expression;
- **W102** ``.item()`` on a jax-valued expression;
- **W103** ``np.asarray()``/``np.array()`` on a jax-valued expression;
- **W104** ``jax.device_get`` in a function whose scope chain never
  calls ``record_host_fetch`` — an *uninstrumented* fetch that
  ``host_syncs_per_update`` telemetry cannot see.

``utils/sync_telemetry.py`` itself is exempt: it IS the instrument.
"""

from __future__ import annotations

import ast
from typing import Optional

from photon_ml_tpu.analysis.core import Finding
from photon_ml_tpu.analysis.dataflow import Dataflow, is_jax
from photon_ml_tpu.analysis.package import ModuleInfo, PackageIndex

_EXEMPT_SUFFIX = "utils/sync_telemetry.py"
_RECORD_FETCH = "record_host_fetch"
_CONVERTERS = {"float", "int", "bool"}
_NP_CONVERTERS = {"numpy.asarray", "numpy.array", "numpy.ascontiguousarray"}


def build_scope_map(tree: ast.Module) -> dict[int, Optional[ast.AST]]:
    """Map ``id(node)`` → innermost enclosing function def (None at
    module level), and each function def → its own parent scope."""
    scope_of: dict[int, Optional[ast.AST]] = {}

    def visit(node: ast.AST, scope: Optional[ast.AST]) -> None:
        scope_of[id(node)] = scope
        child_scope = node if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)) \
            else scope
        for child in ast.iter_child_nodes(node):
            visit(child, child_scope)

    visit(tree, None)
    return scope_of


def _instrumented_scopes(mod: ModuleInfo,
                         scope_of: dict[int, Optional[ast.AST]]
                         ) -> set[Optional[int]]:
    """Scopes (id of function def, or None for module level) containing
    a direct ``record_host_fetch()`` call."""
    out: set[Optional[int]] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            d = mod.resolve(node.func)
            if d is not None and (d.endswith("." + _RECORD_FETCH)
                                  or d == _RECORD_FETCH):
                scope = scope_of.get(id(node))
                out.add(None if scope is None else id(scope))
    return out


def check(modules: list[ModuleInfo], index: PackageIndex,
          flows: dict[str, Dataflow], ctx) -> list[Finding]:
    findings: list[Finding] = []
    for mod in modules:
        if mod.relpath.endswith(_EXEMPT_SUFFIX):
            continue
        flow = flows[mod.relpath]
        scope_of = build_scope_map(mod.tree)
        instrumented = _instrumented_scopes(mod, scope_of)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            d = mod.resolve(node.func)
            # W101: float()/int()/bool() — only the true builtins (a
            # local or imported redefinition resolves to a dotted name)
            if isinstance(node.func, ast.Name) \
                    and node.func.id in _CONVERTERS and d is None \
                    and node.args \
                    and is_jax(flow.tag(node.args[0])):
                findings.append(Finding(
                    "W101", mod.relpath, node.lineno, node.col_offset,
                    f"{node.func.id}() on a jax-array value forces a "
                    f"blocking device→host sync — batch it into one "
                    f"instrumented jax.device_get"))
            # W102: .item()
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args \
                    and is_jax(flow.tag(node.func.value)):
                findings.append(Finding(
                    "W102", mod.relpath, node.lineno, node.col_offset,
                    ".item() on a jax-array value forces a blocking "
                    "device→host sync — batch it into one instrumented "
                    "jax.device_get"))
            # W103: np.asarray(jax_value)
            elif d in _NP_CONVERTERS and node.args \
                    and is_jax(flow.tag(node.args[0])):
                findings.append(Finding(
                    "W103", mod.relpath, node.lineno, node.col_offset,
                    f"{d.replace('numpy.', 'np.')}() on a jax-array "
                    f"value forces a blocking device→host sync — fetch "
                    f"through an instrumented jax.device_get instead"))
            # W104: un-instrumented jax.device_get
            elif d == "jax.device_get":
                scope = scope_of.get(id(node))
                chain_ok = False
                while True:
                    key = None if scope is None else id(scope)
                    if key in instrumented:
                        chain_ok = True
                        break
                    if scope is None:
                        break
                    scope = scope_of.get(id(scope))
                if not chain_ok:
                    findings.append(Finding(
                        "W104", mod.relpath, node.lineno,
                        node.col_offset,
                        "jax.device_get without record_host_fetch in "
                        "the enclosing function — this blocking fetch "
                        "is invisible to host_syncs_per_update "
                        "telemetry (utils/sync_telemetry.py)"))
    return findings
