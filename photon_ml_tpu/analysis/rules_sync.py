"""W1xx — sync discipline: blocking device→host conversions.

The hot-loop contract (one blocking fetch per coordinate update, every
intentional fetch instrumented through
``utils/sync_telemetry.record_host_fetch``) is enforced dynamically only
on the paths the transfer-guard test executes. These rules check the
whole package statically:

- **W101** ``float()``/``int()``/``bool()`` on a jax-valued expression;
- **W102** ``.item()`` on a jax-valued expression;
- **W103** ``np.asarray()``/``np.array()`` on a jax-valued expression;
- **W104** ``jax.device_get`` in a function whose scope chain never
  calls ``record_host_fetch`` — an *uninstrumented* fetch that
  ``host_syncs_per_update`` telemetry cannot see.
- **W105** pipeline-depth discipline: a deferred epilogue handle (the
  result of a ``dispatch_update``-style call) still unconsumed when a
  SECOND subsequent dispatch is issued — i.e. a fetch that would land
  more than one coordinate late. The double-buffered CD sweep's
  contract is depth ≤ 1: every in-flight block is resolved
  (``resolve_update``/``fetch_update``) before the dispatch after next,
  so divergence recovery only ever has to act ONE update late. A
  deeper pipeline silently widens the rollback window; this rule makes
  that structural instead of tribal knowledge.

``utils/sync_telemetry.py`` itself is exempt: it IS the instrument.
"""

from __future__ import annotations

import ast
from typing import Optional

from photon_ml_tpu.analysis.core import Finding
from photon_ml_tpu.analysis.dataflow import Dataflow, is_jax
from photon_ml_tpu.analysis.package import ModuleInfo, PackageIndex

_EXEMPT_SUFFIX = "utils/sync_telemetry.py"
_RECORD_FETCH = "record_host_fetch"
_CONVERTERS = {"float", "int", "bool"}
_NP_CONVERTERS = {"numpy.asarray", "numpy.array", "numpy.ascontiguousarray"}

# W105: calls whose name ends with the dispatch suffix produce a deferred
# epilogue handle; ones ending with a consume suffix resolve it. Suffix
# matching covers both the bare closure names in coordinate_descent.py
# and dotted/imported forms.
_DISPATCH_SUFFIX = "dispatch_update"
_CONSUME_SUFFIXES = ("resolve_update", "fetch_update")
#: Loop bodies are interpreted this many times so a handle created in
#: iteration k and aged by the dispatches of iterations k+1 and k+2 is
#: observed crossing the depth-1 line.
_LOOP_PASSES = 3


def build_scope_map(tree: ast.Module) -> dict[int, Optional[ast.AST]]:
    """Map ``id(node)`` → innermost enclosing function def (None at
    module level), and each function def → its own parent scope."""
    scope_of: dict[int, Optional[ast.AST]] = {}

    def visit(node: ast.AST, scope: Optional[ast.AST]) -> None:
        scope_of[id(node)] = scope
        child_scope = node if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)) \
            else scope
        for child in ast.iter_child_nodes(node):
            visit(child, child_scope)

    visit(tree, None)
    return scope_of


def _instrumented_scopes(mod: ModuleInfo,
                         scope_of: dict[int, Optional[ast.AST]]
                         ) -> set[Optional[int]]:
    """Scopes (id of function def, or None for module level) containing
    a direct ``record_host_fetch()`` call."""
    out: set[Optional[int]] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            d = mod.resolve(node.func)
            if d is not None and (d.endswith("." + _RECORD_FETCH)
                                  or d == _RECORD_FETCH):
                scope = scope_of.get(id(node))
                out.add(None if scope is None else id(scope))
    return out


def _call_suffix_name(mod: ModuleInfo, node: ast.Call) -> Optional[str]:
    """Best-effort callable name for suffix matching: the resolved dotted
    name when the package index knows it, else the bare/attr name."""
    d = mod.resolve(node.func)
    if d is not None:
        return d
    if isinstance(node.func, ast.Name):
        return node.func.id
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None


class _PipelineDepthWalker:
    """Abstract interpreter for W105: tracks, per function scope, which
    variables hold a deferred dispatch handle and how many SUBSEQUENT
    dispatches each has survived unconsumed (its "age"). A handle
    reaching age 2 at a dispatch site is a finding — that fetch would
    land more than one coordinate late.

    Consumption = the variable passed to a ``resolve_update``/
    ``fetch_update``-suffixed call, rebound, deleted, or transferred to
    another name (``pending = cur`` moves the handle, it doesn't copy
    it). ``If`` branches merge keeping only handles live on BOTH paths
    (max age) — precision over recall; loop bodies run ``_LOOP_PASSES``
    times so loop-carried ages surface."""

    def __init__(self, mod: ModuleInfo, findings: list):
        self.mod = mod
        self.findings = findings
        self.state: dict[str, int] = {}

    # -- entry points -------------------------------------------------------

    def run(self, body: list) -> None:
        self.state = {}
        self._stmts(body)

    # -- statement dispatch -------------------------------------------------

    def _stmts(self, stmts) -> None:
        for s in stmts or []:
            self._stmt(s)

    def _stmt(self, s: ast.AST) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            sub = _PipelineDepthWalker(self.mod, self.findings)
            sub.run(s.body)
            return
        if isinstance(s, ast.ClassDef):
            sub = _PipelineDepthWalker(self.mod, self.findings)
            sub.run(s.body)
            return
        if isinstance(s, (ast.For, ast.AsyncFor)):
            self._expr(s.iter)
            for _ in range(_LOOP_PASSES):
                self._stmts(s.body)
            self._stmts(s.orelse)
            return
        if isinstance(s, ast.While):
            self._expr(s.test)
            for _ in range(_LOOP_PASSES):
                self._stmts(s.body)
            self._stmts(s.orelse)
            return
        if isinstance(s, ast.If):
            self._expr(s.test)
            before = dict(self.state)
            self._stmts(s.body)
            after_body = self.state
            self.state = dict(before)
            self._stmts(s.orelse)
            after_else = self.state
            # keep only handles alive on BOTH paths (precision: a handle
            # consumed on either path may well be consumed at runtime)
            self.state = {
                name: max(after_body[name], after_else[name])
                for name in set(after_body) & set(after_else)}
            return
        if isinstance(s, ast.Try):
            # conservative flattening: body, then handlers, then
            # orelse/finally see the accumulated state — consumption on
            # any of these paths counts
            self._stmts(s.body)
            for h in s.handlers:
                self._stmts(h.body)
            self._stmts(s.orelse)
            self._stmts(s.finalbody)
            return
        if isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                self._expr(item.context_expr)
            self._stmts(s.body)
            return
        if isinstance(s, ast.Assign):
            self._assign(s)
            return
        if isinstance(s, ast.AnnAssign) and s.value is not None:
            if isinstance(s.target, ast.Name):
                self._bind(s.target.id, s.value)
            else:
                self._expr(s.value)
            return
        if isinstance(s, ast.Delete):
            for t in s.targets:
                if isinstance(t, ast.Name):
                    self.state.pop(t.id, None)
            return
        for child in ast.iter_child_nodes(s):
            if isinstance(child, ast.expr):
                self._expr(child)

    def _assign(self, s: ast.Assign) -> None:
        # rebinding any tracked name kills its old handle
        for t in s.targets:
            for name_node in ast.walk(t):
                if isinstance(name_node, ast.Name):
                    self.state.pop(name_node.id, None)
        if len(s.targets) == 1 and isinstance(s.targets[0], ast.Name):
            self._bind(s.targets[0].id, s.value)
        else:
            self._expr(s.value)

    def _bind(self, target: str, value: ast.expr) -> None:
        if isinstance(value, ast.Call):
            name = _call_suffix_name(self.mod, value)
            if name is not None and name.endswith(_DISPATCH_SUFFIX):
                self._visit_call_args(value)
                self._age_all(value)
                self.state[target] = 0
                return
        if isinstance(value, ast.Name) and value.id in self.state:
            self.state[target] = self.state.pop(value.id)  # transfer
            return
        self._expr(value)

    # -- expressions --------------------------------------------------------

    def _expr(self, e: Optional[ast.expr]) -> None:
        if e is None:
            return
        for node in ast.walk(e):
            if not isinstance(node, ast.Call):
                continue
            name = _call_suffix_name(self.mod, node)
            if name is None:
                continue
            if any(name.endswith(sfx) for sfx in _CONSUME_SUFFIXES):
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        self.state.pop(arg.id, None)
                for kw in node.keywords:  # fetch_update(p=handle) consumes
                    if isinstance(kw.value, ast.Name):
                        self.state.pop(kw.value.id, None)
            elif name.endswith(_DISPATCH_SUFFIX):
                # un-bound dispatch still advances the pipeline clock
                self._age_all(node)

    def _visit_call_args(self, call: ast.Call) -> None:
        for arg in call.args:
            self._expr(arg)
        for kw in call.keywords:
            self._expr(kw.value)

    def _age_all(self, at: ast.Call) -> None:
        for name in list(self.state):
            self.state[name] += 1
            if self.state[name] >= 2:
                self.findings.append(Finding(
                    "W105", self.mod.relpath, at.lineno, at.col_offset,
                    f"deferred epilogue handle {name!r} is still "
                    f"unconsumed at its second subsequent dispatch — "
                    f"the fetch would land more than one coordinate "
                    f"late (pipeline depth > 1); resolve it "
                    f"(resolve_update/fetch_update) at most one "
                    f"dispatch later"))
                # report once per handle per site chain
                self.state.pop(name, None)


def check(modules: list[ModuleInfo], index: PackageIndex,
          flows: dict[str, Dataflow], ctx) -> list[Finding]:
    findings: list[Finding] = []
    for mod in modules:
        if mod.relpath.endswith(_EXEMPT_SUFFIX):
            continue
        flow = flows[mod.relpath]
        scope_of = build_scope_map(mod.tree)
        instrumented = _instrumented_scopes(mod, scope_of)
        _PipelineDepthWalker(mod, findings).run(mod.tree.body)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            d = mod.resolve(node.func)
            # W101: float()/int()/bool() — only the true builtins (a
            # local or imported redefinition resolves to a dotted name)
            if isinstance(node.func, ast.Name) \
                    and node.func.id in _CONVERTERS and d is None \
                    and node.args \
                    and is_jax(flow.tag(node.args[0])):
                findings.append(Finding(
                    "W101", mod.relpath, node.lineno, node.col_offset,
                    f"{node.func.id}() on a jax-array value forces a "
                    f"blocking device→host sync — batch it into one "
                    f"instrumented jax.device_get"))
            # W102: .item()
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args \
                    and is_jax(flow.tag(node.func.value)):
                findings.append(Finding(
                    "W102", mod.relpath, node.lineno, node.col_offset,
                    ".item() on a jax-array value forces a blocking "
                    "device→host sync — batch it into one instrumented "
                    "jax.device_get"))
            # W103: np.asarray(jax_value)
            elif d in _NP_CONVERTERS and node.args \
                    and is_jax(flow.tag(node.args[0])):
                findings.append(Finding(
                    "W103", mod.relpath, node.lineno, node.col_offset,
                    f"{d.replace('numpy.', 'np.')}() on a jax-array "
                    f"value forces a blocking device→host sync — fetch "
                    f"through an instrumented jax.device_get instead"))
            # W104: un-instrumented jax.device_get
            elif d == "jax.device_get":
                scope = scope_of.get(id(node))
                chain_ok = False
                while True:
                    key = None if scope is None else id(scope)
                    if key in instrumented:
                        chain_ok = True
                        break
                    if scope is None:
                        break
                    scope = scope_of.get(id(scope))
                if not chain_ok:
                    findings.append(Finding(
                        "W104", mod.relpath, node.lineno,
                        node.col_offset,
                        "jax.device_get without record_host_fetch in "
                        "the enclosing function — this blocking fetch "
                        "is invisible to host_syncs_per_update "
                        "telemetry (utils/sync_telemetry.py)"))
    return findings
