"""photonlint: AST-based invariant checking for the TPU training stack.

The runtime can only spot-check this package's hard invariants where a
test happens to tread — the transfer-guard test enforces the
one-fetch-per-update contract on the paths it executes, bit-exact resume
dies silently if nondeterminism leaks into a jitted region, and the
README ``PHOTON_FAULTS`` table drifts from the actual ``fault_point()``
sites without anything noticing. These are *structural* properties of
the source (DrJAX frames the whole stack as program transformations), so
this subpackage checks them statically over the entire tree. The
analysis is whole-program: a package index resolves imports, module
constants, classes (methods, attribute types, bases) and a fixpoint
over return values, so method calls on objects built in other modules
join the dataflow and mesh axes declared anywhere ground-truth the
collectives checked everywhere.

- **W1xx sync discipline** — blocking device→host conversions
  (``float``/``int``/``bool``/``.item()``/``np.asarray``/
  ``jax.device_get``) applied to jax-array-producing expressions outside
  the instrumented fetch sites (``utils/sync_telemetry.py`` discipline).
- **W2xx jit purity / trace hazards** — impure calls (time, random,
  I/O, logging), Python branching on traced values, and host-callback
  ordering under resume (unordered ``io_callback``, impure
  ``pure_callback``) inside ``jax.jit``/``pjit``-ed functions and
  package-local functions reachable from them.
- **W3xx donation safety** — an argument passed at a ``donate_argnums``
  call site must not be read again afterwards in the same function,
  including by the next iteration of an enclosing loop.
- **W4xx fault-point drift** — ``fault_point("name")`` sites and the
  README ``PHOTON_FAULTS`` table must agree in both directions.
- **W5xx checkpoint-schema drift** — snapshot fields written at
  ``CheckpointManager.save`` sites must match the fields read back on
  the restore/resume paths.
- **W6xx collective safety** — collective axis names must come from a
  real defining site (``Mesh`` ctor / ``pmap(axis_name=...)`` /
  ``*_AXIS`` constant); no collectives under replica- or
  host-divergent control flow; ``shard_map`` spec tuples must match
  the callee's arity; ``PartitionSpec`` axes must exist.
- **W7xx retrace risk** — data-dependent shapes (``len``/``.shape``)
  flowing into jitted calls, and — given ``--trace-evidence`` —
  ``xla.retrace`` span records from a real run mapped back to the
  dispatch sites that caused them.
- **W8xx precision discipline** — low-precision reductions without an
  f32 accumulator, unguarded float64, dtype-erasing host round-trips,
  implicit mixed-dtype promotion in loss/grad paths.
- **W9xx thread safety** — inconsistently guarded shared state,
  non-async-signal-safe handlers, unjoined threads, lock-order
  inversion.
- **WAxx wire-protocol drift** — serve-plane string contracts: NDJSON
  ``kind``s sent vs dispatched, typed-error names raised/rendered vs
  the ``typed_error`` parse table and the transport-classification
  set, writer field sets vs kind-pinned reader accesses.
- **WBxx telemetry-taxonomy drift** — metric/span names emitted vs the
  README taxonomy tables vs every consumer (``photon_status``,
  ``bench.py``, trace tools, chaos assertions — loaded as auxiliary
  modules), plus label-key drift between emit sites sharing a name.

Entry points: :func:`photon_ml_tpu.analysis.runner.lint` (library) and
``tools/photonlint.py`` (CLI). Per-line suppressions use
``# photonlint: allow-<rule>(reason)`` and a committed baseline file
grandfathers known findings (see README "Static analysis"). Runs can
be incremental: ``cache_dir=`` / ``--cache-dir`` keys per-file
artifacts and a whole-program findings replay on content hashes (see
:mod:`photon_ml_tpu.analysis.cache`).
"""

from photon_ml_tpu.analysis.core import Finding, LintReport  # noqa: F401
from photon_ml_tpu.analysis.runner import lint  # noqa: F401
