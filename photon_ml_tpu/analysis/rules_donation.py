"""W3xx — donation safety.

``donate_argnums`` hands a buffer to XLA as scratch: after the call the
python-side array is deleted, and any later read returns garbage or
raises — the aliasing bug ``game/random_effect.py`` dodges by hand (its
plain-path warm start can BE coordinate descent's live last-good state,
so only the compacted re-dispatch path donates x0).

**W301** fires when a name passed at a donated position of a donating
call is read again later in the same function without an intervening
rebind. Donating callables are found syntactically: module-level or
local bindings of ``jax.jit(..., donate_argnums=...)`` /
``partial(jax.jit, donate_argnums=...)(impl)``, one level of plain-name
aliasing (``fn = _donating_variant``), and inline
``jax.jit(f, donate_argnums=...)(x)`` calls.

Loop-carried reads are in scope too: a donating call inside a
``for``/``while`` body whose donated name is never rebound anywhere in
that loop reads the deleted buffer on the *next* iteration — the
donation from iteration k poisons the argument of iteration k+1. The
idiomatic self-rebind ``x = donating(x)`` stays clean (the assignment
target counts as the rebind).
"""

from __future__ import annotations

import ast
from typing import Optional

from photon_ml_tpu.analysis.core import Finding
from photon_ml_tpu.analysis.dataflow import Dataflow
from photon_ml_tpu.analysis.package import (
    ModuleInfo, PackageIndex, jit_wrapping_call, _jit_options,
)
from photon_ml_tpu.analysis.rules_sync import build_scope_map


def _donating_names_module(mod: ModuleInfo,
                           index: PackageIndex) -> dict[str, set[int]]:
    """Module-level names bound to donating jit wrappers."""
    out: dict[str, set[int]] = {}
    for b in index.jit_bindings:
        if b.mod is mod and b.bound_name and b.donate_idx:
            out.setdefault(b.bound_name, set()).update(b.donate_idx)
    return out


def _donation_of_call(mod: ModuleInfo, call: ast.Call,
                      donating: dict[str, set[int]]) -> set[int]:
    """Donated positional indices for one call expression (empty when
    the callee is not known to donate)."""
    if isinstance(call.func, ast.Name) and call.func.id in donating:
        return donating[call.func.id]
    # inline: jax.jit(f, donate_argnums=(0,))(x)
    if isinstance(call.func, ast.Call):
        wrap = jit_wrapping_call(mod, call.func)
        if wrap is not None:
            _, donate = _jit_options(mod, wrap, None)
            return donate
    return set()


def _collect_local_donating(mod: ModuleInfo, fdef,
                            module_donating: dict[str, set[int]]
                            ) -> dict[str, set[int]]:
    """Donating names visible in one function: module-level bindings,
    local jit bindings, and one hop of plain-name aliasing (covers the
    ``fn = _fit_blocks; if fast: fn = _fit_blocks_donate`` pattern —
    may-analysis, so a conditionally-donating alias counts)."""
    donating = dict(module_donating)
    assigns = [n for n in ast.walk(fdef) if isinstance(n, ast.Assign)]
    for n in assigns:
        if len(n.targets) != 1 or not isinstance(n.targets[0], ast.Name):
            continue
        target = n.targets[0].id
        if isinstance(n.value, ast.Call):
            wrap = jit_wrapping_call(mod, n.value)
            if wrap is not None:
                _, donate = _jit_options(mod, wrap, None)
                if donate:
                    donating.setdefault(target, set()).update(donate)
    for n in assigns:  # alias hop, after direct bindings are known
        if len(n.targets) != 1 or not isinstance(n.targets[0], ast.Name):
            continue
        if isinstance(n.value, ast.Name) and n.value.id in donating:
            donating.setdefault(n.targets[0].id, set()).update(
                donating[n.value.id])
    return donating


def _stmt_of(fdef, node) -> Optional[ast.stmt]:
    """Innermost statement of ``fdef`` whose subtree contains ``node``."""
    best = None
    for s in ast.walk(fdef):
        if isinstance(s, ast.stmt) and any(c is node for c in ast.walk(s)):
            best = s  # walk order visits outer statements first
    return best


def _later_read(fdef, name: str, call: ast.Call) -> Optional[ast.Name]:
    """First Load of ``name`` after the donating ``call`` completes that
    is not preceded by a rebinding of the same name (a rebind kills the
    hazard: the variable no longer aliases the donated buffer).

    Positions are (lineno, col) so a read on the call's OWN line —
    ``return donating(x) + x`` — still counts, and the idiomatic
    self-rebind ``x = donating(x)`` does not: the assignment targets of
    the statement containing the call re-bind the name the moment the
    call returns."""
    after = (call.end_lineno or call.lineno,
             call.end_col_offset or call.col_offset)
    stmt = _stmt_of(fdef, call)
    rebind = None
    if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target]
        for tgt in targets:
            for n in ast.walk(tgt):
                if isinstance(n, ast.Name) and n.id == name:
                    rebind = after  # rebound as soon as the call returns
    for n in ast.walk(fdef):
        if isinstance(n, ast.Name) and n.id == name \
                and isinstance(n.ctx, (ast.Store, ast.Del)) \
                and (n.lineno, n.col_offset) > after:
            pos = (n.lineno, n.col_offset)
            if rebind is None or pos < rebind:
                rebind = pos
    best: Optional[ast.Name] = None
    for n in ast.walk(fdef):
        if isinstance(n, ast.Name) and n.id == name \
                and isinstance(n.ctx, ast.Load) \
                and (n.lineno, n.col_offset) > after:
            if rebind is not None and (n.lineno, n.col_offset) > rebind:
                continue
            if best is None or (n.lineno, n.col_offset) < (best.lineno,
                                                           best.col_offset):
                best = n
    return best


def _loop_carried_hazard(fdef, call: ast.Call,
                         name: str) -> Optional[ast.AST]:
    """The innermost enclosing loop in which ``name`` is donated by
    ``call`` but never rebound — so the next iteration reuses the
    deleted buffer. None when there is no such loop."""
    innermost = None
    for node in ast.walk(fdef):
        if isinstance(node, (ast.For, ast.While)) and \
                any(c is call for c in ast.walk(node)):
            innermost = node  # walk visits outer loops first
    if innermost is None:
        return None
    for n in ast.walk(innermost):
        if isinstance(n, ast.Name) and n.id == name \
                and isinstance(n.ctx, (ast.Store, ast.Del)):
            return None  # rebound inside the loop: hazard cleared
    return innermost


def check(modules: list[ModuleInfo], index: PackageIndex,
          flows: dict[str, Dataflow], ctx) -> list[Finding]:
    findings: list[Finding] = []
    for mod in modules:
        module_donating = _donating_names_module(mod, index)
        scope_of = build_scope_map(mod.tree)
        fdefs = [n for n in ast.walk(mod.tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for fdef in fdefs:
            donating = _collect_local_donating(mod, fdef, module_donating)
            if not donating:
                continue
            for call in ast.walk(fdef):
                if not isinstance(call, ast.Call):
                    continue
                # only calls whose innermost function scope is THIS fdef
                # (nested defs get their own pass)
                if scope_of.get(id(call)) is not fdef:
                    continue
                donate_idx = _donation_of_call(mod, call, donating)
                for i in sorted(donate_idx):
                    if i >= len(call.args):
                        continue
                    arg = call.args[i]
                    if not isinstance(arg, ast.Name):
                        continue  # *args / expressions: not tracked
                    read = _later_read(fdef, arg.id, call)
                    if read is not None:
                        findings.append(Finding(
                            "W301", mod.relpath, call.lineno,
                            call.col_offset,
                            f"'{arg.id}' is donated to XLA at argument "
                            f"{i} here but read again at line "
                            f"{read.lineno} — donated buffers are "
                            f"deleted; copy first or drop the read"))
                        continue
                    loop = _loop_carried_hazard(fdef, call, arg.id)
                    if loop is not None:
                        kind = "for" if isinstance(loop, ast.For) \
                            else "while"
                        findings.append(Finding(
                            "W301", mod.relpath, call.lineno,
                            call.col_offset,
                            f"'{arg.id}' is donated to XLA at argument "
                            f"{i} inside the `{kind}` loop at line "
                            f"{loop.lineno} without being rebound — "
                            f"the next iteration reads the deleted "
                            f"buffer; rebind it (x = fn(x)) or stop "
                            f"donating in a loop"))
    return findings
