"""W9xx — host-concurrency safety: threads, locks, signal handlers.

The host side of the system (obs/heartbeat, obs/export, utils/preempt,
the I/O loaders) runs daemon threads, locks, and signal handlers that no
test can exhaustively race. These rules make the conventions structural:

- **W901** unguarded shared state, two variants sharing one rule id:

  *thread-shared* — an attribute (or module global) written inside a
  thread body (the transitive closure of methods reachable from a
  ``threading.Thread(target=...)`` root, resolved through the class
  index) and accessed from a non-thread method with no lock in common;

  *inconsistent guard* — an attribute written under ``with self._lock:``
  in one method but written with no lock at all in another. The lock set
  is inferred from enclosing ``with`` scopes over attributes assigned
  ``threading.Lock()``/``RLock()``/``Condition()`` (and module-level
  lock globals).

  Attributes holding intrinsically thread-safe objects (locks, Events,
  queues, deques, Thread handles) are exempt, as are ``__init__``/
  ``__post_init__`` and the method that constructs the Thread (writes
  there happen-before ``start()``).
- **W902** a signal handler (anything registered via ``signal.signal``)
  doing more than async-signal-safe work: allowed are assignments,
  lock-scoped flag latching, ``Event`` ``set``/``is_set``/``clear``,
  dict ``.get``, ``signal.*``/``os.kill``/``os.getpid`` calls, and
  calls into own methods that themselves pass the same check
  (``utils/preempt.py``'s latch-and-chain handler is the exemplar).
- **W903** a thread started but never joined: a ``Thread`` stored on
  ``self`` with ``.start()`` called and no ``self.<attr>.join(...)``
  anywhere in the class, or a local ``Thread`` started and not joined
  in the same function — shutdown then can't bound the thread's
  lifetime (daemon threads die mid-write on interpreter exit).
- **W904** inconsistent nested lock order: ``with A: with B:`` at one
  site and ``with B: with A:`` at another, anywhere in the package —
  the classic deadlock shape. Lock identity is
  ``<class>.<attr>``/``<module>.<global>``, so the check is
  whole-program.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from photon_ml_tpu.analysis.core import Finding
from photon_ml_tpu.analysis.dataflow import Dataflow
from photon_ml_tpu.analysis.package import (
    ClassInfo, ModuleInfo, PackageIndex,
)

_LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition"}
_SYNC_CTORS = _LOCK_CTORS | {
    "threading.Event", "threading.Semaphore", "threading.BoundedSemaphore",
    "threading.Barrier", "threading.Thread", "threading.local",
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue", "collections.deque",
}
_MUTATING_METHODS = {"append", "appendleft", "add", "extend", "insert",
                     "pop", "popleft", "remove", "discard", "update",
                     "setdefault", "clear", "put", "put_nowait"}
_SAFE_HANDLER_ATTRS = {"set", "clear", "is_set", "get"}
_SAFE_HANDLER_CALLS = {"os.kill", "os.getpid", "str", "int", "float",
                       "bool", "len", "repr", "format"}
_EXEMPT_METHODS = {"__init__", "__post_init__"}


# -- shared per-class facts -------------------------------------------------


@dataclasses.dataclass
class _Access:
    attr: str
    write: bool
    method: str
    line: int
    col: int
    locks: frozenset


class _ClassFacts:
    """Everything W901/W903 need about one class: attribute constructor
    kinds, lock attributes, per-method attribute accesses with held
    locks, thread roots and their method closures."""

    def __init__(self, info: ClassInfo, index: PackageIndex,
                 module_locks: set[str]):
        self.info = info
        self.index = index
        self.module_locks = module_locks
        self.attr_ctors: dict[str, str] = {}
        self.thread_targets: list[tuple[str, str, ast.Call]] = []
        # (spawn method, target method, ctor call)
        self.accesses: list[_Access] = []
        self.order_pairs: list[tuple[str, str, str, int]] = []
        self.joined_attrs: set[str] = set()
        self.started_attrs: dict[str, ast.Call] = {}
        self._collect_ctors()
        self.lock_attrs = {a for a, c in self.attr_ctors.items()
                           if c in _LOCK_CTORS}
        self.sync_attrs = {a for a, c in self.attr_ctors.items()
                           if c in _SYNC_CTORS}
        for name, fdef in info.methods.items():
            self._walk_method(name, fdef)

    def _collect_ctors(self) -> None:
        mod = self.info.mod
        for fdef in self.info.methods.values():
            for node in ast.walk(fdef):
                target = value = None
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    target, value = node.targets[0], node.value
                elif isinstance(node, ast.AnnAssign) and node.value:
                    target, value = node.target, node.value
                if isinstance(target, ast.Attribute) \
                        and isinstance(target.value, ast.Name) \
                        and isinstance(value, ast.Call):
                    d = mod.resolve(value.func)
                    if d in _SYNC_CTORS:
                        self.attr_ctors.setdefault(target.attr, d)

    # -- lock identity ------------------------------------------------------

    def _lock_name(self, node, self_name: str) -> Optional[str]:
        """Lock identity of a with-item context expression, or None."""
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == self_name \
                and node.attr in self.lock_attrs:
            return f"{self.info.dotted}.{node.attr}"
        if isinstance(node, ast.Name):
            d = self.info.mod.resolve(node)
            if d in self.module_locks:
                return d
        return None

    # -- per-method walk ----------------------------------------------------

    def _walk_method(self, name: str, fdef) -> None:
        pos = fdef.args.posonlyargs + fdef.args.args
        if not pos:
            return
        self_name = pos[0].arg
        self._stmts(fdef.body, name, self_name, frozenset())
        for node in ast.walk(fdef):
            if not isinstance(node, ast.Call):
                continue
            d = self.info.mod.resolve(node.func)
            if d == "threading.Thread":
                tgt = None
                for kw in node.keywords:
                    if kw.arg == "target":
                        tgt = kw.value
                if isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == self_name:
                    self.thread_targets.append((name, tgt.attr, node))
            elif isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Attribute) and \
                    isinstance(node.func.value.value, ast.Name) and \
                    node.func.value.value.id == self_name:
                # self.<attr>.join() / self.<attr>.start()
                if node.func.attr == "join":
                    self.joined_attrs.add(node.func.value.attr)
                elif node.func.attr == "start" and \
                        node.func.value.attr in self.sync_attrs:
                    self.started_attrs.setdefault(
                        node.func.value.attr, node)

    def _stmts(self, stmts, method, self_name, held) -> None:
        for s in stmts or []:
            self._stmt(s, method, self_name, held)

    def _stmt(self, s, method, self_name, held) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def runs later under unknown locks
            self._stmts(s.body, method, self_name, frozenset())
            return
        if isinstance(s, (ast.With, ast.AsyncWith)):
            new = set(held)
            for item in s.items:
                self._expr(item.context_expr, method, self_name, held)
                nm = self._lock_name(item.context_expr, self_name)
                if nm is not None:
                    for outer in held:
                        self.order_pairs.append(
                            (outer, nm, method, item.context_expr.lineno))
                    new.add(nm)
            self._stmts(s.body, method, self_name, frozenset(new))
            return
        if isinstance(s, (ast.For, ast.AsyncFor)):
            self._expr(s.iter, method, self_name, held)
            self._stmts(s.body, method, self_name, held)
            self._stmts(s.orelse, method, self_name, held)
            return
        if isinstance(s, ast.While):
            self._expr(s.test, method, self_name, held)
            self._stmts(s.body, method, self_name, held)
            self._stmts(s.orelse, method, self_name, held)
            return
        if isinstance(s, ast.If):
            self._expr(s.test, method, self_name, held)
            self._stmts(s.body, method, self_name, held)
            self._stmts(s.orelse, method, self_name, held)
            return
        if isinstance(s, ast.Try):
            self._stmts(s.body, method, self_name, held)
            for h in s.handlers:
                self._stmts(h.body, method, self_name, held)
            self._stmts(s.orelse, method, self_name, held)
            self._stmts(s.finalbody, method, self_name, held)
            return
        if isinstance(s, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = s.targets if isinstance(s, ast.Assign) else [s.target]
            for t in targets:
                attr = self._attr_of(t, self_name)
                if attr is not None:
                    self.accesses.append(_Access(
                        attr, True, method, t.lineno, t.col_offset, held))
                else:
                    self._expr(t, method, self_name, held)
            value = s.value
            if value is not None:
                self._expr(value, method, self_name, held)
            if isinstance(s, ast.AugAssign):
                attr = self._attr_of(s.target, self_name)
                if attr is not None:  # x += 1 also reads
                    self.accesses.append(_Access(
                        attr, False, method, s.target.lineno,
                        s.target.col_offset, held))
            return
        for child in ast.iter_child_nodes(s):
            if isinstance(child, ast.expr):
                self._expr(child, method, self_name, held)

    def _attr_of(self, node, self_name) -> Optional[str]:
        """self.X, self.X[...] (container mutation) → attribute name."""
        while isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == self_name:
            return node.attr
        return None

    def _expr(self, e, method, self_name, held) -> None:
        if e is None:
            return
        for node in ast.walk(e):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == self_name:
                self.accesses.append(_Access(
                    node.attr, False, method, node.lineno,
                    node.col_offset, held))
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATING_METHODS:
                attr = self._attr_of(node.func.value, self_name)
                if attr is not None:
                    self.accesses.append(_Access(
                        attr, True, method, node.lineno,
                        node.col_offset, held))

    # -- thread closure -----------------------------------------------------

    def closure(self, root: str) -> set[str]:
        """Methods transitively reachable from a thread root via
        ``self.<m>()`` calls, resolved through the class index."""
        seen: set[str] = set()
        stack = [root]
        while stack:
            m = stack.pop()
            if m in seen:
                continue
            seen.add(m)
            hit = self.index.resolve_method(self.info.dotted, m)
            if hit is None:
                continue
            _, fdef = hit
            pos = fdef.args.posonlyargs + fdef.args.args
            if not pos:
                continue
            self_name = pos[0].arg
            for node in ast.walk(fdef):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id == self_name:
                    stack.append(node.func.attr)
        return seen


# -- W901 -------------------------------------------------------------------


def _w901_class(facts: _ClassFacts, findings: list) -> None:
    info = facts.info
    exempt_attrs = facts.sync_attrs
    spawn_methods = {m for m, _, _ in facts.thread_targets}

    # thread-shared variant
    thread_methods: set[str] = set()
    roots = []
    for _, target, _ in facts.thread_targets:
        thread_methods |= facts.closure(target)
        roots.append(target)
    reported: set[str] = set()
    if thread_methods:
        outside_exempt = _EXEMPT_METHODS | spawn_methods
        by_attr_writes: dict[str, list[_Access]] = {}
        by_attr_outside: dict[str, list[_Access]] = {}
        for a in facts.accesses:
            if a.attr in exempt_attrs or a.attr in reported:
                continue
            if a.method in thread_methods and a.write:
                by_attr_writes.setdefault(a.attr, []).append(a)
            if a.method not in thread_methods and \
                    a.method not in outside_exempt:
                by_attr_outside.setdefault(a.attr, []).append(a)
        for attr in sorted(set(by_attr_writes) & set(by_attr_outside)):
            for w in by_attr_writes[attr]:
                hit = next((o for o in by_attr_outside[attr]
                            if not (w.locks & o.locks)), None)
                if hit is not None:
                    findings.append(Finding(
                        "W901", info.mod.relpath, w.line, w.col,
                        f"attribute {attr!r} is written from the "
                        f"{roots[0]!r} thread body but accessed in "
                        f"{hit.method!r} (line {hit.line}) with no lock "
                        f"in common — guard both sides with one lock or "
                        f"hand the value over via an Event/queue"))
                    reported.add(attr)
                    break

    # inconsistent-guard variant: a class lock guards SOME accesses of an
    # attribute (reads count — an unlocked write races locked readers just
    # as hard as locked writers) while another method writes it bare.
    if not facts.lock_attrs:
        return
    lock_ids = {f"{info.dotted}.{a}" for a in facts.lock_attrs} \
        | facts.module_locks
    by_attr: dict[str, list[_Access]] = {}
    for a in facts.accesses:
        if a.attr not in exempt_attrs \
                and a.method not in _EXEMPT_METHODS:
            by_attr.setdefault(a.attr, []).append(a)
    for attr, accs in sorted(by_attr.items()):
        if attr in reported:
            continue
        locked = [x for x in accs if x.locks & lock_ids]
        bare = [x for x in accs if x.write and not x.locks]
        if locked and bare:
            lk = sorted(locked[0].locks & lock_ids)[0]
            b = bare[0]
            findings.append(Finding(
                "W901", info.mod.relpath, b.line, b.col,
                f"attribute {attr!r} is accessed under "
                f"{lk.rsplit('.', 1)[-1]!r} in {locked[0].method!r} "
                f"(line {locked[0].line}) but written with no lock here "
                f"in {b.method!r} — acquire the same lock on every "
                f"access of a guarded attribute"))


def _module_locks(mod: ModuleInfo) -> set[str]:
    out = set()
    for name, value in mod.constants.items():
        if isinstance(value, ast.Call) and \
                mod.resolve(value.func) in _LOCK_CTORS:
            out.add(f"{mod.module_name}.{name}")
    return out


def _w901_globals(mod: ModuleInfo, locks: set[str],
                  findings: list) -> None:
    """Inconsistent-guard variant for ``global``-declared writes."""
    writes: dict[str, list[tuple[str, int, int, frozenset]]] = {}

    def walk_fn(fdef, declared: set[str]) -> None:
        def stmts(body, held):
            for s in body or []:
                stmt(s, held)

        def stmt(s, held):
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                inner = {n for g in ast.walk(s)
                         if isinstance(g, ast.Global) for n in g.names}
                if inner:
                    walk_fn(s, inner)
                return
            if isinstance(s, (ast.With, ast.AsyncWith)):
                new = set(held)
                for item in s.items:
                    if isinstance(item.context_expr, ast.Name):
                        d = mod.resolve(item.context_expr)
                        if d in locks:
                            new.add(d)
                stmts(s.body, frozenset(new))
                return
            if isinstance(s, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = s.targets if isinstance(s, ast.Assign) \
                    else [s.target]
                for t in targets:
                    if isinstance(t, ast.Name) and t.id in declared:
                        writes.setdefault(t.id, []).append(
                            (fdef.name, t.lineno, t.col_offset, held))
                return
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(s, field, None)
                if isinstance(sub, list):
                    stmts(sub, held)
            for h in getattr(s, "handlers", []) or []:
                stmts(h.body, held)

        stmts(fdef.body, frozenset())

    for node in mod.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            declared = {n for g in ast.walk(node)
                        if isinstance(g, ast.Global) for n in g.names}
            if declared:
                walk_fn(node, declared)
    for name, sites in sorted(writes.items()):
        locked = [s for s in sites if s[3]]
        bare = [s for s in sites if not s[3]]
        if locked and bare and locked[0][0] != bare[0][0]:
            fn, line, col, _ = bare[0]
            lk = sorted(locked[0][3])[0]
            findings.append(Finding(
                "W901", mod.relpath, line, col,
                f"module global {name!r} is written under "
                f"{lk.rsplit('.', 1)[-1]!r} in {locked[0][0]!r} but "
                f"written with no lock here in {fn!r} — acquire the "
                f"same lock on every write"))


# -- W902 -------------------------------------------------------------------


def _handler_violations(info: ClassInfo, index: PackageIndex, fdef,
                        depth: int = 0, seen=None) -> list[tuple]:
    """(node, description) for non-async-signal-safe work in a handler,
    recursing into own methods (depth-limited)."""
    if seen is None:
        seen = set()
    if depth > 3 or id(fdef) in seen:
        return []
    seen.add(id(fdef))
    mod = info.mod
    pos = fdef.args.posonlyargs + fdef.args.args
    self_name = pos[0].arg if pos else None
    out: list[tuple] = []
    for node in ast.walk(fdef):
        if not isinstance(node, ast.Call):
            continue
        d = mod.resolve(node.func)
        if d is not None:
            if d.startswith("signal.") or d in _SAFE_HANDLER_CALLS:
                continue
        if isinstance(node.func, ast.Name) and d is None and \
                node.func.id in _SAFE_HANDLER_CALLS:
            continue
        if isinstance(node.func, ast.Attribute):
            base = node.func.value
            if isinstance(base, ast.Name) and base.id == self_name:
                hit = index.resolve_method(info.dotted, node.func.attr)
                if hit is not None:
                    out.extend(_handler_violations(
                        hit[0], index, hit[1], depth + 1, seen))
                    continue
            if node.func.attr in _SAFE_HANDLER_ATTRS:
                continue
        desc = d or (node.func.attr if isinstance(node.func, ast.Attribute)
                     else getattr(node.func, "id", "<call>"))
        out.append((node, desc))
    return out


def _w902(modules, index, findings) -> None:
    for info in index.classes.values():
        handlers: set[str] = set()
        for fdef in info.methods.values():
            pos = fdef.args.posonlyargs + fdef.args.args
            if not pos:
                continue
            self_name = pos[0].arg
            for node in ast.walk(fdef):
                if isinstance(node, ast.Call) and \
                        info.mod.resolve(node.func) == "signal.signal" \
                        and len(node.args) == 2:
                    h = node.args[1]
                    if isinstance(h, ast.Attribute) and \
                            isinstance(h.value, ast.Name) and \
                            h.value.id == self_name:
                        handlers.add(h.attr)
        for hname in sorted(handlers):
            hit = index.resolve_method(info.dotted, hname)
            if hit is None:
                continue
            for node, desc in _handler_violations(hit[0], index, hit[1]):
                findings.append(Finding(
                    "W902", hit[0].mod.relpath, node.lineno,
                    node.col_offset,
                    f"signal handler {hname!r} calls {desc} — handlers "
                    f"run inside arbitrary interrupted frames and must "
                    f"only latch flags/Events (set/clear, lock-scoped "
                    f"assignment, signal.*/os.kill chaining); move this "
                    f"work to the thread that observes the flag"))


# -- W903 / W904 ------------------------------------------------------------


def _w903(facts: _ClassFacts, findings: list) -> None:
    info = facts.info
    thread_attrs = {a for a, c in facts.attr_ctors.items()
                    if c == "threading.Thread"}
    for attr, start in sorted(facts.started_attrs.items()):
        if attr in thread_attrs and attr not in facts.joined_attrs:
            findings.append(Finding(
                "W903", info.mod.relpath, start.lineno, start.col_offset,
                f"thread {attr!r} is started but no method of "
                f"{info.dotted.rsplit('.', 1)[-1]} ever joins it — "
                f"shutdown cannot bound its lifetime (a daemon thread "
                f"dies mid-write on interpreter exit); add a stop/join "
                f"path"))


def _w903_locals(mod: ModuleInfo, findings: list) -> None:
    """t = threading.Thread(...); t.start() with no t.join in scope.

    A thread handed off — returned, appended to a worker list, passed to
    another call, or stored on an object — is the new owner's problem
    and is not flagged; only a thread whose sole uses in its scope are
    construction and ``.start()`` is a leak."""
    from photon_ml_tpu.analysis.rules_sync import build_scope_map

    scope_of = build_scope_map(mod.tree)
    made: dict[tuple, int] = {}
    started: dict[tuple, ast.Call] = {}
    joined: set[tuple] = set()
    other_uses: dict[tuple, int] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.value, ast.Call) and \
                mod.resolve(node.value.func) == "threading.Thread" and \
                isinstance(node.targets[0], ast.Name):
            sid = scope_of.get(id(node.value))
            made[(None if sid is None else id(sid),
                  node.targets[0].id)] = node.lineno
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name):
            sid = scope_of.get(id(node))
            key = (None if sid is None else id(sid), node.func.value.id)
            if node.func.attr == "start":
                started[key] = node
            elif node.func.attr == "join":
                joined.add(key)
            else:
                other_uses[key] = other_uses.get(key, 0) + 1
        elif isinstance(node, ast.Name) and \
                isinstance(getattr(node, "ctx", None), ast.Load):
            sid = scope_of.get(id(node))
            key = (None if sid is None else id(sid), node.id)
            other_uses[key] = other_uses.get(key, 0) + 1
    for key, line in sorted(made.items(), key=lambda kv: kv[1]):
        if key not in started or key in joined:
            continue
        # every Load of the name counts once for .start()'s receiver;
        # any use beyond that is a hand-off to another owner
        if other_uses.get(key, 0) > 1:
            continue
        start_call = started[key]
        findings.append(Finding(
            "W903", mod.relpath, start_call.lineno,
            start_call.col_offset,
            f"local thread {key[1]!r} is started but never joined in "
            f"this scope — shutdown cannot bound its lifetime; join it "
            f"or hand it to an owner that does"))


def _w904(order_pairs: list[tuple[str, str, str, str, int]],
          findings: list) -> None:
    """order_pairs: (outer, inner, relpath, method, line)."""
    first: dict[tuple[str, str], tuple[str, str, int]] = {}
    for outer, inner, relpath, method, line in order_pairs:
        first.setdefault((outer, inner), (relpath, method, line))
    reported: set[frozenset] = set()
    for (outer, inner), (relpath, method, line) in sorted(
            first.items(), key=lambda kv: (kv[1][0], kv[1][2])):
        rev = first.get((inner, outer))
        pair = frozenset((outer, inner))
        if rev is None or pair in reported or outer == inner:
            continue
        reported.add(pair)
        findings.append(Finding(
            "W904", relpath, line, 0,
            f"lock {inner.rsplit('.', 1)[-1]!r} acquired while holding "
            f"{outer.rsplit('.', 1)[-1]!r} here, but {rev[0]}:{rev[2]} "
            f"({rev[1]}) nests them the other way round — pick one "
            f"global acquisition order to rule out deadlock"))


def check(modules: list[ModuleInfo], index: PackageIndex,
          flows: dict[str, Dataflow], ctx) -> list[Finding]:
    findings: list[Finding] = []
    module_locks_by_mod = {m.module_name: _module_locks(m)
                           for m in modules}
    all_order_pairs: list[tuple] = []
    for info in index.classes.values():
        locks = module_locks_by_mod.get(info.mod.module_name, set())
        facts = _ClassFacts(info, index, locks)
        _w901_class(facts, findings)
        _w903(facts, findings)
        all_order_pairs.extend(
            (outer, inner, info.mod.relpath, f"{info.dotted}.{method}",
             line)
            for outer, inner, method, line in facts.order_pairs)
    for mod in modules:
        locks = module_locks_by_mod.get(mod.module_name, set())
        if locks:
            _w901_globals(mod, locks, findings)
        _w903_locals(mod, findings)
    _w902(modules, index, findings)
    _w904(all_order_pairs, findings)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    # class walks can visit a site twice (AugAssign read+write) — dedupe
    seen: set[tuple] = set()
    out: list[Finding] = []
    for f in findings:
        key = (f.rule, f.path, f.line, f.col, f.message)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out
