"""WBxx — telemetry-taxonomy drift.

The observability plane is held together by names: every
``REGISTRY.counter/gauge/histogram("...")`` emit, every
``trace.span("...")`` / ``trace.record_span("...")``, the README
taxonomy tables operators read, and
the consumers that aggregate the stream (``tools/photon_status.py``,
``bench.py``, ``tools/trace_report.py``, ``tools/trace_diff.py``, the
chaos drill's assertions). A renamed counter breaks the dashboard
silently: the emit side keeps counting, the consumer reads ``None``
forever. These rules reconcile the three corners:

- **WB00** a telemetry name built from a fully dynamic expression —
  statically unauditable (an f-string with a literal head is tracked
  as a prefix and matched by prefix everywhere below; a name drawn
  from a same-scope ``for name, ... in <literal tuple of tuples>``
  loop — the stage-span table idiom — resolves to each row's literal
  first element, constant slices included, so data-driven emit loops
  stay auditable without suppressions).
- **WB01** an emitted metric/span name missing from the README
  taxonomy tables (the ``| span |`` / ``| metric |`` tables).
- **WB02** a README taxonomy row naming a metric/span nothing emits.
- **WB03** a *consumer* reading a metric/span name nothing emits —
  the phantom-consumer / silent-dashboard bug class. Consumer shapes:
  ``totals.get("name")`` / ``totals["name"]`` reads off heartbeat
  ``metric_totals``, record-name comparisons
  (``rec.get("name") == "cd.update"``, directly or through a local),
  registry READS (``.counter("x").total()/.by_label()``), and literal
  arguments to helpers whose parameter flows into a totals lookup.
- **WB04** label-key drift between emit sites sharing one name: the
  per-label breakdown silently fragments when one site tags
  ``reason=`` and another doesn't. Only sites whose mutate call
  (``.inc/.set/.observe``) is statically linked (chained or through a
  same-scope local) contribute a label set; unresolved sites are
  EXCLUDED, not treated as empty.

Reconciliation against the README only runs when the relevant table
exists (fixture runs pass READMEs without them). Consumer files that
are not part of the lint path set (``tools/``, ``bench.py``) are
loaded as *auxiliary* modules by the runner — they are scanned for
reads and honor inline suppressions, but no other family lints them.

The registry/trace implementations themselves (``obs/metrics.py``,
``obs/trace.py``) are skipped — their parameterized emit shims would
read as dynamic-name emits.
"""

from __future__ import annotations

import ast
import re

from photon_ml_tpu.analysis.core import Finding
from photon_ml_tpu.analysis.dataflow import Dataflow
from photon_ml_tpu.analysis.package import (
    ModuleInfo, PackageIndex, name_value,
)

_METRIC_ATTRS = {"counter", "gauge", "histogram"}
_MUTATORS = {"inc", "set", "observe"}
_READERS = {"total", "value", "by_label", "records", "snapshot", "items"}
_SKIP_SUFFIXES = ("obs/metrics.py", "obs/trace.py")

_T_HEADER_RE = re.compile(r"^\s*\|\s*(span|metric)s?\s*\|",
                          re.IGNORECASE)
_TABLE_LINE_RE = re.compile(r"^\s*\|")
_NAME_RE = re.compile(r"`([\w.\-\[\]*]+)`")
_CONSUMED_NAME_RE = re.compile(r"^[a-z][a-z0-9_.]*$")


def parse_taxonomy(readme_lines: list[str]) -> dict[str, dict[str, int]]:
    """``{"span": {name: line}, "metric": {name: line}}`` from every
    markdown table whose header's first cell is ``span`` or ``metric``.
    A namespace that has NO table at all is absent from the result —
    the caller skips reconciliation for it (fixture READMEs). One row's
    first cell may document several names (``ckpt.save`` /
    ``ckpt.restore``)."""
    out: dict[str, dict[str, int]] = {}
    namespace = None
    for i, line in enumerate(readme_lines, start=1):
        if namespace is None:
            m = _T_HEADER_RE.match(line)
            if m:
                namespace = m.group(1).lower()
                out.setdefault(namespace, {})
            continue
        if not _TABLE_LINE_RE.match(line):
            namespace = None
            m = _T_HEADER_RE.match(line)
            if m:
                namespace = m.group(1).lower()
                out.setdefault(namespace, {})
            continue
        cells = line.split("|")
        first = cells[1] if len(cells) > 1 else ""
        for name in _NAME_RE.findall(first):
            out[namespace].setdefault(name, i)
    return out


def _scoped_walk(root: ast.AST):
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _scopes(mod: ModuleInfo):
    """Every analysis scope: the module top level, then each def."""
    yield mod.tree
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _metric_call(mod: ModuleInfo, index: PackageIndex, node: ast.AST):
    """``(kind, form, name, name_node)`` when ``node`` constructs a
    metric handle (``<reg>.counter("x")``) or opens a span
    (``trace.span("x", ...)``), else None."""
    if not (isinstance(node, ast.Call) and node.args):
        return None
    if isinstance(node.func, ast.Attribute) \
            and node.func.attr in _METRIC_ATTRS:
        form, name = name_value(mod, index, node.args[0])
        return (node.func.attr, form, name, node.args[0])
    dotted = mod.resolve(node.func)
    if dotted is not None and "trace" in dotted \
            and (dotted.endswith(".span")
                 or dotted.endswith(".record_span")):
        form, name = name_value(mod, index, node.args[0])
        return ("span", form, name, node.args[0])
    return None


def _mutator_labels(call: ast.Call) -> frozenset:
    return frozenset(kw.arg for kw in call.keywords
                     if kw.arg is not None)


class _Site:
    __slots__ = ("kind", "form", "name", "mod", "line", "col", "labels")

    def __init__(self, kind, form, name, mod, node, labels):
        self.kind = kind          # counter | gauge | histogram | span
        self.form = form          # literal | prefix
        self.name = name
        self.mod = mod
        self.line = node.lineno
        self.col = node.col_offset
        self.labels = labels      # frozenset | None (unresolved)


def _literal_seq(node: ast.AST):
    """First-element string literals of a literal tuple/list whose
    every element is itself a tuple/list led by a string constant
    (the ``(("serve.batch_form", s, e), ...)`` span-table idiom);
    None when any row breaks the shape."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    names: list[str] = []
    for elt in node.elts:
        if (isinstance(elt, (ast.Tuple, ast.List)) and elt.elts
                and isinstance(elt.elts[0], ast.Constant)
                and isinstance(elt.elts[0].value, str)):
            names.append(elt.elts[0].value)
        else:
            return None
    return names


def _iter_literal_names(node: ast.AST, seq_vars: dict):
    """Resolve a ``for``-loop iterable to the literal names it yields:
    an inline span table, a local bound to one, or a constant slice of
    such a local (``stage_spans[1:]``)."""
    direct = _literal_seq(node)
    if direct is not None:
        return direct
    if isinstance(node, ast.Name):
        return seq_vars.get(node.id)
    if isinstance(node, ast.Subscript) \
            and isinstance(node.value, ast.Name) \
            and node.value.id in seq_vars \
            and isinstance(node.slice, ast.Slice):
        bounds = []
        for b in (node.slice.lower, node.slice.upper, node.slice.step):
            if b is None:
                bounds.append(None)
            elif isinstance(b, ast.Constant) and isinstance(b.value, int):
                bounds.append(b.value)
            else:
                return None
        return seq_vars[node.value.id][slice(*bounds)]
    return None


def _collect_loop_emits(scope: ast.AST, mod: ModuleInfo,
                        index: PackageIndex) -> dict[int, tuple]:
    """``{id(call): literal names}`` for every telemetry call whose
    name argument is a loop variable bound — by the INNERMOST enclosing
    for-loop, so two loops reusing one variable name never cross — to
    a statically literal span table."""
    seq_vars: dict[str, list] = {}
    for node in _scoped_walk(scope):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            names = _literal_seq(node.value)
            if names is not None:
                seq_vars[node.targets[0].id] = names
    out: dict[int, tuple] = {}

    def visit(node: ast.AST, bindings: dict) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if isinstance(node, ast.For):
            names = _iter_literal_names(node.iter, seq_vars)
            if names and isinstance(node.target, ast.Tuple) \
                    and node.target.elts \
                    and isinstance(node.target.elts[0], ast.Name):
                bindings = dict(bindings)
                bindings[node.target.elts[0].id] = tuple(names)
        inner = _metric_call(mod, index, node)
        if inner is not None:
            _kind, form, _name, name_node = inner
            if form == "dynamic" and isinstance(name_node, ast.Name) \
                    and name_node.id in bindings:
                out[id(node)] = bindings[name_node.id]
        for child in ast.iter_child_nodes(node):
            visit(child, bindings)

    for child in ast.iter_child_nodes(scope):
        visit(child, {})
    return out


def _scan_module(mod: ModuleInfo, index: PackageIndex,
                 emits: list, consumes: list, findings: list) -> None:
    """One module's emit sites, registry-read consumes, and WB00s."""
    skip_emits = mod.relpath.endswith(_SKIP_SUFFIXES)
    for scope in _scopes(mod):
        handled: set[int] = set()
        var_metric: dict[str, tuple] = {}
        loop_emits = _collect_loop_emits(scope, mod, index)
        # pass 1: chained forms and handle-variable bindings
        for node in _scoped_walk(scope):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Call):
                inner = _metric_call(mod, index, node.func.value)
                if inner is not None:
                    handled.add(id(node.func.value))
                    kind, form, name, name_node = inner
                    if form == "dynamic":
                        if not skip_emits:
                            findings.append(_wb00(mod, name_node, kind))
                        continue
                    if node.func.attr in _MUTATORS and kind != "span":
                        if not skip_emits:
                            emits.append(_Site(
                                kind, form, name, mod, name_node,
                                _mutator_labels(node)))
                    elif node.func.attr in _READERS:
                        consumes.append((form, name, mod, node))
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                inner = _metric_call(mod, index, node.value)
                if inner is not None and inner[0] != "span":
                    handled.add(id(node.value))
                    var_metric[node.targets[0].id] = inner
        # pass 2: mutations/reads through a bound handle variable
        seen_vars: set[str] = set()
        for node in _scoped_walk(scope):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in var_metric):
                continue
            kind, form, name, name_node = var_metric[node.func.value.id]
            if form == "dynamic":
                if node.func.value.id not in seen_vars \
                        and not skip_emits:
                    findings.append(_wb00(mod, name_node, kind))
                    seen_vars.add(node.func.value.id)
                continue
            if node.func.attr in _MUTATORS:
                if not skip_emits:
                    emits.append(_Site(kind, form, name, mod, node,
                                       _mutator_labels(node)))
            elif node.func.attr in _READERS:
                consumes.append((form, name, mod, node))
        # pass 3: spans and unlinked metric handles
        for node in _scoped_walk(scope):
            inner = _metric_call(mod, index, node)
            if inner is None or id(node) in handled:
                continue
            kind, form, name, name_node = inner
            if kind != "span":
                continue  # bare unlinked handle: neither emit nor read
            if skip_emits:
                continue
            if form == "dynamic":
                names = loop_emits.get(id(node))
                if names is not None:
                    for nm in names:
                        emits.append(_Site(kind, "literal", nm, mod,
                                           name_node,
                                           _mutator_labels(node)))
                else:
                    findings.append(_wb00(mod, name_node, kind))
            else:
                emits.append(_Site(kind, form, name, mod, name_node,
                                   _mutator_labels(node)))


def _wb00(mod: ModuleInfo, node: ast.AST, kind: str) -> Finding:
    return Finding(
        "WB00", mod.relpath, node.lineno, node.col_offset,
        f"{kind} name is a fully dynamic expression — the telemetry "
        f"taxonomy must stay statically auditable (use a literal or an "
        f"f-string with a literal head, or suppress with the reason "
        f"the name is dynamic)")


# -- consumer-side scan ----------------------------------------------------


def _totals_recv(node: ast.AST) -> bool:
    try:
        text = ast.unparse(node)
    except Exception:  # pragma: no cover
        return False
    return text.endswith("totals")


def _literal_names(node: ast.AST):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, str)]
    return []


def _totals_helpers(mods: list[ModuleInfo]) -> dict[str, int]:
    """``{dotted function name: param index}`` for helpers whose
    parameter flows into a totals lookup (``totals.get(name)`` /
    ``totals[name]`` / ``name in totals``)."""
    out: dict[str, int] = {}
    for mod in mods:
        for fdef in ast.walk(mod.tree):
            if not isinstance(fdef, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            params = [a.arg for a in fdef.args.posonlyargs
                      + fdef.args.args]
            if not params:
                continue
            flow_params: set[str] = set()
            for node in _scoped_walk(fdef):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "get" and node.args
                        and isinstance(node.args[0], ast.Name)
                        and _totals_recv(node.func.value)):
                    flow_params.add(node.args[0].id)
                elif (isinstance(node, ast.Subscript)
                        and isinstance(node.slice, ast.Name)
                        and _totals_recv(node.value)):
                    flow_params.add(node.slice.id)
                elif (isinstance(node, ast.Compare)
                        and len(node.ops) == 1
                        and isinstance(node.ops[0], (ast.In, ast.NotIn))
                        and isinstance(node.left, ast.Name)
                        and _totals_recv(node.comparators[0])):
                    flow_params.add(node.left.id)
            for p in flow_params:
                if p in params:
                    out[f"{mod.module_name}.{fdef.name}"] = \
                        params.index(p)
    return out


def _scan_consumers(mod: ModuleInfo, helpers: dict[str, int],
                    consumes: list) -> None:
    """Totals reads, record-name comparisons, and helper calls."""
    for scope in _scopes(mod):
        namevars: set[str] = set()
        if scope is not mod.tree:
            for node in _scoped_walk(scope):
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and isinstance(node.value, ast.Call)
                        and isinstance(node.value.func, ast.Attribute)
                        and node.value.func.attr == "get"
                        and node.value.args
                        and isinstance(node.value.args[0], ast.Constant)
                        and node.value.args[0].value == "name"):
                    namevars.add(node.targets[0].id)
        for node in _scoped_walk(scope):
            # totals.get("x") / totals["x"]
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get" and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and _totals_recv(node.func.value)):
                consumes.append(("literal", node.args[0].value, mod,
                                 node))
            elif (isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, ast.Load)
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)
                    and _totals_recv(node.value)):
                consumes.append(("literal", node.slice.value, mod,
                                 node))
            # rec.get("name") == "cd.update" / name in ("a", "b")
            elif (isinstance(node, ast.Compare) and len(node.ops) == 1
                    and isinstance(node.ops[0],
                                   (ast.Eq, ast.NotEq, ast.In,
                                    ast.NotIn))):
                left = node.left
                is_name_read = (
                    isinstance(left, ast.Name) and left.id in namevars)
                if not is_name_read and isinstance(left, ast.Call) \
                        and isinstance(left.func, ast.Attribute) \
                        and left.func.attr == "get" and left.args \
                        and isinstance(left.args[0], ast.Constant) \
                        and left.args[0].value == "name":
                    is_name_read = True
                if not is_name_read and isinstance(left, ast.Subscript) \
                        and isinstance(left.slice, ast.Constant) \
                        and left.slice.value == "name":
                    is_name_read = True
                if not is_name_read:
                    continue
                for name in _literal_names(node.comparators[0]):
                    if _CONSUMED_NAME_RE.match(name):
                        consumes.append(("literal", name, mod, node))
            # _serve_metric_total(trace, "retries")-style helper calls
            elif isinstance(node, ast.Call):
                dotted = mod.resolve(node.func)
                if dotted in helpers:
                    pos = helpers[dotted]
                    if pos < len(node.args) and isinstance(
                            node.args[pos], ast.Constant) and isinstance(
                            node.args[pos].value, str):
                        consumes.append(("literal",
                                         node.args[pos].value, mod,
                                         node.args[pos]))


# -- driver ----------------------------------------------------------------


def check(modules: list[ModuleInfo], index: PackageIndex,
          flows: dict[str, Dataflow], ctx) -> list[Finding]:
    findings: list[Finding] = []
    aux = list(getattr(ctx, "aux_modules", None) or [])
    emits: list[_Site] = []
    consumes: list[tuple] = []   # (form, name, mod, node)
    for mod in modules:
        _scan_module(mod, index, emits, consumes, findings)
    helpers = _totals_helpers(modules + aux)
    for mod in modules + aux:
        _scan_consumers(mod, helpers, consumes)

    emitted_literals = {s.name for s in emits if s.form == "literal"}
    emitted_prefixes = {s.name for s in emits if s.form == "prefix"}

    def emitted(name: str) -> bool:
        return name in emitted_literals or any(
            name.startswith(p) for p in emitted_prefixes)

    # WB01/WB02 — README reconcile, per namespace, when a table exists
    taxonomy = parse_taxonomy(ctx.readme_lines) \
        if ctx.readme_lines is not None else {}
    for namespace, is_ns in (("span", lambda s: s.kind == "span"),
                             ("metric", lambda s: s.kind != "span")):
        table = taxonomy.get(namespace)
        if table is None:
            continue
        first_site: dict[str, _Site] = {}
        ns_names: set[str] = set()
        ns_prefixes: set[str] = set()
        for s in sorted((s for s in emits if is_ns(s)),
                        key=lambda s: (s.mod.relpath, s.line, s.col)):
            (ns_prefixes if s.form == "prefix" else ns_names).add(s.name)
            first_site.setdefault(s.name, s)
        for name in sorted(ns_names):
            if name in table:
                continue
            s = first_site[name]
            findings.append(Finding(
                "WB01", s.mod.relpath, s.line, s.col,
                f"emitted {namespace} \"{name}\" has no row in the "
                f"README {namespace} taxonomy table — document what it "
                f"measures and its labels"))
        for prefix in sorted(ns_prefixes):
            if any(doc.startswith(prefix) for doc in table):
                continue
            s = first_site[prefix]
            findings.append(Finding(
                "WB01", s.mod.relpath, s.line, s.col,
                f"emitted {namespace} family \"{prefix}*\" has no row "
                f"in the README {namespace} taxonomy table — document "
                f"the family"))
        for doc, line in sorted(table.items()):
            doc_ok = doc in ns_names or any(
                doc.startswith(p) for p in ns_prefixes) or (
                doc.endswith("*") and any(
                    n.startswith(doc[:-1]) for n in ns_names))
            if not doc_ok:
                findings.append(Finding(
                    "WB02", ctx.readme_relpath or "README.md", line, 0,
                    f"README {namespace} taxonomy documents `{doc}` "
                    f"but nothing emits it — remove the row or restore "
                    f"the emit site"))

    # WB03 — phantom consumers
    if emits:
        for form, name, mod, node in consumes:
            if form != "literal" or emitted(name):
                continue
            findings.append(Finding(
                "WB03", mod.relpath, node.lineno, node.col_offset,
                f"reads metric/span \"{name}\" but nothing emits it — "
                f"phantom consumer (this dashboard/assertion went "
                f"silently dark)"))

    # WB04 — label-key drift between emit sites sharing one name
    by_name: dict[str, list[_Site]] = {}
    for s in emits:
        if s.form == "literal" and s.labels is not None:
            by_name.setdefault(s.name, []).append(s)
    for name, sites in sorted(by_name.items()):
        sites.sort(key=lambda s: (s.mod.relpath, s.line, s.col))
        ref = sites[0]
        for s in sites[1:]:
            if s.labels == ref.labels:
                continue
            findings.append(Finding(
                "WB04", s.mod.relpath, s.line, s.col,
                f"emit of \"{name}\" uses label keys "
                f"{{{', '.join(sorted(s.labels)) or ''}}} but the emit "
                f"at {ref.mod.relpath}:{ref.line} uses "
                f"{{{', '.join(sorted(ref.labels)) or ''}}} — per-label "
                f"breakdowns fragment across sites"))
    return findings
