"""Lightweight jax-value dataflow over one module's AST.

A tiny abstract interpreter tracks, per scope and in program order,
which names (may) hold jax arrays. The abstraction has four tags:

- ``JAX``    — a jax array, or a pytree/container of them (both sync on
  a host conversion, so the lint treats them alike);
- ``HOST``   — definitely host data (numpy / result of
  ``jax.device_get``) — conversions on these are free;
- ``JAXFN``  — a traced callable (``jax.jit(f)``, ``jax.vmap(f)``, a
  known jax-returning package function passed through ``partial``):
  *calling* it yields ``JAX``;
- ``JITWRAP``— a jit decorator factory (``partial(jax.jit, ...)``):
  calling it yields ``JAXFN``.

Unknown stays ``None`` and every rule treats unknown as clean — the
tracker is deliberately biased toward precision over recall (a finding
should mean something; the dynamic transfer-guard test remains the
recall backstop for what the dataflow cannot see).

Branches merge with may-semantics (``JAX`` wins), loops run their body
twice to pick up loop-carried values, and nested ``def``/``lambda``
bodies are analyzed at their definition point with a copy of the
enclosing environment as closure — call-time environments may differ,
which is an accepted approximation for lint purposes.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from photon_ml_tpu.analysis.package import ModuleInfo, PackageIndex

JAX = "jax"
HOST = "host"
JAXFN = "jaxfn"
JITWRAP = "jitwrap"
# Literal tuples/lists keep per-element tags — ("tuple", (tag, ...)) —
# so unpacking `a, b = (host_thing, jax_thing)` doesn't smear JAX onto
# both targets. Any other JAX-containing container collapses to JAX.
# Instances of package classes carry ("inst", dotted_class): method
# calls on them dispatch through the class index, so
# ``coord.score(...)`` joins the dataflow cross-module.


def inst_class(tag) -> Optional[str]:
    """Dotted class name when ``tag`` is a package-class instance."""
    if isinstance(tag, tuple) and len(tag) == 2 and tag[0] == "inst":
        return tag[1]
    return None


def is_jax(tag) -> bool:
    """True when a tag means 'jax array or a container holding one'."""
    if tag == JAX:
        return True
    if isinstance(tag, tuple) and tag and tag[0] == "tuple":
        return any(is_jax(t) for t in tag[1])
    return False


def _elt_tags(tag):
    """Per-element tags when unpacking ``tag``, or None when unknown
    arity (plain JAX unpacks to JAX elements)."""
    if isinstance(tag, tuple) and tag and tag[0] == "tuple":
        return tag[1]
    return None

# External call targets that produce jax values.
JAX_VALUE_PREFIXES = (
    "jax.numpy.", "jax.nn.", "jax.lax.", "jax.ops.", "jax.random.",
    "jax.scipy.", "jax.tree.",
)
JAX_VALUE_EXACT = {"jax.device_put", "jax.numpy", "jax.make_array_from_callback"}
# Calls that produce traced callables.
JAXFN_MAKERS = {
    "jax.jit", "jax.pjit", "jax.experimental.pjit.pjit", "jax.vmap",
    "jax.pmap", "jax.grad", "jax.value_and_grad", "jax.jacfwd",
    "jax.jacrev", "jax.hessian", "jax.checkpoint", "jax.remat",
    "jax.shard_map", "jax.experimental.shard_map.shard_map",
}
HOST_PRODUCERS = {"jax.device_get"}
# jax-array attributes that are themselves jax-valued; anything else
# (.shape, .dtype, .ndim, ...) drops the tag.
JAX_ATTRS = {"T", "mT", "at", "real", "imag"}
# jax-array methods whose result is host data, not another array.
HOST_METHODS = {"item", "tolist"}


@dataclasses.dataclass
class Dataflow:
    """Result of interpreting one module: a tag for every expression
    node (keyed by ``id(node)``) and the return-value tags of every
    function body encountered."""

    tags: dict[int, Optional[str]]
    fn_returns: dict[int, list[Optional[str]]]  # id(fdef) -> return tags

    def tag(self, node: ast.AST) -> Optional[str]:
        return self.tags.get(id(node))


def analyze_module(mod: ModuleInfo, index: PackageIndex,
                   jit_param_tags: Optional[dict[int, dict[str, str]]]
                   = None) -> Dataflow:
    """Interpret a whole module: top-level statements in order, then
    every ``def`` (at its definition point, with the enclosing env as
    closure). ``jit_param_tags`` maps ``id(FunctionDef)`` to initial
    parameter tags (the runner marks non-static params of jitted
    functions as ``JAX``)."""
    interp = _Interp(mod, index, jit_param_tags or {})
    interp.run_block(mod.tree.body, env={})
    return Dataflow(tags=interp.tags, fn_returns=interp.fn_returns)


class _Interp:
    def __init__(self, mod: ModuleInfo, index: PackageIndex,
                 jit_param_tags: dict[int, dict[str, str]]):
        self.mod = mod
        self.index = index
        self.jit_param_tags = jit_param_tags
        self.tags: dict[int, Optional[str]] = {}
        self.fn_returns: dict[int, list[Optional[str]]] = {}
        self._ret_stack: list[list[Optional[str]]] = []

    # -- statements --------------------------------------------------------

    def run_block(self, body, env: dict) -> dict:
        for stmt in body:
            env = self.stmt(stmt, env)
        return env

    def stmt(self, s: ast.stmt, env: dict) -> dict:
        if isinstance(s, ast.Assign):
            t = self.expr(s.value, env)
            for tgt in s.targets:
                self.bind(tgt, t, env)
        elif isinstance(s, ast.AnnAssign):
            if s.value is not None:
                self.bind(s.target, self.expr(s.value, env), env)
        elif isinstance(s, ast.AugAssign):
            t = self.expr(s.value, env)
            if isinstance(s.target, ast.Name):
                cur = env.get(s.target.id)
                env[s.target.id] = JAX if (is_jax(t) or is_jax(cur)) \
                    else cur
        elif isinstance(s, ast.Return):
            t = self.expr(s.value, env) if s.value is not None else None
            if self._ret_stack:
                self._ret_stack[-1].append(t)
        elif isinstance(s, ast.Expr):
            self.expr(s.value, env)
        elif isinstance(s, ast.If):
            self.expr(s.test, env)
            env_a = self.run_block(s.body, dict(env))
            env_b = self.run_block(s.orelse, dict(env))
            env = _merge(env_a, env_b)
        elif isinstance(s, ast.For):
            it = self.expr(s.iter, env)
            self.bind(s.target, JAX if is_jax(it) else None, env)
            for _ in range(2):  # pick up loop-carried tags
                env = _merge(env, self.run_block(s.body, dict(env)))
            env = self.run_block(s.orelse, env)
        elif isinstance(s, ast.While):
            self.expr(s.test, env)
            for _ in range(2):
                env = _merge(env, self.run_block(s.body, dict(env)))
            env = self.run_block(s.orelse, env)
        elif isinstance(s, ast.With):
            for item in s.items:
                t = self.expr(item.context_expr, env)
                if item.optional_vars is not None:
                    self.bind(item.optional_vars, t, env)
            env = self.run_block(s.body, env)
        elif isinstance(s, ast.Try):
            env = self.run_block(s.body, env)
            base = dict(env)
            for h in s.handlers:
                env = _merge(env, self.run_block(h.body, dict(base)))
            env = self.run_block(s.orelse, env)
            env = self.run_block(s.finalbody, env)
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._enter_function(s, env)
            env[s.name] = self._def_tag(s)
        elif isinstance(s, ast.ClassDef):
            class_dotted = f"{self.mod.module_name}.{s.name}"
            self_tag = ("inst", class_dotted) \
                if class_dotted in self.index.classes else None
            for sub in s.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    self._enter_function(sub, dict(env),
                                         self_tag=self_tag)
            env[s.name] = None
        elif isinstance(s, (ast.Raise, ast.Assert, ast.Delete)):
            for child in ast.iter_child_nodes(s):
                if isinstance(child, ast.expr):
                    self.expr(child, env)
        # Import/Global/Pass/Break/Continue: nothing to track
        return env

    def _def_tag(self, fdef) -> Optional[str]:
        from photon_ml_tpu.analysis.package import jit_wrapping_call
        for dec in fdef.decorator_list:
            d = self.mod.resolve(dec)
            if d in JAXFN_MAKERS or jit_wrapping_call(self.mod, dec) \
                    is not None:
                return JAXFN
        dotted = f"{self.mod.module_name}.{fdef.name}"
        return JAXFN if dotted in self.index.jax_fns else None

    def _enter_function(self, fdef, closure_env: dict,
                        self_tag=None) -> None:
        env = dict(closure_env)
        a = fdef.args
        params = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            params.append(a.vararg.arg)
        if a.kwarg:
            params.append(a.kwarg.arg)
        for p in params:
            env.pop(p, None)
        pos = a.posonlyargs + a.args
        static = any(isinstance(d, ast.Name)
                     and d.id in ("staticmethod", "classmethod")
                     for d in fdef.decorator_list)
        if self_tag is not None and pos and not static:
            env[pos[0].arg] = self_tag
        for p, tag in self.jit_param_tags.get(id(fdef), {}).items():
            env[p] = tag
        for d in fdef.args.defaults + fdef.args.kw_defaults:
            if d is not None:
                self.expr(d, closure_env)
        self._ret_stack.append([])
        self.run_block(fdef.body, env)
        self.fn_returns[id(fdef)] = self._ret_stack.pop()

    def bind(self, target, tag, env: dict) -> None:
        if isinstance(target, ast.Name):
            if tag is None:
                env.pop(target.id, None)
            else:
                env[target.id] = tag
        elif isinstance(target, (ast.Tuple, ast.List)):
            elts = _elt_tags(tag)
            if elts is not None and len(elts) == len(target.elts) \
                    and not any(isinstance(e, ast.Starred)
                                for e in target.elts):
                for elt, t in zip(target.elts, elts):
                    self.bind(elt, t, env)
            else:
                # unpacking a jax pytree/array yields jax elements
                for elt in target.elts:
                    self.bind(elt, JAX if is_jax(tag) else None, env)
        elif isinstance(target, ast.Starred):
            self.bind(target.value, tag, env)
        # attribute/subscript stores: no tracking

    # -- expressions -------------------------------------------------------

    def expr(self, e: Optional[ast.expr], env: dict) -> Optional[str]:
        if e is None:
            return None
        tag = self._expr_inner(e, env)
        self.tags[id(e)] = tag
        return tag

    def _expr_inner(self, e: ast.expr, env: dict) -> Optional[str]:
        if isinstance(e, ast.Name):
            return env.get(e.id)
        if isinstance(e, ast.Call):
            return self._call(e, env)
        if isinstance(e, ast.Attribute):
            base = self.expr(e.value, env)
            if is_jax(base) and e.attr in JAX_ATTRS:
                return JAX
            c = inst_class(base)
            if c is not None:
                ac = self.index.attr_class(c, e.attr)
                if ac is not None:
                    return ("inst", ac)
                hit = self.index.resolve_method(c, e.attr)
                if hit is not None:
                    info, fdef = hit
                    is_prop = any(
                        isinstance(d, ast.Name) and d.id == "property"
                        for d in fdef.decorator_list)
                    if is_prop and f"{info.dotted}.{e.attr}" in \
                            self.index.jax_methods:
                        return JAX
            return None
        if isinstance(e, ast.BinOp):
            tags = (self.expr(e.left, env), self.expr(e.right, env))
            return JAX if any(is_jax(t) for t in tags) else None
        if isinstance(e, ast.UnaryOp):
            return self.expr(e.operand, env)
        if isinstance(e, ast.Compare):
            tags = [self.expr(e.left, env)]
            tags.extend(self.expr(c, env) for c in e.comparators)
            if all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                   for op in e.ops):
                return None  # identity/membership checks are host bools
            return JAX if any(is_jax(t) for t in tags) else None
        if isinstance(e, ast.BoolOp):
            tags = [self.expr(v, env) for v in e.values]
            return JAX if any(is_jax(t) for t in tags) else None
        if isinstance(e, ast.IfExp):
            self.expr(e.test, env)
            tags = (self.expr(e.body, env), self.expr(e.orelse, env))
            return JAX if any(is_jax(t) for t in tags) else None
        if isinstance(e, ast.Subscript):
            t = self.expr(e.value, env)
            self.expr(e.slice, env)
            elts = _elt_tags(t)
            if elts is not None and isinstance(e.slice, ast.Constant) \
                    and isinstance(e.slice.value, int) \
                    and -len(elts) <= e.slice.value < len(elts):
                return elts[e.slice.value]
            return JAX if is_jax(t) else None
        if isinstance(e, (ast.Tuple, ast.List)) and not any(
                isinstance(v, ast.Starred) for v in e.elts):
            tags = tuple(self.expr(v, env) for v in e.elts)
            return ("tuple", tags) if any(t is not None for t in tags) \
                else None
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            tags = [self.expr(v, env) for v in e.elts]
            return JAX if any(is_jax(t) for t in tags) else None
        if isinstance(e, ast.Dict):
            tags = set()
            for k in e.keys:
                if k is not None:
                    self.expr(k, env)
            tags.update(self.expr(v, env) for v in e.values)
            return JAX if any(is_jax(t) for t in tags) else None
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                          ast.DictComp)):
            return self._comprehension(e, env)
        if isinstance(e, ast.Starred):
            return self.expr(e.value, env)
        if isinstance(e, ast.JoinedStr):
            for v in e.values:
                if isinstance(v, ast.FormattedValue):
                    self.expr(v.value, env)
            return None
        if isinstance(e, ast.FormattedValue):
            self.expr(e.value, env)
            return None
        if isinstance(e, ast.Lambda):
            inner = dict(env)
            a = e.args
            for p in a.posonlyargs + a.args + a.kwonlyargs:
                inner.pop(p.arg, None)
            self.expr(e.body, inner)
            return None
        if isinstance(e, ast.NamedExpr):
            t = self.expr(e.value, env)
            self.bind(e.target, t, env)
            return t
        if isinstance(e, (ast.Await, ast.YieldFrom)):
            return self.expr(e.value, env)
        if isinstance(e, ast.Yield):
            if e.value is not None:
                self.expr(e.value, env)
            return None
        return None  # Constant, Slice handled via Subscript, etc.

    def _comprehension(self, e, env: dict) -> Optional[str]:
        inner = dict(env)
        elt_jax = False
        for gen in e.generators:
            it = self.expr(gen.iter, inner)
            self.bind(gen.target, JAX if is_jax(it) else None, inner)
            for cond in gen.ifs:
                self.expr(cond, inner)
        if isinstance(e, ast.DictComp):
            self.expr(e.key, inner)
            elt_jax = is_jax(self.expr(e.value, inner))
        else:
            elt_jax = is_jax(self.expr(e.elt, inner))
        return JAX if elt_jax else None

    def _call(self, e: ast.Call, env: dict) -> Optional[str]:
        func_tag = self.expr(e.func, env)
        arg_tags = [self.expr(a, env) for a in e.args]
        for kw in e.keywords:
            self.expr(kw.value, env)
        d = self.mod.resolve(e.func)
        if d is not None:
            if d in HOST_PRODUCERS:
                return HOST
            if d in JAX_VALUE_EXACT or any(
                    d.startswith(p) for p in JAX_VALUE_PREFIXES):
                return JAX
            if d in JAXFN_MAKERS:
                return JAXFN
            if d in self.index.jax_fns:
                return JAX
            if d in self.index.classes:
                return ("inst", d)
            if d == "dataclasses.replace" and e.args:
                # replace() preserves the instance's (or pytree's) kind
                return arg_tags[0]
            if d == "functools.partial" and e.args:
                inner = self.mod.resolve(e.args[0])
                if inner in JIT_WRAP_TARGETS:
                    return JITWRAP
                if inner in JAXFN_MAKERS:
                    return JITWRAP
                if inner is not None and (
                        inner in self.index.jax_fns or any(
                            inner.startswith(p)
                            for p in JAX_VALUE_PREFIXES)):
                    return JAXFN
                if arg_tags and arg_tags[0] in (JAXFN,):
                    return JAXFN
                return None
            if d.startswith("numpy."):
                return HOST
            if d in ("float", "int", "bool", "str", "len"):
                return HOST
            if d in ("tuple", "list", "dict", "set", "sorted", "zip"):
                return JAX if any(is_jax(t) for t in arg_tags) else None
        # method call on a jax value: x.sum() is jax, x.item() is host
        if isinstance(e.func, ast.Attribute):
            base = self.tags.get(id(e.func.value))
            if is_jax(base):
                return HOST if e.func.attr in HOST_METHODS else JAX
            # method call on a package-class instance: dispatch through
            # the class index (cross-module receiver-type inference)
            c = inst_class(base)
            if c is not None:
                hit = self.index.resolve_method(c, e.func.attr)
                if hit is not None:
                    info, _fdef = hit
                    if f"{info.dotted}.{e.func.attr}" in \
                            self.index.jax_methods:
                        return JAX
        if func_tag == JAXFN:
            return JAX
        if func_tag == JITWRAP:
            return JAXFN
        return None


JIT_WRAP_TARGETS = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}


_HOST_ANNOTATIONS = {"float", "int", "bool", "str"}


def _host_annotated(fdef) -> bool:
    ret = fdef.returns
    return isinstance(ret, ast.Name) and ret.id in _HOST_ANNOTATIONS


def _merge(a: dict, b: dict) -> dict:
    """May-union of two branch environments: JAX dominates, a name bound
    in either branch stays bound."""
    out = dict(a)
    for k, v in b.items():
        cur = out.get(k)
        if cur == v or cur is None:
            out[k] = v
        elif is_jax(v) or is_jax(cur):
            out[k] = JAX
    return out


def infer_jax_functions(index: PackageIndex, max_rounds: int = 4) -> None:
    """Fixpoint: a top-level package function whose (any) return value
    tags JAX is itself jax-returning — so ``float(metrics.peak_f1(...))``
    is visible as a sync even though ``peak_f1`` lives in another
    module. Methods get the same treatment into ``index.jax_methods``
    (keyed ``<defining class dotted>.<method>``), which is what lets
    ``float(coord.score(...))`` fire W1xx through a receiver whose class
    lives in a different module. Converges in a round or two on this
    package; bounded for safety.

    A ``-> float/int/bool/str`` return annotation is trusted as a host
    scalar: such a function is a deliberate device→host accessor (the
    sync lives — and is reviewed — inside it), so its *callers* are not
    re-flagged for consuming the already-host result."""
    from photon_ml_tpu.analysis.package import jit_wrapping_call

    # jit/vmap/grad-decorated methods are jax-returning by construction
    for info in index.classes.values():
        for name, fdef in info.methods.items():
            for dec in fdef.decorator_list:
                d = info.mod.resolve(dec)
                if d in JAXFN_MAKERS or \
                        jit_wrapping_call(info.mod, dec) is not None:
                    index.jax_methods.add(f"{info.dotted}.{name}")
    for _ in range(max_rounds):
        grew = False
        for mod in index.modules:
            flow = analyze_module(mod, index)
            for name, node in mod.toplevel_defs.items():
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    dotted = f"{mod.module_name}.{name}"
                    if dotted in index.jax_fns or \
                            _host_annotated(node):
                        continue
                    if any(is_jax(t)
                           for t in flow.fn_returns.get(id(node), [])):
                        index.jax_fns.add(dotted)
                        grew = True
                elif isinstance(node, ast.ClassDef):
                    info = index.classes.get(
                        f"{mod.module_name}.{name}")
                    if info is None:
                        continue
                    for mname, fdef in info.methods.items():
                        key = f"{info.dotted}.{mname}"
                        if key in index.jax_methods or \
                                _host_annotated(fdef):
                            continue
                        if any(is_jax(t) for t in
                               flow.fn_returns.get(id(fdef), [])):
                            index.jax_methods.add(key)
                            grew = True
        if not grew:
            return
