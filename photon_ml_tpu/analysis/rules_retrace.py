"""W7xx — retrace risk: data-dependent shapes entering jit.

XLA compiles one program per distinct argument *shape*: an array built
as ``jnp.zeros(len(rows))`` recompiles every time the batch size
wobbles, which is exactly the per-dispatch stall ``obs/compile.py``'s
``xla.retrace`` spans exist to catch at runtime. These rules catch it
before the job runs:

- **W701** an argument of a call to a jitted entry point is constructed
  by an array maker (``jnp.zeros``/``ones``/``full``/``empty``/
  ``arange``/``reshape``) whose shape expression derives from a
  data-dependent Python value — ``len(...)``, ``.shape[...]``,
  ``.size`` — that never passed through a padding/bucketing helper
  (anything named ``pad*``/``*bucket*``/``round_up*``/``*pow2*``, e.g.
  ``pad_rows_to_multiple``). Padded values are shape-stable by
  construction and stay clean.
- **W702** (only with ``--trace-evidence <dir>``) a runtime
  ``xla.retrace`` record from ``obs/compile.py`` names a dispatch site
  that static analysis found nothing wrong with — the run retraced
  there anyway, so the risk is proven, not hypothesized. The finding
  lands on the ``obs_compile.call("<site>", ...)`` source line and
  carries the argument and shape transition from the trace. Sites that
  already have a static W701 in the same function are not re-reported:
  the evidence confirms the existing finding instead of duplicating it.

Both rules treat unknown as clean: a shape that cannot be traced back
to a data-dependent source is not flagged.
"""

from __future__ import annotations

import ast
import json
from pathlib import Path
from typing import Optional

from photon_ml_tpu.analysis.core import Finding
from photon_ml_tpu.analysis.dataflow import Dataflow
from photon_ml_tpu.analysis.package import ModuleInfo, PackageIndex

_ARRAY_MAKERS = {
    "jax.numpy.zeros", "jax.numpy.ones", "jax.numpy.full",
    "jax.numpy.empty", "jax.numpy.arange", "jax.numpy.broadcast_to",
    "jax.numpy.reshape",
}
# A value that went through one of these is considered shape-stabilized.
_PADDING_MARKERS = ("pad", "bucket", "pow2", "round_up")
_DYN_SOURCES = {"len"}
_DYN_ATTRS = {"shape", "size", "nbytes"}


def _call_name(mod: ModuleInfo, call: ast.Call) -> Optional[str]:
    d = mod.resolve(call.func)
    if d is not None:
        return d
    if isinstance(call.func, ast.Name):
        return call.func.id
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    return None


def _is_padding_call(name: Optional[str]) -> bool:
    if name is None:
        return False
    last = name.split(".")[-1].lower()
    return any(m in last for m in _PADDING_MARKERS)


class _DynShapes:
    """Per-function map of names holding data-dependent Python sizes.

    Two passes over the body in statement order (so loop-carried
    propagation settles); a name assigned from a padding/bucketing call
    is *cleared* — that is the sanctioned way to stabilize a shape.
    """

    def __init__(self, mod: ModuleInfo, owner, scope_of, scope):
        self.mod = mod
        self.dyn: dict[str, str] = {}  # name -> provenance note
        for _ in range(2):
            for node in ast.walk(owner):
                if scope_of.get(id(node)) is not scope:
                    continue  # nested defs track their own sizes
                if isinstance(node, ast.Assign) and \
                        len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name):
                    self._bind(node.targets[0].id, node.value)
                elif isinstance(node, ast.AnnAssign) and \
                        node.value is not None and \
                        isinstance(node.target, ast.Name):
                    self._bind(node.target.id, node.value)

    def _bind(self, name: str, value: ast.expr) -> None:
        why = self.provenance(value)
        if why is not None:
            self.dyn[name] = why
        elif isinstance(value, ast.Call) and \
                _is_padding_call(_call_name(self.mod, value)):
            self.dyn.pop(name, None)

    def provenance(self, e: ast.expr) -> Optional[str]:
        """Why ``e`` is a data-dependent size, or None when it is not."""
        if isinstance(e, ast.Name):
            return self.dyn.get(e.id)
        if isinstance(e, ast.Call):
            name = _call_name(self.mod, e)
            if _is_padding_call(name):
                return None
            if name in _DYN_SOURCES:
                return f"{name}(...)"
            if name in ("int", "max", "min", "abs", "sum"):
                for arg in e.args:
                    why = self.provenance(arg)
                    if why is not None:
                        return why
            return None
        if isinstance(e, ast.Attribute) and e.attr in _DYN_ATTRS:
            return f".{e.attr}"
        if isinstance(e, ast.Subscript):
            return self.provenance(e.value)
        if isinstance(e, ast.BinOp):
            return self.provenance(e.left) or self.provenance(e.right)
        if isinstance(e, ast.UnaryOp):
            return self.provenance(e.operand)
        if isinstance(e, (ast.Tuple, ast.List)):
            for elt in e.elts:
                why = self.provenance(elt)
                if why is not None:
                    return why
        return None


def _jitted_callables(index: PackageIndex) -> set[str]:
    """Dotted names whose *call* triggers a trace."""
    out: set[str] = set()
    for b in index.jit_bindings:
        out.add(b.impl)
        if b.bound_name:
            out.add(f"{b.mod.module_name}.{b.bound_name}")
    return out


def _dyn_shape_in_arg(dyn: _DynShapes, mod: ModuleInfo,
                      arg: ast.expr) -> Optional[tuple[str, str]]:
    """(maker, provenance) when ``arg`` contains an array-maker call
    with a data-dependent shape expression."""
    for node in ast.walk(arg):
        if not isinstance(node, ast.Call):
            continue
        d = mod.resolve(node.func)
        if d not in _ARRAY_MAKERS:
            continue
        shape_nodes = list(node.args[:1]) + [
            kw.value for kw in node.keywords if kw.arg == "shape"]
        if d == "jax.numpy.reshape":
            shape_nodes = list(node.args[1:2])
        for sn in shape_nodes:
            why = dyn.provenance(sn)
            if why is not None:
                return d.split(".")[-1], why
    return None


def _check_w701(modules: list[ModuleInfo], index: PackageIndex
                ) -> list[Finding]:
    from photon_ml_tpu.analysis.rules_sync import build_scope_map

    jitted = _jitted_callables(index)
    findings: list[Finding] = []
    for mod in modules:
        scope_of = build_scope_map(mod.tree)
        fdefs = [n for n in ast.walk(mod.tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for fdef in [None] + fdefs:
            body_owner = fdef if fdef is not None else mod.tree
            dyn = _DynShapes(mod, body_owner, scope_of, fdef)
            if not dyn.dyn:
                continue
            for call in ast.walk(body_owner):
                if not isinstance(call, ast.Call):
                    continue
                if scope_of.get(id(call)) is not fdef:
                    continue
                d = mod.resolve(call.func)
                if d not in jitted:
                    continue
                for i, arg in enumerate(call.args):
                    hit = _dyn_shape_in_arg(dyn, mod, arg)
                    if hit is None:
                        continue
                    maker, why = hit
                    findings.append(Finding(
                        "W701", mod.relpath, call.lineno,
                        call.col_offset,
                        f"argument {i} of jitted {d.split('.')[-1]}() "
                        f"is built with jnp.{maker}() whose shape "
                        f"comes from {why} — every distinct value "
                        f"recompiles; pad or bucket it (e.g. "
                        f"pad_rows_to_multiple) before the jit "
                        f"boundary"))
    return findings


# -- trace evidence (W702) -------------------------------------------------


def load_retrace_records(trace_dir) -> list[dict]:
    """``xla.retrace`` span records from every ``*.jsonl`` in a trace
    directory (the format ``obs/trace.py`` streams). Unparseable lines
    are skipped — traces are telemetry, not inputs we trust."""
    records: list[dict] = []
    d = Path(trace_dir)
    if not d.is_dir():
        return records
    for f in sorted(d.glob("*.jsonl")):
        try:
            text = f.read_text()
        except OSError:
            continue
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and rec.get("name") == "xla.retrace":
                records.append(rec)
    return records


def _dispatch_sites(modules: list[ModuleInfo]
                    ) -> dict[str, tuple[ModuleInfo, ast.Call]]:
    """site name -> the ``obs_compile.call("<site>", ...)`` location."""
    out: dict[str, tuple[ModuleInfo, ast.Call]] = {}
    for mod in modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            d = mod.resolve(node.func)
            if d is None or not d.endswith(".compile.call"):
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and \
                    isinstance(first.value, str):
                out.setdefault(first.value, (mod, node))
    return out


def _check_w702(modules: list[ModuleInfo], trace_dir,
                w701: list[Finding]) -> list[Finding]:
    records = load_retrace_records(trace_dir)
    if not records:
        return []
    sites = _dispatch_sites(modules)
    static_files = {f.path for f in w701}
    findings: list[Finding] = []
    seen: set[tuple[str, str]] = set()
    for rec in records:
        labels = rec.get("labels") or {}
        site = labels.get("site")
        if not isinstance(site, str) or site not in sites:
            continue
        arg = str(labels.get("arg", "?"))
        if (site, arg) in seen:
            continue
        seen.add((site, arg))
        mod, call = sites[site]
        if mod.relpath in static_files:
            continue  # the static W701 already owns this file's story
        field = labels.get("field", "shape")
        old, new = labels.get("old", "?"), labels.get("new", "?")
        findings.append(Finding(
            "W702", mod.relpath, call.lineno, call.col_offset,
            f"runtime retrace evidence at site {site!r}: argument "
            f"{arg} changed {field} {old} → {new} between dispatches "
            f"and static analysis saw nothing — pad/bucket the "
            f"argument or mark it static at this call"))
    return findings


def check(modules: list[ModuleInfo], index: PackageIndex,
          flows: dict[str, Dataflow], ctx) -> list[Finding]:
    findings = _check_w701(modules, index)
    trace_dir = getattr(ctx, "trace_dir", None)
    if trace_dir is not None:
        findings.extend(_check_w702(modules, trace_dir, findings))
    return findings
