"""W6xx — collective safety on the sharded mesh.

PR 12 made the training loop genuinely distributed: `shard_map` entity
shards, `psum` score exchanges, replica-sharded weight updates. A
mismatched axis name or a collective under replica-divergent control
flow passes every single-device CPU test and then deadlocks (or worse,
silently mis-reduces) on a real mesh. These rules make the axis/spec
discipline mechanical:

- **W601** a collective (``lax.psum``/``pmean``/``all_gather``/
  ``psum_scatter``/``axis_index``/... — plus the package's quantized
  wrappers ``qpsum``/``qall_gather``, which forward the axis verbatim)
  whose *literal* axis name matches no axis the program ever defines. The axis universe is built from
  defining sites only — ``Mesh(..., axis_names)`` constructions,
  ``jax.pmap(axis_name=...)``, and the package's ``*_AXIS`` string
  constants — never from collectives themselves (a typo must not define
  its own axis). Axis arguments that do not resolve to a literal (e.g.
  an ``axis_name`` function parameter, as in ``optimize/``) are skipped:
  unknown is clean.
- **W602** a collective lexically under an ``if``/``while`` whose
  condition is a traced (per-replica) value or queries
  ``jax.process_index``/``process_count``: replicas can disagree about
  reaching the collective, which deadlocks the mesh. This is the
  ``accept``-flag pattern PR 12 had to get right by hand.
- **W603** ``shard_map(f, ..., in_specs=..., out_specs=...)`` whose
  literal spec-tuple arity disagrees with ``f``'s positional signature
  (in_specs) or with ``f``'s literal tuple returns (out_specs). Only
  fires when ``f`` resolves to exactly one statically-known def — a
  name that is also rebound by assignment in scope is skipped.
- **W604** ``PartitionSpec`` naming an axis no mesh defines (the
  sharding-side twin of W601).
"""

from __future__ import annotations

import ast
from typing import Optional

from photon_ml_tpu.analysis.core import Finding
from photon_ml_tpu.analysis.dataflow import Dataflow, is_jax
from photon_ml_tpu.analysis.package import (
    ModuleInfo, PackageIndex, literal_in,
)

# collective -> index of its positional axis-name argument
_COLLECTIVES = {
    "jax.lax.psum": 1, "jax.lax.pmean": 1, "jax.lax.pmax": 1,
    "jax.lax.pmin": 1, "jax.lax.all_gather": 1,
    "jax.lax.psum_scatter": 1, "jax.lax.all_to_all": 1,
    "jax.lax.ppermute": 1, "jax.lax.axis_index": 0,
    "jax.lax.axis_size": 0, "jax.lax.pshuffle": 1,
    # the package's quantized wrappers forward their axis name to the
    # lax collectives verbatim — same axis discipline, same findings
    # (call sites replacing lax.psum with qpsum must not lose W601/W602)
    "photon_ml_tpu.parallel.quantized_collectives.qpsum": 1,
    "photon_ml_tpu.parallel.quantized_collectives.qall_gather": 1,
}
_AXIS_KWARGS = ("axis_name", "axis_index_groups_axis")

_SHARD_MAP_EXACT = {
    "jax.shard_map", "jax.experimental.shard_map.shard_map",
    "jax.experimental.shard_map",
}
_PSPEC_EXACT = {"jax.sharding.PartitionSpec", "PartitionSpec"}
_PROCESS_QUERIES = {"jax.process_index", "jax.process_count",
                    "jax.host_id", "jax.host_count"}


def _short(dotted: str) -> str:
    return dotted.split(".")[-1]


def _axes_label(axes: set[str]) -> str:
    return ", ".join(repr(a) for a in sorted(axes)) if axes \
        else "none defined anywhere in the program"


def _axis_node(call: ast.Call, pos: int) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg in _AXIS_KWARGS:
            return kw.value
    if pos < len(call.args):
        return call.args[pos]
    return None


def _is_shard_map(mod: ModuleInfo, call: ast.Call) -> bool:
    d = mod.resolve(call.func)
    if d is not None:
        if d in _SHARD_MAP_EXACT:
            return True
        # wrapper convention: the version-compat `_shard_map` helpers.
        # Exact last-component match only — `run_glm_shard_map` is a
        # *user* of shard_map, not the primitive.
        if _short(d) in ("shard_map", "_shard_map"):
            return True
        return False
    name = call.func.id if isinstance(call.func, ast.Name) else (
        call.func.attr if isinstance(call.func, ast.Attribute) else None)
    return name in ("shard_map", "_shard_map")


class _BranchMap(ast.NodeVisitor):
    """id(node) -> enclosing If/While chain, reset at function borders."""

    def __init__(self):
        self.branches: dict[int, tuple] = {}
        self._stack: list = []

    def visit(self, node):
        self.branches[id(node)] = tuple(self._stack)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            saved, self._stack = self._stack, []
            super().generic_visit(node)
            self._stack = saved
            return
        if isinstance(node, (ast.If, ast.While)):
            # the test itself is *outside* the controlled region
            self.branches[id(node.test)] = tuple(self._stack)
            for child in ast.walk(node.test):
                self.branches[id(child)] = tuple(self._stack)
            self._stack.append(node)
            for stmt in node.body + node.orelse:
                self.visit(stmt)
            self._stack.pop()
            return
        super().generic_visit(node)

    def generic_visit(self, node):
        self.visit(node)


def _divergent_test(mod: ModuleInfo, flow: Dataflow,
                    test: ast.expr) -> Optional[str]:
    """Why a branch condition can differ across replicas, or None."""
    if is_jax(flow.tag(test)):
        return "a traced per-replica value"
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            d = mod.resolve(node.func)
            if d in _PROCESS_QUERIES:
                return f"{_short(d)}() (differs per host)"
    return None


def _find_callee(mod: ModuleInfo, scope_of, call: ast.Call,
                 fn_node: ast.expr) -> Optional[ast.AST]:
    """The single FunctionDef/Lambda the shard_map target resolves to,
    or None when unknown or ambiguous (e.g. the name is also rebound by
    an Assign somewhere in scope — distributed.py's conditional
    ``local_fit``)."""
    if isinstance(fn_node, ast.Lambda):
        return fn_node
    if not isinstance(fn_node, ast.Name):
        return None
    name = fn_node.id
    defs: list[ast.AST] = []
    assigned = False
    scope = scope_of.get(id(call))
    while scope is not None:
        for node in ast.walk(scope):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == name:
                defs.append(node)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name) and n.id == name:
                            assigned = True
        scope = scope_of.get(id(scope))
    top = mod.toplevel_defs.get(name)
    if isinstance(top, (ast.FunctionDef, ast.AsyncFunctionDef)):
        defs.append(top)
    if name in mod.constants:
        assigned = True
    if assigned or len(set(id(d) for d in defs)) != 1:
        return None
    return defs[0]


def _positional_arity(fdef) -> int:
    a = fdef.args
    return len(a.posonlyargs) + len(a.args)


def _literal_tuple_arity(node: ast.expr) -> Optional[int]:
    if isinstance(node, (ast.Tuple, ast.List)):
        return len(node.elts)
    return None


def _return_arities(fdef) -> set[Optional[int]]:
    """Literal tuple length of each return in ``fdef``'s own scope
    (None = a non-tuple / unknown-arity return)."""
    from photon_ml_tpu.analysis.rules_sync import build_scope_map
    scope_of = build_scope_map(ast.Module(body=[fdef], type_ignores=[]))
    out: set[Optional[int]] = set()
    for node in ast.walk(fdef):
        if isinstance(node, ast.Return) and scope_of.get(id(node)) is fdef:
            out.add(_literal_tuple_arity(node.value)
                    if node.value is not None else None)
    return out


def _spec_kwarg(call: ast.Call, kwarg: str,
                pos: int) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == kwarg:
            return kw.value
    if pos < len(call.args):
        return call.args[pos]
    return None


def check(modules: list[ModuleInfo], index: PackageIndex,
          flows: dict[str, Dataflow], ctx) -> list[Finding]:
    from photon_ml_tpu.analysis.rules_sync import build_scope_map

    findings: list[Finding] = []
    axes = index.mesh_axes
    for mod in modules:
        flow = flows[mod.relpath]
        scope_of = build_scope_map(mod.tree)
        branch_map = _BranchMap()
        branch_map.visit(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            d = mod.resolve(node.func)
            if d in _COLLECTIVES:
                short = _short(d)
                if d.startswith("jax.lax."):
                    short = f"lax.{short}"
                axis_node = _axis_node(node, _COLLECTIVES[d])
                value = literal_in(mod, index, axis_node) \
                    if axis_node is not None else None
                names = (value,) if isinstance(value, str) else (
                    value if isinstance(value, tuple) else ())
                for axis in names:
                    if isinstance(axis, str) and axis not in axes:
                        findings.append(Finding(
                            "W601", mod.relpath, node.lineno,
                            node.col_offset,
                            f"{short}() over unknown axis "
                            f"{axis!r} — no Mesh/pmap defines it; "
                            f"known axes: {_axes_label(axes)}"))
                # W602: collective under replica-divergent control flow
                for branch in branch_map.branches.get(id(node), ()):
                    why = _divergent_test(mod, flow, branch.test)
                    if why is not None:
                        kind = "if" if isinstance(branch, ast.If) \
                            else "while"
                        findings.append(Finding(
                            "W602", mod.relpath, node.lineno,
                            node.col_offset,
                            f"{short}() under a Python `{kind}` "
                            f"(line {branch.lineno}) branching on "
                            f"{why} — replicas that disagree about "
                            f"entering the branch deadlock the "
                            f"collective; hoist it out or use "
                            f"lax.cond with a replicated predicate"))
                        break  # one W602 per collective is enough
            elif _is_shard_map(mod, node) and node.args:
                findings.extend(_check_shard_map(
                    mod, index, scope_of, node, axes))
            elif d in _PSPEC_EXACT:
                for arg in node.args:
                    value = literal_in(mod, index, arg)
                    names = (value,) if isinstance(value, str) else (
                        value if isinstance(value, tuple) else ())
                    for axis in names:
                        if isinstance(axis, str) and axis not in axes:
                            findings.append(Finding(
                                "W604", mod.relpath, node.lineno,
                                node.col_offset,
                                f"PartitionSpec axis {axis!r} is not "
                                f"defined by any mesh — known axes: "
                                f"{_axes_label(axes)}"))
    return findings


def _check_shard_map(mod: ModuleInfo, index: PackageIndex, scope_of,
                     call: ast.Call, axes: set[str]) -> list[Finding]:
    findings: list[Finding] = []
    callee = _find_callee(mod, scope_of, call, call.args[0])
    in_specs = _spec_kwarg(call, "in_specs", 2)
    out_specs = _spec_kwarg(call, "out_specs", 3)
    if callee is not None and in_specs is not None:
        want = _literal_tuple_arity(in_specs)
        have = _positional_arity(callee)
        if want is not None and want != have:
            name = getattr(callee, "name", "<lambda>")
            findings.append(Finding(
                "W603", mod.relpath, call.lineno, call.col_offset,
                f"shard_map in_specs has {want} spec(s) but "
                f"{name}() takes {have} positional argument(s) — "
                f"each positional argument needs exactly one spec"))
    if callee is not None and out_specs is not None:
        want = _literal_tuple_arity(out_specs)
        if want is not None:
            arities = _return_arities(callee)
            if arities and None not in arities and \
                    all(a != want for a in arities):
                name = getattr(callee, "name", "<lambda>")
                got = sorted(a for a in arities if a is not None)
                findings.append(Finding(
                    "W603", mod.relpath, call.lineno, call.col_offset,
                    f"shard_map out_specs has {want} spec(s) but "
                    f"{name}() returns tuple(s) of length "
                    f"{'/'.join(map(str, got))} — out_specs must "
                    f"mirror the return structure"))
    return findings
