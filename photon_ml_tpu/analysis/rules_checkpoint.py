"""W5xx — checkpoint-schema drift.

A snapshot is a dict contract between the save sites
(``game/coordinate_descent.save_snapshot``, the multi-host
``save_snapshot``) and every restore/resume path that indexes into what
``CheckpointManager.restore`` returns. The dict is schemaless by design
(checkpoint.py stays framework-free), so nothing at runtime catches a
writer renaming a field until a resume quietly ``.get(...)``-defaults it
away — the silent flavor of the bug class PR 2's bit-exact drill exists
for.

- **W501** a key read on a restore path that NO save site writes;
- **W502** a key written by a save site that NO restore path reads.

Writers: dict literals passed (directly or through one local name) to
``<ckpt-ish>.save(step, state)`` calls — receivers whose name matches
``ckpt``/``checkpoint`` — or to a save WRAPPER: a plain call whose
function name mentions both ``ckpt``/``checkpoint`` and ``save`` and
that mirrors the save shape plus the manager up front,
``(manager, step, snapshot, ...)`` — e.g.
``_checkpoint_save_contained(mgr, step, {...})`` — so hoisting the
save into a containment helper keeps the schema visible while a
2-arg name-alike helper doesn't pollute the key union.
Readers: string subscripts and ``.get`` calls
on snapshot variables — names bound from ``<ckpt-ish>.restore()`` or
``loads_state(...)``, plus the conventional names ``snap`` /
``resume_snapshot`` / ``snapshot``. Both directions compare against the
union across the package, so the single-process and multi-host schemas
coexist without cross-flagging.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from photon_ml_tpu.analysis.core import Finding
from photon_ml_tpu.analysis.dataflow import Dataflow
from photon_ml_tpu.analysis.package import ModuleInfo, PackageIndex

_CKPT_RECV_RE = re.compile(r"ckpt|checkpoint", re.IGNORECASE)
_SAVE_WRAPPER_RE = re.compile(r"(?=.*(?:ckpt|checkpoint))(?=.*save)",
                              re.IGNORECASE)
_SNAP_NAMES = {"snap", "snapshot", "resume_snapshot"}


def _receiver_name(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _dict_keys(d: ast.Dict) -> Optional[set[str]]:
    keys = set()
    for k in d.keys:
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            keys.add(k.value)
        elif k is None:
            return None  # **spread: key set unknowable, skip this writer
    return keys


def _resolve_dict_arg(fdef_or_mod_body, arg: ast.expr,
                      before_line: int) -> Optional[ast.Dict]:
    """A dict literal argument, or the nearest preceding single-target
    assignment of one to the given name."""
    if isinstance(arg, ast.Dict):
        return arg
    if not isinstance(arg, ast.Name):
        return None
    best: Optional[tuple[int, ast.Dict]] = None
    for n in ast.walk(fdef_or_mod_body):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Name) \
                and n.targets[0].id == arg.id \
                and isinstance(n.value, ast.Dict) \
                and n.lineno < before_line:
            if best is None or n.lineno > best[0]:
                best = (n.lineno, n.value)
    return best[1] if best else None


def _snapshot_vars(mod: ModuleInfo) -> set[str]:
    """Names that hold a restored snapshot somewhere in the module."""
    out = set(_SNAP_NAMES)
    for n in ast.walk(mod.tree):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Name) \
                and isinstance(n.value, ast.Call):
            call = n.value
            if isinstance(call.func, ast.Attribute) \
                    and call.func.attr == "restore":
                recv = _receiver_name(call.func.value)
                if recv and _CKPT_RECV_RE.search(recv):
                    out.add(n.targets[0].id)
            else:
                d = mod.resolve(call.func)
                if d is not None and d.endswith("loads_state"):
                    out.add(n.targets[0].id)
    return out


def check(modules: list[ModuleInfo], index: PackageIndex,
          flows: dict[str, Dataflow], ctx) -> list[Finding]:
    # ---- writers ---------------------------------------------------------
    from photon_ml_tpu.analysis.rules_sync import build_scope_map

    written: dict[str, list[tuple[ModuleInfo, ast.Call]]] = {}
    any_writer = False
    for mod in modules:
        scope_of = build_scope_map(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            state_args: list[ast.expr] = []
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "save" \
                    and len(node.args) >= 2:
                recv = _receiver_name(node.func.value)
                if recv and _CKPT_RECV_RE.search(recv):
                    state_args = [node.args[1]]
            elif isinstance(node.func, ast.Name) \
                    and _SAVE_WRAPPER_RE.search(node.func.id) \
                    and len(node.args) >= 3:
                # containment wrappers mirror the .save shape plus the
                # manager up front — (manager, step, snapshot, ...) —
                # so only args[2:] are schema candidates; a 2-arg
                # helper that happens to match the name (e.g.
                # save_checkpoint_report(mgr, {...})) is not a save site
                state_args = list(node.args[2:])
            if not state_args:
                continue
            scope = scope_of.get(id(node)) or mod.tree
            d = None
            for arg in state_args:
                d = _resolve_dict_arg(scope, arg, node.lineno)
                if d is not None:
                    break
            if d is None:
                continue
            keys = _dict_keys(d)
            if keys is None:
                continue
            any_writer = True
            for k in keys:
                written.setdefault(k, []).append((mod, node))
    # ---- readers ---------------------------------------------------------
    read: dict[str, list[tuple[ModuleInfo, ast.AST]]] = {}
    any_reader = False
    for mod in modules:
        snap_vars = _snapshot_vars(mod)
        for node in ast.walk(mod.tree):
            key = None
            if isinstance(node, ast.Subscript) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in snap_vars \
                    and isinstance(node.slice, ast.Constant) \
                    and isinstance(node.slice.value, str):
                key = node.slice.value
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "get" \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id in snap_vars \
                    and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                key = node.args[0].value
            if key is not None:
                any_reader = True
                read.setdefault(key, []).append((mod, node))
    # ---- reconcile -------------------------------------------------------
    findings: list[Finding] = []
    if any_writer:
        for key, sites in sorted(read.items()):
            if key in written:
                continue
            for mod, node in sites:
                findings.append(Finding(
                    "W501", mod.relpath, node.lineno, node.col_offset,
                    f"snapshot key '{key}' is read on a restore path "
                    f"but no checkpoint save site writes it — resume "
                    f"will silently default/KeyError"))
    if any_reader:
        for key, sites in sorted(written.items()):
            if key in read:
                continue
            for mod, node in sites:
                findings.append(Finding(
                    "W502", mod.relpath, node.lineno, node.col_offset,
                    f"snapshot key '{key}' is written at this save "
                    f"site but never read by any restore path — dead "
                    f"schema field or a renamed reader"))
    return findings
