"""W2xx — jit purity / retrace hazards.

Anything executed while tracing a ``jax.jit``/``pjit`` region runs at
*trace* time, not run time: a ``time.time()`` or ``np.random`` call
bakes one trace-time value into the compiled program (the exact class of
bug that breaks PR 2's bit-exact resume), and a Python ``if``/``while``
on a traced value either raises at runtime or — worse — silently
retraces per distinct shape/value. Scope is the static call closure:
functions directly wrapped in jit plus package-local functions reachable
from them through the call graph.

- **W201** impure call (``time.*``, ``random.*``, ``np.random.*``,
  ``logging.*``, ``print``/``open``/``input``) inside jit-traced code;
- **W202** ``if``/``while`` whose condition is a traced value. For
  directly-jitted functions, non-static parameters count as traced
  (``static_argnums``/``static_argnames`` are resolved from the jit
  call site — including one module-level constant hop); for reachable
  helpers only locally-derived jax values count, which biases toward
  precision over recall.
- **W203** host-callback ordering under checkpoint resume:
  ``io_callback`` without ``ordered=True`` inside jit-traced code may
  execute in a different order after a restore-and-replay than it did
  in the original run (the side effects PR 9's resume contract cares
  about — progress lines, telemetry appends — land out of order), and
  ``pure_callback`` wrapping a known-impure callable (``time.*``,
  ``random.*``, ...) invites jit to cache/elide the "pure" result.
"""

from __future__ import annotations

import ast

from photon_ml_tpu.analysis.core import Finding
from photon_ml_tpu.analysis.dataflow import Dataflow, is_jax
from photon_ml_tpu.analysis.package import ModuleInfo, PackageIndex

_IMPURE_PREFIXES = ("time.", "random.", "numpy.random.", "logging.")
_IMPURE_EXACT = {"print", "open", "input", "breakpoint",
                 "numpy.random"}
# escape hatch for calls that LOOK impure but are jit-legal (none known
# yet; populate before reaching for a suppression in shared helpers)
_PURE_EXCEPTIONS: set[str] = set()

_IO_CALLBACKS = {"jax.experimental.io_callback", "jax.io_callback",
                 "io_callback"}
_PURE_CALLBACKS = {"jax.pure_callback",
                   "jax.experimental.pure_callback", "pure_callback"}


def _io_callback_ordered(node: ast.Call) -> bool:
    for kw in node.keywords:
        if kw.arg == "ordered":
            return isinstance(kw.value, ast.Constant) \
                and kw.value.value is True
    return False


def _short_root(root: str) -> str:
    return root.split(".")[-1]


def check(modules: list[ModuleInfo], index: PackageIndex,
          flows: dict[str, Dataflow], ctx) -> list[Finding]:
    findings: list[Finding] = []
    reachable = index.jit_reachable()
    seen_fdefs: set[int] = set()
    for fn, root in sorted(reachable.items()):
        mod, fdef = index.functions[fn]
        if id(fdef) in seen_fdefs:
            continue
        seen_fdefs.add(id(fdef))
        flow = flows[mod.relpath]
        via = "" if fn == root else \
            f" (reachable from jitted {_short_root(root)})"
        for node in ast.walk(fdef):
            if isinstance(node, ast.Call):
                d = mod.resolve(node.func)
                if d is None and isinstance(node.func, ast.Name):
                    d = node.func.id  # true builtins resolve to None
                if d is None:
                    continue
                if d in _IO_CALLBACKS:
                    if not _io_callback_ordered(node):
                        findings.append(Finding(
                            "W203", mod.relpath, node.lineno,
                            node.col_offset,
                            f"io_callback without ordered=True inside "
                            f"jit-traced code{via} — unordered "
                            f"callbacks can replay in a different "
                            f"order after a checkpoint resume, "
                            f"breaking the resume contract for host "
                            f"side effects"))
                elif d in _PURE_CALLBACKS and node.args:
                    target = mod.resolve(node.args[0])
                    if target is None and isinstance(node.args[0],
                                                     ast.Name):
                        target = node.args[0].id
                    if target is not None and (
                            target in _IMPURE_EXACT
                            or target.startswith(_IMPURE_PREFIXES)):
                        findings.append(Finding(
                            "W203", mod.relpath, node.lineno,
                            node.col_offset,
                            f"pure_callback wrapping impure "
                            f"{target}(){via} — jit may cache, elide "
                            f"or reorder a 'pure' callback; use "
                            f"io_callback(..., ordered=True) for "
                            f"side-effecting host calls"))
                elif (d in _IMPURE_EXACT
                        or d.startswith(_IMPURE_PREFIXES)) \
                        and d not in _PURE_EXCEPTIONS:
                    findings.append(Finding(
                        "W201", mod.relpath, node.lineno,
                        node.col_offset,
                        f"impure call {d}() inside jit-traced code"
                        f"{via} — its value is frozen at trace time "
                        f"and breaks bit-exact resume"))
            elif isinstance(node, (ast.If, ast.While)):
                if is_jax(flow.tag(node.test)):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    findings.append(Finding(
                        "W202", mod.relpath, node.lineno,
                        node.col_offset,
                        f"Python `{kind}` on a traced value inside "
                        f"jit-traced code{via} — use jnp.where/"
                        f"lax.cond, or mark the argument static"))
    return findings
