"""photonlint core types: findings, suppressions, baseline, rule table.

Identity model: a finding's *baseline key* is ``(rule, path, message)``
— deliberately line-number-free, so an unrelated edit that shifts a file
does not churn the committed baseline. Multiple identical findings in
one file are matched by count (the baseline entry carries how many
occurrences are grandfathered; extras are new).

Suppression grammar (checked, not free-form)::

    # photonlint: allow-W104(telemetry counted by the caller)
    # photonlint: allow-W1xx(whole family, e.g. for a fixture file)

The rule token is an exact id (``W104``) or a family wildcard
(``W1xx``). The parenthesized reason is REQUIRED — an empty or missing
reason makes the comment malformed and surfaces as a ``W001`` finding
instead of silently suppressing. A suppression on a comment-only line
applies to the next source line; otherwise it applies to its own line.
"""

from __future__ import annotations

import dataclasses
import json
import re
from collections import Counter
from typing import Iterable

# Rule catalog: id -> one-line description (the single source of truth —
# the CLI's --list-rules and the README table are generated from here).
RULES: dict[str, str] = {
    "W001": "malformed photonlint suppression comment",
    "W002": "photonlint suppression that suppresses nothing (stale "
            "directive)",
    "W101": "float()/int()/bool() on a jax-array value forces a blocking "
            "device→host sync",
    "W102": ".item() on a jax-array value forces a blocking device→host "
            "sync",
    "W103": "np.asarray() on a jax-array value forces a blocking "
            "device→host sync",
    "W104": "jax.device_get outside an instrumented fetch site (no "
            "record_host_fetch in the enclosing function)",
    "W105": "deferred epilogue handle still unresolved at its second "
            "subsequent dispatch — pipeline depth exceeds the recovery "
            "contract",
    "W201": "impure call (time/random/np.random/I-O/logging) inside "
            "jit-traced code",
    "W202": "Python if/while branches on a traced value inside jit — "
            "retrace hazard / nondeterministic resume",
    "W203": "host callback whose effects can replay out of order on "
            "resume (unordered io_callback / impure pure_callback in "
            "jit-reachable code)",
    "W301": "buffer donated via donate_argnums is read again later in "
            "the same function",
    "W401": "fault_point() site name missing from the README "
            "PHOTON_FAULTS table",
    "W402": "README PHOTON_FAULTS table row names a fault point with no "
            "fault_point() site",
    "W403": "fault_point() called with a non-literal name (statically "
            "unanalyzable)",
    "W501": "snapshot key read on a restore path but never written by "
            "any checkpoint save site",
    "W502": "snapshot key written at a checkpoint save site but never "
            "read by any restore path",
    "W601": "collective (psum/pmean/all_gather/...) over an axis name "
            "that matches no enclosing shard_map/pmap axis or known "
            "mesh axis",
    "W602": "collective reachable under Python control flow that can "
            "diverge across replicas — cross-device deadlock risk",
    "W603": "shard_map in_specs/out_specs arity does not match the "
            "callee's signature/returns",
    "W604": "PartitionSpec names an axis that no mesh in the program "
            "defines",
    "W701": "jit-entry argument whose shape derives from a "
            "data-dependent Python value without a padding/bucketing "
            "helper — per-batch retrace risk",
    "W702": "runtime xla.retrace evidence at a jit site with no static "
            "finding (from --trace-evidence)",
    "W801": "reduction (sum/dot/matmul/psum/segment_sum/...) over a "
            "bf16/f16/runtime-selected dtype without an f32 accumulator "
            "(preferred_element_type / explicit dtype / upcast)",
    "W802": "float64 construction in jit-reachable code with no "
            "jax_enable_x64 config guard — silently truncates to f32 "
            "under the default config",
    "W803": "jax value round-tripped through np.asarray and fed back "
            "into a jitted callable — dtype/weak-type erasing, silent "
            "retrace on the promoted dtype",
    "W804": "bf16/f16 mixed with f32/f64 by implicit promotion in a "
            "loss/gradient path — the precision decision should be an "
            "explicit cast",
    "W901": "shared attribute/global written without the lock that "
            "guards it elsewhere (thread-body write visible to "
            "unlocked readers, or lock held on some writes but not "
            "all)",
    "W902": "signal handler doing more than async-signal-safe "
            "flag/Event latching",
    "W903": "thread started with no join/stop in any shutdown path — "
            "its lifetime is unbounded at exit",
    "W904": "inconsistent nested lock acquisition order across the "
            "package — deadlock shape",
    "WA00": "wire-protocol string (message kind / error name) built "
            "from a fully dynamic expression — statically unauditable",
    "WA01": "protocol kind sent by a client but handled by no server "
            "dispatch — the request can only come back as an "
            "unknown-kind error",
    "WA02": "server dispatch handles a protocol kind that no client "
            "ever sends (dead handler / renamed request)",
    "WA03": "typed serve error that can reach the wire but parses back "
            "as a generic error — name missing from typed_error()'s "
            "table",
    "WA04": "transport-classification set names an error that no code "
            "path can put on the wire (stale or aliased exception "
            "name)",
    "WA05": "reader accesses a wire-message field that no writer of "
            "that kind ever sets",
    "WB00": "telemetry name (counter/gauge/histogram/span) built from "
            "a fully dynamic expression — statically unauditable",
    "WB01": "emitted telemetry name missing from the README taxonomy "
            "tables",
    "WB02": "README taxonomy table row names a metric/span that "
            "nothing emits",
    "WB03": "consumer reads a metric/span name that nothing emits — "
            "phantom consumer / silent dashboard",
    "WB04": "label-key drift between emit sites sharing one metric "
            "name (per-label breakdowns silently fragment)",
}

FAMILIES = ("W0", "W1", "W2", "W3", "W4", "W5", "W6", "W7", "W8", "W9",
            "WA", "WB")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str  # posix path relative to the lint root
    line: int
    col: int
    message: str

    @property
    def baseline_key(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.message)

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} " \
               f"{self.message}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class LintReport:
    """Outcome of one lint run, after suppression + baseline filtering."""

    new: list[Finding]
    baselined: list[Finding]
    suppressed: list[Finding]
    stale_baseline: list[dict]  # entries whose findings no longer exist
    files_checked: int = 0
    # populated when an incremental cache is in play / --stats is asked
    cache_stats: dict | None = None
    timings: dict[str, float] | None = None

    @property
    def ok(self) -> bool:
        return not self.new

    def format_text(self) -> str:
        out = [f.format() for f in self.new]
        out.append(
            f"photonlint: {len(self.new)} new finding(s), "
            f"{len(self.baselined)} baselined, "
            f"{len(self.suppressed)} suppressed, "
            f"{self.files_checked} file(s) checked")
        if self.stale_baseline:
            out.append(
                f"photonlint: note: {len(self.stale_baseline)} stale "
                f"baseline entr(ies) no longer match any finding — "
                f"refresh with --write-baseline")
        return "\n".join(out)

    def format_json(self) -> str:
        return json.dumps({
            "version": 1,
            "ok": self.ok,
            "new": [f.to_json() for f in self.new],
            "baselined": [f.to_json() for f in self.baselined],
            "suppressed_count": len(self.suppressed),
            "stale_baseline": self.stale_baseline,
            "files_checked": self.files_checked,
        }, indent=2, sort_keys=True)


# -- suppressions ----------------------------------------------------------

# Valid:   photonlint: allow-W104(reason text)
# Family:  photonlint: allow-W1xx(reason text)
_ALLOW_RE = re.compile(
    r"photonlint:\s*allow-(W[0-9A-Z](?:\d\d|xx))\(([^)]*)\)")
# A comment is a directive only when it STARTS with the marker — prose
# that merely mentions the word is ignored.
_DIRECTIVE_RE = re.compile(r"^#\s*photonlint:")
_COMMENT_ONLY_RE = re.compile(r"^\s*#")


def rule_matches(pattern: str, rule: str) -> bool:
    """``W104`` matches exactly; ``W1xx`` matches the whole family."""
    if pattern.endswith("xx"):
        return rule.startswith(pattern[:-2])
    return rule == pattern


def _comments(lines: list[str]):
    """(line, comment text) for every real comment token — strings and
    docstrings that merely contain '#' are not comments."""
    import io
    import tokenize

    try:
        tokens = tokenize.generate_tokens(
            io.StringIO("\n".join(lines) + "\n").readline)
        return [(tok.start[0], tok.string) for tok in tokens
                if tok.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # fall back to a line scan (still anchored on '#')
        out = []
        for i, raw in enumerate(lines, start=1):
            if "#" in raw:
                out.append((i, raw[raw.index("#"):]))
        return out


def parse_suppressions(
    lines: list[str], relpath: str
) -> tuple[dict[int, list[tuple[str, str]]], list[Finding]]:
    """Scan source comments for suppression directives.

    Returns ``(by_line, malformed)`` where ``by_line`` maps an
    *effective* 1-based line number to ``(rule_pattern, reason)`` pairs
    (a comment-only line's suppressions shift down to the next line, so
    they can sit above a long statement), and ``malformed`` holds W001
    findings for directives that failed to parse or lack a reason.
    """
    by_line: dict[int, list[tuple[str, str]]] = {}
    malformed: list[Finding] = []
    for i, comment in _comments(lines):
        if not _DIRECTIVE_RE.match(comment):
            continue
        raw = lines[i - 1]
        matches = list(_ALLOW_RE.finditer(comment))
        target = i
        if _COMMENT_ONLY_RE.match(raw):
            # standalone comment: guard the next SOURCE line, skipping
            # blank lines and further comment-only lines in between
            target = i + 1
            while target <= len(lines) and (
                    not lines[target - 1].strip()
                    or _COMMENT_ONLY_RE.match(lines[target - 1])):
                target += 1
        if not matches:
            malformed.append(Finding(
                "W001", relpath, i, max(raw.find("#"), 0),
                "unrecognized photonlint directive — expected "
                "# photonlint: allow-<rule>(reason)"))
            continue
        for m in matches:
            pattern, reason = m.group(1), m.group(2).strip()
            if not reason:
                malformed.append(Finding(
                    "W001", relpath, i, max(raw.find("#"), 0),
                    f"suppression allow-{pattern} has no reason — write "
                    f"# photonlint: allow-{pattern}(why this is safe)"))
                continue
            by_line.setdefault(target, []).append((pattern, reason))
    return by_line, malformed


def apply_suppressions(
    findings: Iterable[Finding],
    by_file: dict[str, dict[int, list[tuple[str, str]]]],
) -> tuple[list[Finding], list[Finding], set[tuple[str, int, str]]]:
    """Split findings into (kept, suppressed) using per-line directives.

    Also returns the set of directives that actually fired, as
    ``(path, line, rule_pattern)`` triples — the complement feeds W002
    (stale-suppression) detection.
    """
    kept: list[Finding] = []
    suppressed: list[Finding] = []
    used: set[tuple[str, int, str]] = set()
    for f in findings:
        entries = by_file.get(f.path, {}).get(f.line, [])
        hit = False
        for p, _ in entries:
            if rule_matches(p, f.rule):
                used.add((f.path, f.line, p))
                hit = True
        if hit:
            suppressed.append(f)
        else:
            kept.append(f)
    return kept, suppressed, used


def unused_suppressions(
    by_file: dict[str, dict[int, list[tuple[str, str]]]],
    used: set[tuple[str, int, str]],
) -> list[Finding]:
    """W002 findings for directives that suppressed nothing.

    A directive is *used* when at least one finding on its target line
    matched its pattern; everything else is dead weight that would hide
    a future regression, so it surfaces as a finding of its own.
    """
    out: list[Finding] = []
    for path, by_line in sorted(by_file.items()):
        for line, entries in sorted(by_line.items()):
            for pattern, _reason in entries:
                if (path, line, pattern) not in used:
                    out.append(Finding(
                        "W002", path, line, 0,
                        f"suppression allow-{pattern} suppresses "
                        f"nothing — remove the stale directive"))
    return out


# -- baseline --------------------------------------------------------------

BASELINE_VERSION = 1


def load_baseline(path) -> list[dict]:
    """Read a baseline file; returns its entry list ([] when absent)."""
    import os

    if path is None or not os.path.exists(path):
        return []
    with open(path) as fh:
        data = json.load(fh)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path} has version {data.get('version')!r}; this "
            f"photonlint understands version {BASELINE_VERSION}")
    return list(data.get("entries", []))


def write_baseline(path, findings: Iterable[Finding]) -> int:
    """Write all ``findings`` as the new baseline; returns entry count."""
    counts = Counter(f.baseline_key for f in findings)
    entries = [
        {"rule": rule, "path": p, "message": message, "count": n}
        for (rule, p, message), n in sorted(counts.items())
    ]
    with open(path, "w") as fh:
        json.dump({
            "version": BASELINE_VERSION,
            "tool": "photonlint",
            "comment": "Grandfathered findings. Regenerate with "
                       "`python tools/photonlint.py --write-baseline`; "
                       "entries are (rule, path, message)-keyed and "
                       "line-number-free so edits that only move code "
                       "do not churn this file.",
            "entries": entries,
        }, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return len(entries)


def apply_baseline(
    findings: list[Finding], entries: list[dict]
) -> tuple[list[Finding], list[Finding], list[dict]]:
    """Split findings into (new, baselined); also report stale entries.

    Matching is by ``(rule, path, message)`` with per-key counts: a key
    budget of N grandfathers the first N occurrences (ordered by line)
    and everything beyond is new.
    """
    budget: Counter = Counter()
    for e in entries:
        budget[(e["rule"], e["path"], e["message"])] += int(
            e.get("count", 1))
    used: Counter = Counter()
    new: list[Finding] = []
    baselined: list[Finding] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col)):
        key = f.baseline_key
        if used[key] < budget[key]:
            used[key] += 1
            baselined.append(f)
        else:
            new.append(f)
    stale = [
        {"rule": r, "path": p, "message": m,
         "count": budget[(r, p, m)] - used[(r, p, m)]}
        for (r, p, m) in budget
        if used[(r, p, m)] < budget[(r, p, m)]
    ]
    return new, baselined, stale
