"""WAxx — wire-protocol drift across the serve plane.

The NDJSON scoring protocol (``serve/protocol.py``) is a set of string
contracts: request/response ``kind``s, the ``wire_error`` /
``typed_error`` error grammar, and the fleet's transport-classification
set. Nothing enforces them at runtime beyond "the test happened to
exercise that path" — PR 17's review chased exactly this bug class
(misclassified reply error names, a probe kind mismatch). These rules
make the contracts whole-program, both-directions checks:

- **WA00** a protocol string (message kind) built from a fully dynamic
  expression — statically unauditable; use a literal (an f-string with
  a literal head is tracked as a prefix) or suppress with a reason.
- **WA01** a ``kind`` sent by a client (``.request({...})`` /
  ``.dispatch(shard, {...})``) that no server dispatch compares for —
  the request can only come back ``unknown kind``.
- **WA02** a server dispatch arm for a ``kind`` no client ever sends —
  dead handler or renamed request.
- **WA03** a subclass of the typed client error base
  (``ServeRequestError``) that is raised somewhere (so ``wire_error``
  can put its name on the wire) but is neither a key of the
  ``_TYPED_ERRORS`` parse table nor referenced by ``typed_error()`` —
  the far side demotes it to a generic error.
- **WA04** a name in ``_TRANSPORT_REPLY_ERRORS`` that no code path can
  put on the wire: not producible by any server-side
  ``f"{type(e).__name__}: {e}"`` render (the f-string must START with
  the type name — that is the wire grammar) for a compatible caught
  type, and not the canonical name of anything raised. The classic
  instance: ``"IOError"`` — in Python 3 ``IOError is OSError``, so
  ``type(e).__name__`` can never render it.
- **WA05** a field read off a kind-guarded wire message that no writer
  of that kind ever sets. Writers are dict literals carrying
  ``"kind": K`` (plus same-function ``msg["field"] = ...`` follow-ups);
  a ``**spread`` makes the writer's field set OPEN and exempts the
  kind (``stats`` replies splice dynamic scorer stats in, so absence
  cannot be claimed).

Scope: the kind/field analysis runs over modules with a ``serve`` path
component or that import from one — the telemetry record plane
(``obs/``) speaks its own ``"kind"``-keyed record grammar and must not
cross-contaminate the serve universe. WA03/WA04 anchor on the
``_TYPED_ERRORS`` / ``_TRANSPORT_REPLY_ERRORS`` definitions and scan
package-wide. Everything is syntactic; resolution failures bias toward
silence (an unresolvable receiver contributes nothing, except the
deliberate WA00 signal for dynamic names at true protocol positions).
"""

from __future__ import annotations

import ast
import builtins

from photon_ml_tpu.analysis.core import Finding
from photon_ml_tpu.analysis.dataflow import Dataflow
from photon_ml_tpu.analysis.package import (
    ModuleInfo, PackageIndex, name_value,
)

_TYPED_TABLE_NAME = "_TYPED_ERRORS"
_TRANSPORT_SET_NAME = "_TRANSPORT_REPLY_ERRORS"
_TYPED_BASE = "ServeRequestError"


# -- small AST helpers -----------------------------------------------------


def _scoped_walk(root: ast.AST):
    """Walk statements without descending into nested defs/classes
    (each function scope is analyzed on its own)."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _functions(mod: ModuleInfo):
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _unwrap_recv(node: ast.AST) -> ast.AST:
    # ``(client.hello or {}).get(...)`` guards read through the BoolOp
    while isinstance(node, ast.BoolOp) and node.values:
        node = node.values[0]
    return node


def _recv_key(node: ast.AST) -> str:
    return ast.unparse(_unwrap_recv(node))


def _get_call_key(node: ast.AST):
    """``(receiver, "field")`` when ``node`` is ``<recv>.get("field")``
    (optionally with a default), else None."""
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get" and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)):
        return node.func.value, node.args[0].value
    return None


def _serve_scope(modules: list[ModuleInfo]) -> list[ModuleInfo]:
    scoped = []
    for mod in modules:
        if "serve" in mod.module_name.split("."):
            scoped.append(mod)
            continue
        if any(".serve." in t or t.startswith("serve.")
               for t in mod.imports.values()):
            scoped.append(mod)
    return scoped


# -- per-function protocol scan -------------------------------------------


class _FnScan:
    """Everything one function scope contributes to the kind universe."""

    def __init__(self, mod: ModuleInfo, index: PackageIndex,
                 fdef: ast.AST):
        self.mod = mod
        self.index = index
        self.fdef = fdef
        self.kindvars: dict[str, ast.AST] = {}   # var -> .get receiver
        self.dict_vars: dict[str, ast.Dict] = {}
        self.sub_writes: dict[str, set[str]] = {}
        self.sends: list[tuple[str, str, ast.AST]] = []
        self.handled: list[tuple[str, str, ast.AST]] = []
        self.dynamic: list[tuple[ast.AST, str]] = []  # WA00 sites
        # (polarity, kind, recv_key, compare node, enclosing If or None)
        self.guards: list[tuple[str, str, str, ast.AST,
                                ast.AST | None]] = []
        self._collect_assigns()
        self._collect_sends()
        self._collect_compares()

    def _collect_assigns(self) -> None:
        for node in _scoped_walk(self.fdef):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, val = node.targets[0], node.value
                if isinstance(tgt, ast.Name):
                    if isinstance(val, ast.Dict):
                        self.dict_vars[tgt.id] = val
                    else:
                        got = _get_call_key(val)
                        if got is not None and got[1] == "kind":
                            self.kindvars[tgt.id] = got[0]
                elif (isinstance(tgt, ast.Subscript)
                        and isinstance(tgt.value, ast.Name)
                        and isinstance(tgt.slice, ast.Constant)
                        and isinstance(tgt.slice.value, str)):
                    self.sub_writes.setdefault(
                        tgt.value.id, set()).add(tgt.slice.value)

    def _send_candidate(self, call: ast.Call):
        """The wire-message dict of a ``.request({...})`` /
        ``.dispatch(shard, {...})`` call, else None."""
        if not isinstance(call.func, ast.Attribute):
            return None
        if call.func.attr == "request" and call.args:
            cand = call.args[0]
        elif call.func.attr == "dispatch" and len(call.args) >= 2:
            cand = call.args[1]
        else:
            return None
        if isinstance(cand, ast.Name):
            cand = self.dict_vars.get(cand.id)
        return cand if isinstance(cand, ast.Dict) else None

    def _collect_sends(self) -> None:
        for node in _scoped_walk(self.fdef):
            if not isinstance(node, ast.Call):
                continue
            d = self._send_candidate(node)
            if d is None:
                continue
            kind_node = _dict_key_value(d, "kind")
            if kind_node is None:
                continue
            form, val = name_value(self.mod, self.index, kind_node)
            if form == "dynamic":
                self.dynamic.append((kind_node, "request kind"))
            else:
                self.sends.append((form, val, kind_node))

    def _collect_compares(self) -> None:
        for node in _scoped_walk(self.fdef):
            if not (isinstance(node, ast.Compare) and len(node.ops) == 1
                    and isinstance(node.ops[0], (ast.Eq, ast.NotEq))):
                continue
            left, right = node.left, node.comparators[0]
            recv = None
            if isinstance(left, ast.Name) and left.id in self.kindvars:
                recv = self.kindvars[left.id]
            else:
                got = _get_call_key(left)
                if got is not None and got[1] == "kind":
                    recv = got[0]
            if recv is None:
                continue
            form, val = name_value(self.mod, self.index, right)
            if form == "dynamic":
                self.dynamic.append((node, "dispatch kind comparison"))
                continue
            polarity = "eq" if isinstance(node.ops[0], ast.Eq) else "ne"
            if polarity == "eq" and form == "literal":
                self.handled.append((form, val, node))
            self.guards.append(
                (polarity, val if form == "literal" else None,
                 _recv_key(recv), node, None))

    def writer_sets(self) -> list[tuple[str, set[str], bool, ast.AST]]:
        """``(kind, fields, open, node)`` for every dict literal in this
        scope carrying a literal ``"kind"`` entry."""
        out = []
        var_of = {id(d): v for v, d in self.dict_vars.items()}
        for node in _scoped_walk(self.fdef):
            if not isinstance(node, ast.Dict):
                continue
            kind_node = _dict_key_value(node, "kind")
            if kind_node is None:
                continue
            form, val = name_value(self.mod, self.index, kind_node)
            if form != "literal":
                continue
            fields: set[str] = set()
            open_set = False
            for k in node.keys:
                if k is None:  # **spread — unknowable statically
                    open_set = True
                elif isinstance(k, ast.Constant) and isinstance(
                        k.value, str):
                    fields.add(k.value)
                else:
                    open_set = True
            v = var_of.get(id(node))
            if v is not None:
                fields |= self.sub_writes.get(v, set())
            out.append((val, fields, open_set, node))
        return out

    def guarded_reads(self) -> list[tuple[str, str, ast.AST]]:
        """``(kind, field, node)`` for field reads on receivers whose
        wire kind is pinned by a guard in this scope."""
        out: list[tuple[str, str, ast.AST]] = []
        # Eq guards pin the kind inside the If arm they test for.
        for node in _scoped_walk(self.fdef):
            if not isinstance(node, ast.If):
                continue
            guard = self._if_guard(node.test)
            if guard is None:
                continue
            polarity, kind, key = guard
            if kind is None:
                continue
            if polarity == "eq":
                for stmt in node.body:
                    out.extend((kind, f, n)
                               for f, n in self._reads(stmt, key))
        # NotEq guards (bad-reply bail-outs) pin the kind for the whole
        # scope — but only when the receiver is guarded for ONE kind.
        ne_by_key: dict[str, set[str]] = {}
        for polarity, kind, key, _node, _ in self.guards:
            if polarity == "ne" and kind is not None:
                ne_by_key.setdefault(key, set()).add(kind)
        for key, kinds in ne_by_key.items():
            if len(kinds) != 1:
                continue
            kind = next(iter(kinds))
            out.extend((kind, f, n) for f, n in self._reads(
                self.fdef, key))
        return out

    def _if_guard(self, test: ast.AST):
        for polarity, kind, key, node, _ in self.guards:
            if node is test:
                return polarity, kind, key
        return None

    def _reads(self, root: ast.AST, key: str):
        for node in _scoped_walk(root):
            got = _get_call_key(node)
            if got is not None and got[1] != "kind" \
                    and _recv_key(got[0]) == key:
                yield got[1], node
            elif (isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, ast.Load)
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)
                    and node.slice.value != "kind"
                    and _recv_key(node.value) == key):
                yield node.slice.value, node


def _dict_key_value(d: ast.Dict, key: str):
    for k, v in zip(d.keys, d.values):
        if isinstance(k, ast.Constant) and k.value == key:
            return v
    return None


# -- WA03: typed-error parse table ----------------------------------------


def _typed_table(modules: list[ModuleInfo]):
    """(set of table key names, True if a table exists)."""
    keys: set[str] = set()
    found = False
    for mod in modules:
        expr = mod.constants.get(_TYPED_TABLE_NAME)
        if isinstance(expr, ast.Dict):
            found = True
            keys.update(k.value for k in expr.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str))
    return keys, found


def _typed_error_refs(index: PackageIndex) -> set[str]:
    refs: set[str] = set()
    for dotted, (_mod, fdef) in index.functions.items():
        if dotted.endswith(".typed_error"):
            refs.update(n.id for n in ast.walk(fdef)
                        if isinstance(n, ast.Name))
    return refs


def _subclasses_of(index: PackageIndex, base_suffix: str) -> set[str]:
    bases = {d for d in index.classes if d.endswith("." + base_suffix)
             or d == base_suffix}
    out: set[str] = set()
    changed = True
    while changed:
        changed = False
        for dotted, info in index.classes.items():
            if dotted in out or dotted in bases:
                continue
            if any(b in bases or b in out for b in info.bases):
                out.add(dotted)
                changed = True
    return out


def _check_typed_errors(modules, index) -> list[Finding]:
    keys, found = _typed_table(modules)
    if not found:
        return []
    refs = _typed_error_refs(index)
    typed = _subclasses_of(index, _TYPED_BASE)
    findings = []
    for mod in modules:
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Raise) and node.exc is not None):
                continue
            exc = node.exc
            cls_node = exc.func if isinstance(exc, ast.Call) else exc
            dotted = mod.resolve(cls_node)
            if dotted is None or dotted not in typed:
                continue
            name = dotted.rsplit(".", 1)[-1]
            if name in keys or name in refs:
                continue
            findings.append(Finding(
                "WA03", mod.relpath, node.lineno, node.col_offset,
                f"{name} raised here can reach the wire via wire_error "
                f"but is missing from typed_error()'s "
                f"{_TYPED_TABLE_NAME} table — clients parse it back as "
                f"a GENERIC ServeRequestError"))
    return findings


# -- WA04: transport-classification set -----------------------------------


def _genuine_builtin_exc(name: str):
    """The builtin exception class truly named ``name`` — alias entries
    (``IOError`` → ``OSError``) resolve to None."""
    cls = getattr(builtins, name, None)
    if isinstance(cls, type) and issubclass(cls, BaseException) \
            and cls.__name__ == name:
        return cls
    return None


def _builtin_subclass_names(caught: type) -> set[str]:
    return {n for n in dir(builtins)
            if (c := _genuine_builtin_exc(n)) is not None
            and issubclass(c, caught)}


def _package_subclass_names(index: PackageIndex, dotted: str) -> set[str]:
    names = {dotted.rsplit(".", 1)[-1]}
    seen = {dotted}
    changed = True
    while changed:
        changed = False
        for cand, info in index.classes.items():
            if cand in seen:
                continue
            if any(b in seen for b in info.bases):
                seen.add(cand)
                names.add(cand.rsplit(".", 1)[-1])
                changed = True
    return names


def _renders_leading_type_name(body: list[ast.stmt],
                               bound: str) -> bool:
    """True when the handler body holds an f-string that STARTS with
    ``type(<bound>).__name__`` — the ``Name: message`` wire render."""
    for stmt in body:
        for node in ast.walk(stmt):
            if not (isinstance(node, ast.JoinedStr) and node.values):
                continue
            head = node.values[0]
            if not isinstance(head, ast.FormattedValue):
                continue
            v = head.value
            if (isinstance(v, ast.Attribute) and v.attr == "__name__"
                    and isinstance(v.value, ast.Call)
                    and isinstance(v.value.func, ast.Name)
                    and v.value.func.id == "type"
                    and v.value.args
                    and isinstance(v.value.args[0], ast.Name)
                    and v.value.args[0].id == bound):
                return True
    return False


def _emittable_error_names(modules, index) -> set[str]:
    names: set[str] = set()
    for mod in modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ExceptHandler) and node.name \
                    and _renders_leading_type_name(node.body, node.name):
                types = node.type.elts if isinstance(
                    node.type, ast.Tuple) else \
                    ([node.type] if node.type is not None else [])
                for t in types:
                    dotted = mod.resolve(t)
                    if dotted is not None and dotted in index.classes:
                        names |= _package_subclass_names(index, dotted)
                        continue
                    seg = t.id if isinstance(t, ast.Name) else (
                        t.attr if isinstance(t, ast.Attribute) else None)
                    if seg is None:
                        continue
                    cls = getattr(builtins, seg, None)
                    if isinstance(cls, type) and issubclass(
                            cls, BaseException):
                        names |= _builtin_subclass_names(cls)
            elif isinstance(node, ast.Raise) and node.exc is not None:
                exc = node.exc
                cls_node = exc.func if isinstance(exc, ast.Call) else exc
                dotted = mod.resolve(cls_node)
                if dotted is not None and dotted in index.classes:
                    names.add(dotted.rsplit(".", 1)[-1])
                    continue
                seg = cls_node.id if isinstance(cls_node, ast.Name) \
                    else (cls_node.attr if isinstance(
                        cls_node, ast.Attribute) else None)
                if seg is not None:
                    cls = getattr(builtins, seg, None)
                    if isinstance(cls, type) and issubclass(
                            cls, BaseException):
                        names.add(cls.__name__)  # canonical, not alias
    return names


def _transport_set_elements(mod: ModuleInfo):
    expr = mod.constants.get(_TRANSPORT_SET_NAME)
    if expr is None:
        return []
    if isinstance(expr, ast.Call) and expr.args and isinstance(
            expr.func, ast.Name) and expr.func.id in ("frozenset", "set"):
        expr = expr.args[0]
    if isinstance(expr, (ast.Set, ast.Tuple, ast.List)):
        return [e for e in expr.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, str)]
    return []


def _check_transport_set(modules, index) -> list[Finding]:
    sets = [(mod, _transport_set_elements(mod)) for mod in modules]
    sets = [(mod, elts) for mod, elts in sets if elts]
    if not sets:
        return []
    emittable = _emittable_error_names(modules, index)
    findings = []
    for mod, elts in sets:
        for e in elts:
            if e.value in emittable:
                continue
            hint = ""
            cls = getattr(builtins, e.value, None)
            if isinstance(cls, type) and issubclass(cls, BaseException) \
                    and cls.__name__ != e.value:
                hint = (f" (in Python 3, {e.value} is an alias of "
                        f"{cls.__name__} — type(e).__name__ can never "
                        f"render it)")
            findings.append(Finding(
                "WA04", mod.relpath, e.lineno, e.col_offset,
                f"{_TRANSPORT_SET_NAME} names \"{e.value}\" but no code "
                f"path can put that name on the wire{hint} — remove the "
                f"dead entry or restore the emitting path"))
    return findings


# -- driver ----------------------------------------------------------------


def check(modules: list[ModuleInfo], index: PackageIndex,
          flows: dict[str, Dataflow], ctx) -> list[Finding]:
    findings: list[Finding] = []
    serve_mods = _serve_scope(modules)

    sends: list[tuple[str, str, ModuleInfo, ast.AST]] = []
    handled: list[tuple[str, ModuleInfo, ast.AST]] = []
    writers: dict[str, dict] = {}
    guarded_reads: list[tuple[str, str, ModuleInfo, ast.AST]] = []
    for mod in serve_mods:
        for fdef in _functions(mod):
            scan = _FnScan(mod, index, fdef)
            for node, what in scan.dynamic:
                findings.append(Finding(
                    "WA00", mod.relpath, node.lineno, node.col_offset,
                    f"{what} is a fully dynamic expression — the wire "
                    f"protocol must stay statically enumerable (use a "
                    f"literal, or suppress with the reason the name is "
                    f"dynamic)"))
            sends.extend((form, val, mod, node)
                         for form, val, node in scan.sends)
            handled.extend((val, mod, node)
                           for _form, val, node in scan.handled)
            for kind, fields, open_set, _node in scan.writer_sets():
                w = writers.setdefault(
                    kind, {"fields": set(), "open": False})
                w["fields"] |= fields
                w["open"] = w["open"] or open_set
            guarded_reads.extend((kind, field, mod, node)
                                 for kind, field, node
                                 in scan.guarded_reads())

    handled_kinds = {val for val, _m, _n in handled}
    sent_literals = {val for form, val, _m, _n in sends
                     if form == "literal"}
    sent_prefixes = {val for form, val, _m, _n in sends
                     if form == "prefix"}
    if handled_kinds:
        for form, val, mod, node in sends:
            ok = (val in handled_kinds if form == "literal"
                  else any(h.startswith(val) for h in handled_kinds))
            if not ok:
                findings.append(Finding(
                    "WA01", mod.relpath, node.lineno, node.col_offset,
                    f"protocol kind \"{val}\" is sent here but no "
                    f"server dispatch handles it — every such request "
                    f"comes back as an unknown-kind error"))
    if sent_literals or sent_prefixes:
        for val, mod, node in handled:
            ok = val in sent_literals or any(
                val.startswith(p) for p in sent_prefixes)
            if not ok:
                findings.append(Finding(
                    "WA02", mod.relpath, node.lineno, node.col_offset,
                    f"server dispatch handles kind \"{val}\" but no "
                    f"client sends it — dead handler or renamed "
                    f"request"))
    for kind, field, mod, node in guarded_reads:
        w = writers.get(kind)
        if w is None or w["open"] or field in w["fields"]:
            continue
        findings.append(Finding(
            "WA05", mod.relpath, node.lineno, node.col_offset,
            f"reads field \"{field}\" off a \"{kind}\" message, but no "
            f"writer of that kind sets it (writers set: "
            f"{', '.join(sorted(w['fields'])) or 'nothing'})"))

    findings.extend(_check_typed_errors(modules, index))
    findings.extend(_check_transport_set(modules, index))
    return findings
