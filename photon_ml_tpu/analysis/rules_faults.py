"""W4xx — fault-point drift.

The ``PHOTON_FAULTS`` README table is operator-facing documentation of
every drillable fault site; PR 2 already grew the sites faster than the
table once. These rules keep the two in sync in both directions:

- **W401** a ``fault_point("name")`` call site whose name has no row in
  the README table;
- **W402** a README table row naming a point with no call site;
- **W403** a ``fault_point`` call whose name argument is not a string
  literal (statically unanalyzable — use a literal, the registry is a
  closed set by design).

The table is located by its markdown header row (first cell ``point``)
inside the README; rows are ``| `name` | ... |``.
"""

from __future__ import annotations

import ast
import re

from photon_ml_tpu.analysis.core import Finding
from photon_ml_tpu.analysis.dataflow import Dataflow
from photon_ml_tpu.analysis.package import ModuleInfo, PackageIndex

_HEADER_RE = re.compile(r"^\s*\|\s*point\s*\|", re.IGNORECASE)
_ROW_RE = re.compile(r"^\s*\|\s*`([\w.\-]+)`\s*\|")
_TABLE_LINE_RE = re.compile(r"^\s*\|")


def parse_fault_table(readme_lines: list[str]) -> dict[str, int]:
    """``{fault point name: 1-based README line}`` from the first
    markdown table whose header's first cell is ``point``."""
    out: dict[str, int] = {}
    in_table = False
    for i, line in enumerate(readme_lines, start=1):
        if not in_table:
            if _HEADER_RE.match(line):
                in_table = True
            continue
        if not _TABLE_LINE_RE.match(line):
            break  # table ended
        m = _ROW_RE.match(line)
        if m:
            out[m.group(1)] = i
    return out


def _is_fault_point(mod: ModuleInfo, call: ast.Call) -> bool:
    d = mod.resolve(call.func)
    return d is not None and (
        d == "photon_ml_tpu.utils.faults.fault_point"
        or d.endswith(".fault_point"))


def check(modules: list[ModuleInfo], index: PackageIndex,
          flows: dict[str, Dataflow], ctx) -> list[Finding]:
    if ctx.readme_lines is None:
        return []  # no README to reconcile against (fixture runs)
    table = parse_fault_table(ctx.readme_lines)
    findings: list[Finding] = []
    seen_sites: set[str] = set()
    for mod in modules:
        if mod.relpath.endswith("utils/faults.py"):
            continue  # the registry itself (docstrings / default wiring)
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and _is_fault_point(mod, node)):
                continue
            if not node.args or not (
                    isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                findings.append(Finding(
                    "W403", mod.relpath, node.lineno, node.col_offset,
                    "fault_point() name is not a string literal — the "
                    "fault registry must stay statically enumerable"))
                continue
            name = node.args[0].value
            seen_sites.add(name)
            if name not in table:
                findings.append(Finding(
                    "W401", mod.relpath, node.lineno, node.col_offset,
                    f"fault_point(\"{name}\") has no row in the README "
                    f"PHOTON_FAULTS table — document where it fires "
                    f"and its tag format"))
    for name, line in sorted(table.items()):
        if name not in seen_sites:
            findings.append(Finding(
                "W402", ctx.readme_relpath or "README.md", line, 0,
                f"PHOTON_FAULTS table documents `{name}` but no "
                f"fault_point(\"{name}\") site exists — remove the row "
                f"or restore the site"))
    return findings
