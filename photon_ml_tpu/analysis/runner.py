"""photonlint runner: load → index → dataflow → rules → filter → report.

Library entry point is :func:`lint`; ``tools/photonlint.py`` is the CLI
wrapper. The run is pure (no package code is imported or executed) and
deterministic: findings sort by (path, line, col, rule).
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Iterable, Optional

from photon_ml_tpu.analysis import (
    core, dataflow, rules_checkpoint, rules_collectives, rules_donation,
    rules_dtype, rules_faults, rules_jit, rules_retrace, rules_sync,
    rules_threads,
)
from photon_ml_tpu.analysis.core import Finding, LintReport
from photon_ml_tpu.analysis.package import (
    ModuleInfo, PackageIndex, build_index,
)

RULE_MODULES = {
    "W1": rules_sync,
    "W2": rules_jit,
    "W3": rules_donation,
    "W4": rules_faults,
    "W5": rules_checkpoint,
    "W6": rules_collectives,
    "W7": rules_retrace,
    "W8": rules_dtype,
    "W9": rules_threads,
}


@dataclasses.dataclass
class LintContext:
    root: Path
    readme_path: Optional[Path]
    readme_lines: Optional[list[str]]
    readme_relpath: Optional[str]
    trace_dir: Optional[Path] = None


def _collect_files(root: Path, paths: Iterable[str]) -> list[Path]:
    files: list[Path] = []
    for p in paths:
        path = (root / p) if not Path(p).is_absolute() else Path(p)
        if path.is_dir():
            files.extend(sorted(
                f for f in path.rglob("*.py")
                if "__pycache__" not in f.parts))
        elif path.suffix == ".py":
            files.append(path)
        else:
            raise FileNotFoundError(f"no such file or directory: {p}")
    return files


def collect_findings(
    root: Path,
    paths: Optional[Iterable[str]] = None,
    readme: Optional[Path] = None,
    families: Optional[set[str]] = None,
    trace_dir: Optional[Path] = None,
) -> tuple[list[Finding], list[ModuleInfo], PackageIndex]:
    """Run the rule families and return raw findings (before suppression
    and baseline filtering)."""
    root = Path(root)
    files = _collect_files(root, paths or ["photon_ml_tpu"])
    modules = [ModuleInfo.load(f, root) for f in files]
    index = build_index(modules)
    dataflow.infer_jax_functions(index)

    # Jit params become tracers: mark non-static params JAX per binding
    # whose statics resolved (unknown statics → no tags → no W202 FPs).
    tags_by_mod: dict[str, dict[int, dict[str, str]]] = {}
    for b in index.jit_bindings:
        if b.fdef is None or b.static_names is None:
            continue
        a = b.fdef.args
        params = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        tags = {p: dataflow.JAX for p in params
                if p not in b.static_names}
        tags_by_mod.setdefault(b.mod.relpath, {})[id(b.fdef)] = tags
    flows = {
        mod.relpath: dataflow.analyze_module(
            mod, index, tags_by_mod.get(mod.relpath))
        for mod in modules
    }

    if readme is not None and Path(readme).exists():
        readme_path = Path(readme)
        readme_lines = readme_path.read_text().splitlines()
        try:
            readme_relpath = readme_path.relative_to(root).as_posix()
        except ValueError:
            readme_relpath = readme_path.name
    else:
        readme_path = readme_lines = readme_relpath = None
    ctx = LintContext(root=root, readme_path=readme_path,
                      readme_lines=readme_lines,
                      readme_relpath=readme_relpath,
                      trace_dir=trace_dir)

    findings: list[Finding] = []
    enabled = families or set(RULE_MODULES)
    for family, rule_mod in sorted(RULE_MODULES.items()):
        if family in enabled:
            findings.extend(rule_mod.check(modules, index, flows, ctx))
    if families is None or "W0" in families:
        for mod in modules:
            findings.extend(mod.malformed)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, modules, index


def lint(
    root,
    paths: Optional[Iterable[str]] = None,
    readme=None,
    baseline=None,
    families: Optional[set[str]] = None,
    trace_dir: Optional[Path] = None,
    changed_paths: Optional[set[str]] = None,
) -> LintReport:
    """Full lint pass: rules, then per-line suppressions, then baseline.

    ``baseline`` is a path (entries grandfather existing findings) or
    None to report everything as new. ``changed_paths`` (root-relative
    posix paths) restricts the *report* to findings in those files; the
    analysis itself is always whole-program, so cross-module findings
    (a W801 whose accumulator lives two calls away, a W904 lock-order
    pair) still resolve against the unchanged half of the package.
    """
    findings, modules, _ = collect_findings(
        Path(root), paths, readme, families, trace_dir)
    by_file = {m.relpath: m.suppressions for m in modules}
    kept, suppressed, used = core.apply_suppressions(findings, by_file)
    if families is None:
        # W002 needs every family's verdict: on a partial run an
        # off-family directive would merely LOOK unused.
        w002 = core.unused_suppressions(by_file, used)
        w002_kept, w002_suppressed, _ = core.apply_suppressions(
            w002, by_file)
        kept = sorted(kept + w002_kept,
                      key=lambda f: (f.path, f.line, f.col, f.rule))
        suppressed.extend(w002_suppressed)
    if changed_paths is not None:
        kept = [f for f in kept if f.path in changed_paths]
    entries = core.load_baseline(baseline)
    new, baselined, stale = core.apply_baseline(kept, entries)
    return LintReport(new=new, baselined=baselined,
                      suppressed=suppressed, stale_baseline=stale,
                      files_checked=len(modules))


def write_baseline(
    root,
    path,
    paths: Optional[Iterable[str]] = None,
    readme=None,
    families: Optional[set[str]] = None,
) -> int:
    """Grandfather every current (non-suppressed) finding into
    ``path``. Stale entries are pruned by construction: the file is
    rewritten from the findings that exist *now*, so anything fixed
    since the last refresh simply never re-enters. Returns the number
    of baseline entries written."""
    findings, modules, _ = collect_findings(
        Path(root), paths, readme, families)
    by_file = {m.relpath: m.suppressions for m in modules}
    kept, _, used = core.apply_suppressions(findings, by_file)
    if families is None:
        w002 = core.unused_suppressions(by_file, used)
        w002_kept, _, _ = core.apply_suppressions(w002, by_file)
        kept = kept + w002_kept
    return core.write_baseline(path, kept)
