"""photonlint runner: load → index → dataflow → rules → filter → report.

Library entry point is :func:`lint`; ``tools/photonlint.py`` is the CLI
wrapper. The run is pure (no package code is imported or executed) and
deterministic: findings sort by (path, line, col, rule).

Two kinds of extra inputs ride along with the package modules:

- **auxiliary consumer modules** (``bench.py``, ``tools/…``) are loaded
  for the WB telemetry-consumer scan only — they honor inline
  suppressions but are not linted by any other family;
- an optional **incremental cache** (``cache_dir=…``): per-file
  ``ModuleInfo`` artifacts keyed on content, plus a whole-program
  findings replay that skips module loading entirely when nothing
  changed. Suppression, baseline and ``changed_paths`` filtering always
  run live on top of replayed findings, so they stay authoritative.
"""

from __future__ import annotations

import dataclasses
import time
from pathlib import Path
from typing import Iterable, Optional

from photon_ml_tpu.analysis import (
    core, dataflow, rules_checkpoint, rules_collectives, rules_donation,
    rules_dtype, rules_faults, rules_jit, rules_protocol, rules_retrace,
    rules_sync, rules_telemetry, rules_threads,
)
from photon_ml_tpu.analysis.cache import LintCache
from photon_ml_tpu.analysis.core import Finding, LintReport
from photon_ml_tpu.analysis.package import (
    ModuleInfo, PackageIndex, build_index,
)

RULE_MODULES = {
    "W1": rules_sync,
    "W2": rules_jit,
    "W3": rules_donation,
    "W4": rules_faults,
    "W5": rules_checkpoint,
    "W6": rules_collectives,
    "W7": rules_retrace,
    "W8": rules_dtype,
    "W9": rules_threads,
    "WA": rules_protocol,
    "WB": rules_telemetry,
}

# Telemetry consumers that live outside the default lint path set.
# Loaded (when present) so WB03 sees the reads that actually power the
# dashboards; every other family ignores them.
AUX_CONSUMER_FILES = (
    "bench.py",
    "tools/photon_status.py",
    "tools/trace_report.py",
    "tools/trace_diff.py",
    "tools/chaos_drill.py",
)


@dataclasses.dataclass
class LintContext:
    root: Path
    readme_path: Optional[Path]
    readme_lines: Optional[list[str]]
    readme_relpath: Optional[str]
    trace_dir: Optional[Path] = None
    aux_modules: Optional[list[ModuleInfo]] = None


def _collect_files(root: Path, paths: Iterable[str]) -> list[Path]:
    files: list[Path] = []
    for p in paths:
        path = (root / p) if not Path(p).is_absolute() else Path(p)
        if path.is_dir():
            files.extend(sorted(
                f for f in path.rglob("*.py")
                if "__pycache__" not in f.parts))
        elif path.suffix == ".py":
            files.append(path)
        else:
            raise FileNotFoundError(f"no such file or directory: {p}")
    return files


def _aux_paths(root: Path, files: list[Path]) -> list[Path]:
    taken = {f.resolve() for f in files}
    out: list[Path] = []
    for rel in AUX_CONSUMER_FILES:
        p = root / rel
        if p.exists() and p.resolve() not in taken:
            out.append(p)
    return out


def collect_findings(
    root: Path,
    paths: Optional[Iterable[str]] = None,
    readme: Optional[Path] = None,
    families: Optional[set[str]] = None,
    trace_dir: Optional[Path] = None,
    cache: Optional[LintCache] = None,
) -> tuple[list[Finding], list[ModuleInfo], list[ModuleInfo],
           PackageIndex, dict[str, float]]:
    """Run the rule families and return raw findings (before suppression
    and baseline filtering), the package and auxiliary modules, the
    index, and per-family wall-clock timings."""
    root = Path(root)
    files = _collect_files(root, paths or ["photon_ml_tpu"])

    def load(f: Path) -> ModuleInfo:
        if cache is not None:
            return cache.load_module(f, root)[0]
        return ModuleInfo.load(f, root)

    modules = [load(f) for f in files]
    aux_modules = [load(f) for f in _aux_paths(root, files)]
    index = build_index(modules)
    dataflow.infer_jax_functions(index)

    # Jit params become tracers: mark non-static params JAX per binding
    # whose statics resolved (unknown statics → no tags → no W202 FPs).
    tags_by_mod: dict[str, dict[int, dict[str, str]]] = {}
    for b in index.jit_bindings:
        if b.fdef is None or b.static_names is None:
            continue
        a = b.fdef.args
        params = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        tags = {p: dataflow.JAX for p in params
                if p not in b.static_names}
        tags_by_mod.setdefault(b.mod.relpath, {})[id(b.fdef)] = tags
    flows = {
        mod.relpath: dataflow.analyze_module(
            mod, index, tags_by_mod.get(mod.relpath))
        for mod in modules
    }

    if readme is not None and Path(readme).exists():
        readme_path = Path(readme)
        readme_lines = readme_path.read_text().splitlines()
        try:
            readme_relpath = readme_path.relative_to(root).as_posix()
        except ValueError:
            readme_relpath = readme_path.name
    else:
        readme_path = readme_lines = readme_relpath = None
    ctx = LintContext(root=root, readme_path=readme_path,
                      readme_lines=readme_lines,
                      readme_relpath=readme_relpath,
                      trace_dir=trace_dir,
                      aux_modules=aux_modules)

    findings: list[Finding] = []
    timings: dict[str, float] = {}
    enabled = families or set(RULE_MODULES)
    for family, rule_mod in sorted(RULE_MODULES.items()):
        if family in enabled:
            t0 = time.perf_counter()
            findings.extend(rule_mod.check(modules, index, flows, ctx))
            timings[family] = time.perf_counter() - t0
    if families is None or "W0" in families:
        for mod in modules:
            findings.extend(mod.malformed)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, modules, aux_modules, index, timings


def _program_key(cache: LintCache, root: Path,
                 paths: Optional[Iterable[str]],
                 readme, families: Optional[set[str]]) -> str:
    """Key the whole-program replay on every input byte the rules can
    see: the lint file set, the auxiliary consumers, and the README.
    Main and aux roles are tagged so the same file set split
    differently cannot collide."""
    files = _collect_files(root, paths or ["photon_ml_tpu"])
    keys: list[str] = []
    for role, group in (("main", files), ("aux", _aux_paths(root, files))):
        for f in group:
            try:
                rel = f.relative_to(root).as_posix()
            except ValueError:
                rel = f.as_posix()
            keys.append(f"{role}:{cache.file_key(rel, f.read_bytes())}")
    readme_bytes = None
    if readme is not None and Path(readme).exists():
        readme_bytes = Path(readme).read_bytes()
    return cache.program_key(keys, readme_bytes, families)


def lint(
    root,
    paths: Optional[Iterable[str]] = None,
    readme=None,
    baseline=None,
    families: Optional[set[str]] = None,
    trace_dir: Optional[Path] = None,
    changed_paths: Optional[set[str]] = None,
    cache_dir=None,
) -> LintReport:
    """Full lint pass: rules, then per-line suppressions, then baseline.

    ``baseline`` is a path (entries grandfather existing findings) or
    None to report everything as new. ``changed_paths`` (root-relative
    posix paths) restricts the *report* to findings in those files; the
    analysis itself is always whole-program, so cross-module findings
    (a W801 whose accumulator lives two calls away, a W904 lock-order
    pair) still resolve against the unchanged half of the package.

    ``cache_dir`` enables the incremental cache (see
    :mod:`photon_ml_tpu.analysis.cache`). A ``--trace-evidence`` run
    bypasses the program-level replay — W702 reads evidence files the
    cache key cannot see — but still reuses per-file artifacts.
    """
    root = Path(root)
    cache = LintCache(cache_dir) if cache_dir is not None else None
    payload = pkey = None
    timings: Optional[dict[str, float]] = None
    if cache is not None and trace_dir is None:
        pkey = _program_key(cache, root, paths, readme, families)
        payload = cache.load_program(pkey)
    if payload is not None:
        findings = payload["findings"]
        by_file = payload["by_file"]
        aux_by_file = payload["aux_by_file"]
        files_checked = payload["files_checked"]
    else:
        findings, modules, aux_modules, _, timings = collect_findings(
            root, paths, readme, families, trace_dir, cache=cache)
        by_file = {m.relpath: m.suppressions for m in modules}
        aux_by_file = {m.relpath: m.suppressions for m in aux_modules}
        files_checked = len(modules)
        if pkey is not None:
            cache.store_program(pkey, {
                "findings": findings,
                "by_file": by_file,
                "aux_by_file": aux_by_file,
                "files_checked": files_checked,
            })
    merged = dict(by_file)
    merged.update(aux_by_file)
    kept, suppressed, used = core.apply_suppressions(findings, merged)
    if families is None:
        # W002 needs every family's verdict: on a partial run an
        # off-family directive would merely LOOK unused. Auxiliary
        # consumer files are excluded — only WB ever looks at them, so
        # an off-family directive there is not provably dead.
        w002 = core.unused_suppressions(by_file, used)
        w002_kept, w002_suppressed, _ = core.apply_suppressions(
            w002, merged)
        kept = sorted(kept + w002_kept,
                      key=lambda f: (f.path, f.line, f.col, f.rule))
        suppressed.extend(w002_suppressed)
    if changed_paths is not None:
        kept = [f for f in kept if f.path in changed_paths]
    entries = core.load_baseline(baseline)
    new, baselined, stale = core.apply_baseline(kept, entries)
    return LintReport(new=new, baselined=baselined,
                      suppressed=suppressed, stale_baseline=stale,
                      files_checked=files_checked,
                      cache_stats=cache.stats() if cache else None,
                      timings=timings)


def write_baseline(
    root,
    path,
    paths: Optional[Iterable[str]] = None,
    readme=None,
    families: Optional[set[str]] = None,
) -> int:
    """Grandfather every current (non-suppressed) finding into
    ``path``. Stale entries are pruned by construction: the file is
    rewritten from the findings that exist *now*, so anything fixed
    since the last refresh simply never re-enters. Returns the number
    of baseline entries written."""
    findings, modules, aux_modules, _, _ = collect_findings(
        Path(root), paths, readme, families)
    by_file = {m.relpath: m.suppressions for m in modules}
    merged = dict(by_file)
    merged.update({m.relpath: m.suppressions for m in aux_modules})
    kept, _, used = core.apply_suppressions(findings, merged)
    if families is None:
        w002 = core.unused_suppressions(by_file, used)
        w002_kept, _, _ = core.apply_suppressions(w002, merged)
        kept = kept + w002_kept
    return core.write_baseline(path, kept)
