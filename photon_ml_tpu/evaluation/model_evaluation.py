"""Full-model metric maps + best-lambda selection.

TPU-native replacement for the reference's legacy evaluation
(reference: photon-ml/src/main/scala/com/linkedin/photon/ml/Evaluation.scala
:32-152 — produces a Map[metricName -> value] per model; metric names :32-39)
and ModelSelection.scala (best-lambda pick per task: AUC for classifiers,
RMSE / mean loss for regressions).
"""

from __future__ import annotations

from typing import Mapping

import jax.numpy as jnp

from photon_ml_tpu.data.batch import Batch
from photon_ml_tpu.evaluation import metrics
from photon_ml_tpu.models.glm import GeneralizedLinearModel, score_batch
from photon_ml_tpu.ops.losses import get_loss
from photon_ml_tpu.optimize.config import TaskType

# Metric name constants (Evaluation.scala:32-39).
MEAN_ABSOLUTE_ERROR = "MEAN_ABSOLUTE_ERROR"
MEAN_SQUARED_ERROR = "MEAN_SQUARED_ERROR"
ROOT_MEAN_SQUARED_ERROR = "ROOT_MEAN_SQUARED_ERROR"
AREA_UNDER_PRECISION_RECALL = "AREA_UNDER_PRECISION_RECALL"
AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS = (
    "AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS")
PEAK_F1_SCORE = "PEAK_F1_SCORE"
DATA_LOG_LIKELIHOOD = "DATA_LOG_LIKELIHOOD"
AKAIKE_INFORMATION_CRITERION = "AKAIKE_INFORMATION_CRITERION"


def evaluate_model(model: GeneralizedLinearModel, batch: Batch
                   ) -> dict[str, float]:
    """Compute the task-appropriate metric map on a validation batch."""
    margins = score_batch(model, batch)
    predictions = model.mean(margins)
    labels, weights = batch.labels, batch.weights
    out: dict[str, float] = {
        MEAN_ABSOLUTE_ERROR: float(
            metrics.mean_absolute_error(labels, predictions, weights)),
        MEAN_SQUARED_ERROR: float(
            metrics.mean_squared_error(labels, predictions, weights)),
        ROOT_MEAN_SQUARED_ERROR: float(
            metrics.root_mean_squared_error(labels, predictions, weights)),
    }
    k = model.coefficients.dim

    if model.task == TaskType.LOGISTIC_REGRESSION:
        out[AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS] = float(
            metrics.area_under_roc_curve(labels, margins, weights))
        out[AREA_UNDER_PRECISION_RECALL] = float(
            metrics.area_under_pr_curve(labels, margins, weights))
        out[PEAK_F1_SCORE] = float(metrics.peak_f1(labels, margins, weights))
    elif model.task == TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM:
        out[AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS] = float(
            metrics.area_under_roc_curve(labels, margins, weights))
        loss = get_loss("smoothed_hinge")
        out["SMOOTHED_HINGE_LOSS"] = float(
            metrics.mean_loss(loss, labels, margins, weights))

    ll_fn = {
        TaskType.LOGISTIC_REGRESSION: metrics.logistic_log_likelihood,
        TaskType.POISSON_REGRESSION: metrics.poisson_log_likelihood,
        TaskType.LINEAR_REGRESSION: metrics.linear_log_likelihood,
    }.get(model.task)
    if ll_fn is not None:
        mean_ll = float(ll_fn(labels, margins, weights))
        out[DATA_LOG_LIKELIHOOD] = mean_ll
        total_ll = mean_ll * float(jnp.sum(weights))
        out[AKAIKE_INFORMATION_CRITERION] = float(
            metrics.akaike_information_criterion(jnp.asarray(total_ll), k))
    return out


def select_best_model(
    per_lambda_metrics: Mapping[float, Mapping[str, float]],
    task: TaskType,
) -> float:
    """Best-lambda selection (ModelSelection.scala): max AUC for classifiers,
    min RMSE for linear, max log-likelihood for Poisson. Returns the winning
    lambda."""
    if not per_lambda_metrics:
        raise ValueError("no models to select from")
    if task in (TaskType.LOGISTIC_REGRESSION,
                TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM):
        key, best = AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS, max
    elif task == TaskType.LINEAR_REGRESSION:
        key, best = ROOT_MEAN_SQUARED_ERROR, min
    else:
        key, best = DATA_LOG_LIKELIHOOD, max
    return best(per_lambda_metrics, key=lambda lam: per_lambda_metrics[lam][key])
