"""Full-model metric maps + best-lambda selection.

TPU-native replacement for the reference's legacy evaluation
(reference: photon-ml/src/main/scala/com/linkedin/photon/ml/Evaluation.scala
:32-152 — produces a Map[metricName -> value] per model; metric names :32-39)
and ModelSelection.scala (best-lambda pick per task: AUC for classifiers,
RMSE / mean loss for regressions).

Where the reference evaluates one model at a time with one Spark job per
metric (Evaluation.scala:100-152), the whole lambda grid is evaluated in ONE
jitted call: coefficients stacked ``[L, D]``, margins as a single ``[L, N]``
matmul, every metric vmapped over the grid axis, and a single device->host
fetch of the packed ``[num_metrics, L]`` result. On a remote accelerator
this turns ~8 x L tiny blocking dispatches into one.
"""

from __future__ import annotations

from functools import partial
from typing import Mapping, Sequence

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.batch import Batch
from photon_ml_tpu.evaluation import metrics
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.ops.losses import get_loss
from photon_ml_tpu.optimize.config import TaskType
from photon_ml_tpu.utils.sync_telemetry import record_host_fetch

# Metric name constants (Evaluation.scala:32-39).
MEAN_ABSOLUTE_ERROR = "MEAN_ABSOLUTE_ERROR"
MEAN_SQUARED_ERROR = "MEAN_SQUARED_ERROR"
ROOT_MEAN_SQUARED_ERROR = "ROOT_MEAN_SQUARED_ERROR"
AREA_UNDER_PRECISION_RECALL = "AREA_UNDER_PRECISION_RECALL"
AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS = (
    "AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS")
PEAK_F1_SCORE = "PEAK_F1_SCORE"
DATA_LOG_LIKELIHOOD = "DATA_LOG_LIKELIHOOD"
AKAIKE_INFORMATION_CRITERION = "AKAIKE_INFORMATION_CRITERION"


def _metric_names(task: TaskType) -> list[str]:
    """Metric set per task (Evaluation.scala:100-152), fixed order so the
    jitted kernel can return a packed [num_metrics, L] array."""
    names = [MEAN_ABSOLUTE_ERROR, MEAN_SQUARED_ERROR, ROOT_MEAN_SQUARED_ERROR]
    if task == TaskType.LOGISTIC_REGRESSION:
        names += [AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS,
                  AREA_UNDER_PRECISION_RECALL, PEAK_F1_SCORE]
    elif task == TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM:
        names += [AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS,
                  "SMOOTHED_HINGE_LOSS"]
    if task in (TaskType.LOGISTIC_REGRESSION, TaskType.POISSON_REGRESSION,
                TaskType.LINEAR_REGRESSION):
        names += [DATA_LOG_LIKELIHOOD, AKAIKE_INFORMATION_CRITERION]
    return names


@partial(jax.jit, static_argnums=(0,))
def _evaluate_grid_kernel(task: TaskType, W: jnp.ndarray, batch: Batch
                          ) -> jnp.ndarray:
    """All metrics for all L models in one XLA computation.

    Returns ``[num_metrics, L]`` in ``_metric_names(task)`` order. Margins
    for the whole grid are one batched matmul; rank-based metrics (AUC / PR
    AUC / peak F1) vmap their sort over the grid axis.
    """
    labels, weights = batch.labels, batch.weights
    zero = jnp.zeros((), W.dtype)
    margins = jax.vmap(lambda w: batch.margins(w, zero))(W)  # [L, N]

    if task == TaskType.LOGISTIC_REGRESSION:
        predictions = jax.nn.sigmoid(margins)
    elif task == TaskType.POISSON_REGRESSION:
        predictions = jnp.exp(margins)
    else:
        predictions = margins

    def per_model(metric_fn, use_margins=False):
        src = margins if use_margins else predictions
        return jax.vmap(lambda x: metric_fn(labels, x, weights))(src)

    rows = [
        per_model(metrics.mean_absolute_error),
        per_model(metrics.mean_squared_error),
        per_model(metrics.root_mean_squared_error),
    ]
    if task == TaskType.LOGISTIC_REGRESSION:
        rows += [
            per_model(metrics.area_under_roc_curve, use_margins=True),
            per_model(metrics.area_under_pr_curve, use_margins=True),
            per_model(metrics.peak_f1, use_margins=True),
        ]
    elif task == TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM:
        loss = get_loss("smoothed_hinge")
        rows += [
            per_model(metrics.area_under_roc_curve, use_margins=True),
            per_model(partial(metrics.mean_loss, loss), use_margins=True),
        ]
    ll_fn = {
        TaskType.LOGISTIC_REGRESSION: metrics.logistic_log_likelihood,
        TaskType.POISSON_REGRESSION: metrics.poisson_log_likelihood,
        TaskType.LINEAR_REGRESSION: metrics.linear_log_likelihood,
    }.get(task)
    if ll_fn is not None:
        mean_ll = per_model(ll_fn, use_margins=True)  # [L]
        total_ll = mean_ll * jnp.sum(weights)
        k = W.shape[1]
        rows += [mean_ll,
                 metrics.akaike_information_criterion(total_ll, k)]
    return jnp.stack(rows)


def evaluate_model_grid(models: Sequence[GeneralizedLinearModel],
                        batch: Batch) -> list[dict[str, float]]:
    """Metric maps for a whole lambda grid: one jitted call + one host fetch
    (replaces the reference's per-model, per-metric Spark jobs)."""
    if not models:
        return []
    task = models[0].task
    if any(m.task != task for m in models):
        raise ValueError("evaluate_model_grid requires a homogeneous task")
    dim = models[0].coefficients.means.shape
    for i, m in enumerate(models):
        if m.coefficients.means.shape != dim:
            raise ValueError(
                f"evaluate_model_grid requires homogeneous coefficient "
                f"dimensions: model 0 has shape {tuple(dim)} but model {i} "
                f"has {tuple(m.coefficients.means.shape)}")
    W = jnp.stack([m.coefficients.means for m in models])
    # the whole [num_metrics, L] grid comes back in this one fetch
    packed = jax.device_get(_evaluate_grid_kernel(task, W, batch))
    record_host_fetch(site="eval.grid")
    names = _metric_names(task)
    return [{name: float(packed[j, i]) for j, name in enumerate(names)}
            for i in range(len(models))]


def evaluate_model(model: GeneralizedLinearModel, batch: Batch
                   ) -> dict[str, float]:
    """Compute the task-appropriate metric map on a validation batch
    (single-model view of :func:`evaluate_model_grid`)."""
    return evaluate_model_grid([model], batch)[0]


def select_best_model(
    per_lambda_metrics: Mapping[float, Mapping[str, float]],
    task: TaskType,
) -> float:
    """Best-lambda selection (ModelSelection.scala): max AUC for classifiers,
    min RMSE for linear, max log-likelihood for Poisson. Returns the winning
    lambda."""
    if not per_lambda_metrics:
        raise ValueError("no models to select from")
    if task in (TaskType.LOGISTIC_REGRESSION,
                TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM):
        key, best = AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS, max
    elif task == TaskType.LINEAR_REGRESSION:
        key, best = ROOT_MEAN_SQUARED_ERROR, min
    else:
        key, best = DATA_LOG_LIKELIHOOD, max
    return best(per_lambda_metrics, key=lambda lam: per_lambda_metrics[lam][key])
