"""Core metric kernels, all computed on device with sort-based algorithms.

TPU-native replacement for the reference's metric stack
(reference: photon-ml/src/main/scala/com/linkedin/photon/ml/Evaluation.scala
:32-152 — MAE/MSE/RMSE, ROC AUC + PR AUC via MLlib BinaryClassificationMetrics,
peak F1, per-datum log-likelihood, AIC; evaluation/
AreaUnderROCCurveEvaluator.scala:34-35; AreaUnderROCCurveLocalEvaluator.scala:25).

The Spark implementations shuffle (score, label) pairs into threshold bins;
here every metric is one jitted sort + cumulative sums — exact (no binning),
weighted, tie-aware, and O(n log n) on device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jnp.ndarray


# --- regression metrics -----------------------------------------------------


def mean_absolute_error(labels: Array, predictions: Array,
                        weights: Array | None = None) -> Array:
    return _wmean(jnp.abs(predictions - labels), weights)


def mean_squared_error(labels: Array, predictions: Array,
                       weights: Array | None = None) -> Array:
    d = predictions - labels
    return _wmean(d * d, weights)


def root_mean_squared_error(labels: Array, predictions: Array,
                            weights: Array | None = None) -> Array:
    return jnp.sqrt(mean_squared_error(labels, predictions, weights))


def _wmean(x: Array, weights: Array | None) -> Array:
    if weights is None:
        return jnp.mean(x)
    return jnp.sum(weights * x) / jnp.sum(weights)


# --- ROC AUC (exact, weighted, tie-aware) ----------------------------------


def segment_auc_stats(labels: Array, scores: Array, weights: Array | None,
                      entity_ids: Array, num_entities: int
                      ) -> tuple[Array, Array, Array]:
    """Per-entity Mann-Whitney numerator + class weights, one fused kernel.

    Returns ``(num_e, pos_e, neg_e)`` per entity, where AUC_e =
    num_e / (pos_e * neg_e) when both classes are present. Global AUC is the
    ``num_entities=1`` case; per-entity sharded AUC passes real ids. One
    lexsort by (entity, score) + segment reductions replaces the reference's
    groupBy-entity / local-evaluator-per-entity loop (ShardedEvaluator ->
    AreaUnderROCCurveLocalEvaluator per entity). Ties contribute half,
    matching MLlib's curve integration.
    """
    w = jnp.ones_like(scores) if weights is None else weights
    n = scores.shape[0]
    order = jnp.lexsort((scores, entity_ids))
    e_s = entity_ids[order]
    s_s = scores[order]
    pos_s = labels[order] > 0.5
    wp_s = jnp.where(pos_s, w[order], 0.0)
    wn_s = jnp.where(pos_s, 0.0, w[order])

    # Exclusive global cumsum of negative weight, made per-entity by
    # subtracting the entity-start value (cumsum is nondecreasing, so the
    # entity minimum IS the start value).
    cum_n = jnp.concatenate([jnp.zeros(1, w.dtype), jnp.cumsum(wn_s)[:-1]])
    ent_start = jax.ops.segment_min(cum_n, e_s, num_segments=num_entities)
    n_below_in_entity = cum_n - ent_start[e_s]

    # Tie groups within an entity.
    new_group = jnp.concatenate(
        [jnp.ones(1, bool), (e_s[1:] != e_s[:-1]) | (s_s[1:] != s_s[:-1])])
    gid = jnp.cumsum(new_group.astype(jnp.int32)) - 1
    g_n = jax.ops.segment_sum(wn_s, gid, num_segments=n)
    g_below = jax.ops.segment_min(n_below_in_entity, gid, num_segments=n)

    contrib = wp_s * (g_below[gid] + 0.5 * g_n[gid])
    num_e = jax.ops.segment_sum(contrib, e_s, num_segments=num_entities)
    pos_e = jax.ops.segment_sum(wp_s, e_s, num_segments=num_entities)
    neg_e = jax.ops.segment_sum(wn_s, e_s, num_segments=num_entities)
    return num_e, pos_e, neg_e


def area_under_roc_curve(labels: Array, scores: Array,
                         weights: Array | None = None) -> Array:
    """P(score_pos > score_neg) + 0.5 P(tie), weighted.

    Exact rank statistic — equivalent to the trapezoidal area under the full
    (unbinned) ROC curve.
    """
    ids = jnp.zeros(scores.shape[0], jnp.int32)
    num, pos, neg = segment_auc_stats(labels, scores, weights, ids, 1)
    denom = pos[0] * neg[0]
    auc = num[0] / jnp.where(denom > 0.0, denom, 1.0)
    # Single-class input has no ranking information: neutral 0.5 (keeps
    # best-model comparisons well-defined instead of NaN).
    return jnp.where(denom > 0.0, auc, 0.5)


# --- PR AUC and peak F1 -----------------------------------------------------


def _pr_points(labels: Array, scores: Array, weights: Array | None):
    """Precision/recall at every distinct-score threshold (descending)."""
    w = jnp.ones_like(scores) if weights is None else weights
    pos = labels > 0.5
    order = jnp.argsort(-scores)
    s = scores[order]
    wp = jnp.where(pos, w, 0.0)[order]
    wt = w[order]

    cum_tp = jnp.cumsum(wp)
    cum_pred_pos = jnp.cumsum(wt)
    total_pos = jnp.sum(wp)

    # A threshold is valid at the LAST element of each tie group (descending
    # order => cumulative counts include the full group there).
    is_boundary = jnp.concatenate([s[:-1] != s[1:], jnp.ones(1, bool)])
    # where-guards, not finfo.tiny: TPU flushes tiny to zero, turning the
    # zero-positive / zero-weight cases into 0/0 = NaN.
    precision = cum_tp / jnp.where(cum_pred_pos > 0.0, cum_pred_pos, 1.0)
    recall = cum_tp / jnp.where(total_pos > 0.0, total_pos, 1.0)
    return precision, recall, is_boundary, cum_tp, cum_pred_pos, total_pos


def area_under_pr_curve(labels: Array, scores: Array,
                        weights: Array | None = None) -> Array:
    """Trapezoidal area under the precision-recall curve, with the MLlib
    convention of an initial (r=0, p=p(first threshold)) point."""
    precision, recall, is_boundary, *_ = _pr_points(labels, scores, weights)
    # Keep only boundary points; masked points collapse onto their group end
    # by forcing zero-width trapezoids (same recall).
    n = recall.shape[0]
    idx = jnp.arange(n)
    # For non-boundary positions use the previous boundary's values by
    # replacing with the next boundary position values: since trapezoid width
    # uses diffs of recall, duplicating recall at non-boundaries adds zero
    # area only if we substitute the GROUP-END values. Build via gather of
    # the next boundary index.
    next_boundary = jnp.flip(
        jax.lax.associative_scan(
            jnp.minimum, jnp.where(jnp.flip(is_boundary), jnp.flip(idx), n - 1)))
    p_b = precision[next_boundary]
    r_b = recall[next_boundary]
    r_prev = jnp.concatenate([jnp.zeros(1, r_b.dtype), r_b[:-1]])
    p_prev = jnp.concatenate([p_b[:1], p_b[:-1]])
    # Trapezoid areas are tiny per-row quantities: accumulate in at least
    # f32 regardless of the score dtype, then return that accumulator.
    return jnp.sum((r_b - r_prev) * 0.5 * (p_b + p_prev),
                   dtype=jnp.promote_types(p_b.dtype, jnp.float32))


def peak_f1(labels: Array, scores: Array, weights: Array | None = None) -> Array:
    """max over thresholds of 2 P R / (P + R)."""
    precision, recall, is_boundary, *_ = _pr_points(labels, scores, weights)
    pr_sum = precision + recall
    f1 = jnp.where(pr_sum > 0.0, 2.0 * precision * recall
                   / jnp.where(pr_sum > 0.0, pr_sum, 1.0), 0.0)
    return jnp.max(jnp.where(is_boundary, f1, -jnp.inf))


# --- per-datum log-likelihoods & AIC ---------------------------------------


def logistic_log_likelihood(labels: Array, margins: Array,
                            weights: Array | None = None) -> Array:
    """Mean per-datum Bernoulli log-likelihood (Evaluation.scala:142-152)."""
    ll = -(jnp.logaddexp(0.0, margins) - labels * margins)
    return _wmean(ll, weights)


def poisson_log_likelihood(labels: Array, margins: Array,
                           weights: Array | None = None) -> Array:
    """Mean Poisson log-likelihood with the log Gamma(y+1) constant
    (Evaluation.scala:128-140)."""
    ll = labels * margins - jnp.exp(margins) - jax.lax.lgamma(labels + 1.0)
    return _wmean(ll, weights)


def linear_log_likelihood(labels: Array, margins: Array,
                          weights: Array | None = None) -> Array:
    """Gaussian log-likelihood with unit variance."""
    d = labels - margins
    ll = -0.5 * (d * d + jnp.log(2.0 * jnp.pi))
    return _wmean(ll, weights)


def akaike_information_criterion(total_log_likelihood: Array,
                                 num_parameters: int) -> Array:
    """AIC = 2k - 2 ln L (Evaluation.scala:100-112)."""
    return 2.0 * num_parameters - 2.0 * total_log_likelihood


# --- mean loss metrics (Evaluator family) ----------------------------------


def mean_loss(loss, labels: Array, margins: Array,
              weights: Array | None = None) -> Array:
    """Weighted mean pointwise loss — the LogisticLoss/PoissonLoss/
    SquaredLoss/SmoothedHingeLoss evaluator family
    (evaluation/*LossEvaluator.scala)."""
    return _wmean(loss.loss(margins, labels), weights)


# --- precision@k ------------------------------------------------------------


def precision_at_k(labels: Array, scores: Array, k: int,
                   valid: Array | None = None) -> Array:
    """Fraction of positives among the top-k scored items.

    ``valid`` masks padded rows (per-entity padded blocks); invalid rows are
    pushed to -inf so they never enter the top k.
    """
    s = scores if valid is None else jnp.where(valid, scores, -jnp.inf)
    _, top_idx = jax.lax.top_k(s, k)
    top_labels = labels[top_idx]
    if valid is not None:
        top_valid = valid[top_idx]
        denom = jnp.maximum(jnp.sum(top_valid), 1)
        return jnp.sum(jnp.where(top_valid, top_labels > 0.5, False)) / denom
    # Mean of 0/1 indicators: accumulate in at least f32 (a bf16 mean of
    # >256 rows loses the low bits), cast back to the caller's dtype.
    acc_t = jnp.promote_types(scores.dtype, jnp.float32)
    return jnp.mean((top_labels > 0.5).astype(acc_t)).astype(scores.dtype)
