"""Evaluator family: global and sharded (per-entity) metrics.

TPU-native re-design of the reference's evaluator hierarchy
(reference: photon-ml/src/main/scala/com/linkedin/photon/ml/evaluation/
Evaluator.scala:24-78, ShardedEvaluator.scala:28, EvaluatorType.scala,
ShardedEvaluatorType.scala:31-43, AreaUnderROCCurveLocalEvaluator.scala:25,
PrecisionAtKLocalEvaluator.scala).

The reference's sharded evaluators group scores per entity with an RDD
groupBy, then run a local evaluator per entity on the driver-side iterator.
Here per-entity AUC / precision@k are computed for ALL entities at once with
lexsort + segment reductions — one fused device program, no grouping shuffle.

Evaluator.betterThan direction is preserved: AUC/precision are
larger-is-better; RMSE and mean-loss evaluators are smaller-is-better.
"""

from __future__ import annotations

import dataclasses
import enum
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.evaluation import metrics
from photon_ml_tpu.ops.losses import get_loss
from photon_ml_tpu.utils.sync_telemetry import record_host_fetch

Array = jnp.ndarray


class EvaluatorType(enum.Enum):
    AUC = "AUC"
    RMSE = "RMSE"
    LOGISTIC_LOSS = "LOGISTIC_LOSS"
    POISSON_LOSS = "POISSON_LOSS"
    SQUARED_LOSS = "SQUARED_LOSS"
    SMOOTHED_HINGE_LOSS = "SMOOTHED_HINGE_LOSS"
    # Sharded types carry the id-type name they shard by (e.g. "userId"):
    # reference format "precision@k:idType" / "AUC:idType"
    # (ShardedEvaluatorType.scala:31-43).
    SHARDED_AUC = "SHARDED_AUC"
    SHARDED_PRECISION_AT_K = "SHARDED_PRECISION_AT_K"


LARGER_IS_BETTER = {
    EvaluatorType.AUC, EvaluatorType.SHARDED_AUC,
    EvaluatorType.SHARDED_PRECISION_AT_K,
}


@dataclasses.dataclass(frozen=True)
class EvaluatorSpec:
    """Parsed evaluator request (type + sharding id-type + k)."""

    evaluator_type: EvaluatorType
    id_type: Optional[str] = None  # entity id column for sharded evaluators
    k: int = 1  # for precision@k

    @staticmethod
    def parse(s: str) -> "EvaluatorSpec":
        """Parse the reference CLI spellings: ``AUC``, ``RMSE``,
        ``LOGISTIC_LOSS``..., ``AUC:userId``, ``precision@5:songId``."""
        t = s.strip()
        low = t.lower()
        if low.startswith("precision@"):
            body = t.split(":", 1)
            head = body[0]
            k = int(head.split("@", 1)[1])
            if len(body) != 2 or not body[1]:
                raise ValueError(f"precision@k requires an id type: {s!r}")
            return EvaluatorSpec(EvaluatorType.SHARDED_PRECISION_AT_K,
                                 id_type=body[1], k=k)
        if ":" in t:
            head, id_type = t.split(":", 1)
            if head.upper() != "AUC":
                raise ValueError(f"unknown sharded evaluator {s!r}")
            if not id_type:
                raise ValueError(f"sharded AUC requires an id type: {s!r}")
            return EvaluatorSpec(EvaluatorType.SHARDED_AUC, id_type=id_type)
        return EvaluatorSpec(EvaluatorType(t.upper()))

    @property
    def name(self) -> str:
        if self.evaluator_type == EvaluatorType.SHARDED_PRECISION_AT_K:
            return f"precision@{self.k}:{self.id_type}"
        if self.evaluator_type == EvaluatorType.SHARDED_AUC:
            return f"AUC:{self.id_type}"
        return self.evaluator_type.value

    def better_than(self, a: float, b: float) -> bool:
        if self.evaluator_type in LARGER_IS_BETTER:
            return a > b
        return a < b


def _device_metric(
    spec: EvaluatorSpec,
    scores: Array,
    labels: Array,
    weights: Array | None,
    entity_ids: Array | None,
    num_entities: int | None,
) -> Array:
    """One metric as a device scalar — dispatched asynchronously, never
    fetched here (the caller batches fetches; see ``evaluate_many``)."""
    t = spec.evaluator_type
    if t == EvaluatorType.AUC:
        return metrics.area_under_roc_curve(labels, scores, weights)
    if t == EvaluatorType.RMSE:
        return metrics.root_mean_squared_error(labels, scores, weights)
    if t in (EvaluatorType.LOGISTIC_LOSS, EvaluatorType.POISSON_LOSS,
             EvaluatorType.SQUARED_LOSS, EvaluatorType.SMOOTHED_HINGE_LOSS):
        loss = get_loss({
            EvaluatorType.LOGISTIC_LOSS: "logistic",
            EvaluatorType.POISSON_LOSS: "poisson",
            EvaluatorType.SQUARED_LOSS: "squared",
            EvaluatorType.SMOOTHED_HINGE_LOSS: "smoothed_hinge",
        }[t])
        return metrics.mean_loss(loss, labels, scores, weights)
    if t == EvaluatorType.SHARDED_AUC:
        if entity_ids is None or num_entities is None:
            raise ValueError("sharded AUC needs entity_ids + num_entities")
        return sharded_auc(labels, scores, entity_ids, num_entities,
                           weights)
    if t == EvaluatorType.SHARDED_PRECISION_AT_K:
        if entity_ids is None or num_entities is None:
            raise ValueError("precision@k needs entity_ids + num_entities")
        return sharded_precision_at_k(labels, scores, entity_ids,
                                      num_entities, spec.k)
    raise ValueError(f"unhandled evaluator {spec}")


def evaluate(
    spec: EvaluatorSpec,
    scores: Array,
    labels: Array,
    weights: Array | None = None,
    entity_ids: Array | None = None,
    num_entities: int | None = None,
) -> float:
    """Evaluate one metric over (scores, labels[, weights]).

    For sharded evaluators, ``entity_ids`` must be dense ids in
    ``[0, num_entities)`` aligned with scores (the id-type resolution from
    GameDatum happens in the data layer). Costs exactly one instrumented
    device→host fetch; evaluating several metrics should go through
    :func:`evaluate_many`, which shares a single fetch across all of
    them.
    """
    value = jax.device_get(_device_metric(
        spec, scores, labels, weights, entity_ids, num_entities))
    record_host_fetch(site="eval.metric")
    return float(value)


def resolve_entity_ids(
    specs: list[EvaluatorSpec],
    id_columns,
    id_vocabs,
) -> tuple[dict[str, Array], dict[str, int]]:
    """Resolve each sharded spec's id type once into the dense-id column
    and vocab size :func:`evaluate_many` expects (shared by the training
    and scoring drivers so the resolution cannot drift between them)."""
    ids_by_type: dict[str, Array] = {}
    num_by_type: dict[str, int] = {}
    for spec in specs:
        if spec.id_type and spec.id_type not in ids_by_type:
            ids_by_type[spec.id_type] = jnp.asarray(
                id_columns[spec.id_type])
            num_by_type[spec.id_type] = len(id_vocabs[spec.id_type])
    return ids_by_type, num_by_type


def evaluate_many(
    specs: list[EvaluatorSpec],
    scores: Array,
    labels: Array,
    weights: Array | None = None,
    entity_ids_by_type: dict[str, Array] | None = None,
    num_entities_by_type: dict[str, int] | None = None,
) -> dict[str, float]:
    """All requested metrics with ONE blocking device→host fetch.

    Every metric kernel is dispatched first (device scalars only), then
    the whole tuple comes back in a single ``jax.device_get`` routed
    through ``utils/sync_telemetry`` — so validation metrics show up in
    ``host_syncs_per_update`` telemetry instead of costing one hidden
    round-trip per metric. Sharded specs resolve their entity ids from
    the ``*_by_type`` mappings (keyed by the spec's ``id_type``).
    """
    device_vals = []
    for spec in specs:
        eid = nent = None
        if spec.id_type is not None:
            eid = (entity_ids_by_type or {}).get(spec.id_type)
            nent = (num_entities_by_type or {}).get(spec.id_type)
            if eid is None or nent is None:
                raise ValueError(
                    f"evaluator {spec.name!r} needs entity ids for id "
                    f"type {spec.id_type!r}")
        device_vals.append(_device_metric(
            spec, scores, labels, weights, eid, nent))
    fetched = jax.device_get(tuple(device_vals))
    record_host_fetch(site="eval.metrics")
    return {spec.name: float(v) for spec, v in zip(specs, fetched)}


@partial(jax.jit, static_argnums=(3,))
def sharded_auc(labels: Array, scores: Array, entity_ids: Array,
                num_entities: int, weights: Array | None = None) -> Array:
    """Unweighted mean of per-entity AUCs over entities with both classes.

    Delegates to the shared segment kernel (metrics.segment_auc_stats) —
    global AUC is its num_entities=1 case, so tie/weight handling can never
    diverge between the two paths.
    """
    num_e, pos_e, neg_e = metrics.segment_auc_stats(
        labels, scores, weights, entity_ids, num_entities)
    denom = pos_e * neg_e
    valid = denom > 0.0
    auc_e = num_e / jnp.where(valid, denom, 1.0)
    return jnp.sum(jnp.where(valid, auc_e, 0.0)) / jnp.maximum(
        jnp.sum(valid), 1)


@partial(jax.jit, static_argnums=(3, 4))
def sharded_precision_at_k(labels: Array, scores: Array, entity_ids: Array,
                           num_entities: int, k: int) -> Array:
    """Mean per-entity precision among each entity's top-k scores.

    Sort by (entity, -score); the first k rows of each entity segment are its
    top k. Entities with fewer than k rows use all their rows (reference
    local evaluator takes min(k, n)).
    """
    order = jnp.lexsort((-scores, entity_ids))
    e_s = entity_ids[order]
    # Hit/count indicators accumulate in (at least) f32: bf16 segment sums
    # round away increments once a segment count passes 256.
    acc_t = jnp.promote_types(scores.dtype, jnp.float32)
    pos_s = (labels[order] > 0.5).astype(acc_t)

    # Rank within entity = global position - entity start position.
    n = scores.shape[0]
    idx = jnp.arange(n)
    ent_start = jax.ops.segment_min(idx, e_s, num_segments=num_entities)
    rank = idx - ent_start[e_s]
    in_top = rank < k

    hits_e = jax.ops.segment_sum(jnp.where(in_top, pos_s, 0.0), e_s,
                                 num_segments=num_entities)
    cnt_e = jax.ops.segment_sum(in_top.astype(acc_t), e_s,
                                num_segments=num_entities)
    has_rows = cnt_e > 0
    prec_e = hits_e / jnp.maximum(cnt_e, jnp.finfo(acc_t).tiny)
    mean = jnp.sum(jnp.where(has_rows, prec_e, 0.0)) / jnp.maximum(
        jnp.sum(has_rows), 1)
    return mean.astype(scores.dtype)
