"""Single-GLM training over a regularization-weight grid with warm starts.

TPU-native replacement for the reference's ModelTraining
(reference: photon-ml/src/main/scala/com/linkedin/photon/ml/
ModelTraining.scala:103-215): sort the lambda list descending, fold over it
warm-starting each fit from the previous optimum (:182-208), and return all
per-lambda models plus their optimization trackers.

Because ``l2_lambda`` is a traced leaf of the objective pytree, the entire
grid reuses ONE compiled solver kernel — the reference instead rebuilds a
Breeze optimizer per lambda and re-broadcasts coefficients per iteration.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional, Sequence

import jax.numpy as jnp

from photon_ml_tpu.data.batch import Batch
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.optimize.common import BoxConstraints, OptimizationResult
from photon_ml_tpu.optimize.config import (
    GLMOptimizationConfiguration,
    OptimizerType,
    RegularizationContext,
    RegularizationType,
    TaskType,
)
from photon_ml_tpu.optimize.problem import GLMOptimizationProblem

Array = jnp.ndarray


@dataclasses.dataclass(frozen=True)
class TrainedModel:
    regularization_weight: float
    model: GeneralizedLinearModel
    result: OptimizationResult  # tracker: trajectory + convergence reason


def train_glm_grid(
    batch: Batch,
    task: TaskType,
    regularization_weights: Sequence[float],
    optimizer_type: OptimizerType = OptimizerType.LBFGS,
    regularization_context: RegularizationContext = RegularizationContext(
        RegularizationType.L2),
    max_iterations: int = 80,
    tolerance: float = 1e-6,
    normalization: NormalizationContext = NormalizationContext(),
    box: Optional[BoxConstraints] = None,
    compute_variances: bool = False,
    warm_start: bool = True,
    l1_mask: Optional[Array] = None,
    initial_by_weight: Optional[Mapping[float, Array]] = None,
    track_iterates: bool = False,
) -> list[TrainedModel]:
    """Train one GLM per regularization weight, descending, warm-started.

    ``initial_by_weight`` supplies a per-lambda starting point in the
    problem's (normalized) coefficient space — e.g. the same lambda's
    optimum from a previous retrain, as the reference's fitting diagnostic
    threads through scanLeft (FittingDiagnostic.scala:48-110). It takes
    precedence over the previous-lambda warm start.

    Returns models ordered as the (descending-sorted) weights were trained.
    """
    weights = sorted(set(float(w) for w in regularization_weights), reverse=True)
    if not weights:
        raise ValueError("at least one regularization weight is required")

    out: list[TrainedModel] = []
    init = None
    for lam in weights:
        cfg = GLMOptimizationConfiguration(
            max_iterations=max_iterations,
            tolerance=tolerance,
            regularization_weight=lam,
            optimizer_type=optimizer_type,
            regularization_context=regularization_context,
        )
        problem = GLMOptimizationProblem(
            config=cfg, task=task, normalization=normalization, box=box,
            compute_variances=compute_variances, l1_mask=l1_mask,
            track_iterates=track_iterates)
        start = init
        if initial_by_weight is not None and lam in initial_by_weight:
            start = jnp.asarray(initial_by_weight[lam])
        model, result = problem.run(batch, initial=start)
        out.append(TrainedModel(lam, model, result))
        if warm_start:
            # Warm start in normalized coefficient space
            # (ModelTraining.scala:182-208 passes the raw optimum forward).
            init = result.coefficients
    return out
