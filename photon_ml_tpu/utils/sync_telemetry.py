"""Process-wide counter of explicit device→host fetch sites.

Every INTENTIONAL blocking device→host read in the training path — the CD
fused-epilogue fetch, a lazy tracker/optimizer-history materialization,
the lane-compaction unconverged-mask fetch, a checkpoint snapshot's
payload fetch — calls :func:`record_host_fetch` next to its
``jax.device_get``. bench.py divides the count over a warm run by the
number of coordinate updates to report ``host_syncs_per_update``: 1.0
means the one-round-trip contract held, and a lazy-materialization
regression (e.g. a tracker forced inside the hot loop) shows up as > 1.0
in the very next BENCH record.

This counts the *instrumented* sites only. A raw ``float()``/
``np.asarray`` sneaked into the hot loop is invisible here by
construction — catching those is the transfer-guard test's job
(tests/test_sync_discipline.py).
"""

from __future__ import annotations

HOST_FETCHES = {"count": 0}


def record_host_fetch(n: int = 1) -> None:
    HOST_FETCHES["count"] += n


def reset_host_fetches() -> None:
    HOST_FETCHES["count"] = 0


def host_fetch_count() -> int:
    return HOST_FETCHES["count"]
