"""Process-wide counter of explicit device→host fetch sites.

Every INTENTIONAL blocking device→host read in the training path — the CD
fused-epilogue fetch, a lazy tracker/optimizer-history materialization,
the lane-compaction unconverged-mask fetch, a checkpoint snapshot's
payload fetch — calls :func:`record_host_fetch` next to its
``jax.device_get``, tagging WHERE with ``site=...``. bench.py divides the
count over a warm run by the number of coordinate updates to report
``host_syncs_per_update``: 1.0 means the one-round-trip contract held,
and a lazy-materialization regression (e.g. a tracker forced inside the
hot loop) shows up as > 1.0 in the very next BENCH record — with the
per-site breakdown (:func:`host_fetches_by_site`) naming the culprit.

Since the observability layer landed this module is a thin shim over the
labeled ``host_fetches`` counter in ``photon_ml_tpu.obs.metrics.REGISTRY``
(one storage, two views): :func:`host_fetch_count` is the label-sum, so
bench.py and the transfer-guard tests keep their exact legacy contract
while ``metrics.jsonl`` gets per-site attribution for free. Third-party
callers that never pass ``site`` land under ``"unlabeled"``.

This counts the *instrumented* sites only. A raw ``float()``/
``np.asarray`` sneaked into the hot loop is invisible here by
construction — catching those is the transfer-guard test's job
(tests/test_sync_discipline.py) and photonlint W1xx's.
"""

from __future__ import annotations

from photon_ml_tpu.obs.metrics import REGISTRY

#: Name of the labeled counter in ``obs.metrics.REGISTRY``.
HOST_FETCH_COUNTER = "host_fetches"


def record_host_fetch(n: int = 1, site: str = "unlabeled") -> None:
    REGISTRY.counter(HOST_FETCH_COUNTER).inc(n, site=site)


def reset_host_fetches() -> None:
    REGISTRY.counter(HOST_FETCH_COUNTER).reset()


def host_fetch_count() -> int:
    return int(REGISTRY.counter(HOST_FETCH_COUNTER).total())


def host_fetches_by_site() -> dict[str, int]:
    """Per-site fetch counts; values sum to :func:`host_fetch_count`."""
    return {k: int(v) for k, v in
            REGISTRY.counter(HOST_FETCH_COUNTER).by_label("site").items()}
