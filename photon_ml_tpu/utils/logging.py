"""Run-scoped leveled logger + phase timers.

Re-design of the reference's observability utilities:

- ``PhotonLogger`` (reference: photon-ml/src/main/scala/com/linkedin/
  photon/ml/util/PhotonLogger.scala:36-506): an slf4j-style leveled logger
  writing to one file per run (HDFS there, local file here), used by both
  drivers. Default level DEBUG, same level set.
- ``Timer`` (util/Timer.scala): start/stop/duration wrapped around every
  driver phase (cli/game/training/Driver.scala:648-711) and coordinate-
  descent iterations (algorithm/CoordinateDescent.scala:132-141).
"""

from __future__ import annotations

import contextlib
import enum
import os
import sys
import time
from typing import Optional, TextIO


class LogLevel(enum.IntEnum):
    DEBUG = 10
    INFO = 20
    WARN = 30
    ERROR = 40


class PhotonLogger:
    """Leveled logger writing to a file and (optionally) stderr."""

    def __init__(self, log_path: Optional[str] = None,
                 level: LogLevel = LogLevel.DEBUG,
                 echo: bool = True):
        self.level = level
        self._echo = echo
        self._fh: Optional[TextIO] = None
        if log_path:
            os.makedirs(os.path.dirname(log_path) or ".", exist_ok=True)
            self._fh = open(log_path, "a")

    def _log(self, level: LogLevel, msg: str) -> None:
        if level < self.level:
            return
        stamp = time.strftime("%Y-%m-%d %H:%M:%S")
        line = f"{stamp} [{level.name}] {msg}"
        if self._fh:
            self._fh.write(line + "\n")
            self._fh.flush()
        if self._echo:
            print(line, file=sys.stderr)

    def debug(self, msg: str) -> None:
        self._log(LogLevel.DEBUG, msg)

    def info(self, msg: str) -> None:
        self._log(LogLevel.INFO, msg)

    def warn(self, msg: str) -> None:
        self._log(LogLevel.WARN, msg)

    def error(self, msg: str) -> None:
        self._log(LogLevel.ERROR, msg)

    # Callable so it can be passed anywhere a plain `logger(msg)` is taken
    # (coordinate descent, validators).
    def __call__(self, msg: str) -> None:
        self.info(msg)

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None


class Timer:
    """util/Timer.scala analog: start/stop/duration."""

    def __init__(self):
        self._start: Optional[float] = None
        self._stop: Optional[float] = None

    def start(self) -> "Timer":
        self._start = time.perf_counter()
        self._stop = None
        return self

    def stop(self) -> "Timer":
        if self._start is None:
            raise RuntimeError("Timer.stop() before start()")
        self._stop = time.perf_counter()
        return self

    @property
    def duration_seconds(self) -> float:
        if self._start is None:
            raise RuntimeError("Timer not started")
        end = self._stop if self._stop is not None else time.perf_counter()
        return end - self._start

    @contextlib.contextmanager
    def measure(self):
        self.start()
        try:
            yield self
        finally:
            self.stop()


@contextlib.contextmanager
def timed_phase(name: str, logger: Optional[PhotonLogger] = None):
    """Driver-phase timing idiom (cli/game/training/Driver.scala:648-711).
    Also opens a ``driver.phase`` span, so every driver phase lands in the
    run's trace when ``--trace-dir`` is on (no-op otherwise)."""
    from photon_ml_tpu.obs import trace

    t = Timer().start()
    try:
        with trace.span("driver.phase", phase=name):
            yield t
    finally:
        t.stop()
        if logger:
            logger.info(f"{name} took {t.duration_seconds:.3f}s")
