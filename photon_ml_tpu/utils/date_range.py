"""Date ranges and dated input-path resolution.

Re-design of the reference's dated-ingestion utilities (reference:
photon-ml/src/main/scala/com/linkedin/photon/ml/util/DateRange.scala:27-100
and util/IOUtils.scala:85-126 getInputPathsWithinDateRange): training/
validation directories laid out as ``<base>/daily/yyyy/MM/dd`` are selected
by a ``yyyyMMdd-yyyyMMdd`` range string or a ``start-end`` days-ago pair
(the GAME driver's --train-date-range / --train-date-range-days-ago flags,
cli/game/training/Params.scala).
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import os
from typing import Optional, Sequence


@dataclasses.dataclass(frozen=True)
class DateRange:
    """Inclusive [start, end] day range (util/DateRange.scala:27)."""

    start: _dt.date
    end: _dt.date

    def __post_init__(self):
        if self.start > self.end:
            raise ValueError(
                f"Invalid range: start date {self.start} comes after end "
                f"date {self.end}.")

    def __str__(self) -> str:
        return f"{self.start}-{self.end}"

    def days(self) -> list[_dt.date]:
        n = (self.end - self.start).days
        return [self.start + _dt.timedelta(days=i) for i in range(n + 1)]

    @staticmethod
    def from_dates(start: str, end: str,
                   pattern: str = "%Y%m%d") -> "DateRange":
        try:
            s = _dt.datetime.strptime(start, pattern).date()
            e = _dt.datetime.strptime(end, pattern).date()
        except ValueError as exc:
            raise ValueError(
                f"Couldn't parse the date range: {start}-{end}") from exc
        return DateRange(s, e)  # range-order errors propagate as-is

    @staticmethod
    def from_range(range_str: str,
                   pattern: str = "%Y%m%d") -> "DateRange":
        """``yyyyMMdd-yyyyMMdd`` (DateRange.fromDateString analog)."""
        parts = range_str.split("-")
        if len(parts) != 2:
            raise ValueError(
                f"Couldn't parse the date range: {range_str!r} (expected "
                f"'yyyyMMdd-yyyyMMdd')")
        return DateRange.from_dates(parts[0], parts[1], pattern)

    @staticmethod
    def from_days_ago(start_days_ago: int, end_days_ago: int,
                      today: Optional[_dt.date] = None) -> "DateRange":
        """``start-end`` days-ago pair → concrete range
        (util/DateRange.fromDaysAgo analog; start is further back)."""
        today = today or _dt.date.today()
        return DateRange(today - _dt.timedelta(days=start_days_ago),
                         today - _dt.timedelta(days=end_days_ago))

    @staticmethod
    def from_days_ago_range(range_str: str,
                            today: Optional[_dt.date] = None) -> "DateRange":
        parts = range_str.split("-")
        if len(parts) != 2:
            raise ValueError(
                f"Couldn't parse the days-ago range: {range_str!r} "
                f"(expected 'start-end')")
        return DateRange.from_days_ago(int(parts[0]), int(parts[1]), today)


def input_paths_within_date_range(
        input_dirs: Sequence[str] | str,
        date_range: DateRange,
        error_on_missing: bool = False) -> list[str]:
    """``<base>/daily/yyyy/MM/dd`` paths within the range
    (util/IOUtils.scala:85-126). Missing days are skipped unless
    ``error_on_missing``; an entirely empty result raises."""
    if isinstance(input_dirs, str):
        input_dirs = [input_dirs]
    out: list[str] = []
    for base in input_dirs:
        daily = os.path.join(base, "daily")
        candidates = [
            os.path.join(daily, f"{d.year:04d}", f"{d.month:02d}",
                         f"{d.day:02d}")
            for d in date_range.days()]
        if error_on_missing:
            for p in candidates:
                if not os.path.exists(p):
                    raise FileNotFoundError(f"Path {p} does not exist!")
        existing = [p for p in candidates if os.path.exists(p)]
        if not existing:
            raise FileNotFoundError(
                f"No data folder found between {date_range.start} and "
                f"{date_range.end} in {daily}")
        out.extend(existing)
    return out


def resolve_input_paths(
        input_dirs: str,
        date_range: Optional[str] = None,
        date_range_days_ago: Optional[str] = None,
        today: Optional[_dt.date] = None) -> list[str]:
    """GAME driver flag resolution: comma-separated input dirs, optionally
    narrowed by --*-date-range / --*-date-range-days-ago (the two flags are
    mutually exclusive, cli/game/training/Params.scala)."""
    dirs = [d for d in input_dirs.split(",") if d.strip()]
    if date_range and date_range_days_ago:
        raise ValueError(
            "date-range and date-range-days-ago are mutually exclusive")
    if date_range:
        return input_paths_within_date_range(
            dirs, DateRange.from_range(date_range))
    if date_range_days_ago:
        return input_paths_within_date_range(
            dirs, DateRange.from_days_ago_range(date_range_days_ago,
                                                today))
    return dirs
