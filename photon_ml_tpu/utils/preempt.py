"""Cooperative preemption: the stop flag and every source that sets it.

Real fleets rarely kill a trainer outright — they SIGTERM it with a
grace window (preemption), or enforce a wall-clock quota, or ask it to
step aside via an out-of-band file. All three reduce to the same
contract here: a :class:`StopController` owns one sticky stop flag, and
``run_coordinate_descent`` polls it ONLY at commit barriers (raw block
boundaries — the same places snapshots are legal), resolves any
in-flight pipelined handle, takes a final snapshot, and raises
:class:`PreemptionRequested`. The driver turns that into a
``PHOTON_PREEMPTED step=<sweep>.<coord>`` line, ``run_end
{status: "preempted"}``, and the documented requeue exit code
(``cli.PREEMPTED_EXIT``) — and a resume from the final snapshot is
bit-exact vs the uninterrupted run, exactly like crash resume.

Sources, in polling order:

- **explicit** — ``request_stop(reason)``, used by the signal handlers
  (SIGTERM/SIGINT set the flag; a SECOND delivery of the same signal
  restores the previous disposition and re-raises it, so a stuck run
  can still be forced down);
- **deadline** — ``max_train_seconds`` measured on a monotonic clock
  from controller construction (the driver builds it at startup, so
  the budget covers ingest + compile, like a scheduler quota does);
- **stop file** — existence of ``stop_file``, stat'ed at most every
  :data:`STOP_FILE_POLL_SECS` so the hot loop never pays a per-block
  filesystem round trip.

The CD loop accepts ANY object with a ``should_stop() -> str | None``
method — tests drive deterministic stops with a counter fake.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Optional

# Minimum seconds between stop-file stat() calls: a commit barrier can
# arrive every few milliseconds on small sweeps and the flag is advisory
# anyway — one pending poll per quarter second is plenty responsive.
STOP_FILE_POLL_SECS = 0.25


class PreemptionRequested(Exception):
    """A stop source fired and the CD loop reached a commit barrier:
    the final snapshot (when checkpointing is on) is already written by
    the time this propagates. ``sweep``/``coordinate_index`` name the
    NEXT unit of work — the exact resume point, same convention as the
    snapshot schema's "about to run this coordinate"."""

    def __init__(self, reason: str, sweep: int, coordinate_index: int):
        self.reason = reason
        self.sweep = int(sweep)
        self.coordinate_index = int(coordinate_index)
        super().__init__(
            f"preemption requested ({reason}) at step {self.step}")

    @property
    def step(self) -> str:
        """``<sweep>.<coord>`` — the greppable position format shared
        with fault tags and the ``PHOTON_PREEMPTED`` line."""
        return f"{self.sweep}.{self.coordinate_index}"


class StopController:
    """One sticky stop flag fed by signals, a wall-clock deadline, and
    a cooperative stop file; polled by the training loop at commit
    barriers via :meth:`should_stop`."""

    def __init__(self, max_train_seconds: Optional[float] = None,
                 stop_file: Optional[str] = None,
                 clock=time.monotonic):
        self._clock = clock
        self._event = threading.Event()
        self._reason: Optional[str] = None
        self._lock = threading.Lock()
        self._deadline = (clock() + float(max_train_seconds)
                          if max_train_seconds and max_train_seconds > 0
                          else None)
        self._stop_file = stop_file or None
        self._next_file_poll = clock()  # first poll is free
        self._prev_handlers: dict[int, object] = {}

    # -- flag -----------------------------------------------------------

    def request_stop(self, reason: str) -> None:
        """Latch the flag (first reason wins; later calls are no-ops).
        Safe from signal handlers and other threads."""
        with self._lock:
            if self._reason is None:
                self._reason = reason
        self._event.set()

    @property
    def stop_requested(self) -> bool:
        return self._event.is_set()

    def should_stop(self) -> Optional[str]:
        """The poll the CD loop runs at every commit barrier: returns
        the stop reason, or None to keep training. Checks the latched
        flag first (free), then the deadline (one clock read), then the
        stop file (throttled stat)."""
        if self._event.is_set():
            return self._reason
        now = self._clock()
        if self._deadline is not None and now >= self._deadline:
            self.request_stop("deadline:max_train_seconds")
            return self._reason
        if self._stop_file is not None and now >= self._next_file_poll:
            self._next_file_poll = now + STOP_FILE_POLL_SECS
            if os.path.exists(self._stop_file):
                self.request_stop(f"stop_file:{self._stop_file}")
                return self._reason
        return None

    # -- signals --------------------------------------------------------

    def install_signal_handlers(
            self, signums=(signal.SIGTERM, signal.SIGINT)) -> None:
        """Route SIGTERM/SIGINT into the stop flag. A SECOND delivery of
        the same signal restores the previous disposition and re-raises
        it — the escape hatch when the run never reaches a barrier (the
        supervisor's SIGTERM→grace→SIGKILL ladder relies on kill; an
        operator at a terminal gets the familiar double-Ctrl-C)."""
        for signum in signums:
            self._prev_handlers[signum] = signal.getsignal(signum)
            signal.signal(signum, self._on_signal)

    def _on_signal(self, signum, frame) -> None:
        if self._event.is_set():
            prev = self._prev_handlers.get(signum, signal.SIG_DFL)
            signal.signal(signum, prev)
            os.kill(os.getpid(), signum)
            return
        self.request_stop(f"signal:{signal.Signals(signum).name}")

    def uninstall_signal_handlers(self) -> None:
        """Restore the dispositions saved by
        :meth:`install_signal_handlers` (tests and the bench probe run
        controllers in-process, back to back)."""
        while self._prev_handlers:
            signum, prev = self._prev_handlers.popitem()
            signal.signal(signum, prev)
