"""Training event bus: emitter + listeners with typed event classes.

Re-design of the reference's event system (reference: photon-ml/src/main/
scala/com/linkedin/photon/ml/event/): ``EventEmitter`` trait mixed into the
legacy driver (Driver.scala:110-119 registers listeners by class name from
``--event-listeners``), ``Event`` case classes (Event.scala:27-66):
PhotonSetupEvent, TrainingStartEvent, TrainingFinishEvent,
PhotonOptimizationLogEvent (carrying per-model trackers + metrics).
"""

from __future__ import annotations

import dataclasses
import importlib
import threading
from typing import Any, Callable, Optional


@dataclasses.dataclass(frozen=True)
class Event:
    """event/Event.scala base."""


@dataclasses.dataclass(frozen=True)
class PhotonSetupEvent(Event):
    log_dir: str
    input_path: str
    params_summary: str


@dataclasses.dataclass(frozen=True)
class TrainingStartEvent(Event):
    timestamp: float


@dataclasses.dataclass(frozen=True)
class TrainingFinishEvent(Event):
    timestamp: float


@dataclasses.dataclass(frozen=True)
class PhotonOptimizationLogEvent(Event):
    """Per-model optimization record (Event.scala:60-66): the regularization
    weight, the optimizer state history, and validation metrics if any."""

    regularization_weight: float
    states: Any  # OptimizationResult / tracker
    metrics: Optional[dict[str, float]] = None
    # Metrics of every per-iteration model snapshot when the driver ran
    # with --validate-per-iteration (Event.scala:60-66 perIterationMetrics).
    per_iteration_metrics: Optional[list[dict[str, float]]] = None


@dataclasses.dataclass(frozen=True)
class FaultEvent(Event):
    """A detected (or injected) fault: non-finite objective/state, an
    exception out of a coordinate update, a failed checkpoint write. The
    robustness layer's observable record (no reference analog — Spark's
    lineage recovery was silent)."""

    point: str  # fault-point name, e.g. "cd.update"
    coordinate_id: Optional[str] = None
    iteration: Optional[int] = None
    message: str = ""


@dataclasses.dataclass(frozen=True)
class RecoveryEvent(Event):
    """The recovery action taken for a fault: ``retried`` (re-ran the
    update from last-good state), ``recovered`` (a retry produced a finite
    state), ``skipped`` (kept last-good and moved on, degraded), or
    ``aborted`` (policy exhausted)."""

    action: str
    coordinate_id: Optional[str] = None
    iteration: Optional[int] = None
    attempts: int = 0
    message: str = ""


@dataclasses.dataclass(frozen=True)
class CoordinateQuarantinedEvent(Event):
    """A coordinate exhausted its per-coordinate failure budget
    (``RecoveryPolicy.quarantine_after``) and is frozen at its last-good
    state for the rest of the run; the other coordinates keep descending.
    The chronically-diverging-coordinate terminal record — one bad
    coordinate no longer burns the global retry budget or aborts the
    run."""

    coordinate_id: str
    iteration: int
    failures: int
    message: str = ""


@dataclasses.dataclass(frozen=True)
class ShardQuarantinedEvent(Event):
    """A data shard was skipped by the degraded-ingest layer
    (``photon_ml_tpu/data/ingest.py``): corrupt, truncated, or
    persistently unreadable after retries. Training continues on the
    surviving shards; the recorded coverage fraction and the
    ``--max-shard-loss-frac`` threshold decide whether the run is
    allowed to proceed degraded or must abort cleanly."""

    path: str
    stage: str  # "open" | "decode" | "index"
    reason: str = ""


EventListener = Callable[[Event], None]

_ERROR_LOGGER = None


def _error_logger():
    """Module-level fallback logger for contained listener failures
    (stderr-only; created lazily so importing this module stays cheap)."""
    global _ERROR_LOGGER
    if _ERROR_LOGGER is None:
        from photon_ml_tpu.utils.logging import PhotonLogger

        _ERROR_LOGGER = PhotonLogger(log_path=None, echo=True)
    return _ERROR_LOGGER


class EventEmitter:
    """event/EventEmitter.scala analog: registration + locked dispatch."""

    def __init__(self):
        self._listeners: list[EventListener] = []
        self._lock = threading.Lock()

    def register_listener(self, listener: EventListener) -> None:
        with self._lock:
            self._listeners.append(listener)

    def register_listener_by_name(self, qualified_name: str) -> None:
        """Instantiate a listener from ``module.Class`` / ``module.func``
        (the reference's --event-listeners class-name injection,
        Driver.scala:110-118)."""
        module_name, _, attr = qualified_name.rpartition(".")
        if not module_name:
            raise ValueError(
                f"listener name {qualified_name!r} must be module-qualified")
        obj = getattr(importlib.import_module(module_name), attr)
        listener = obj() if isinstance(obj, type) else obj
        self.register_listener(listener)

    def send_event(self, event: Event) -> None:
        """Dispatch ``event`` to every listener. A listener exception is
        CONTAINED: it is logged (utils/logging) and counted on the
        ``listener_errors`` metric instead of propagating into the
        training loop that emitted the event — a broken log shipper must
        not kill a multi-hour run — and the remaining listeners still
        run."""
        with self._lock:
            listeners = list(self._listeners)
        for listener in listeners:
            try:
                listener(event)
            except Exception as e:  # noqa: BLE001 — containment is the point
                from photon_ml_tpu.obs.metrics import REGISTRY

                name = getattr(listener, "__qualname__",
                               type(listener).__name__)
                REGISTRY.counter("listener_errors").inc(listener=name)
                _error_logger().warn(
                    f"event listener {name!r} raised on "
                    f"{type(event).__name__}: {e!r} (contained)")

    def clear_listeners(self) -> None:
        with self._lock:
            self._listeners.clear()
