"""Deadline/backoff retry combinator with deterministic jitter.

The graceful-degradation layer's lowest brick: every I/O the trainer
cannot afford to die on (checkpoint writes, Avro shard reads, index-map
loads, trace export) goes through :func:`call_with_retry`, which retries
TRANSIENT failures (``OSError`` and the drillable
:class:`~photon_ml_tpu.utils.faults.InjectedFault`) with exponential
backoff and gives up into :class:`RetryExhaustedError` — the typed
signal the quarantine/clean-abort layers above dispatch on. Permanent
failures (``ValueError`` from a corrupt decode, say) propagate on the
first attempt; retrying a deterministic error only burns the deadline.

Determinism: the jitter is a keyed blake2b hash of
``(seed, site, attempt)`` — two processes (or two runs) retrying the
same site walk the identical delay sequence, so a chaos drill's timing
is replayable and a test can assert the exact schedule
(:func:`backoff_delays`).

Observability: each RETRY (not the first attempt — the common path pays
nothing) increments ``retries{site=...}`` on the metrics registry and
runs under a ``retry.attempt`` span, so ``metrics.jsonl`` answers "which
I/O site is flaky and how hard are we working around it".
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from typing import Callable, Optional, TypeVar

from photon_ml_tpu.utils.faults import InjectedFault

# NOTE: the obs imports (trace span + retries counter) live inside
# call_with_retry's RETRY path, not at module level — obs/run.py imports
# this module, and the first attempt (the only hot path) needs neither.

T = TypeVar("T")


class RetryExhaustedError(RuntimeError):
    """A retried operation failed every attempt (or hit its deadline).

    Carries the last underlying exception as ``__cause__`` plus the
    ``site``/``attempts`` the failure burned — the typed terminal signal
    the degraded-ingest quarantine and the drivers' clean-abort path
    dispatch on (never a bare stack-trace crash)."""

    def __init__(self, site: str, attempts: int, last: BaseException,
                 deadline_hit: bool = False):
        why = "deadline exceeded" if deadline_hit else "attempts exhausted"
        super().__init__(
            f"{site}: {why} after {attempts} attempt(s); "
            f"last error: {last!r}")
        self.site = site
        self.attempts = attempts
        self.last = last
        self.deadline_hit = deadline_hit


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded-retry schedule: ``max_attempts`` tries, exponential
    backoff from ``base_delay_seconds`` capped at ``max_delay_seconds``,
    an optional wall-clock ``deadline_seconds`` over the WHOLE call
    (sleeps included), and the exception classes worth retrying."""

    max_attempts: int = 4
    base_delay_seconds: float = 0.02
    max_delay_seconds: float = 1.0
    deadline_seconds: Optional[float] = None
    retry_on: tuple = (OSError, InjectedFault)
    # Subclasses of retry_on that are PERMANENT anyway: a missing path
    # stays missing — retrying only burns the deadline and rewraps a
    # clear FileNotFoundError callers (and tests) dispatch on.
    permanent_on: tuple = (FileNotFoundError,)
    seed: int = 0


#: The package default: 4 attempts, ~20/40/80 ms jittered backoff. I/O
#: call sites share it so the worst-case stall per shard stays bounded
#: well under a second.
DEFAULT_POLICY = RetryPolicy()


def _jitter_factor(seed: int, site: str, attempt: int) -> float:
    """Deterministic jitter in [0.5, 1.0): keyed hash, not a PRNG, so
    the sequence depends only on (seed, site, attempt)."""
    key = f"{seed}:{site}:{attempt}".encode("utf-8")
    h = int.from_bytes(hashlib.blake2b(key, digest_size=8).digest(), "big")
    return 0.5 + (h / 2.0 ** 64) * 0.5


def backoff_delays(site: str, policy: RetryPolicy = DEFAULT_POLICY
                   ) -> list[float]:
    """The exact sleep schedule ``call_with_retry`` walks for ``site``:
    ``min(base * 2^n, max) * jitter(seed, site, n)`` for each retry slot
    (one fewer than ``max_attempts``). Deterministic — tests assert it
    verbatim."""
    out = []
    for attempt in range(max(policy.max_attempts - 1, 0)):
        raw = min(policy.base_delay_seconds * (2.0 ** attempt),
                  policy.max_delay_seconds)
        out.append(raw * _jitter_factor(policy.seed, site, attempt))
    return out


def call_with_retry(fn: Callable[[], T], site: str,
                    policy: RetryPolicy = DEFAULT_POLICY,
                    warn: Optional[Callable[[str], None]] = None) -> T:
    """Run ``fn`` with the retry protocol for ``site``.

    - an exception NOT in ``policy.retry_on`` propagates immediately
      (permanent failures don't burn the schedule);
    - retryable failures sleep the deterministic backoff and re-run,
      incrementing ``retries{site=...}`` and opening a ``retry.attempt``
      span per retry;
    - when attempts (or the deadline) run out the last error is wrapped
      in :class:`RetryExhaustedError`.
    """
    from photon_ml_tpu.obs import trace
    from photon_ml_tpu.obs.metrics import REGISTRY

    t0 = time.monotonic()
    delays = backoff_delays(site, policy)
    attempt = 0
    while True:
        try:
            if attempt == 0:
                return fn()
            with trace.span("retry.attempt", site=site, attempt=attempt):
                return fn()
        except policy.retry_on as e:
            if isinstance(e, policy.permanent_on):
                raise
            attempt += 1
            if attempt >= policy.max_attempts:
                raise RetryExhaustedError(site, attempt, e) from e
            delay = delays[attempt - 1]
            if (policy.deadline_seconds is not None
                    and time.monotonic() - t0 + delay
                    > policy.deadline_seconds):
                raise RetryExhaustedError(site, attempt, e,
                                          deadline_hit=True) from e
            REGISTRY.counter("retries").inc(site=site)
            if warn is not None:
                warn(f"{site}: attempt {attempt} failed ({e!r}); "
                     f"retrying in {delay * 1e3:.0f} ms")
            time.sleep(delay)
