"""Deterministic fault injection: named fault points with scripted failures.

The reference inherited fault tolerance from Spark's lineage-based task
retry (SURVEY §1, §5.4) and never had to test its own failure paths; the
multi-controller JAX port owns every failure mode itself, so it needs a
way to script them reproducibly. This module is the single switchboard:
production code calls :func:`fault_point` at named sites and tests (or an
operator drilling a cluster) arm failures against those names.

The fault points instrumented in the codebase are enumerated in
:data:`FAULT_POINTS` (the machine-readable registry the chaos campaign
``tools/chaos_drill.py`` sweeps) and documented row-for-row in the README
``PHOTON_FAULTS`` table, which photonlint W401/W402 keeps in sync with
the call sites in both directions.

Modes:

- ``raise``    — raise :class:`InjectedFault` (a transient stand-in the
                 retry layer in ``utils/retry.py`` recovers from)
- ``nan``      — poison the float arrays passed to the point
- ``delay``    — sleep ``arg`` seconds (default 1.0)
- ``slow``     — sleep like ``delay`` but with a small default (0.05s):
                 the "laggy NFS" drill for I/O sites
- ``corrupt``  — flip bytes in the middle of the file/dir passed to the
                 point
- ``partial``  — truncate the file/dir passed to the point to half its
                 size (a torn write)
- ``kill``     — ``os._exit(arg)`` (default 17)
- ``signal``   — ``os.kill(os.getpid(), SIGTERM)``: the preemption
                 drill. Unlike ``kill`` the process is NOT scripted
                 dead — the driver's graceful-stop handler latches its
                 stop flag, ``fault_point`` returns, and training runs
                 on to the next commit barrier where it snapshots and
                 exits with the documented preempted code
- ``io_error`` — raise ``OSError(EIO)`` (retryable I/O failure)
- ``enospc``   — raise ``OSError(ENOSPC)`` (disk full)
- ``flaky``    — probabilistic ``OSError(EIO)``: each VISIT to the point
                 fires with probability ``arg`` (default 0.5), decided by
                 a deterministic hash of (``PHOTON_FAULTS_SEED``, point,
                 tag, visit index) — the same seed reproduces the same
                 firing pattern in every process, so a flaky-I/O drill is
                 replayable bit-for-bit

Arming:

- programmatic: ``arm("cd.update", "raise", times=2)``
- environment:  ``PHOTON_FAULTS="worker.start@0=kill:1;ckpt.save=raise:1"``
  — ``point[@tag]=mode[:times[:arg]]``, ``;``-separated. ``times`` bounds
  total firings (default 1); ``arg`` is seconds for ``delay``/``slow``,
  the exit code for ``kill``, and the firing probability for ``flaky``.
  A ``@tag`` suffix restricts the spec to call sites passing that ``tag``
  (e.g. the multi-host process id), so one shared environment can target
  a single worker of a gang.

Cross-process accounting: when ``PHOTON_FAULTS_STATE_DIR`` is set, each
firing atomically claims a marker file there (``O_CREAT|O_EXCL``), so a
``times=1`` kill fires in exactly one process incarnation even after a
supervisor relaunches the worker with the same environment — the property
the gang-restart tests depend on.
"""

from __future__ import annotations

import dataclasses
import errno
import hashlib
import os
import threading
import time
from typing import Any, Optional

ENV_SPECS = "PHOTON_FAULTS"
ENV_STATE_DIR = "PHOTON_FAULTS_STATE_DIR"
ENV_SEED = "PHOTON_FAULTS_SEED"

MODES = ("raise", "nan", "delay", "slow", "corrupt", "partial", "kill",
         "signal", "io_error", "enospc", "flaky")


@dataclasses.dataclass(frozen=True)
class FaultPointInfo:
    """One registered fault site: where it fires and which modes make
    sense there (the chaos campaign sweeps ``point × modes``)."""

    description: str
    modes: tuple[str, ...]
    has_path: bool = False  # the site passes a file/dir (corrupt/partial)
    multihost_only: bool = False


#: The registry of drillable fault points. tools/chaos_drill.py sweeps
#: this table; the README PHOTON_FAULTS table documents it row-for-row
#: (photonlint W401/W402 reconciles table ⇄ call sites, and
#: tests/test_chaos.py reconciles table ⇄ this registry).
FAULT_POINTS: dict[str, FaultPointInfo] = {
    "cd.update": FaultPointInfo(
        "after each coordinate-descent coordinate update "
        "(game/coordinate_descent.py); tag <sweep>.<coordinate_index>",
        modes=("raise", "nan", "delay", "kill", "signal")),
    "cd.sweep": FaultPointInfo(
        "at the top of each CD sweep (single-process and multi-host "
        "loops); tag = sweep index",
        modes=("delay", "kill", "signal")),
    "optimizer.gradient": FaultPointInfo(
        "on the solver output of a GLM solve (optimize/problem.py)",
        modes=("raise", "nan")),
    "re.shard_dispatch": FaultPointInfo(
        "on the coefficient block of a mesh-sharded random-effect solve, "
        "after the sharded dispatch resolves (game/random_effect.py); "
        "tag = bucket index",
        modes=("raise", "nan")),
    "ckpt.save": FaultPointInfo(
        "after a snapshot's tmp dir is written, before the atomic "
        "rename (utils/checkpoint.py)",
        modes=("raise", "kill", "corrupt"), has_path=True),
    "ckpt.restore": FaultPointInfo(
        "on the snapshot about to be read, before it is read "
        "(utils/checkpoint.py)",
        modes=("raise", "corrupt"), has_path=True),
    "ckpt.write_bytes": FaultPointInfo(
        "after the snapshot's array payload is written, before it is "
        "checksummed (utils/checkpoint.py)",
        modes=("io_error", "enospc", "flaky", "partial", "kill",
               "signal"),
        has_path=True),
    "io.shard_open": FaultPointInfo(
        "before an Avro shard's bytes are opened/read (io/avro.py "
        "interpreted reader AND io/native_avro.py native reader); tag = "
        "shard basename",
        modes=("raise", "io_error", "flaky", "slow", "delay")),
    "io.avro_read": FaultPointInfo(
        "per shard at decode time in the part-iteration loops "
        "(io/avro.py read_directory, io/data_format.py GAME ingest); "
        "tag = shard basename; corrupt/partial mutate the shard on disk",
        modes=("raise", "io_error", "corrupt", "partial", "flaky"),
        has_path=True),
    "io.index_map": FaultPointInfo(
        "on a feature index-map load (io/index_map.py IndexMap.load / "
        "OffHeapIndexMap, io/data_format.py NameAndTermFeatureSets.load)",
        modes=("raise", "io_error", "flaky", "slow")),
    "obs.flush": FaultPointInfo(
        "before the observability layer appends spans/metrics to the "
        "trace dir (obs/run.py)",
        modes=("io_error", "enospc", "flaky")),
    "obs.export": FaultPointInfo(
        "on the telemetry exporter's writer thread, before each "
        "connect/write of a record batch to the --telemetry-endpoint "
        "consumer (obs/export.py); a batch that exhausts its retries "
        "is dropped and counted on telemetry_dropped, never blocks "
        "training",
        modes=("io_error", "slow", "flaky")),
    "obs.otlp": FaultPointInfo(
        "in the OTLP bridge before each HTTP POST of a converted "
        "trace/metric batch to the collector (obs/otlp.py, driven by "
        "tools/otlp_bridge.py); a failed POST is dropped and counted "
        "on telemetry_dropped{kind=otlp} — the bridge (and the run it "
        "watches) always exits clean",
        modes=("io_error", "slow", "flaky")),
    "worker.start": FaultPointInfo(
        "in a multi-host worker right after jax.distributed.initialize "
        "(parallel/multihost.py); tag = process id",
        modes=("raise", "kill", "delay"), multihost_only=True),
    "serve.request": FaultPointInfo(
        "in a scoring-service connection thread, per decoded request "
        "before dispatch (serve/service.py); tag = request kind. "
        "Connection-scoped: a firing fails THAT request/connection with "
        "an error response — the service keeps serving",
        modes=("raise", "io_error", "delay", "flaky")),
    "serve.batch": FaultPointInfo(
        "in the scoring-service device loop, per micro-batch before "
        "scoring (serve/service.py); tag = batch request count. "
        "raise aborts the service cleanly; io_error fails that batch's "
        "requests with error responses (the service keeps serving); "
        "signal drains and exits preempted; kill scripts it dead for "
        "the supervisor-relaunch drill",
        modes=("raise", "io_error", "delay", "kill", "signal")),
    "serve.model_load": FaultPointInfo(
        "in the scoring-service swap loader thread, on the CANDIDATE "
        "model dir before it is read (serve/service.py, off the hot "
        "path, wrapped in utils/retry); tag = requested model id; path "
        "= the candidate's first coefficient artifact. io_error retries "
        "then refuses; corrupt flips bytes in the candidate so the load "
        "(or the canary) refuses the swap — the service keeps serving "
        "the current generation either way; slow stalls only the "
        "loader thread, never live scoring",
        modes=("io_error", "corrupt", "slow", "kill"), has_path=True),
    "serve.swap": FaultPointInfo(
        "in the scoring-service device loop, at the atomic generation "
        "flip after the canary gate passes (serve/service.py); tag = "
        "candidate generation; path = the candidate's first coefficient "
        "artifact. io_error refuses the flip (the old generation keeps "
        "serving); slow stalls the flip (SIGTERM during the stall still "
        "drains and exits 75); kill dies mid-flip for the "
        "supervisor-relaunch drill (the relaunch serves exactly one "
        "consistent generation); corrupt flips candidate bytes on disk "
        "AFTER load — the flip is insensitive, it serves from memory",
        modes=("io_error", "corrupt", "slow", "kill"), has_path=True),
    "serve.route": FaultPointInfo(
        "in a scorer FLEET MEMBER's connection thread, per routed "
        "sub-request arriving over a member-role connection from the "
        "fleet router (serve/service.py; the router's dispatch path is "
        "serve/fleet.py); tag = the member's fleet index. raise/"
        "io_error/flaky fail THAT sub-request with an error response — "
        "the router retries through utils/retry, fails over to the "
        "shard's fallback member, or sheds typed; slow stalls the "
        "sub-request inside the router's member timeout; kill dies the "
        "member mid-request for the no-black-hole drill (every "
        "in-flight request must still get a reply or a typed shed, and "
        "the supervised relaunch re-admits only on the live generation)",
        modes=("raise", "io_error", "delay", "slow", "flaky", "kill")),
}


class InjectedFault(RuntimeError):
    """Raised by a ``raise``-mode fault point (and by mis-armed specs)."""

    def __init__(self, point: str, message: str = ""):
        super().__init__(message or f"injected fault at {point!r}")
        self.point = point


@dataclasses.dataclass
class FaultSpec:
    """One armed failure: fires at ``point`` up to ``times`` times.

    ``probability`` only matters for ``flaky``: each VISIT decides
    independently (and deterministically, see :func:`flaky_decision`)
    whether to fire; ``times`` still bounds the total firings."""

    point: str
    mode: str
    times: int = 1
    tag: Optional[str] = None  # only fire for matching fault_point(tag=...)
    # None = mode default (1.0s for delay, 0.05s for slow) — a sentinel,
    # not a magic value, so an EXPLICIT 1.0s slow drill stays 1.0s
    delay_seconds: Optional[float] = None
    exit_code: int = 17
    probability: float = 0.5
    fired: int = 0
    visits: int = 0  # flaky-mode visit counter (the decision index)

    def __post_init__(self):
        if self.mode not in MODES:
            raise ValueError(f"unknown fault mode {self.mode!r}; "
                             f"expected one of {MODES}")
        if self.delay_seconds is None:
            self.delay_seconds = 0.05 if self.mode == "slow" else 1.0
        if not 0.0 <= self.probability <= 1.0:
            raise ValueError(
                f"flaky probability must be in [0, 1], "
                f"got {self.probability}")


def flaky_decision(seed: int, point: str, tag: Optional[str],
                   visit: int, probability: float) -> bool:
    """Deterministic per-visit firing decision for ``flaky`` mode: a
    keyed blake2b hash of (seed, point, tag, visit) mapped to [0, 1) and
    compared against ``probability`` — the same seed reproduces the same
    firing pattern in every process that visits the point the same
    number of times (the replayability contract the flaky-I/O drills
    depend on)."""
    if probability <= 0.0:
        return False
    if probability >= 1.0:
        return True
    key = f"{seed}:{point}:{tag or ''}:{visit}".encode("utf-8")
    h = int.from_bytes(
        hashlib.blake2b(key, digest_size=8).digest(), "big")
    return (h / 2.0 ** 64) < probability


class FaultRegistry:
    """Thread-safe registry of armed specs + per-point hit counters."""

    def __init__(self):
        self._lock = threading.Lock()
        self._specs: list[FaultSpec] = []
        self._hits: dict[str, int] = {}
        self._env_loaded = False

    # -- arming ------------------------------------------------------------

    def arm(self, point: str, mode: str, times: int = 1,
            tag: Optional[str] = None,
            delay_seconds: Optional[float] = None,
            exit_code: int = 17, probability: float = 0.5) -> FaultSpec:
        spec = FaultSpec(point=point, mode=mode, times=times, tag=tag,
                         delay_seconds=delay_seconds, exit_code=exit_code,
                         probability=probability)
        with self._lock:
            self._specs.append(spec)
        return spec

    def disarm_all(self) -> None:
        with self._lock:
            self._specs.clear()
            self._hits.clear()
            # a later env change (tests monkeypatching PHOTON_FAULTS) must
            # be re-read after an explicit reset
            self._env_loaded = False

    def hits(self, point: str) -> int:
        with self._lock:
            return self._hits.get(point, 0)

    # -- env parsing -------------------------------------------------------

    def _ensure_env_loaded(self) -> None:
        with self._lock:
            if self._env_loaded:
                return
            self._env_loaded = True
            raw = os.environ.get(ENV_SPECS, "")
        for spec in parse_fault_specs(raw):
            with self._lock:
                self._specs.append(spec)

    # -- firing ------------------------------------------------------------

    def _claim(self, spec: FaultSpec) -> bool:
        """Reserve one firing of ``spec``; False when its budget is spent.

        With a state dir the budget is shared across processes via
        exclusive-create marker files; otherwise it is per-process.
        """
        state_dir = os.environ.get(ENV_STATE_DIR)
        if not state_dir:
            with self._lock:
                if spec.fired >= spec.times:
                    return False
                spec.fired += 1
                return True
        os.makedirs(state_dir, exist_ok=True)
        # the key carries the FULL spec identity (not just point+mode):
        # two distinct specs on the same point must not contend for the
        # same markers and silently starve one another's budget
        key = "_".join(str(p) for p in (
            spec.point, spec.tag or "", spec.mode, spec.times,
            spec.delay_seconds, spec.exit_code,
            spec.probability)).replace(os.sep, "_")
        for n in range(spec.times):
            marker = os.path.join(state_dir, f"{key}.{n}")
            try:
                fd = os.open(marker, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.write(fd, str(os.getpid()).encode())
                os.close(fd)
                with self._lock:
                    spec.fired += 1
                return True
            except FileExistsError:
                continue
        return False

    def fire(self, point: str, tag: Optional[str] = None,
             arrays: Any = None, path: Optional[str] = None) -> Any:
        """Run the fault protocol for ``point``; returns ``arrays``
        (possibly poisoned). See :func:`fault_point`."""
        self._ensure_env_loaded()
        with self._lock:
            specs = [s for s in self._specs
                     if s.point == point and (s.tag is None or s.tag == tag)]
        if not specs:
            return arrays
        for spec in specs:
            if spec.mode == "flaky":
                # the per-visit decision is deterministic in
                # (PHOTON_FAULTS_SEED, point, tag, visit index): same
                # seed → same firing pattern in every process
                with self._lock:
                    visit = spec.visits
                    spec.visits += 1
                seed = int(os.environ.get(ENV_SEED, "0") or 0)
                if not flaky_decision(seed, point, tag, visit,
                                      spec.probability):
                    continue
            if not self._claim(spec):
                continue
            with self._lock:
                self._hits[point] = self._hits.get(point, 0) + 1
            if spec.mode == "raise":
                raise InjectedFault(point)
            if spec.mode in ("io_error", "flaky"):
                raise OSError(errno.EIO,
                              f"injected I/O error at {point!r}")
            if spec.mode == "enospc":
                raise OSError(errno.ENOSPC,
                              f"injected ENOSPC at {point!r}")
            if spec.mode in ("delay", "slow"):
                time.sleep(spec.delay_seconds)
            elif spec.mode == "kill":
                os._exit(spec.exit_code)
            elif spec.mode == "signal":
                # the preemption drill: deliver a real SIGTERM to
                # ourselves. With a graceful-stop handler installed this
                # latches the stop flag and RETURNS — training continues
                # to its next commit barrier; without one, Python's
                # default disposition terminates the process.
                import signal as _signal

                os.kill(os.getpid(), _signal.SIGTERM)
            elif spec.mode == "nan":
                arrays = poison_arrays(arrays)
            elif spec.mode in ("corrupt", "partial"):
                if path is None:
                    raise InjectedFault(
                        point, f"{spec.mode}-mode fault at {point!r} "
                               f"needs a path at the call site")
                (corrupt_path if spec.mode == "corrupt"
                 else truncate_path)(path)
        return arrays


def parse_fault_specs(raw: str) -> list[FaultSpec]:
    """Parse the ``PHOTON_FAULTS`` syntax (see module docstring)."""
    specs = []
    for item in raw.split(";"):
        item = item.strip()
        if not item:
            continue
        name, _, rhs = item.partition("=")
        if not rhs:
            raise ValueError(f"bad fault spec {item!r}: expected "
                             f"point[@tag]=mode[:times[:arg]]")
        point, _, tag = name.partition("@")
        parts = rhs.split(":")
        mode = parts[0]
        times = int(parts[1]) if len(parts) > 1 and parts[1] else 1
        kwargs: dict[str, Any] = {}
        if len(parts) > 2 and parts[2]:
            if mode in ("delay", "slow"):
                kwargs["delay_seconds"] = float(parts[2])
            elif mode == "kill":
                kwargs["exit_code"] = int(parts[2])
            elif mode == "flaky":
                kwargs["probability"] = float(parts[2])
        specs.append(FaultSpec(point=point.strip(), mode=mode, times=times,
                               tag=tag or None, **kwargs))
    return specs


def poison_arrays(arrays: Any) -> Any:
    """NaN-fill every FLOAT array leaf of a (possibly nested) structure;
    scalars and None pass through untouched. Integer/bool leaves are left
    intact rather than silently filled with a finite sentinel
    (``full_like(int_arr, nan)`` yields INT_MIN, which would evade every
    is-finite divergence guard and corrupt state without tripping
    recovery)."""
    import numpy as np

    if arrays is None:
        return None
    if isinstance(arrays, dict):
        return {k: poison_arrays(v) for k, v in arrays.items()}
    if isinstance(arrays, (list, tuple)):
        out = [poison_arrays(v) for v in arrays]
        return type(arrays)(out)
    if hasattr(arrays, "shape") and hasattr(arrays, "dtype"):
        import jax.numpy as jnp

        # jnp.issubdtype, not np: it also classifies ml_dtypes like
        # bfloat16 as inexact
        if not jnp.issubdtype(arrays.dtype, jnp.inexact):
            return arrays
        if isinstance(arrays, np.ndarray):
            return np.full_like(arrays, np.nan)
        return jnp.full_like(arrays, jnp.nan)
    return arrays


def truncate_path(path: str) -> None:
    """Truncate ``path`` (a file) to half its size, or every regular file
    under it (a directory) — the torn/partial-write primitive the
    ``partial`` fault mode and the degraded-ingest drills use."""
    if os.path.isdir(path):
        for name in sorted(os.listdir(path)):
            sub = os.path.join(path, name)
            if os.path.isfile(sub):
                truncate_path(sub)
        return
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.truncate(size // 2)


def corrupt_path(path: str) -> None:
    """Flip bytes in the middle of ``path`` (a file), or of every regular
    file under it (a directory) — the scripted disk-corruption primitive
    the checkpoint-hardening tests drive."""
    if os.path.isdir(path):
        for name in sorted(os.listdir(path)):
            sub = os.path.join(path, name)
            if os.path.isfile(sub):
                corrupt_path(sub)
        return
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.seek(size // 2)
        chunk = fh.read(min(64, max(1, size - size // 2)))
        fh.seek(size // 2)
        fh.write(bytes(b ^ 0xFF for b in chunk))


_REGISTRY = FaultRegistry()


def arm(point: str, mode: str, times: int = 1, tag: Optional[str] = None,
        **kwargs) -> FaultSpec:
    """Arm a fault programmatically (tests); see FaultRegistry.arm."""
    return _REGISTRY.arm(point, mode, times=times, tag=tag, **kwargs)


def disarm_all() -> None:
    _REGISTRY.disarm_all()


def hits(point: str) -> int:
    """How many times faults fired at ``point`` in THIS process."""
    return _REGISTRY.hits(point)


def fault_point(point: str, tag: Optional[str] = None, arrays: Any = None,
                path: Optional[str] = None) -> Any:
    """Declare a named fault site. No-op (returns ``arrays`` unchanged)
    unless a matching spec is armed via :func:`arm` or ``PHOTON_FAULTS``.

    ``arrays`` is the structure a ``nan``-mode fault poisons; ``path`` is
    the file/dir a ``corrupt``-mode fault flips bytes in; ``tag`` lets a
    spec target one caller (e.g. one process id) among many.
    """
    return _REGISTRY.fire(point, tag=tag, arrays=arrays, path=path)
