"""Run-scoped utilities: logging, events, checkpoints, date ranges."""

from __future__ import annotations


def parse_flag(value) -> bool:
    """Parse a CLI boolean flag string the way the reference's Scala drivers
    parse "true"/"false" option values (one shared definition so every
    driver accepts the same spellings)."""
    return str(value).strip().lower() in ("true", "1", "yes")
