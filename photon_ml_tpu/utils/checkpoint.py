"""Mid-training checkpoint/resume for coordinate descent and λ grids.

The reference has NO mid-training checkpointing (SURVEY §5.4) — only
terminal model save/load plus warm starts across the λ grid
(ModelTraining.scala:182-208) and across CD iterations. This module adds the
TPU-idiomatic upgrade the survey prescribes: periodic snapshots of
(coordinate states, CD iteration, λ index) so long runs resume instead of
restart. Format: one directory per step holding a JSON manifest (structure +
scalars) and an ``.npz`` of array leaves — readable without the framework.

Hardened against torn and corrupted writes (the Spark-lineage-free world
owns its own durability): data files carry crc32 checksums in the
manifest, every file is fsync'd before the atomic rename publishes the
step, and :meth:`CheckpointManager.latest_valid_step` verifies integrity
so a restore falls back PAST a truncated/corrupt/partial step dir to the
newest intact one instead of dying on it. Retention never prunes the
last verified snapshot (corrupt newer steps don't garbage-collect the
only intact fallback), and both halves of the durability story are
drillable: ``ckpt.save`` fires before the atomic rename, ``ckpt.restore``
fires on the step about to be read.

API mirrors an orbax CheckpointManager (save/restore/latest_step/all_steps)
without taking the dependency for plain-array states.

Sync discipline: this module is framework-free (numpy only) and
``save()`` host-serializes whatever leaves it is given — a device-array
leaf would be fetched implicitly, one blocking transfer per leaf. Hot-path
callers therefore pre-fetch the WHOLE snapshot with one explicit
``jax.device_get`` of the payload pytree before calling ``save()`` (see
``run_coordinate_descent.save_snapshot``): the checkpoint is the single
designated fetch point for device state, and the one-round-trip hot-loop
contract (game/coordinate_descent.py) stays intact between snapshots.
"""

from __future__ import annotations

import io
import json
import os
import shutil
import zlib
from typing import Any, Optional

import numpy as np

from photon_ml_tpu.obs import trace
from photon_ml_tpu.utils.faults import fault_point, hits as fault_hits

_MANIFEST = "manifest.json"
_ARRAYS = "arrays.npz"
_STEP_PREFIX = "step_"
_TMP_SUFFIX = ".tmp"


class CheckpointCorruptionError(RuntimeError):
    """An explicitly requested step failed integrity verification."""


def _flatten(obj: Any, path: str, arrays: dict[str, np.ndarray]):
    """Structure with array leaves → JSON-able skeleton + array table."""
    if isinstance(obj, dict):
        return {"__kind__": "dict",
                "items": {k: _flatten(v, f"{path}.{k}", arrays)
                          for k, v in obj.items()}}
    if isinstance(obj, (list, tuple)):
        return {"__kind__": "list" if isinstance(obj, list) else "tuple",
                "items": [_flatten(v, f"{path}[{i}]", arrays)
                          for i, v in enumerate(obj)]}
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return {"__kind__": "scalar", "value": obj}
    arr = np.asarray(obj)
    arrays[path] = arr
    return {"__kind__": "array", "key": path, "dtype": str(arr.dtype)}


def _unflatten(spec: Any, arrays: dict[str, np.ndarray]) -> Any:
    kind = spec["__kind__"]
    if kind == "dict":
        return {k: _unflatten(v, arrays) for k, v in spec["items"].items()}
    if kind in ("list", "tuple"):
        items = [_unflatten(v, arrays) for v in spec["items"]]
        return items if kind == "list" else tuple(items)
    if kind == "scalar":
        return spec["value"]
    return arrays[spec["key"]]


def dumps_state(state: Any) -> bytes:
    """Serialize a checkpoint-shaped structure (nested dict/list/tuple with
    scalar and NUMERIC array leaves) to one self-describing byte string —
    the same skeleton+npz format as an on-disk step, zipped in memory. Used
    by the multi-host resume path: process 0 restores the snapshot and
    broadcasts these bytes to the re-formed gang, so every process resumes
    from the identical state without sharing a filesystem."""
    arrays: dict[str, np.ndarray] = {}
    skeleton = _flatten(state, "root", arrays)
    # keys are "root..."-prefixed, so the skeleton entry can't collide
    arrays["__skeleton__"] = np.frombuffer(
        json.dumps(skeleton).encode("utf-8"), dtype=np.uint8)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def loads_state(data: bytes) -> Any:
    """Inverse of :func:`dumps_state`."""
    with np.load(io.BytesIO(data)) as npz:
        arrays = {k: npz[k] for k in npz.files}
    skeleton = json.loads(arrays.pop("__skeleton__").tobytes().decode())
    return _unflatten(skeleton, arrays)


def _file_crc32(path: str) -> str:
    crc = 0
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return f"{crc & 0xFFFFFFFF:08x}"


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # platforms without directory fds
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class CheckpointManager:
    """Step-indexed checkpoint directory with retention + integrity."""

    def __init__(self, directory: str, max_to_keep: Optional[int] = 3):
        self.directory = directory
        self.max_to_keep = max_to_keep
        os.makedirs(directory, exist_ok=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"{_STEP_PREFIX}{step:08d}")

    def all_steps(self) -> list[int]:
        steps = []
        for name in os.listdir(self.directory):
            if name.startswith(_STEP_PREFIX) \
                    and not name.endswith(_TMP_SUFFIX):
                manifest = os.path.join(self.directory, name, _MANIFEST)
                if os.path.exists(manifest):  # ignore partial writes
                    steps.append(int(name[len(_STEP_PREFIX):]))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    # -- integrity ---------------------------------------------------------

    def verify_step(self, step: int) -> bool:
        """True when ``step``'s manifest parses and every checksummed file
        is present with matching crc32. Pre-checksum (v1) step dirs pass
        on file presence alone."""
        d = self._step_dir(step)
        try:
            with open(os.path.join(d, _MANIFEST)) as fh:
                manifest = json.load(fh)
        except (OSError, ValueError):
            return False
        if manifest.get("step") != step or "skeleton" not in manifest:
            return False
        checksums = manifest.get("checksums")
        if checksums is None:  # v1 manifest: presence check only
            return os.path.exists(os.path.join(d, _ARRAYS))
        for name, crc in checksums.items():
            path = os.path.join(d, name)
            try:
                if _file_crc32(path) != crc:
                    return False
            except OSError:
                return False
        return True

    def latest_valid_step(self) -> Optional[int]:
        """Newest step that passes integrity verification, scanning back
        past truncated/corrupt/partial step dirs (the restore entry point
        after an unclean shutdown)."""
        for step in reversed(self.all_steps()):
            if self.verify_step(step):
                return step
        return None

    # -- save/restore ------------------------------------------------------

    def save(self, step: int, state: Any) -> None:
        """Durable and atomic: write + checksum + fsync into a tmp dir,
        then rename; the manifest carries the data files' crc32s."""
        with trace.span("ckpt.save", step=step):
            final = self._step_dir(step)
            tmp = final + _TMP_SUFFIX
            if os.path.exists(tmp):
                shutil.rmtree(tmp)
            os.makedirs(tmp)
            arrays: dict[str, np.ndarray] = {}
            skeleton = _flatten(state, "root", arrays)
            arrays_path = os.path.join(tmp, _ARRAYS)
            np.savez(arrays_path, **arrays)
            _fsync_file(arrays_path)
            # manifest written LAST: its presence marks the step complete
            with open(os.path.join(tmp, _MANIFEST), "w") as fh:
                json.dump({"step": step, "format_version": 2,
                           "checksums": {_ARRAYS: _file_crc32(arrays_path)},
                           "skeleton": skeleton}, fh)
                fh.flush()
                os.fsync(fh.fileno())
            fired_before = fault_hits("ckpt.save")
            fault_point("ckpt.save", path=tmp)
            if os.path.exists(final):
                shutil.rmtree(final)
            os.rename(tmp, final)
            _fsync_dir(self.directory)
            # the bytes just checksummed+fsync'd are known-good unless a
            # ckpt.save drill tampered with them — skip re-reading them in
            # retention's verified-step scan on the common path
            self._retain(trusted_step=(
                None if fault_hits("ckpt.save") != fired_before else step))

    def raise_if_all_corrupt(self) -> None:
        """Raise :class:`CheckpointCorruptionError` when the directory
        HAS step dirs but none passes verification — the caller must not
        silently retrain from scratch over recoverable data loss. Quiet
        on an empty or healthy directory. (Also the pre-flight check the
        multi-host driver runs before any supervisor starts.)"""
        if self.all_steps() and self.latest_valid_step() is None:
            raise CheckpointCorruptionError(
                f"checkpoint dir {self.directory} holds "
                f"{len(self.all_steps())} step(s) but none passes "
                f"integrity verification — refusing to silently start "
                f"over; clear the directory to retrain from scratch")

    def _latest_valid_or_raise(self) -> int:
        step = self.latest_valid_step()
        if step is not None:
            return step
        self.raise_if_all_corrupt()
        raise FileNotFoundError(
            f"no valid checkpoints under {self.directory}")

    def restore(self, step: Optional[int] = None) -> Any:
        """Restore ``step``, or (by default) the newest step that passes
        integrity verification. An explicitly requested corrupt step
        raises :class:`CheckpointCorruptionError` rather than returning
        garbage; so does a directory that HAS step dirs but none intact —
        silently pretending no checkpoint existed would make a caller
        retrain from scratch over recoverable data loss. A directory with
        no steps at all raises FileNotFoundError (a fresh run).

        The ``ckpt.restore`` fault point fires on the step about to be
        read, BEFORE it is read: a ``corrupt``-mode drill flips its bytes
        and the default path must fall back to an older intact step, the
        mirror image of the ``ckpt.save`` drill. The integrity scan is
        re-run only when a fault actually fired (the hit counter moved) —
        the common restore pays for ONE scan."""
        with trace.span("ckpt.restore",
                        step=(-1 if step is None else step)):
            explicit = step is not None
            if not explicit:
                step = self._latest_valid_or_raise()
            fired_before = fault_hits("ckpt.restore")
            fault_point("ckpt.restore", path=self._step_dir(step))
            if explicit:
                if not self.verify_step(step):
                    raise CheckpointCorruptionError(
                        f"checkpoint step {step} under {self.directory} "
                        f"failed integrity verification")
            elif fault_hits("ckpt.restore") != fired_before:
                # a drill just touched the chosen step: re-resolve so a
                # corrupt-mode fault exercises the real fallback path
                step = self._latest_valid_or_raise()
            d = self._step_dir(step)
            with open(os.path.join(d, _MANIFEST)) as fh:
                manifest = json.load(fh)
            with np.load(os.path.join(d, _ARRAYS)) as npz:
                arrays = {k: npz[k] for k in npz.files}
            return _unflatten(manifest["skeleton"], arrays)

    def _retain(self, trusted_step: Optional[int] = None) -> None:
        """Prune to the newest ``max_to_keep`` steps — but never
        garbage-collect the only VERIFIED snapshot: if every step inside
        the keep window is corrupt (torn writes racing a crash), the
        newest verified step outside the window survives too, so a later
        restore still has something intact to fall back to.
        ``trusted_step`` is a step known valid without re-reading it
        (save() just checksummed its bytes), so the common save pays no
        verification I/O at all."""
        if self.max_to_keep is None:
            return
        steps = self.all_steps()
        if len(steps) <= self.max_to_keep:
            return  # nothing would be pruned: skip the verification scan
        keep = set(steps[-self.max_to_keep:])
        # newest-first: the just-written step usually verifies on the
        # first pass, so a pruning save costs one crc read-back at most
        if trusted_step not in keep and not any(
                self.verify_step(s) for s in sorted(keep, reverse=True)):
            for s in reversed(steps):
                if s not in keep and self.verify_step(s):
                    keep.add(s)
                    break
        for step in steps:
            if step not in keep:
                shutil.rmtree(self._step_dir(step), ignore_errors=True)
