"""Mid-training checkpoint/resume for coordinate descent and λ grids.

The reference has NO mid-training checkpointing (SURVEY §5.4) — only
terminal model save/load plus warm starts across the λ grid
(ModelTraining.scala:182-208) and across CD iterations. This module adds the
TPU-idiomatic upgrade the survey prescribes: periodic snapshots of
(coordinate states, CD iteration, λ index) so long runs resume instead of
restart. Format: one directory per step holding a JSON manifest (structure +
scalars) and an ``.npz`` of array leaves — readable without the framework.

Hardened against torn and corrupted writes (the Spark-lineage-free world
owns its own durability): data files carry crc32 checksums in the
manifest, every file is fsync'd before the atomic rename publishes the
step, and :meth:`CheckpointManager.latest_valid_step` verifies integrity
so a restore falls back PAST a truncated/corrupt/partial step dir to the
newest intact one instead of dying on it. Retention never prunes the
last verified snapshot (corrupt newer steps don't garbage-collect the
only intact fallback), and both halves of the durability story are
drillable: ``ckpt.save`` fires before the atomic rename, ``ckpt.restore``
fires on the step about to be read.

API mirrors an orbax CheckpointManager (save/restore/latest_step/all_steps)
without taking the dependency for plain-array states.

Sync discipline: this module is framework-free (numpy only) and
``save()`` host-serializes whatever leaves it is given — a device-array
leaf would be fetched implicitly, one blocking transfer per leaf. Hot-path
callers therefore pre-fetch the WHOLE snapshot with one explicit
``jax.device_get`` of the payload pytree before calling ``save()`` (see
``run_coordinate_descent.save_snapshot``): the checkpoint is the single
designated fetch point for device state, and the one-round-trip hot-loop
contract (game/coordinate_descent.py) stays intact between snapshots.
"""

from __future__ import annotations

import io
import json
import os
import shutil
import zipfile
import zlib
from typing import Any, Optional

import numpy as np

from photon_ml_tpu.obs import trace
from photon_ml_tpu.utils.faults import fault_point, hits as fault_hits
from photon_ml_tpu.utils.retry import (
    RetryExhaustedError,
    RetryPolicy,
    call_with_retry,
)

_MANIFEST = "manifest.json"
_ARRAYS = "arrays.npz"
_STEP_PREFIX = "step_"
_TMP_SUFFIX = ".tmp"

#: Retry schedule for the snapshot payload write (the ``ckpt.write_bytes``
#: fault site): transient ENOSPC/EIO re-write the tmp dir from scratch.
_WRITE_RETRY = RetryPolicy(max_attempts=4, base_delay_seconds=0.02,
                           max_delay_seconds=0.5)


class CheckpointCorruptionError(RuntimeError):
    """An explicitly requested step failed integrity verification."""


#: What a torn-but-checksummed step raises on read: np.load surfaces a
#: truncated npz as BadZipFile, a mangled one as ValueError/KeyError/OSError.
_UNREADABLE_STEP_ERRORS = (OSError, ValueError, KeyError,
                           zipfile.BadZipFile)


class CheckpointWriteError(RuntimeError):
    """A snapshot could not be written durably (retries exhausted — e.g.
    a persistently full disk). The caller decides whether losing THIS
    snapshot is survivable; the coordinate-descent loop treats it as
    degraded-but-alive (training continues, the failure is logged and
    counted) since checkpoints are a durability aid, not training
    state."""


def _flatten(obj: Any, path: str, arrays: dict[str, np.ndarray]):
    """Structure with array leaves → JSON-able skeleton + array table."""
    if isinstance(obj, dict):
        return {"__kind__": "dict",
                "items": {k: _flatten(v, f"{path}.{k}", arrays)
                          for k, v in obj.items()}}
    if isinstance(obj, (list, tuple)):
        return {"__kind__": "list" if isinstance(obj, list) else "tuple",
                "items": [_flatten(v, f"{path}[{i}]", arrays)
                          for i, v in enumerate(obj)]}
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return {"__kind__": "scalar", "value": obj}
    arr = np.asarray(obj)
    arrays[path] = arr
    return {"__kind__": "array", "key": path, "dtype": str(arr.dtype)}


def _unflatten(spec: Any, arrays: dict[str, np.ndarray]) -> Any:
    kind = spec["__kind__"]
    if kind == "dict":
        return {k: _unflatten(v, arrays) for k, v in spec["items"].items()}
    if kind in ("list", "tuple"):
        items = [_unflatten(v, arrays) for v in spec["items"]]
        return items if kind == "list" else tuple(items)
    if kind == "scalar":
        return spec["value"]
    return arrays[spec["key"]]


def dumps_state(state: Any) -> bytes:
    """Serialize a checkpoint-shaped structure (nested dict/list/tuple with
    scalar and NUMERIC array leaves) to one self-describing byte string —
    the same skeleton+npz format as an on-disk step, zipped in memory. Used
    by the multi-host resume path: process 0 restores the snapshot and
    broadcasts these bytes to the re-formed gang, so every process resumes
    from the identical state without sharing a filesystem."""
    arrays: dict[str, np.ndarray] = {}
    skeleton = _flatten(state, "root", arrays)
    # keys are "root..."-prefixed, so the skeleton entry can't collide
    arrays["__skeleton__"] = np.frombuffer(
        json.dumps(skeleton).encode("utf-8"), dtype=np.uint8)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def loads_state(data: bytes) -> Any:
    """Inverse of :func:`dumps_state`."""
    with np.load(io.BytesIO(data)) as npz:
        arrays = {k: npz[k] for k in npz.files}
    skeleton = json.loads(arrays.pop("__skeleton__").tobytes().decode())
    return _unflatten(skeleton, arrays)


def _file_crc32(path: str) -> str:
    crc = 0
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return f"{crc & 0xFFFFFFFF:08x}"


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # platforms without directory fds
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class CheckpointManager:
    """Step-indexed checkpoint directory with retention + integrity."""

    def __init__(self, directory: str, max_to_keep: Optional[int] = 3):
        self.directory = directory
        self.max_to_keep = max_to_keep
        os.makedirs(directory, exist_ok=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"{_STEP_PREFIX}{step:08d}")

    def all_steps(self) -> list[int]:
        steps = []
        for name in os.listdir(self.directory):
            if name.startswith(_STEP_PREFIX) \
                    and not name.endswith(_TMP_SUFFIX):
                manifest = os.path.join(self.directory, name, _MANIFEST)
                if os.path.exists(manifest):  # ignore partial writes
                    steps.append(int(name[len(_STEP_PREFIX):]))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    # -- integrity ---------------------------------------------------------

    def verify_step(self, step: int) -> bool:
        """True when ``step``'s manifest parses and every checksummed file
        is present with matching crc32. Pre-checksum (v1) step dirs pass
        on file presence alone."""
        d = self._step_dir(step)
        try:
            with open(os.path.join(d, _MANIFEST)) as fh:
                manifest = json.load(fh)
        except (OSError, ValueError):
            return False
        if manifest.get("step") != step or "skeleton" not in manifest:
            return False
        checksums = manifest.get("checksums")
        if checksums is None:  # v1 manifest: presence check only
            return os.path.exists(os.path.join(d, _ARRAYS))
        for name, crc in checksums.items():
            path = os.path.join(d, name)
            try:
                if _file_crc32(path) != crc:
                    return False
            except OSError:
                return False
        return True

    def latest_valid_step(self) -> Optional[int]:
        """Newest step that passes integrity verification, scanning back
        past truncated/corrupt/partial step dirs (the restore entry point
        after an unclean shutdown)."""
        for step in reversed(self.all_steps()):
            if self.verify_step(step):
                return step
        return None

    def clean_stale_tmp(self) -> int:
        """Remove leftover ``step_*.tmp`` dirs (a save killed before its
        atomic rename leaves one behind; anything still suffixed ``.tmp``
        is by definition unpublished garbage). Runs on every ``save()``
        and ``restore()`` so a crash-looping run can't accumulate
        partial-write litter. Returns the number removed."""
        removed = 0
        try:
            names = os.listdir(self.directory)
        except OSError:
            return 0
        for name in names:
            if name.startswith(_STEP_PREFIX) and name.endswith(_TMP_SUFFIX):
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)
                removed += 1
        return removed

    # -- save/restore ------------------------------------------------------

    def save(self, step: int, state: Any) -> None:
        """Durable and atomic: write + checksum + fsync into a tmp dir,
        then rename; the manifest carries the data files' crc32s.

        The payload write is retried (``utils/retry``): a transient
        ENOSPC/EIO — drillable at the ``ckpt.write_bytes`` fault point,
        which fires between the array write and its checksum — rewrites
        the tmp dir from scratch; persistent failure raises
        :class:`CheckpointWriteError` with the tmp dir cleaned up, so an
        unwritable disk degrades checkpointing instead of littering the
        directory."""
        with trace.span("ckpt.save", step=step):
            self.clean_stale_tmp()
            final = self._step_dir(step)
            tmp = final + _TMP_SUFFIX
            arrays: dict[str, np.ndarray] = {}
            skeleton = _flatten(state, "root", arrays)
            arrays_path = os.path.join(tmp, _ARRAYS)

            def write_tmp():
                if os.path.exists(tmp):
                    shutil.rmtree(tmp)
                os.makedirs(tmp)
                np.savez(arrays_path, **arrays)
                # fires BETWEEN the payload write and its checksum: a
                # `partial`-mode drill here is a torn write whose crc
                # faithfully records the torn bytes — the published step
                # verifies but cannot be loaded, and restore() must fall
                # back PAST it; enospc/io_error/flaky are transient write
                # failures the retry recovers by rewriting the tmp dir
                fault_point("ckpt.write_bytes", path=arrays_path)
                _fsync_file(arrays_path)
                # manifest written LAST: its presence marks the step
                # complete
                with open(os.path.join(tmp, _MANIFEST), "w") as fh:
                    json.dump(
                        {"step": step, "format_version": 2,
                         "checksums": {_ARRAYS: _file_crc32(arrays_path)},
                         "skeleton": skeleton}, fh)
                    fh.flush()
                    os.fsync(fh.fileno())

            try:
                call_with_retry(write_tmp, site="ckpt.write_bytes",
                                policy=_WRITE_RETRY)
            except RetryExhaustedError as e:
                shutil.rmtree(tmp, ignore_errors=True)
                raise CheckpointWriteError(
                    f"checkpoint step {step} under {self.directory} "
                    f"could not be written: {e}") from e
            fired_before = fault_hits("ckpt.save")
            fault_point("ckpt.save", path=tmp)
            if os.path.exists(final):
                shutil.rmtree(final)
            os.rename(tmp, final)
            _fsync_dir(self.directory)
            # the bytes just checksummed+fsync'd are known-good unless a
            # ckpt.save drill tampered with them POST-checksum — skip
            # re-reading them in retention's verified-step scan on the
            # common path (a ckpt.write_bytes partial-write drill fires
            # PRE-checksum, so its torn bytes still verify: trust holds)
            self._retain(trusted_step=(
                None if fault_hits("ckpt.save") != fired_before else step))

    def raise_if_all_corrupt(self) -> None:
        """Raise :class:`CheckpointCorruptionError` when the directory
        HAS step dirs but none passes verification — the caller must not
        silently retrain from scratch over recoverable data loss. Quiet
        on an empty or healthy directory. (Also the pre-flight check the
        multi-host driver runs before any supervisor starts.)"""
        if self.all_steps() and self.latest_valid_step() is None:
            raise CheckpointCorruptionError(
                f"checkpoint dir {self.directory} holds "
                f"{len(self.all_steps())} step(s) but none passes "
                f"integrity verification — refusing to silently start "
                f"over; clear the directory to retrain from scratch")

    def _latest_valid_or_raise(self) -> int:
        step = self.latest_valid_step()
        if step is not None:
            return step
        self.raise_if_all_corrupt()
        raise FileNotFoundError(
            f"no valid checkpoints under {self.directory}")

    def _read_step(self, step: int) -> Any:
        d = self._step_dir(step)
        with open(os.path.join(d, _MANIFEST)) as fh:
            manifest = json.load(fh)
        with np.load(os.path.join(d, _ARRAYS)) as npz:
            arrays = {k: npz[k] for k in npz.files}
        return _unflatten(manifest["skeleton"], arrays)

    def restore(self, step: Optional[int] = None) -> Any:
        """Restore ``step``, or (by default) the newest step that passes
        integrity verification. An explicitly requested corrupt step
        raises :class:`CheckpointCorruptionError` rather than returning
        garbage; so does a directory that HAS step dirs but none intact —
        silently pretending no checkpoint existed would make a caller
        retrain from scratch over recoverable data loss. A directory with
        no steps at all raises FileNotFoundError (a fresh run).

        The ``ckpt.restore`` fault point fires on the step about to be
        read, BEFORE it is read: a ``corrupt``-mode drill flips its bytes
        and the default path must fall back to an older intact step, the
        mirror image of the ``ckpt.save`` drill.

        Hardened against steps that VERIFY but cannot be loaded (a torn
        write whose checksum faithfully recorded the torn bytes — the
        ``ckpt.write_bytes`` partial drill): a failed read on the default
        path falls back to the next verified+readable step instead of
        crashing; on an explicit step it raises
        :class:`CheckpointCorruptionError`. The common restore still pays
        for exactly ONE integrity scan and ONE read."""
        with trace.span("ckpt.restore",
                        step=(-1 if step is None else step)):
            self.clean_stale_tmp()
            explicit = step is not None
            if not explicit:
                step = self._latest_valid_or_raise()
            fired_before = fault_hits("ckpt.restore")
            fault_point("ckpt.restore", path=self._step_dir(step))
            if explicit:
                if not self.verify_step(step):
                    raise CheckpointCorruptionError(
                        f"checkpoint step {step} under {self.directory} "
                        f"failed integrity verification")
            elif fault_hits("ckpt.restore") != fired_before:
                # a drill just touched the chosen step: re-resolve so a
                # corrupt-mode fault exercises the real fallback path
                step = self._latest_valid_or_raise()
            try:
                return self._read_step(step)
            except _UNREADABLE_STEP_ERRORS as e:
                if explicit:
                    raise CheckpointCorruptionError(
                        f"checkpoint step {step} under {self.directory} "
                        f"verified but could not be loaded: {e!r}") from e
                unreadable = {step}
            # default-path fallback: newest-first past every step that
            # fails verification or fails to load
            for cand in reversed(self.all_steps()):
                if cand in unreadable or not self.verify_step(cand):
                    continue
                try:
                    return self._read_step(cand)
                except _UNREADABLE_STEP_ERRORS:
                    unreadable.add(cand)
            raise CheckpointCorruptionError(
                f"checkpoint dir {self.directory} has no step that both "
                f"verifies and loads ({len(unreadable)} verified step(s) "
                f"failed to read — torn writes?); clear the directory to "
                f"retrain from scratch")

    def _step_loadable(self, step: int) -> bool:
        """Cheap readability probe: a torn write that still checksums
        (the crc was computed over the already-truncated bytes — the
        ``ckpt.write_bytes`` partial drill) breaks the npz's zip central
        directory, so just OPENING it detects the tear without
        decompressing anything. Byte-flip corruption is the crc scan's
        job — the two checks are complementary."""
        try:
            with zipfile.ZipFile(
                    os.path.join(self._step_dir(step), _ARRAYS)):
                return True
        except (OSError, zipfile.BadZipFile):
            return False

    def _retain(self, trusted_step: Optional[int] = None) -> None:
        """Prune to the newest ``max_to_keep`` steps — but never
        garbage-collect the only RESTORABLE snapshot: if every step
        inside the keep window is corrupt OR torn (a torn write still
        checksums — its crc recorded the torn bytes — but cannot be
        loaded), the newest verified+loadable step outside the window
        survives too, so a later restore still has something to fall
        back to. ``trusted_step`` is a step whose bytes save() just
        checksummed, so it skips the crc read-back — but NOT the zip
        probe, which is exactly what catches a torn trusted write."""
        if self.max_to_keep is None:
            return
        steps = self.all_steps()
        if len(steps) <= self.max_to_keep:
            return  # nothing would be pruned: skip the verification scan
        keep = set(steps[-self.max_to_keep:])

        def restorable(s: int) -> bool:
            return ((s == trusted_step or self.verify_step(s))
                    and self._step_loadable(s))

        # newest-first: the just-written step usually passes on the
        # first probe, so a pruning save costs one zip-directory open
        # (and at most one crc read-back) on the common path
        if not any(restorable(s) for s in sorted(keep, reverse=True)):
            for s in reversed(steps):
                if s not in keep and restorable(s):
                    keep.add(s)
                    break
        for step in steps:
            if step not in keep:
                shutil.rmtree(self._step_dir(step), ignore_errors=True)
