"""Mid-training checkpoint/resume for coordinate descent and λ grids.

The reference has NO mid-training checkpointing (SURVEY §5.4) — only
terminal model save/load plus warm starts across the λ grid
(ModelTraining.scala:182-208) and across CD iterations. This module adds the
TPU-idiomatic upgrade the survey prescribes: periodic snapshots of
(coordinate states, CD iteration, λ index) so long runs resume instead of
restart. Format: one directory per step holding a JSON manifest (structure +
scalars) and an ``.npz`` of array leaves — readable without the framework.

API mirrors an orbax CheckpointManager (save/restore/latest_step/all_steps)
without taking the dependency for plain-array states.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Optional

import numpy as np

_MANIFEST = "manifest.json"
_ARRAYS = "arrays.npz"
_STEP_PREFIX = "step_"


def _flatten(obj: Any, path: str, arrays: dict[str, np.ndarray]):
    """Structure with array leaves → JSON-able skeleton + array table."""
    if isinstance(obj, dict):
        return {"__kind__": "dict",
                "items": {k: _flatten(v, f"{path}.{k}", arrays)
                          for k, v in obj.items()}}
    if isinstance(obj, (list, tuple)):
        return {"__kind__": "list" if isinstance(obj, list) else "tuple",
                "items": [_flatten(v, f"{path}[{i}]", arrays)
                          for i, v in enumerate(obj)]}
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return {"__kind__": "scalar", "value": obj}
    arr = np.asarray(obj)
    arrays[path] = arr
    return {"__kind__": "array", "key": path, "dtype": str(arr.dtype)}


def _unflatten(spec: Any, arrays: dict[str, np.ndarray]) -> Any:
    kind = spec["__kind__"]
    if kind == "dict":
        return {k: _unflatten(v, arrays) for k, v in spec["items"].items()}
    if kind in ("list", "tuple"):
        items = [_unflatten(v, arrays) for v in spec["items"]]
        return items if kind == "list" else tuple(items)
    if kind == "scalar":
        return spec["value"]
    return arrays[spec["key"]]


class CheckpointManager:
    """Step-indexed checkpoint directory with retention."""

    def __init__(self, directory: str, max_to_keep: Optional[int] = 3):
        self.directory = directory
        self.max_to_keep = max_to_keep
        os.makedirs(directory, exist_ok=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"{_STEP_PREFIX}{step:08d}")

    def all_steps(self) -> list[int]:
        steps = []
        for name in os.listdir(self.directory):
            if name.startswith(_STEP_PREFIX):
                manifest = os.path.join(self.directory, name, _MANIFEST)
                if os.path.exists(manifest):  # ignore partial writes
                    steps.append(int(name[len(_STEP_PREFIX):]))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def save(self, step: int, state: Any) -> None:
        """Atomic-ish: write into a tmp dir, then rename."""
        final = self._step_dir(step)
        tmp = final + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        arrays: dict[str, np.ndarray] = {}
        skeleton = _flatten(state, "root", arrays)
        np.savez(os.path.join(tmp, _ARRAYS), **arrays)
        # manifest written LAST: its presence marks the step complete
        with open(os.path.join(tmp, _MANIFEST), "w") as fh:
            json.dump({"step": step, "skeleton": skeleton}, fh)
        if os.path.exists(final):
            shutil.rmtree(final)
        os.rename(tmp, final)
        self._retain()

    def restore(self, step: Optional[int] = None) -> Any:
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory}")
        d = self._step_dir(step)
        with open(os.path.join(d, _MANIFEST)) as fh:
            manifest = json.load(fh)
        with np.load(os.path.join(d, _ARRAYS)) as npz:
            arrays = {k: npz[k] for k in npz.files}
        return _unflatten(manifest["skeleton"], arrays)

    def _retain(self) -> None:
        if self.max_to_keep is None:
            return
        steps = self.all_steps()
        for step in steps[:-self.max_to_keep]:
            shutil.rmtree(self._step_dir(step), ignore_errors=True)
