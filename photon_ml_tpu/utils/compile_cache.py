"""Persistent XLA compilation cache for driver processes.

The reference pays JVM+Spark startup per driver run; our analog cost is
XLA compilation of the solver/evaluator kernels (~seconds per kernel on a
remote TPU). A persistent on-disk cache makes every driver run after the
first reuse compiled executables, so short CLI jobs (heart-sized trainings,
scoring runs) are not dominated by compile time.

Opt out with ``PHOTON_DISABLE_COMPILE_CACHE=1`` or point the directory
elsewhere with ``PHOTON_COMPILE_CACHE_DIR``.
"""

from __future__ import annotations

import os

_DEFAULT_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "photon_ml_tpu", "xla")

_enabled = False


def enable_persistent_compile_cache() -> bool:
    """Idempotently turn on JAX's persistent compilation cache. Returns
    whether the cache is active (False when disabled via env or the
    backend rejects it)."""
    global _enabled
    if _enabled:
        return True
    if os.environ.get("PHOTON_DISABLE_COMPILE_CACHE"):
        return False
    cache_dir = os.environ.get("PHOTON_COMPILE_CACHE_DIR", _DEFAULT_DIR)
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # Cache every kernel, however fast it compiled: CLI runs re-pay
        # even sub-second compiles on every invocation otherwise.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        _enabled = True
    except Exception:
        return False
    return _enabled
