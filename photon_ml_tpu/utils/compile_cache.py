"""Persistent XLA compilation cache for driver processes.

The reference pays JVM+Spark startup per driver run; our analog cost is
XLA compilation of the solver/evaluator kernels (~seconds per kernel on a
remote TPU). A persistent on-disk cache makes every driver run after the
first reuse compiled executables, so short CLI jobs (heart-sized trainings,
scoring runs) are not dominated by compile time.

The cache directory is keyed by a machine/backend fingerprint: XLA:CPU AOT
results encode target machine features (AVX-512 variants etc.), and loading
an entry compiled on a different host can mis-execute ("could lead to
execution errors such as SIGILL" per XLA's loader). A shared home directory
must therefore never serve one machine's entries to another.

Growth: when the running JAX exposes ``jax_compilation_cache_max_size`` the
cache is capped (LRU-evicted by JAX) at 1 GiB and every kernel is persisted,
however fast it compiled — short CLI runs are dominated by many sub-second
compiles. On older JAX without the cap, JAX's default persistence thresholds
(compile time >= 1s) apply instead, which slows growth but does not bound
it — long-lived hosts on such versions need external cleanup.
Opt out with ``PHOTON_DISABLE_COMPILE_CACHE=1`` or point the directory
elsewhere with ``PHOTON_COMPILE_CACHE_DIR``.
"""

from __future__ import annotations

import hashlib
import os
import platform

_DEFAULT_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "photon_ml_tpu", "xla")

_MAX_CACHE_BYTES = 1 << 30  # 1 GiB, LRU-evicted by JAX where supported

_enabled = False


def _machine_fingerprint(jax) -> str:
    """Digest of everything that can change generated code: jax/jaxlib
    versions, the active backend, platform triple, and (on Linux) the CPU
    feature flags that XLA:CPU AOT results are specialized to."""
    parts = [
        platform.system(),
        platform.machine(),
        getattr(jax, "__version__", "?"),
    ]
    try:
        import jaxlib

        parts.append(getattr(jaxlib, "__version__", "?"))
    except ImportError:  # pragma: no cover
        pass
    # Requested platform, WITHOUT initializing the backend: drivers enable
    # the cache first thing in main(), and forcing TPU client init there
    # would make --help pay multi-second startup and break any later
    # jax.distributed.initialize() ordering.
    parts.append(os.environ.get("JAX_PLATFORMS")
                 or str(jax.config.jax_platforms or "default"))
    try:
        with open("/proc/cpuinfo") as f:
            block = []
            for ln in f:
                if not ln.strip():
                    break  # end of first processor block
                # Model identity matters beyond the flag list: LLVM enables
                # tuning "features" like prefer-no-gather per CPU *model*
                # (Downfall-affected parts), so two hosts with identical
                # flags can still produce mutually-incompatible AOT code.
                if ln.split(":")[0].strip() in (
                        "vendor_id", "cpu family", "model", "model name",
                        "stepping", "microcode", "flags"):
                    block.append(ln.strip())
        parts.extend(block)
    except OSError:  # pragma: no cover - non-Linux
        pass
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]


def enable_persistent_compile_cache(allow_cpu: bool = False) -> bool:
    """Idempotently turn on JAX's persistent compilation cache. Returns
    whether the cache is active (False when disabled via env or the
    backend rejects it).

    ``allow_cpu=True`` keeps persistence on for CPU-pinned processes too.
    Self-compiled XLA:CPU AOT entries reload and execute correctly on the
    same machine (the fingerprinted directory guarantees that), but the
    loader logs E-level lines about its own tuning-flag set
    (prefer-no-gather/scatter) on every load — callers that opt in (the
    bench, whose CPU-fallback glmix sweep otherwise pays ~17s of repeat
    compiles per process) should suppress those with
    ``TF_CPP_MIN_LOG_LEVEL=3`` before the first jax import."""
    global _enabled
    if _enabled:
        return True
    if os.environ.get("PHOTON_DISABLE_COMPILE_CACHE"):
        return False
    # CPU-only processes skip persistence by default (see allow_cpu above).
    # Known gap: a host with NO platform pin that resolves to CPU by
    # default still persists — resolving the real backend here would force
    # the init this function must avoid (see the fingerprint note below).
    try:
        import jax as _jax

        # an in-process jax_platforms override (scripts pin "cpu" before
        # first backend use) wins over the environment's default
        platforms = (str(_jax.config.jax_platforms or "")
                     or os.environ.get("JAX_PLATFORMS", "")).strip().lower()
    except Exception:  # pragma: no cover
        platforms = (os.environ.get("JAX_PLATFORMS") or "").strip().lower()
    if platforms.startswith("cpu") and not allow_cpu:
        return False
    base_dir = os.environ.get("PHOTON_COMPILE_CACHE_DIR", _DEFAULT_DIR)
    try:
        import jax

        cache_dir = os.path.join(base_dir, _machine_fingerprint(jax))
        os.makedirs(cache_dir, exist_ok=True)
        # One-time sweep: earlier releases wrote entries directly under the
        # base dir (unfingerprinted, possibly compiled on another machine).
        # JAX never reads or LRU-evicts them from there — dead bytes.
        for entry in os.listdir(base_dir):
            path = os.path.join(base_dir, entry)
            if os.path.isfile(path):
                try:
                    os.remove(path)
                except OSError:
                    pass
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        try:
            jax.config.update("jax_compilation_cache_max_size",
                              _MAX_CACHE_BYTES)
            capped = True
        except AttributeError:  # size cap absent on older JAX
            capped = False
        if capped:
            # Growth is bounded by the LRU cap, so persist everything:
            # short CLI runs (heart-sized trainings, scoring) are dominated
            # by many sub-second kernel compiles.
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        _enabled = True
    except Exception:
        return False
    return _enabled
