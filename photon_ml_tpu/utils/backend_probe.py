"""Wedge-resilient probing of the default JAX backend.

The accelerator device tunnel in some environments can wedge at backend
init: a killed client leaves the remote chip grant stuck, after which
every ``jax.devices()`` call in every new process blocks forever. Any
code that must survive that (the bench recorder, the multi-chip dry-run
gate) therefore probes the default backend in a SUBPROCESS with a hard
timeout before initializing it in its own process, and falls back to CPU
with a visible marker otherwise.

The probe is SIGTERMed with a grace period rather than SIGKILLed on
timeout: killing a tunnel client mid-grant-acquisition is exactly what
wedges the tunnel in the first place.

Reference analog: the Spark drivers assume a live cluster and fail fast
(Driver.scala:149-151); here the "cluster" is a device tunnel that can
hang rather than error, so liveness must be established out-of-process.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Callable, Optional

#: Generous allowance for a healthy-but-cold tunnel's first backend init
#: (observed: normal cold init well under this; wedged init never returns).
DEFAULT_PROBE_TIMEOUT_SECS = 240


def default_platform_is_cpu() -> bool:
    """True when this process is already pinned to the CPU platform."""
    return (os.environ.get("JAX_PLATFORMS") or "").split(",")[0] == "cpu"


def probe_default_backend(
    timeout_secs: int = DEFAULT_PROBE_TIMEOUT_SECS,
    log: Callable[[str], None] = print,
) -> Optional[int]:
    """Count the default backend's devices from a timed subprocess.

    Returns the device count on success, or ``None`` when the probe
    failed, hung past ``timeout_secs``, or produced unparsable output —
    in which case a reason is emitted through ``log`` so a fallback is
    always visible in the run record.
    """
    proc = subprocess.Popen(
        [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    try:
        out, _ = proc.communicate(timeout=timeout_secs)
        if proc.returncode == 0:
            # parse only the LAST line: a site import hook may print to
            # stdout before the count
            last = out.strip().splitlines()[-1] if out.strip() else ""
            try:
                return int(last)
            except ValueError:
                log(f"backend probe returned unparsable output {last!r}")
                return None
        reason = f"backend probe rc={proc.returncode}"
    except subprocess.TimeoutExpired:
        reason = f"backend probe hung > {timeout_secs}s"
        proc.terminate()  # SIGTERM first: let the client release its grant
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
    log(reason)
    return None
