"""Multi-host execution: ``jax.distributed`` workers over one global mesh.

The reference's runtime is inherently multi-node (Spark executors over
YARN); the TPU-native counterpart is multi-controller JAX: every host
runs this same program, ``jax.distributed.initialize`` forms the global
device set, and the SAME mesh/shard_map code that runs single-host runs
unchanged over hosts — XLA routes the ``psum`` over ICI within a slice
and DCN across slices (SURVEY §5.8: multi-host only for data-loading and
inter-slice reductions).

This module is the ``local[4]``-of-hosts witness
(photon-test/.../SparkTestUtils.scala:55-69 analog, lifted one level):
``run_worker`` is executed by N CPU processes (each with a virtual
multi-device platform), feeds per-process LOCAL data shards into a global
array (the HDFS-partition analog: no process ever holds another's rows),
runs the explicit shard_map+psum fixed-effect fit
(parallel/distributed.run_glm_shard_map), and checks parity against a
process-local single-device solve. tests/test_multihost.py spawns the
workers; a real pod would launch the same worker per host.
"""

from __future__ import annotations

import argparse

import numpy as np


def _synthetic(rows: int, dim: int, seed: int):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(rows, dim)).astype(np.float32)
    w_true = rng.normal(size=dim).astype(np.float32)
    p = 1.0 / (1.0 + np.exp(-(X @ w_true)))
    y = (rng.uniform(size=rows) < p).astype(np.float32)
    return X, y


def run_worker(process_id: int, num_processes: int, coordinator: str,
               rows: int = 512, dim: int = 16, seed: int = 11) -> None:
    """One multi-host worker: global-mesh shard_map fit + local parity.

    Every worker generates the same deterministic dataset but contributes
    only ITS row range to the global batch (make_array_from_callback reads
    just the addressable shards), mirroring per-host input partitions.
    """
    import jax

    from photon_ml_tpu.utils.backend_probe import default_platform_is_cpu

    if default_platform_is_cpu():
        # a site import hook may re-pin jax_platforms to an accelerator;
        # honor the caller's explicit CPU request (test harness) regardless
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from photon_ml_tpu.data.batch import DenseBatch
    from photon_ml_tpu.optimize.config import (
        GLMOptimizationConfiguration,
        OptimizerType,
        RegularizationContext,
        RegularizationType,
        TaskType,
    )
    from photon_ml_tpu.optimize.problem import GLMOptimizationProblem
    from photon_ml_tpu.parallel.distributed import run_glm_shard_map
    from photon_ml_tpu.parallel.mesh import DATA_AXIS, make_mesh

    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    devs = jax.devices()  # GLOBAL device list across processes
    n_local = len(jax.local_devices())
    assert len(devs) == n_local * num_processes, (len(devs), n_local)
    assert rows % len(devs) == 0, "rows must divide the global device count"
    mesh = make_mesh(num_data=len(devs), num_entity=1, devices=devs)

    X, y = _synthetic(rows, dim, seed)
    host = DenseBatch(
        X=X, labels=y,
        offsets=np.zeros(rows, np.float32),
        weights=np.ones(rows, np.float32),
    )
    sharding = NamedSharding(mesh, P(DATA_AXIS))

    def to_global(leaf):
        # the callback receives per-shard index tuples and returns only
        # the addressable (process-local) row ranges
        return jax.make_array_from_callback(
            leaf.shape, sharding, lambda idx: leaf[idx])

    gbatch = DenseBatch(
        X=to_global(host.X), labels=to_global(host.labels),
        offsets=to_global(host.offsets), weights=to_global(host.weights))

    problem = GLMOptimizationProblem(
        config=GLMOptimizationConfiguration(
            max_iterations=25, tolerance=1e-8, regularization_weight=0.5,
            optimizer_type=OptimizerType.LBFGS,
            regularization_context=RegularizationContext(
                RegularizationType.L2)),
        task=TaskType.LOGISTIC_REGRESSION)

    model, result = run_glm_shard_map(problem, gbatch, mesh)
    w = np.asarray(model.coefficients.means)
    assert np.all(np.isfinite(w))

    # Process-local single-device reference fit on the full dataset.
    local_batch = DenseBatch(
        X=jnp.asarray(X), labels=jnp.asarray(y),
        offsets=jnp.zeros(rows, jnp.float32),
        weights=jnp.ones(rows, jnp.float32))
    local_model, _ = problem.run(local_batch)
    np.testing.assert_allclose(
        w, np.asarray(local_model.coefficients.means),
        rtol=2e-4, atol=2e-4)
    print(f"PARITY_OK process={process_id} devices={len(devs)} "
          f"iters={result.iterations}", flush=True)
    jax.distributed.shutdown()


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="photon-ml-tpu multi-host shard_map demo worker")
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--num-processes", type=int, required=True)
    ap.add_argument("--coordinator", required=True,
                    help="host:port of process 0's coordination service")
    ap.add_argument("--rows", type=int, default=512)
    ap.add_argument("--dim", type=int, default=16)
    args = ap.parse_args(argv)
    run_worker(args.process_id, args.num_processes, args.coordinator,
               rows=args.rows, dim=args.dim)


if __name__ == "__main__":
    main()
