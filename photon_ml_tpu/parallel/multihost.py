"""Multi-host execution: ``jax.distributed`` workers over one global mesh.

The reference's runtime is inherently multi-node (Spark executors over
YARN); the TPU-native counterpart is multi-controller JAX: every host
runs this same program, ``jax.distributed.initialize`` forms the global
device set, and the SAME mesh/shard_map code that runs single-host runs
unchanged over hosts — XLA routes the ``psum`` over ICI within a slice
and DCN across slices (SURVEY §5.8: multi-host only for data-loading and
inter-slice reductions).

This module is the ``local[4]``-of-hosts witness
(photon-test/.../SparkTestUtils.scala:55-69 analog, lifted one level):
``run_worker`` is executed by N CPU processes (each with a virtual
multi-device platform), feeds per-process LOCAL data shards into a global
array (the HDFS-partition analog: no process ever holds another's rows),
runs the explicit shard_map+psum fixed-effect fit
(parallel/distributed.run_glm_shard_map), and checks parity against a
process-local single-device solve. tests/test_multihost.py spawns the
workers; a real pod would launch the same worker per host.
"""

from __future__ import annotations

import argparse

import numpy as np

from photon_ml_tpu.obs import trace


def _distributed_initialize(coordinator: str, num_processes: int,
                            process_id: int,
                            initialization_timeout: int = 300,
                            heartbeat_timeout: int = 100) -> None:
    """``jax.distributed.initialize`` with version-tolerant kwargs.

    The timeout kwargs moved/appeared across jax releases
    (``heartbeat_timeout_seconds`` does not exist in older ones); filter
    by the live signature so a worker fails on REAL cluster problems, not
    on a TypeError before it ever joins.

    On releases whose public API has no heartbeat knob at all, fall back
    to the coordination-service parameters on the internal state
    initializer (detection latency ≈ interval × max_missing): otherwise
    ``heartbeat_timeout`` is silently dropped and a dead gang member
    takes the library default (~100 s) to surface on the survivors —
    the supervisor's relaunch loop would sit idle that whole time."""
    import inspect

    import jax

    kwargs = dict(coordinator_address=coordinator,
                  num_processes=num_processes, process_id=process_id,
                  initialization_timeout=initialization_timeout,
                  heartbeat_timeout_seconds=heartbeat_timeout)
    params = inspect.signature(jax.distributed.initialize).parameters

    def _connect():
        jax.distributed.initialize(
            **{k: v for k, v in kwargs.items() if k in params})

    if "heartbeat_timeout_seconds" not in params:
        try:
            from jax._src import distributed as _dist
            from jax._src import xla_bridge as _bridge

            sparams = inspect.signature(
                _dist.global_state.initialize).parameters
            if ("service_heartbeat_interval_seconds" in sparams
                    and "client_heartbeat_interval_seconds" in sparams
                    and not _bridge.backends_are_initialized()):
                interval = max(1, int(heartbeat_timeout) // 10)
                missing = max(2, int(heartbeat_timeout) // interval)

                def _connect():
                    _dist.global_state.initialize(
                        coordinator_address=coordinator,
                        num_processes=num_processes,
                        process_id=process_id,
                        initialization_timeout=initialization_timeout,
                        service_heartbeat_interval_seconds=interval,
                        service_max_missing_heartbeats=missing,
                        client_heartbeat_interval_seconds=interval,
                        client_max_missing_heartbeats=missing)
        except Exception:
            pass
    # gang formation AND re-formation trace here: a supervisor-relaunched
    # worker re-enters this span on its way back into the gang, so the
    # trace shows how long each (re-)join blocked on the coordinator
    with trace.span("gang.form", process=process_id,
                    num_processes=num_processes):
        _connect()


def _synthetic(rows: int, dim: int, seed: int):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(rows, dim)).astype(np.float32)
    w_true = rng.normal(size=dim).astype(np.float32)
    p = 1.0 / (1.0 + np.exp(-(X @ w_true)))
    y = (rng.uniform(size=rows) < p).astype(np.float32)
    return X, y


def run_worker(process_id: int, num_processes: int, coordinator: str,
               rows: int = 512, dim: int = 16, seed: int = 11) -> None:
    """One multi-host worker: global-mesh shard_map fit + local parity.

    Every worker generates the same deterministic dataset but contributes
    only ITS row range to the global batch (make_array_from_callback reads
    just the addressable shards), mirroring per-host input partitions.
    """
    import jax

    from photon_ml_tpu.utils.backend_probe import default_platform_is_cpu

    if default_platform_is_cpu():
        # a site import hook may re-pin jax_platforms to an accelerator;
        # honor the caller's explicit CPU request (test harness) regardless
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from photon_ml_tpu.data.batch import DenseBatch
    from photon_ml_tpu.optimize.config import (
        GLMOptimizationConfiguration,
        OptimizerType,
        RegularizationContext,
        RegularizationType,
        TaskType,
    )
    from photon_ml_tpu.optimize.problem import GLMOptimizationProblem
    from photon_ml_tpu.parallel.distributed import run_glm_shard_map
    from photon_ml_tpu.parallel.mesh import DATA_AXIS, make_mesh

    _distributed_initialize(coordinator, num_processes, process_id)
    devs = jax.devices()  # GLOBAL device list across processes
    n_local = len(jax.local_devices())
    assert len(devs) == n_local * num_processes, (len(devs), n_local)
    assert rows % len(devs) == 0, "rows must divide the global device count"
    mesh = make_mesh(num_data=len(devs), num_entity=1, devices=devs)

    X, y = _synthetic(rows, dim, seed)
    host = DenseBatch(
        X=X, labels=y,
        offsets=np.zeros(rows, np.float32),
        weights=np.ones(rows, np.float32),
    )
    sharding = NamedSharding(mesh, P(DATA_AXIS))

    def to_global(leaf):
        # the callback receives per-shard index tuples and returns only
        # the addressable (process-local) row ranges
        return jax.make_array_from_callback(
            leaf.shape, sharding, lambda idx: leaf[idx])

    gbatch = DenseBatch(
        X=to_global(host.X), labels=to_global(host.labels),
        offsets=to_global(host.offsets), weights=to_global(host.weights))

    problem = GLMOptimizationProblem(
        config=GLMOptimizationConfiguration(
            max_iterations=25, tolerance=1e-8, regularization_weight=0.5,
            optimizer_type=OptimizerType.LBFGS,
            regularization_context=RegularizationContext(
                RegularizationType.L2)),
        task=TaskType.LOGISTIC_REGRESSION)

    model, result = run_glm_shard_map(problem, gbatch, mesh)
    w = np.asarray(model.coefficients.means)
    assert np.all(np.isfinite(w))

    # Process-local single-device reference fit on the full dataset.
    local_batch = DenseBatch(
        X=jnp.asarray(X), labels=jnp.asarray(y),
        offsets=jnp.zeros(rows, jnp.float32),
        weights=jnp.ones(rows, jnp.float32))
    local_model, _ = problem.run(local_batch)
    np.testing.assert_allclose(
        w, np.asarray(local_model.coefficients.means),
        rtol=2e-4, atol=2e-4)
    print(f"PARITY_OK process={process_id} devices={len(devs)} "
          f"iters={result.iterations}", flush=True)
    jax.distributed.shutdown()


# ---------------------------------------------------------------------------
# Host-data exchange helpers (the broadcast/shuffle analog for host metadata)
# ---------------------------------------------------------------------------


def allgather_ragged(arr: np.ndarray) -> list[np.ndarray]:
    """All processes exchange a 1-D (or row-major) numeric array of
    process-dependent length; returns the per-process arrays in process
    order. Pads to the global max length and rides two device allgathers
    (jax.experimental.multihost_utils.process_allgather) — the host-side
    analog of the reference's driver↔executor metadata collects."""
    from jax.experimental import multihost_utils as mhu

    arr = np.ascontiguousarray(arr)
    n = np.asarray([arr.shape[0]], dtype=np.int64)
    ns = np.asarray(mhu.process_allgather(n)).reshape(-1)
    cap = int(ns.max()) if len(ns) else 0
    pad = np.zeros((cap,) + arr.shape[1:], arr.dtype)
    pad[: arr.shape[0]] = arr
    g = np.asarray(mhu.process_allgather(pad))
    if g.ndim == pad.ndim:  # single-process: no leading process axis added
        g = g[None]
    return [g[p, : int(ns[p])] for p in range(len(ns))]


def allgather_strings(strings: np.ndarray) -> list[np.ndarray]:
    """Exchange per-process string arrays (object/str dtype) across all
    processes. Each string is length-prefixed — a per-process int64 length
    array rides alongside the concatenated UTF-8 buffer — so ids are
    reconstructed by exact byte offsets and arbitrary content (including
    NUL bytes, which a separator-based framing would mis-split on) round-
    trips intact."""
    encoded = [str(s).encode("utf-8") for s in strings]
    lens = np.asarray([len(b) for b in encoded], dtype=np.int64)
    buf = np.frombuffer(b"".join(encoded), dtype=np.uint8)
    lens_g = allgather_ragged(lens)
    bufs_g = allgather_ragged(buf)
    out = []
    for ln, b in zip(lens_g, bufs_g):
        assert int(ln.sum()) == b.shape[0], (int(ln.sum()), b.shape[0])
        if len(ln) == 0:
            out.append(np.zeros(0, dtype=object))
            continue
        ends = np.cumsum(ln)
        raw = b.tobytes()
        out.append(np.asarray(
            [raw[e - n:e].decode("utf-8")
             for n, e in zip(ln.tolist(), ends.tolist())], dtype=object))
    return out


def allgather_csr(mat) -> list:
    """Exchange per-process CSR row blocks; returns per-process matrices
    (same column dimension) in process order."""
    import scipy.sparse as sp

    lens = np.diff(mat.indptr).astype(np.int64)
    lens_g = allgather_ragged(lens)
    idx_g = allgather_ragged(np.asarray(mat.indices, np.int64))
    dat_g = allgather_ragged(np.asarray(mat.data, np.float64))
    out = []
    for ln, ix, dv in zip(lens_g, idx_g, dat_g):
        indptr = np.concatenate([[0], np.cumsum(ln)])
        out.append(sp.csr_matrix(
            (dv, ix.astype(np.int32), indptr),
            shape=(len(ln), mat.shape[1])))
    return out


# ---------------------------------------------------------------------------
# Multi-host GAME training (fixed + random effect)
# ---------------------------------------------------------------------------

#: Pad-row entity id: never collides with data ids (allgather_strings is
#: length-prefixed, so the value itself is unconstrained); its coefficient
#: row is dropped from results.
_PAD_ENTITY = "\x01__pad__\x01"


def run_game_worker(
    process_id: int,
    num_processes: int,
    coordinator: str,
    train_paths,
    feature_shard_sections: dict,
    index_maps: dict,
    fixed_coordinate: tuple,
    random_coordinates,
    task,
    num_iterations: int = 1,
    num_buckets: int = 1,
    initialization_timeout: int = 60,
    heartbeat_timeout: int = 100,
    blocks_dir=None,
    checkpoint_dir=None,
    checkpoint_every_coordinates: int = 0,
    precision: str = "f32",
    collective_quant: str = "none",
    stop=None,
) -> dict:
    """One multi-host GAME training process: fixed + random effects CD.

    The cluster-program analog of the reference's GAME training driver
    (cli/game/training/Driver.scala:642-726 — the driver IS the cluster
    program): every host runs this same function with ITS OWN avro part
    files (``train_paths``), and the global batch exists only as a mesh-
    sharded array.

    Data movement per axis:
    - **Fixed-effect rows never leave their process.** Each process feeds
      its local (padded) row range into the global mesh via
      ``jax.make_array_from_callback``; the L-BFGS fit runs through the
      shard_map+psum backend over all hosts' devices.
    - **Scalar columns and the (narrow) random-effect shards are
      host-allgathered**, then every process builds its OWN entity slice
      of the padded blocks (per-host-sharded streamed build) and the
      blocks' entity axis is sharded over an all-devices entity mesh:
      each device solves a contiguous slice of entity lanes under the
      jitted vmapped solver (zero comm in the hot loop) — the reference's
      entity-partitioned executors (RandomEffectCoordinate.scala:104-113),
      now across hosts.

    ``fixed_coordinate`` = (coord_id, FixedEffectDataConfiguration,
    GLMOptimizationConfiguration); ``random_coordinates`` is a LIST of
    (coord_id, RandomEffectDataConfiguration,
    GLMOptimizationConfiguration, factored_or_None) updated in order each
    CD iteration — the full GAME shape (e.g. fixed + per-user + per-item)
    runs as one cluster program. ``factored`` entries are
    (re_cfg, latent_cfg, mf_cfg) tuples for factored coordinates. Returns
    a dict with the fixed coefficients, a per-coordinate map of
    per-entity RE coefficients keyed by raw entity id, and the final
    objective — identical on every process.

    With ``checkpoint_dir``, process 0 snapshots the CD state after each
    sweep (plus mid-sweep at the ``checkpoint_every_coordinates``
    cadence) and, on startup, restores the newest intact snapshot and
    BROADCASTS it to the whole gang — so a gang re-formed after a
    supervisor restart resumes training mid-run instead of restarting
    from scratch. Only process 0 ever touches the directory; the other
    hosts need no shared filesystem.

    ``stop`` (any object with ``should_stop() -> str | None``) makes the
    gang preemptable: each member polls its LOCAL flag at the gang-
    synchronous safe points (after each committed coordinate update) and
    the flags are allgathered, so one member's SIGTERM/deadline/stop-file
    stops EVERY member at the same coordinate — the collective snapshot
    fires once, then all members raise
    :class:`~photon_ml_tpu.utils.preempt.PreemptionRequested`.

    ``precision`` / ``collective_quant`` are the mixed-precision flag
    pair (cli/args.py): storage dtype for the design-matrix tiles and
    RE blocks, and the wire format of the mesh collectives. Both shape
    every member's traced collective programs (payload dtypes and
    shapes), so a mismatch would wedge the gang mid-collective — they
    ride the same formation-time signature check as the checkpoint
    cadence and fail fast with the per-process values.
    """
    import os

    import jax

    from photon_ml_tpu.utils.backend_probe import default_platform_is_cpu

    if default_platform_is_cpu():
        jax.config.update("jax_platforms", "cpu")

    _distributed_initialize(
        coordinator, num_processes, process_id,
        initialization_timeout=initialization_timeout,
        heartbeat_timeout=heartbeat_timeout)
    # Fault-injection hooks for the committed failure-path tests: a worker
    # that dies mid-run (after joining the cluster, before any collective)
    # must surface as a bounded coordination error on the survivors, not a
    # hang — Spark's task-failure semantics analog (SURVEY §5.3). The
    # registry point ("worker.start", tagged by process id) is the general
    # switchboard (kill/delay/raise via PHOTON_FAULTS); the env hook below
    # is the legacy spelling kept for the original survivor-bound test.
    from photon_ml_tpu.utils.faults import fault_point

    fault_point("worker.start", tag=str(process_id))
    if os.environ.get("PHOTON_MH_TEST_EXIT_AFTER_INIT") == str(process_id):
        os._exit(17)
    try:
        return _game_worker_body(
            process_id, num_processes, train_paths,
            feature_shard_sections, index_maps, fixed_coordinate,
            random_coordinates, task, num_iterations, num_buckets,
            blocks_dir, checkpoint_dir, checkpoint_every_coordinates,
            precision=precision, collective_quant=collective_quant,
            stop=stop)
    finally:
        jax.distributed.shutdown()


def _game_worker_body(
        process_id, num_processes, train_paths, feature_shard_sections,
        index_maps, fixed_coordinate, random_coordinates, task,
        num_iterations, num_buckets, blocks_dir=None, checkpoint_dir=None,
        checkpoint_every_coordinates=0, precision="f32",
        collective_quant="none", stop=None):
    """Post-initialize body of :func:`run_game_worker` (imports deferred
    until the distributed backend is live)."""
    import os

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from photon_ml_tpu.data.batch import DenseBatch
    from photon_ml_tpu.game.dataset import (
        GameDataset,
        build_random_effect_dataset_streamed,
        dataset_row_stream,
    )
    from photon_ml_tpu.game.random_effect import (
        RandomEffectOptimizationProblem,
        score_random_effect,
    )
    from photon_ml_tpu.io.data_format import load_game_dataset_avro
    from photon_ml_tpu.ops.losses import get_loss
    from photon_ml_tpu.optimize.config import TASK_LOSS_NAME
    from photon_ml_tpu.optimize.problem import GLMOptimizationProblem
    from photon_ml_tpu.parallel.distributed import run_glm_shard_map
    from photon_ml_tpu.parallel.mesh import DATA_AXIS, make_mesh

    # Precision / collective-quant shape the TRACED collective programs
    # (payload dtypes, int8 q+scale shapes), so a per-host mismatch would
    # wedge the gang mid-collective with an opaque shape error — validate
    # locally BEFORE any ingestion or collective work, then gang-check
    # the codes alongside the checkpoint cadence below.
    from photon_ml_tpu.cli.args import PRECISION_CHOICES, precision_dtype
    from photon_ml_tpu.parallel.quantized_collectives import (
        QUANT_MODES,
        check_quant_mode,
    )

    if precision not in PRECISION_CHOICES:
        raise ValueError(f"unknown precision {precision!r}; "
                         f"expected one of {PRECISION_CHOICES}")
    check_quant_mode(collective_quant)
    # Host-side staging stays f32 everywhere; the storage dtype applies
    # at the device commit (to_global/to_global_ent), mirroring the
    # single-host builders' device-commit cast.
    store_dtype = np.dtype(precision_dtype(precision))

    devs = jax.devices()
    n_local = len(jax.local_devices())
    mesh = make_mesh(num_data=len(devs), num_entity=1, devices=devs)

    f_cid, f_data_cfg, f_opt_cfg = fixed_coordinate
    id_types = sorted({cfg.random_effect_type
                       for _, cfg, _, _ in random_coordinates})

    # ---- local ingestion: ONLY this process's part files -----------------
    local = load_game_dataset_avro(
        list(train_paths), feature_shard_sections, index_maps,
        id_types=id_types, response_required=True)
    n_loc = local.num_samples
    raw_ids_loc = {t: local.id_vocabs[t][local.id_columns[t]]
                   for t in id_types}

    # ---- padded canonical sample layout ----------------------------------
    # Every process pads its row range to the same L (multiple of the
    # per-process device count) so contiguous data-axis shards of [P*L]
    # rows fall entirely inside one process; pad rows carry weight 0. The
    # layout requires UNIFORM local device counts — verify instead of
    # silently computing mismatched L's and wedging the collectives.
    # -1 = checkpointing off; otherwise the cadence value. Both the flag's
    # PRESENCE and its CADENCE shape the collective schedule (snapshot
    # broadcast + per-save state resharding on every member), so either
    # mismatched across the gang would deadlock it until the heartbeat
    # bound — fail fast with the real reason instead.
    ckpt_sig = (-1 if checkpoint_dir is None
                else int(checkpoint_every_coordinates))
    prec_sig = PRECISION_CHOICES.index(precision)
    quant_sig = QUANT_MODES.index(collective_quant)
    n_all = allgather_ragged(np.asarray(
        [n_loc, n_local, ckpt_sig, prec_sig, quant_sig], np.int64))
    n_per = np.asarray([int(x[0]) for x in n_all])
    dev_per = np.asarray([int(x[1]) for x in n_all])
    if not (dev_per == n_local).all():
        raise RuntimeError(
            f"multi-host GAME needs identical per-process device counts, "
            f"got {dev_per.tolist()}")
    ckpt_per = np.asarray([int(x[2]) for x in n_all])
    if ckpt_per.min() != ckpt_per.max():
        raise RuntimeError(
            f"checkpoint config must be identical on EVERY process of "
            f"the gang (process 0 alone touches --checkpoint-dir, but "
            f"all members issue the snapshot collectives at the same "
            f"--checkpoint-every-coordinates cadence); got per-process "
            f"values {ckpt_per.tolist()} (-1 = checkpointing off)")
    for sig_col, flag, choices in ((3, "--precision", PRECISION_CHOICES),
                                   (4, "--collective-quant", QUANT_MODES)):
        per = np.asarray([int(x[sig_col]) for x in n_all])
        if per.min() != per.max():
            raise RuntimeError(
                f"{flag} must be identical on EVERY process of the gang "
                f"(it shapes the traced collective programs — payload "
                f"dtypes and quantized wire shapes — so a mismatch "
                f"deadlocks the mesh collectives); got per-process "
                f"values {[choices[v] for v in per.tolist()]}")
    L = int(-(-int(n_per.max()) // n_local) * n_local)
    n_pad_total = L * num_processes

    def pad_local(a, fill=0.0, dtype=np.float32):
        out = np.full(L, fill, dtype)
        out[:n_loc] = a
        return out

    resp_loc = pad_local(local.responses)
    off_loc = pad_local(local.offsets)
    wt_loc = pad_local(local.weights)

    # ---- allgather scalar columns + the RE shards ------------------------
    resp_g = np.concatenate(allgather_ragged(resp_loc))
    off_g = np.concatenate(allgather_ragged(off_loc))
    wt_g = np.concatenate(allgather_ragged(wt_loc))
    ids_g = {}
    for t in id_types:
        ids_loc = np.full(L, _PAD_ENTITY, dtype=object)
        ids_loc[:n_loc] = raw_ids_loc[t]
        ids_g[t] = np.concatenate(allgather_strings(ids_loc))
    import scipy.sparse as sp

    shards_g = {}
    for sname in sorted({cfg.feature_shard_id
                         for _, cfg, _, _ in random_coordinates}):
        mat_loc = local.feature_shards[sname].tocsr()
        padded = sp.vstack([
            mat_loc,
            sp.csr_matrix((L - n_loc, mat_loc.shape[1]))]).tocsr()
        shards_g[sname] = sp.vstack(allgather_csr(padded)).tocsr()

    # identical global GameDataset view for the RE coordinates on every
    # process (deterministic build → identical blocks/solves everywhere)
    gdata = GameDataset(
        responses=resp_g, feature_shards=shards_g,
        offsets=off_g.astype(np.float64), weights=wt_g.astype(np.float64))
    for t in id_types:
        gdata.encode_ids(t, ids_g[t])

    # ---- entity-axis sharding over ALL hosts' devices --------------------
    # The blocks are identical on every process (deterministic build);
    # sharding their entity axis over an all-devices entity mesh makes the
    # vmapped solve a real distributed computation — each device solves a
    # contiguous slice of entity lanes with zero comm in the hot loop,
    # the reference's entity-partitioned executors
    # (algorithm/RandomEffectCoordinate.scala:104-113). Blocks were padded
    # to a multiple of the device count (entity_axis_size above).
    from photon_ml_tpu.parallel.mesh import ENTITY_AXIS

    ent_mesh = make_mesh(num_data=1, num_entity=len(devs), devices=devs)

    def to_global_ent(local_arr):
        """Global entity-sharded array from this host's LOCAL slice.

        jax.devices() is process-major, so the entity-axis shard of this
        host's devices is exactly rows [pid*E_loc, (pid+1)*E_loc) of the
        full bucket — the range the sharded build filled; the callback is
        only ever asked for addressable (local) shards.
        """
        arr = np.asarray(local_arr)
        e_loc = arr.shape[0]
        full = (e_loc * num_processes,) + arr.shape[1:]
        lo = process_id * e_loc
        sh = NamedSharding(
            ent_mesh, P(*([ENTITY_AXIS] + [None] * (arr.ndim - 1))))

        def cb(idx):
            # a replicated/size-1 entity axis yields slice(None) — use
            # indices() so the arithmetic survives it
            start, stop, _ = idx[0].indices(full[0])
            return arr[(slice(start - lo, stop - lo),) + tuple(idx[1:])]

        return jax.make_array_from_callback(full, sh, cb)

    _replicate = jax.jit(lambda x: x,
                         out_shardings=NamedSharding(ent_mesh, P()))

    # ---- per-coordinate setup: streamed per-host-sharded block builds ----
    # Every process computes the identical global grouping/plan from the
    # O(N) scalar columns, then allocates and fills ONLY its own
    # contiguous entity slice of every bucket (entity_shard) — no host
    # ever holds another host's blocks, and keep_host_blocks means nothing
    # is committed to a single device before the global-mesh sharding
    # (RandomEffectDataSet.scala:169-206's partitioned shuffle output).
    # Factored coordinates run the latent-refit + Kronecker-fit
    # alternation on the single-block entity-sharded global arrays
    # (FactoredRandomEffectCoordinate.scala:39-257).
    import dataclasses as _dc

    from photon_ml_tpu.game.coordinate import (
        FactoredRandomEffectCoordinate,
    )

    coords = []
    for cid, r_data_cfg, r_opt_cfg, factored in random_coordinates:
        # a factored coordinate always gets a single block (one projection
        # matrix is shared across all entities); plain coordinates keep
        # the requested bucketing — mixing both kinds in one run is fine
        re_ds = build_random_effect_dataset_streamed(
            dataset_row_stream(gdata, r_data_cfg), r_data_cfg,
            raw_dim=gdata.shard_dim(r_data_cfg.feature_shard_id),
            num_buckets=1 if factored is not None else num_buckets,
            entity_axis_size=len(devs),
            blocks_dir=(None if blocks_dir is None
                        else os.path.join(blocks_dir, cid)),
            keep_host_blocks=True,
            entity_shard=(process_id, num_processes))
        for block in re_ds.buckets:
            assert (block.local_entity_offset
                    == process_id * block.X.shape[0])
            for field in ("X", "labels", "base_offsets", "weights",
                          "row_ids"):
                val = getattr(block, field)
                if field == "X":  # design tiles only; scalars stay f32
                    val = np.asarray(val, store_dtype)
                setattr(block, field, to_global_ent(val))
        if re_ds.passive_X is not None:
            # passive rows stay host-side numpy: they enter jitted
            # scoring as replicated constants next to the entity-sharded
            # coefficients
            re_ds.passive_X = np.asarray(re_ds.passive_X)
            re_ds.passive_entity = np.asarray(re_ds.passive_entity)
            re_ds.passive_row_ids = np.asarray(re_ds.passive_row_ids)
            re_ds.passive_offsets = np.asarray(re_ds.passive_offsets)
        fac_coord = None
        if factored is not None:
            fac_re_cfg, fac_latent_cfg, fac_mf_cfg = factored
            b0 = re_ds.buckets[0]
            re_ds = _dc.replace(
                re_ds, X=b0.X, labels=b0.labels,
                base_offsets=b0.base_offsets, weights=b0.weights,
                row_ids=b0.row_ids, buckets=None, _reduced_dim=None)
            fac_coord = FactoredRandomEffectCoordinate(
                dataset=re_ds,
                problem=RandomEffectOptimizationProblem(
                    config=fac_re_cfg, task=task,
                    collective_quant=collective_quant),
                latent_problem=GLMOptimizationProblem(
                    config=fac_latent_cfg, task=task,
                    collective_quant=collective_quant),
                latent_dim=fac_mf_cfg.num_factors,
                num_inner_iterations=fac_mf_cfg.max_number_iterations)
        coords.append({
            "cid": cid,
            "id_type": r_data_cfg.random_effect_type,
            "ds": re_ds,
            "prob": RandomEffectOptimizationProblem(
                config=r_opt_cfg, task=task,
                collective_quant=collective_quant),
            "fac": fac_coord,
        })

    # ---- fixed-effect global batch: local rows only ----------------------
    f_mat = local.feature_shards[f_data_cfg.feature_shard_id].tocsr()
    X_loc = np.zeros((L, f_mat.shape[1]), np.float32)
    X_loc[:n_loc] = f_mat.toarray()
    X_loc = np.asarray(X_loc, store_dtype)
    sharding = NamedSharding(mesh, P(DATA_AXIS))

    def to_global(loc, extra_dims=()):
        shape = (n_pad_total,) + extra_dims

        def cb(idx):
            sl = idx[0]
            lo = sl.start - process_id * L
            return loc[lo:lo + (sl.stop - sl.start)]

        return jax.make_array_from_callback(shape, sharding, cb)

    X_g = to_global(X_loc, (X_loc.shape[1],))
    y_g = to_global(resp_loc)
    w_g = to_global(wt_loc)
    f_problem = GLMOptimizationProblem(config=f_opt_cfg, task=task,
                                       collective_quant=collective_quant)

    def gather_global(x_global):
        """Sharded global [N_pad] vector → replicated numpy on every host."""
        from jax.experimental import multihost_utils as mhu

        shards = sorted(x_global.addressable_shards,
                        key=lambda s: s.index[0].start)
        loc_rows = np.concatenate([np.asarray(s.data) for s in shards])
        return np.asarray(mhu.process_allgather(loc_rows)).reshape(-1)

    @jax.jit
    def fixed_margins(X, w):
        return X @ w

    # ---- checkpoint/resume: process 0 owns the snapshots -----------------
    # Only process 0 reads/writes checkpoint_dir (no shared filesystem
    # needed); the restored snapshot rides a host allgather as one
    # serialized byte buffer, so a gang RE-FORMED after a supervisor
    # restart resumes from the identical mid-run state on every host.
    from photon_ml_tpu.utils.checkpoint import (
        CheckpointManager,
        dumps_state,
        loads_state,
    )
    from photon_ml_tpu.utils.faults import fault_point

    loss = get_loss(TASK_LOSS_NAME[task])
    scores_fixed = np.zeros(n_pad_total, np.float32)
    scores_re = {c["cid"]: np.zeros(n_pad_total, np.float32)
                 for c in coords}
    states = {c["cid"]: None for c in coords}
    regs = {c["cid"]: 0.0 for c in coords}
    w_fixed = None
    objective = None
    update_seq = 1 + len(coords)  # fixed + each RE coordinate, in order
    start_it, start_ci = 0, 0

    ckpt_mgr = None
    if checkpoint_dir is not None:
        snap = None
        if process_id == 0:
            ckpt_mgr = CheckpointManager(checkpoint_dir)
            try:
                snap = ckpt_mgr.restore()
            except FileNotFoundError:
                snap = None
        payload = dumps_state(snap) if snap is not None else b""
        root = allgather_ragged(np.frombuffer(payload, np.uint8))[0]
        if root.size:
            snap = loads_state(root.tobytes())
            start_it = int(snap["sweep"])
            start_ci = int(snap["coordinate_index"])
            if snap["w_fixed"] is not None:
                w_fixed = np.asarray(snap["w_fixed"])
            scores_fixed = np.asarray(snap["scores_fixed"])
            scores_re = {c["cid"]: np.asarray(snap["scores_re"][c["cid"]])
                         for c in coords}
            states = {c["cid"]: snap["re_states"][c["cid"]]
                      for c in coords}
            regs = {c["cid"]: snap["regs"][c["cid"]] for c in coords}
            objective = snap["objective"]
            if process_id == 0:
                print(f"MULTIHOST_RESUME sweep={start_it} "
                      f"coordinate={start_ci}", flush=True)

    def _host_state(v):
        """Coordinate state → replicated host numpy (None passes through;
        factored states are (latent, projection) tuples)."""
        if v is None:
            return None
        if isinstance(v, tuple):
            # photonlint: allow-W103(checkpoint path: replicated-state fetch to host numpy is the point of _host_state)
            return tuple(np.asarray(_replicate(x)) for x in v)
        # photonlint: allow-W103(checkpoint path: replicated-state fetch to host numpy is the point of _host_state)
        return np.asarray(_replicate(v))

    last_saved_step = [None]

    def save_snapshot(sweep, next_ci):
        # EVERY process runs this at the same program points: resharding
        # the entity-sharded global RE states to replicated host copies
        # (_host_state → _replicate) is a collective, so all gang members
        # must participate — only the WRITE below is process 0's alone.
        if checkpoint_dir is None:
            return
        if next_ci >= update_seq:
            sweep, next_ci = sweep + 1, 0
        step = sweep * update_seq + next_ci
        if step == last_saved_step[0]:
            return
        state = {
            "sweep": sweep,
            "coordinate_index": next_ci,
            "w_fixed": None if w_fixed is None else np.asarray(w_fixed),
            "scores_fixed": np.asarray(scores_fixed),
            "scores_re": {cid: np.asarray(s)
                          for cid, s in scores_re.items()},
            "re_states": {cid: _host_state(states[cid]) for cid in states},
            "regs": {cid: float(r) for cid, r in regs.items()},
            "objective": (None if objective is None else float(objective)),
        }
        if ckpt_mgr is not None:
            ckpt_mgr.save(step, state)
        last_saved_step[0] = step

    def maybe_save(sweep, next_ci):
        # sweep-end saves go through save_snapshot directly (after the
        # objective is computed); the cadence only covers mid-sweep points
        if (checkpoint_every_coordinates > 0 and next_ci < update_seq
                and (sweep * update_seq + next_ci)
                % checkpoint_every_coordinates == 0):
            save_snapshot(sweep, next_ci)

    def check_gang_stop(sweep, next_ci):
        # Gang-consensus preemption at a safe point (a committed update,
        # the same places the snapshot cadence fires): every member
        # allgathers its LOCAL stop flag, so one member's SIGTERM/
        # deadline/stop-file stops the WHOLE gang at the same
        # coordinate. The consensus snapshot is a collective (all
        # members reshard; process 0 writes) and dedups against the
        # cadence save that may have just fired at this step.
        if stop is None:
            return
        from photon_ml_tpu.utils.preempt import PreemptionRequested

        local = stop.should_stop()
        flags = allgather_ragged(
            np.asarray([1 if local is not None else 0], np.int32))
        if not any(int(f[0]) for f in flags):
            return
        save_snapshot(sweep, next_ci)
        if next_ci >= update_seq:
            sweep, next_ci = sweep + 1, 0
        raise PreemptionRequested(local or "gang:peer_stop",
                                  sweep, next_ci)

    # ---- coordinate descent: fixed ⇄ random effects ----------------------
    # Offsets for each coordinate = base + Σ other coordinates' scores
    # (CoordinateDescent.scala:143-151's partial-score subtraction).
    for it in range(start_it, num_iterations):
        fault_point("cd.sweep", tag=str(it))
        skip_before = start_ci if it == start_it else 0
        if skip_before <= 0:
            # fixed update (update index 0):
            # offsets = base + Σ RE scores (local slice only)
            re_sum = sum(scores_re.values())
            off_inj = off_loc + re_sum[process_id * L:(process_id + 1) * L]
            batch_g = DenseBatch(X=X_g, labels=y_g,
                                 offsets=to_global(off_inj), weights=w_g)
            model, _ = run_glm_shard_map(
                f_problem, batch_g, mesh,
                initial=None if w_fixed is None else jnp.asarray(w_fixed))
            w_fixed = np.asarray(model.coefficients.means)
            scores_fixed = gather_global(fixed_margins(X_g,
                                                       jnp.asarray(w_fixed)))
            maybe_save(it, 1)
            check_gang_stop(it, 1)

        # random-effect updates in sequence: entity-sharded distributed
        # solves (state stays a global sharded array between iterations)
        for k, c in enumerate(coords):
            ci = k + 1
            if ci < skip_before:
                continue  # mid-sweep resume: already ran before the crash
            cid = c["cid"]
            extra = scores_fixed + sum(
                s for kk, s in scores_re.items() if kk != cid)
            if c["fac"] is not None:
                states[cid], _ = c["fac"].update(states[cid],
                                                 jnp.asarray(extra))
                # photonlint: allow-W103(multi-host CD loop is host-orchestrated: one replicated score fetch per coordinate per sweep by design)
                scores_re[cid] = np.asarray(_replicate(
                    c["fac"].score(states[cid]))).astype(np.float32)
                regs[cid] = c["fac"].regularization_value(states[cid])
            else:
                offs = c["ds"].offsets_with(jnp.asarray(extra))
                states[cid], *_ = c["prob"].run(
                    c["ds"], offs, initial=states[cid])
                # photonlint: allow-W103(multi-host CD loop is host-orchestrated: one replicated score fetch per coordinate per sweep by design)
                scores_re[cid] = np.asarray(_replicate(
                    score_random_effect(c["ds"], states[cid]))).astype(
                        np.float32)
                regs[cid] = c["prob"].regularization_value(states[cid])
            maybe_save(it, ci + 1)
            check_gang_stop(it, ci + 1)

        total = scores_fixed + sum(scores_re.values()) + off_g
        li = loss.loss(jnp.asarray(total), jnp.asarray(resp_g))
        # photonlint: allow-W101(sweep-boundary objective: one scalar sync per sweep, host-orchestrated loop by design)
        objective = float(jnp.sum(jnp.asarray(wt_g) * li))
        objective += float(f_problem.regularization_value(
            jnp.asarray(w_fixed)))
        objective += sum(regs.values())
        save_snapshot(it, update_seq)  # sweep end, objective included

    # drop the pad entity from the returned RE tables
    random_effect = {}
    factored_flags = {}
    for c in coords:
        vocab = gdata.id_vocabs[c["id_type"]]
        codes = c["ds"].entity_codes
        if c["fac"] is not None:
            lat, B = states[c["cid"]]
            # publish in RAW space (latent @ projection), like
            # FactoredRandomEffectModel.to_raw
            # photonlint: allow-W103(end-of-run model publication: final replicated coefficients fetch)
            lat_host = np.asarray(_replicate(lat))
            # photonlint: allow-W103(end-of-run model publication: final replicated coefficients fetch)
            coefs_host = lat_host @ np.asarray(_replicate(B))
        else:
            # photonlint: allow-W103(end-of-run model publication: final replicated coefficients fetch)
            coefs_host = np.asarray(_replicate(states[c["cid"]]))
        random_effect[c["cid"]] = {
            str(vocab[int(code)]): coefs_host[i]
            for i, code in enumerate(codes)
            if vocab[int(code)] != _PAD_ENTITY}
        factored_flags[c["cid"]] = c["fac"] is not None
    return {
        "fixed": {f_cid: w_fixed},
        "random_effect": random_effect,
        "objective": objective,
        "num_processes": num_processes,
        "global_devices": len(devs),
        "rows_global": int(n_per.sum()),
        # witness: the RE entity axis really is sharded over every device
        "re_entity_axis_devices": int(ent_mesh.shape[ENTITY_AXIS]),
        "factored": factored_flags,
    }


# ---------------------------------------------------------------------------
# Worker supervision: relaunch crashed worker processes with bounded backoff
# ---------------------------------------------------------------------------


class SupervisorExhaustedError(RuntimeError):
    """The supervised worker kept failing past its restart budget."""

    def __init__(self, name: str, restarts: int, last_rc: int):
        super().__init__(
            f"{name}: worker failed permanently after {restarts} "
            f"restart(s) (last exit code {last_rc})")
        self.restarts = restarts
        self.last_rc = last_rc


class WorkerSupervisor:
    """Relaunch a crashed worker process with bounded exponential backoff.

    The Spark-driver analog of task retry, lifted to the process level:
    each host runs one supervisor around its worker. When any gang member
    dies, the survivors' collectives error out within the heartbeat bound
    (see TestMultihostFailurePaths), every host's supervisor relaunches
    its own worker, and the gang re-forms on the coordinator — no cross-
    host control plane is needed. Backoff is exponential with
    deterministic per-(name, attempt) jitter so a whole gang restarting
    at once doesn't hammer the coordinator in lockstep.

    ``spawn(attempt)`` must start the worker and return an object with
    ``wait() -> returncode`` (subprocess.Popen fits).
    """

    def __init__(self, spawn, max_restarts: int = 2,
                 backoff_base_seconds: float = 1.0,
                 backoff_max_seconds: float = 30.0,
                 jitter_fraction: float = 0.25,
                 name: str = "worker", log=None):
        self.spawn = spawn
        self.max_restarts = max_restarts
        self.backoff_base_seconds = backoff_base_seconds
        self.backoff_max_seconds = backoff_max_seconds
        self.jitter_fraction = jitter_fraction
        self.name = name
        self.log = log or (lambda s: None)
        self.restart_count = 0

    def backoff_seconds(self, attempt: int) -> float:
        """Exponential backoff for restart ``attempt`` (1-based) with a
        deterministic jitter derived from (name, attempt) — reproducible
        runs, de-synchronized gang members."""
        import zlib

        base = min(self.backoff_base_seconds * (2.0 ** (attempt - 1)),
                   self.backoff_max_seconds)
        seed = zlib.crc32(f"{self.name}:{attempt}".encode()) / 0xFFFFFFFF
        return base * (1.0 + self.jitter_fraction * (2.0 * seed - 1.0))

    def run(self) -> int:
        """Run the worker to successful completion; returns the number of
        restarts it took. Raises SupervisorExhaustedError once
        ``max_restarts`` relaunches have failed."""
        import time

        while True:
            attempt = self.restart_count
            proc = self.spawn(attempt)
            try:
                rc = proc.wait()
            except BaseException:
                # an interrupted/crashed supervisor must not orphan a
                # live worker (it would keep training and hold the
                # coordinator port/gang slot)
                for method in ("terminate", "kill"):
                    try:
                        getattr(proc, method, lambda: None)()
                    except OSError:
                        pass
                if hasattr(proc, "poll"):
                    proc.wait()
                raise
            if rc == 0:
                return self.restart_count
            self.restart_count += 1
            if self.restart_count > self.max_restarts:
                self.log(f"{self.name}: exit code {rc}; restart budget "
                         f"({self.max_restarts}) exhausted")
                raise SupervisorExhaustedError(
                    self.name, self.restart_count - 1, rc)
            delay = self.backoff_seconds(self.restart_count)
            self.log(f"{self.name}: exit code {rc}; restart "
                     f"{self.restart_count}/{self.max_restarts} in "
                     f"{delay:.1f}s")
            time.sleep(delay)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="photon-ml-tpu multi-host shard_map demo worker")
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--num-processes", type=int, required=True)
    ap.add_argument("--coordinator", required=True,
                    help="host:port of process 0's coordination service")
    ap.add_argument("--rows", type=int, default=512)
    ap.add_argument("--dim", type=int, default=16)
    args = ap.parse_args(argv)
    run_worker(args.process_id, args.num_processes, args.coordinator,
               rows=args.rows, dim=args.dim)


if __name__ == "__main__":
    main()
