"""Shared multi-chip GAME acceptance scenario.

One tiny-but-complete GAME training step — a data-sharded fixed effect, an
entity-sharded vmapped random effect, a factored random-effect coordinate
(latent per-entity refit + Kronecker projection fit), mesh-sharded
matrix-factorization scoring, and the explicit shard_map+psum fixed-effect
backend — runnable either over a (data x entity) device mesh or on a single
device with IDENTICAL shapes and padding, so multi-device runs can be
asserted equal to the single-device ground truth.

Used by BOTH the committed multi-device pytest tier (tests/test_multichip.py)
and the driver's ``__graft_entry__.dryrun_multichip`` gate, so the gate and
the test suite witness the same code path — the analog of the reference's
shared local[4] harness plus its GameTestUtils factories
(photon-test/.../SparkTestUtils.scala:55-69,
integTest/.../GameTestUtils.scala:36-270). Coordinate coverage matches the
GAME decomposition (algorithm/FixedEffectCoordinate.scala,
RandomEffectCoordinate.scala:104-113,
FactoredRandomEffectCoordinate.scala:39-257,
model/MatrixFactorizationModel.scala:50,141).
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def run_game_step(
    n_data: int = 4,
    n_entity: int = 2,
    mesh=None,
    seed: int = 3,
) -> dict:
    """One full GAME coordinate-descent sweep on a tiny synthetic dataset.

    ``n_data``/``n_entity`` fix the SHAPES (rows, entity padding) so a
    ``mesh=None`` single-device run is bit-comparable to a mesh run over
    an ``n_data x n_entity`` device mesh. When ``mesh`` is given it must
    have axes sizes (n_data, n_entity); inputs are device_put onto it and
    the fixed-effect solves route through the shard_map+psum backend.

    Returns numpy results for parity assertions:
    ``objectives`` (per-coordinate CD objective values), ``fixed``
    (fixed-effect coefficients), ``re_coefficients`` ([E, D] random-effect
    coefficients, raw space), ``projection`` (factored-RE projection
    matrix), ``latent`` ([E, K] factored-RE latent coefficients),
    ``mf_scores`` (matrix-factorization scores), ``shardmap_fixed``
    (explicit-collectives fixed-effect fit).
    """
    import jax
    import jax.numpy as jnp
    import scipy.sparse as sp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from photon_ml_tpu.game.coordinate import (
        FactoredRandomEffectCoordinate,
        FixedEffectCoordinate,
        RandomEffectCoordinate,
    )
    from photon_ml_tpu.game.coordinate_descent import run_coordinate_descent
    from photon_ml_tpu.game.dataset import (
        GameDataset,
        RandomEffectDataConfiguration,
        build_fixed_effect_dataset,
        build_random_effect_dataset,
    )
    from photon_ml_tpu.game.models import MatrixFactorizationModel
    from photon_ml_tpu.game.random_effect import RandomEffectOptimizationProblem
    from photon_ml_tpu.optimize.config import (
        GLMOptimizationConfiguration,
        OptimizerType,
        RegularizationContext,
        RegularizationType,
        TaskType,
    )
    from photon_ml_tpu.optimize.problem import GLMOptimizationProblem
    from photon_ml_tpu.parallel.distributed import run_glm_shard_map
    from photon_ml_tpu.parallel.mesh import (
        DATA_AXIS,
        ENTITY_AXIS,
        get_default_mesh,
        set_default_mesh,
        shard_batch,
    )
    from photon_ml_tpu.projector.projectors import (
        ProjectorConfig,
        ProjectorType,
    )

    if mesh is not None:
        assert mesh.shape[DATA_AXIS] == n_data, mesh.shape
        assert mesh.shape[ENTITY_AXIS] == n_entity, mesh.shape

    # --- tiny GAME dataset: global shard + per-user shard, rows divisible
    # by the data axis, entities padded to the entity axis.
    n_devices = n_data * n_entity
    rng = np.random.default_rng(seed)
    rows, d_g, d_u, n_users = 16 * n_devices, 12, 6, 4 * n_entity
    n_items = 6
    Xg = rng.normal(size=(rows, d_g))
    Xu = rng.normal(size=(rows, d_u))
    users = rng.integers(0, n_users, size=rows)
    y = (rng.uniform(size=rows) < 0.5).astype(np.float64)
    data = GameDataset(responses=y,
                       feature_shards={"global": sp.csr_matrix(Xg),
                                       "user": sp.csr_matrix(Xu)})
    data.encode_ids("userId", users.astype(str))

    task = TaskType.LOGISTIC_REGRESSION

    def cfg(lam):
        return GLMOptimizationConfiguration(
            max_iterations=3, tolerance=1e-6, regularization_weight=lam,
            optimizer_type=OptimizerType.LBFGS,
            regularization_context=RegularizationContext(
                RegularizationType.L2))

    fe_ds = build_fixed_effect_dataset(data, "global")
    fe_batch = shard_batch(fe_ds.batch, mesh) if mesh is not None \
        else fe_ds.batch
    fixed = FixedEffectCoordinate(
        dataset=fe_ds._replace(batch=fe_batch,
                               base_offsets=fe_ds.base_offsets)
        if hasattr(fe_ds, "_replace") else fe_ds,
        problem=GLMOptimizationProblem(config=cfg(0.1), task=task))

    ent = NamedSharding(mesh, P(ENTITY_AXIS)) if mesh is not None else None
    re_ds = build_random_effect_dataset(
        data, RandomEffectDataConfiguration("userId", "user", 1),
        entity_axis_size=n_entity)
    if ent is not None:
        # entity-major blocks sharded over the entity axis
        re_ds.X = jax.device_put(re_ds.X, ent)
    rand = RandomEffectCoordinate(
        dataset=re_ds,
        problem=RandomEffectOptimizationProblem(config=cfg(0.5), task=task))

    # Factored random effect: identity-projected raw blocks on the same
    # entity sharding; the latent refit's Kronecker batch is sample-major
    # and rides the data axis (FactoredRandomEffectCoordinate.scala:39-257).
    fre_ds = build_random_effect_dataset(
        data, RandomEffectDataConfiguration(
            "userId", "user", 1,
            projector=ProjectorConfig(ProjectorType.IDENTITY)),
        entity_axis_size=n_entity)
    if ent is not None:
        fre_ds.X = jax.device_put(fre_ds.X, ent)
    factored = FactoredRandomEffectCoordinate(
        dataset=fre_ds,
        problem=RandomEffectOptimizationProblem(config=cfg(0.5), task=task),
        latent_problem=GLMOptimizationProblem(config=cfg(0.1), task=task),
        latent_dim=2, num_inner_iterations=1)

    coordinates = {"fixed": fixed, "perUser": rand,
                   "perUserFactored": factored}
    labels = jnp.asarray(data.responses)
    weights = jnp.asarray(data.weights)
    offsets = jnp.asarray(data.offsets)

    # Route fixed-effect solves through the shard_map backend when a mesh
    # is active, as the production drivers do (GLMOptimizationProblem.run's
    # mesh check); restore whatever mesh the caller had.
    prev_mesh = get_default_mesh()
    set_default_mesh(mesh)
    try:
        if mesh is not None:
            with mesh:
                result = run_coordinate_descent(
                    coordinates, 1, task, labels, weights, offsets)
        else:
            result = run_coordinate_descent(
                coordinates, 1, task, labels, weights, offsets)
    finally:
        set_default_mesh(prev_mesh)

    fre_model = result.model.models["perUserFactored"]
    re_model = result.model.models["perUser"]

    # Matrix-factorization scoring: replicated factor tables, data-sharded
    # (row, col) code vectors, one jitted gather+dot
    # (model/MatrixFactorizationModel.scala:50,141's join as a gather).
    k_lat = 3
    mf = MatrixFactorizationModel(
        row_effect_type="userId", col_effect_type="itemId",
        row_factors=jnp.asarray(
            rng.normal(size=(n_users, k_lat)).astype(np.float32)),
        col_factors=jnp.asarray(
            rng.normal(size=(n_items, k_lat)).astype(np.float32)),
    )
    r_codes = jnp.asarray(users.astype(np.int32))
    # every item id appears, so dictionary codes == raw ids below
    items = rng.permutation(
        np.resize(np.arange(n_items, dtype=np.int32), rows))
    c_codes = jnp.asarray(items)
    if mesh is not None:
        data_sharded = NamedSharding(mesh, P((DATA_AXIS, ENTITY_AXIS)))
        repl = NamedSharding(mesh, P())
        r_codes = jax.device_put(r_codes, data_sharded)
        c_codes = jax.device_put(c_codes, data_sharded)
        rf = jax.device_put(mf.row_factors, repl)
        cf = jax.device_put(mf.col_factors, repl)
    else:
        rf, cf = mf.row_factors, mf.col_factors

    @jax.jit
    def mf_score(rf, cf, r, c):
        return jnp.sum(rf[r] * cf[c], axis=-1)

    from photon_ml_tpu.utils.sync_telemetry import record_host_fetch

    mf_scores = np.asarray(jax.device_get(mf_score(rf, cf, r_codes, c_codes)))
    record_host_fetch(site="multichip.parity")
    # parity with the model's host-side scoring path
    data.encode_ids("itemId", items)
    np.testing.assert_allclose(
        # photonlint: allow-W103(parity check: fetching both score paths to host for comparison is the whole point of this tool)
        mf_scores, np.asarray(mf.score(data)), rtol=1e-5, atol=1e-6)

    # --- explicit collectives backend: shard_map + psum fixed-effect fit
    # (mesh=None: the same problem solved locally — the parity referent).
    sm_problem = GLMOptimizationProblem(config=cfg(0.1), task=task)
    if mesh is not None:
        sm_model, _ = run_glm_shard_map(
            sm_problem, shard_batch(fe_ds.batch, mesh), mesh)
    else:
        sm_model, _ = sm_problem.run(fe_ds.batch)

    return {
        "objectives": np.asarray(
            [s.objective for s in result.states], dtype=np.float64),
        "fixed": np.asarray(
            result.model.models["fixed"].coefficients.means),
        "re_coefficients": np.asarray(re_model.to_raw().coefficients
                                      if hasattr(re_model, "to_raw")
                                      else re_model.coefficients),
        "projection": np.asarray(fre_model.projection),
        "latent": np.asarray(fre_model.coefficients_latent),
        "mf_scores": mf_scores,
        "shardmap_fixed": np.asarray(sm_model.coefficients.means),
    }


def check_game_step_multichip(n_devices: int, devices=None,
                              parity_summary: bool = False) -> dict:
    """Build an (n_data x n_entity) mesh over ``n_devices`` devices, run the
    GAME step on it, and sanity-assert finiteness. Returns the results dict
    (the pytest tier additionally asserts parity vs ``run_game_step(mesh=None)``).

    With ``parity_summary=True`` (the dry-run gate's mode) the single-device
    referent is also computed and ONE auditable summary line is printed —
    platform, device count, mesh shape, coordinates covered, and the max
    elementwise deviation from the single-device ground truth — so a green
    gate record witnesses *what* ran, the way the reference's per-test
    logging under SparkTestUtils.sparkTest does.
    """
    import jax

    from photon_ml_tpu.parallel.mesh import make_mesh

    devs = list(devices if devices is not None else jax.devices())
    assert len(devs) >= n_devices, (
        f"need {n_devices} devices, have {len(devs)}")
    # Split the mesh: data-parallel fixed effects x entity-parallel random
    # effects (e.g. 4x2 on 8 devices) — the GAME layout from SURVEY §5.8.
    n_entity = 2 if n_devices % 2 == 0 and n_devices > 1 else 1
    n_data = n_devices // n_entity
    mesh = make_mesh(num_data=n_data, num_entity=n_entity,
                     devices=devs[:n_devices])
    out = run_game_step(n_data=n_data, n_entity=n_entity, mesh=mesh)
    for key, val in out.items():
        assert np.all(np.isfinite(val)), f"non-finite {key}"
    if parity_summary:
        ref = run_game_step(n_data=n_data, n_entity=n_entity, mesh=None)
        max_dev = max(
            float(np.max(np.abs(np.asarray(out[k], dtype=np.float64)
                                - np.asarray(ref[k], dtype=np.float64))))
            for k in out)
        assert max_dev < 1e-3, (
            f"mesh run deviates from single-device referent by {max_dev}")
        print(
            "multichip ok: "
            f"platform={jax.default_backend()} n_devices={n_devices} "
            f"mesh=(data={n_data},entity={n_entity}) "
            "coordinates=fixed,randomEffect,factoredRandomEffect,"
            "mfScoring,shardMapFixed "
            f"max_parity_dev={max_dev:.3e}",
            flush=True)
    return out
