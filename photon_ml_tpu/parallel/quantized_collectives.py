"""EQuARX-style quantized collectives: int8 wire traffic, f32 math.

PR 12 made the GAME collective traffic real — mesh-sharded random-effect
scoring psums a full sample-axis partial every chunk, and the sharded
fixed-effect update all-gathers coefficient/gradient shards every
objective evaluation. EQuARX (PAPERS.md, arXiv 2306.08585) shows that
quantizing exactly this traffic — blockwise-scaled int8 with full-
precision accumulation — costs negligible model quality at 2-4x less
bytes moved. This module is that trade as two drop-in wrappers:

- :func:`qpsum` — ``lax.psum`` with optional int8 payload compression:
  quantize the local partial blockwise (per-block absmax scale),
  all-gather the int8 payload + f32 scales (the compressed wire
  traffic), then dequantize and SUM IN F32 on every device. The
  accumulator is always f32 (photonlint W801-clean by construction);
  only the wire representation is low-precision.
- :func:`qall_gather` — tiled ``lax.all_gather`` of a 1-D shard with
  the same blockwise-int8 wire format, dequantized to the caller's
  dtype on arrival.

Mode ``"none"`` (the default everywhere) is byte-for-byte the plain
collective — callers thread a ``--collective-quant`` flag and pay
nothing until they opt in. Payloads smaller than one quantization block
(scalars: every solver inner product) also fall back to the plain
collective: a 4-byte scalar cannot compress, and quantizing it would
only add error.

Error model: per-block absmax scaling bounds the per-element
quantization error by ``absmax(block) / 127 / 2`` — relative error
~0.4% of the block's largest magnitude. Summing K dequantized shards
in f32 grows the absolute error at most linearly in K. Outlier-heavy
blocks (one huge element) degrade the rest of their block; the block
size trades scale overhead (4 bytes per ``block`` elements) against
outlier blast radius.

Accounting: collectives run inside jit, so byte counting is host-side
at the dispatch sites (:func:`record_collective_bytes`), feeding the
``collective_bytes{site,mode}`` counter. Counts are per-device payload
bytes per collective round — a deliberate lower bound (line-search
extra evaluations inside a fused solver loop are invisible to the
host), consistent across modes so the compression ratio is exact.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.obs.metrics import REGISTRY, MetricsRegistry

Array = jnp.ndarray

#: Wire-format modes for the quantized collective wrappers.
QUANT_MODES = ("none", "int8")

#: Elements per quantization block: per-block f32 absmax scale amortized
#: over this many int8 payload elements (1.6% byte overhead), small
#: enough that one outlier only degrades its own 256-element block.
QUANT_BLOCK = 256


def check_quant_mode(mode: str) -> str:
    """Validate a ``--collective-quant`` value; returns it for chaining."""
    if mode not in QUANT_MODES:
        raise ValueError(
            f"unknown collective-quant mode {mode!r}; "
            f"expected one of {QUANT_MODES}")
    return mode


def quantize_blockwise(x: Array, block: int = QUANT_BLOCK
                       ) -> tuple[Array, Array]:
    """Flatten + pad ``x`` to blocks of ``block`` and quantize each to
    int8 with a per-block absmax scale. Returns ``(q [nb, block] int8,
    scale [nb] f32)``; ``dequantize_blockwise`` inverts it up to the
    documented per-block error bound. Zero blocks quantize to zeros
    with scale 0 (the round trip is exact there)."""
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    pad = (-n) % block
    if pad:
        flat = jnp.pad(flat, (0, pad))
    blocks = flat.reshape(-1, block)
    absmax = jnp.max(jnp.abs(blocks), axis=1)
    scale = absmax * jnp.float32(1.0 / 127.0)
    # scale == 0 => the whole block is 0 => 0 / tiny == 0: no where needed
    safe = jnp.maximum(scale, jnp.finfo(jnp.float32).tiny)
    q = jnp.clip(jnp.round(blocks / safe[:, None]), -127.0, 127.0)
    return q.astype(jnp.int8), scale


def dequantize_blockwise(q: Array, scale: Array) -> Array:
    """int8 blocks + per-block scales back to f32 (``[..., nb, block]``).
    The multiply runs in f32: the f32 accumulator every downstream
    reduction relies on starts here, NOT at the reduction."""
    return q.astype(jnp.float32) * scale[..., None]


def _engages(x: Array, mode: str, block: int) -> bool:
    # static decision (shapes are concrete under trace): sub-block
    # payloads — every scalar psum in the solvers — can't compress
    return mode == "int8" and x.size >= block


def qpsum(x: Array, axis_name, mode: str = "none",
          block: int = QUANT_BLOCK) -> Array:
    """``lax.psum(x, axis_name)`` with optional int8 wire compression.

    ``axis_name=None`` is the identity (the un-sharded caller
    convention shared with ``aggregators._maybe_psum``). Mode
    ``"none"``, scalars, and sub-block payloads take the plain psum.
    int8 mode ships ``ceil(n/block)`` int8 blocks + f32 scales instead
    of ``n`` f32 elements, then dequantizes and sums the K shard
    partials in f32 on every device — same replicated result contract
    as psum, reassociated like any tree reduction."""
    if axis_name is None:
        return x
    x = jnp.asarray(x)
    if not _engages(x, check_quant_mode(mode), block):
        return lax.psum(x, axis_name)
    q, scale = quantize_blockwise(x, block)
    q_all = lax.all_gather(q, axis_name)        # [K, nb, block] int8 wire
    scale_all = lax.all_gather(scale, axis_name)  # [K, nb] f32 wire
    total = jnp.sum(dequantize_blockwise(q_all, scale_all), axis=0,
                    dtype=jnp.float32)
    return total.reshape(-1)[: x.size].reshape(x.shape).astype(x.dtype)


def qall_gather(x: Array, axis_name, mode: str = "none",
                block: int = QUANT_BLOCK) -> Array:
    """Tiled ``lax.all_gather`` of a 1-D shard with optional int8 wire
    compression (the sharded-update iterate/gradient gather of
    arXiv 2004.13336). Every device receives each shard's int8 blocks +
    scales and dequantizes locally, so the full vector is f32-identical
    on all replicas (the bit-identical-iterates invariant survives —
    everyone dequantizes the same bytes)."""
    if axis_name is None:
        return x
    x = jnp.asarray(x)
    if x.ndim != 1 or not _engages(x, check_quant_mode(mode), block):
        return lax.all_gather(x, axis_name, tiled=True)
    n = x.shape[0]
    q, scale = quantize_blockwise(x, block)
    q_all = lax.all_gather(q, axis_name)          # [K, nb, block]
    scale_all = lax.all_gather(scale, axis_name)  # [K, nb]
    deq = dequantize_blockwise(q_all, scale_all)
    k = deq.shape[0]
    # trim each shard's block padding before tiling the shards together
    return deq.reshape(k, -1)[:, :n].reshape(-1).astype(x.dtype)


# -- host-side byte accounting ---------------------------------------------


def collective_payload_bytes(num_elements: int, itemsize: int = 4,
                             mode: str = "none",
                             block: int = QUANT_BLOCK) -> int:
    """Per-device wire payload of one collective round: what one shard
    contributes to the gather/reduce. int8 mode counts the quantized
    blocks plus their f32 scales; sub-block payloads fall back exactly
    like the wrappers do, so the ratio reported by the counters matches
    the bytes the compiled program actually moves."""
    n = int(num_elements)
    if check_quant_mode(mode) == "int8" and n >= block:
        nblocks = -(-n // block)
        return nblocks * block + nblocks * 4
    return n * int(itemsize)


def record_collective_bytes(site: str, mode: str, num_elements: int,
                            itemsize: int = 4, rounds: int = 1,
                            block: int = QUANT_BLOCK,
                            registry: MetricsRegistry = REGISTRY) -> int:
    """Count ``rounds`` collective rounds of the given payload on the
    ``collective_bytes{site,mode}`` counter (host-side: collectives run
    inside jit where counting is impossible — dispatch sites call this
    with their known round count, documented as a lower bound). The
    ``mode`` label records the EFFECTIVE wire format: an int8 request
    whose payload is sub-block ships plain f32, and is labeled so."""
    effective = ("int8" if check_quant_mode(mode) == "int8"
                 and int(num_elements) >= block else "none")
    nbytes = collective_payload_bytes(num_elements, itemsize, mode,
                                      block) * max(0, int(rounds))
    if nbytes:
        registry.counter("collective_bytes").inc(nbytes, site=site,
                                                 mode=effective)
    return nbytes
