"""Explicit-collectives distributed GLM fit: shard_map + psum over the mesh.

The default distributed path lets GSPMD auto-partition the jitted solver
over a row-sharded batch (parallel/mesh.py; SURVEY §5.8). This module is the
*manual* backend — the moral equivalent of the reference's treeAggregate
call sites made explicit (reference: photon-ml/src/main/scala/com/linkedin/
photon/ml/function/ValueAndGradientAggregator.scala:243,
HessianVectorAggregator.scala:146):

- every device runs the SAME L-BFGS/OWL-QN/TRON loop on its row shard;
- each objective evaluation ends in ``lax.psum`` over the ``data`` axis, so
  every device sees the same collective result and the replicated
  coefficient iterates stay bit-identical ACROSS DEVICES (the invariant
  that replaces the reference's coefficient Broadcast);
- per-shard shapes are local, which lets the fused Pallas kernel engage on
  each shard (ops/pallas_kernels.py's shard_map gate).

Use this path when GSPMD's choices need overriding (e.g. to force the
single-pass kernel, or to compose with other manual collectives).

Parity with the local path: psum sums per-shard partials, which reassociates
the floating-point reduction relative to ``GLMOptimizationProblem.run`` on
the full batch. In float64 both paths converge to the same optimum to
machine epsilon; in float32, when the convergence tolerance sits below the
f32 noise floor (~1e-7 relative), the two trajectories stall at points that
differ at the noise-floor scale (~1e-4 coefficient max-abs observed). That
is inherent to distributed summation — the reference's treeAggregate has the
same property vs a sequential fold — and is pinned by
tests/test_mesh_routing.py's paired f64/f32 parity tests.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

try:  # moved across jax versions
    from jax import shard_map as _shard_map_new  # jax >= 0.8

    def _shard_map(f, mesh, in_specs, out_specs):
        try:
            return _shard_map_new(f, mesh=mesh, in_specs=in_specs,
                                  out_specs=out_specs, check_vma=False)
        except TypeError:  # older keyword spelling
            return _shard_map_new(f, mesh=mesh, in_specs=in_specs,
                                  out_specs=out_specs, check_rep=False)
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def _shard_map(f, mesh, in_specs, out_specs):
        return _shard_map_old(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=False)

from photon_ml_tpu.data.batch import Batch, pad_batch
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.optimize.common import OptimizationResult, solver_x0
from photon_ml_tpu.optimize.problem import GLMOptimizationProblem
from photon_ml_tpu.parallel.mesh import DATA_AXIS, pad_rows_to_multiple

Array = jnp.ndarray


def run_glm_shard_map(
        problem: GLMOptimizationProblem,
        batch: Batch,
        mesh,
        initial: Optional[Array] = None,
) -> tuple[GeneralizedLinearModel, OptimizationResult]:
    """Fit ``problem`` on ``batch`` with rows explicitly sharded over the
    mesh ``data`` axis. Works for any row-major batch layout (DenseBatch,
    EllBatch — every array leaf has rows leading). Rows not divisible by
    the data-axis size are padded with zero-weight rows here.
    """
    n_shards = mesh.shape[DATA_AXIS]
    rows = batch.labels.shape[0]
    padded = pad_rows_to_multiple(rows, n_shards)
    if padded != rows:
        batch = pad_batch(batch, padded)

    dim = batch.num_features
    x0 = solver_x0(batch.acc_dtype, dim, initial)
    # psum-ing objective: every reduction crosses the data axis.
    obj = dataclasses.replace(problem.objective(), axis_name=DATA_AXIS)

    def local_fit(shard, x0_rep):
        x, history, progressed = problem.solve(obj, shard, x0_rep)
        return x, history, progressed

    row_specs = jax.tree_util.tree_map(lambda _: P(DATA_AXIS), batch)
    # grads are psum-identical on every device, but the replication checker
    # can't prove it through the while_loop — checking is disabled.
    fit = _shard_map(
        local_fit, mesh,
        in_specs=(row_specs, P()),
        out_specs=(P(), P(), P()),
    )
    x, history, progressed = jax.jit(fit)(batch, x0)

    # Variances/publication run on the full (GSPMD-sharded) batch.
    return problem.publish(x, history, progressed, problem.objective(),
                           batch)
