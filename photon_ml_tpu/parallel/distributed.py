"""Explicit-collectives distributed GLM fit: shard_map + psum over the mesh.

The default distributed path lets GSPMD auto-partition the jitted solver
over a row-sharded batch (parallel/mesh.py; SURVEY §5.8). This module is the
*manual* backend — the moral equivalent of the reference's treeAggregate
call sites made explicit (reference: photon-ml/src/main/scala/com/linkedin/
photon/ml/function/ValueAndGradientAggregator.scala:243,
HessianVectorAggregator.scala:146):

- every device runs the SAME L-BFGS/OWL-QN/TRON loop on its row shard;
- each objective evaluation ends in ``lax.psum`` over the ``data`` axis, so
  every device sees the same collective result and the replicated
  coefficient iterates stay bit-identical ACROSS DEVICES (the invariant
  that replaces the reference's coefficient Broadcast);
- per-shard shapes are local, which lets the fused Pallas kernel engage on
  each shard (ops/pallas_kernels.py's shard_map gate).

Use this path when GSPMD's choices need overriding (e.g. to force the
single-pass kernel, or to compose with other manual collectives).

Parity with the local path: psum sums per-shard partials, which reassociates
the floating-point reduction relative to ``GLMOptimizationProblem.run`` on
the full batch. In float64 both paths converge to the same optimum to
machine epsilon; in float32, when the convergence tolerance sits below the
f32 noise floor (~1e-7 relative), the two trajectories stall at points that
differ at the noise-floor scale (~1e-4 coefficient max-abs observed). That
is inherent to distributed summation — the reference's treeAggregate has the
same property vs a sequential fold — and is pinned by
tests/test_mesh_routing.py's paired f64/f32 parity tests.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

try:  # moved across jax versions
    from jax import shard_map as _shard_map_new  # jax >= 0.8

    def _shard_map(f, mesh, in_specs, out_specs):
        try:
            return _shard_map_new(f, mesh=mesh, in_specs=in_specs,
                                  out_specs=out_specs, check_vma=False)
        except TypeError:  # older keyword spelling
            return _shard_map_new(f, mesh=mesh, in_specs=in_specs,
                                  out_specs=out_specs, check_rep=False)
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def _shard_map(f, mesh, in_specs, out_specs):
        return _shard_map_old(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=False)

from photon_ml_tpu.data.batch import Batch, pad_batch
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.optimize.common import OptimizationResult, solver_x0
from photon_ml_tpu.optimize.problem import GLMOptimizationProblem
from photon_ml_tpu.parallel.mesh import DATA_AXIS, pad_rows_to_multiple
from photon_ml_tpu.parallel.quantized_collectives import (
    qall_gather,
    record_collective_bytes,
)

Array = jnp.ndarray


def run_glm_shard_map(
        problem: GLMOptimizationProblem,
        batch: Batch,
        mesh,
        initial: Optional[Array] = None,
) -> tuple[GeneralizedLinearModel, OptimizationResult]:
    """Fit ``problem`` on ``batch`` with rows explicitly sharded over the
    mesh ``data`` axis. Works for any row-major batch layout (DenseBatch,
    EllBatch — every array leaf has rows leading). Rows not divisible by
    the data-axis size are padded with zero-weight rows here.

    With ``problem.shard_weight_update`` set, the optimizer state and the
    coefficient update are additionally sharded over the SAME data axis
    (arXiv 2004.13336): each replica all-gathers the iterate for the
    objective evaluation, keeps only its gradient/coefficient shard, and
    the converged shard is all-gathered once at the end — instead of
    every replica running the full-dimension two-loop/CG redundantly.
    """
    n_shards = mesh.shape[DATA_AXIS]
    rows = batch.labels.shape[0]
    padded = pad_rows_to_multiple(rows, n_shards)
    if padded != rows:
        batch = pad_batch(batch, padded)

    dim = batch.num_features
    x0 = solver_x0(batch.acc_dtype, dim, initial)
    # psum-ing objective: every reduction crosses the data axis.
    obj = dataclasses.replace(problem.objective(), axis_name=DATA_AXIS)
    row_specs = jax.tree_util.tree_map(lambda _: P(DATA_AXIS), batch)

    shard_update = problem.shard_weight_update
    if shard_update and (problem.box is not None or problem.track_iterates):
        logging.getLogger(__name__).warning(
            "shard_weight_update is incompatible with box constraints / "
            "track_iterates; falling back to the replicated update")
        shard_update = False

    if shard_update:
        local_fit = _sharded_update_local_fit(problem, obj, dim, n_shards,
                                              x0.dtype)
    else:
        def local_fit(shard, x0_rep):
            x, history, progressed = problem.solve(obj, shard, x0_rep)
            return x, history, progressed

    # grads are psum-identical on every device, but the replication checker
    # can't prove it through the while_loop — checking is disabled.
    fit = _shard_map(
        local_fit, mesh,
        in_specs=(row_specs, P()),
        out_specs=(P(), P(), P()),
    )
    x, history, progressed = jax.jit(fit)(batch, x0)

    # Host-side collective-traffic ledger (collectives run inside the
    # jitted loop where counting is impossible): one d-vector gradient
    # psum per iteration on every backend, plus the sharded update's
    # per-evaluation iterate all-gather of one shard. Line-search extra
    # evaluations are invisible here — a documented lower bound, applied
    # identically for both wire modes so the ratio is exact.
    iters = int(history.num_iterations)
    itemsize = jnp.dtype(batch.acc_dtype).itemsize
    record_collective_bytes("fe.grad_psum", problem.collective_quant,
                            dim, itemsize=itemsize, rounds=iters)
    if shard_update:
        d_pad = pad_rows_to_multiple(dim, n_shards)
        record_collective_bytes("fe.iterate_gather",
                                problem.collective_quant,
                                d_pad // n_shards, itemsize=itemsize,
                                rounds=iters)

    # Variances/publication run on the full (GSPMD-sharded) batch.
    return problem.publish(x, history, progressed, problem.objective(),
                           batch)


def _sharded_update_local_fit(problem: GLMOptimizationProblem, obj,
                              dim: int, n_shards: int, dtype):
    """Build the per-replica body of a weight-update-sharded GLM fit.

    The coefficient vector is zero-padded to a multiple of ``n_shards``
    and split evenly; padded coordinates provably stay 0 (their gradient
    is identically 0, and OWL-QN's pseudo-gradient at x=0, g=0, l1>=0 is
    0), so padding never perturbs the solve. The solver itself runs with
    ``update_axis_name`` set, psum-ing every d-vector reduction, which
    makes the sharded recursion exactly the full-dimension one up to
    reduction order.
    """
    d_pad = pad_rows_to_multiple(dim, n_shards)
    shard_d = d_pad // n_shards
    quant = problem.collective_quant

    def gather_full(x_shard):
        # the per-evaluation iterate/gradient gather — the compressible
        # wire traffic of the sharded update (every replica dequantizes
        # the same bytes, so iterates stay replica-identical)
        return qall_gather(x_shard, DATA_AXIS, mode=quant)[:dim]

    def slice_own(full_vec):
        start = lax.axis_index(DATA_AXIS) * shard_d
        return lax.dynamic_slice(jnp.pad(full_vec, (0, d_pad - dim)),
                                 (start,), (shard_d,))

    def vg(x_shard, payload):
        obj_p, data = payload
        f, g = obj_p.calculate(gather_full(x_shard), data)
        return f, slice_own(g)

    def hvp(x_shard, v_shard, payload):
        obj_p, data = payload
        hv = obj_p.hessian_vector(gather_full(x_shard),
                                  gather_full(v_shard), data)
        return slice_own(hv)

    full_mask = (jnp.asarray(problem.l1_mask).astype(dtype)
                 if problem.l1_mask is not None else None)

    def local_fit(shard, x0_rep):
        l1_mask = slice_own(full_mask) if full_mask is not None else None
        x_shard, history, progressed = problem.solve(
            obj, shard, slice_own(x0_rep),
            update_axis_name=DATA_AXIS, vg_fn=vg, hvp_fn=hvp,
            l1_mask=l1_mask)
        # the paper's step: all-gather the updated shard once per solve,
        # not per iteration — the full vector only rematerializes here.
        return gather_full(x_shard), history, progressed

    return local_fit
