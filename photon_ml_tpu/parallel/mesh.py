"""Device mesh + sharding policy: the distributed runtime.

TPU-native replacement for the reference's Spark runtime layer
(reference: Spark 1.6 RDD/Broadcast/treeAggregate; photon-ml's wrappers
RDDLike.scala:30-60, BroadcastLike.scala:25, SparkContextConfiguration.scala:
39-110, and the treeAggregate-depth policy cli/game/training/Driver.scala:
357-363). The mapping (SURVEY §5.8):

- ``treeAggregate(depth)``  ->  XLA all-reduce over the mesh ``data`` axis,
  inserted automatically by GSPMD when a reduction crosses sharded rows.
  The depth-1-vs-2 knob disappears: ICI all-reduce is already tree/ring.
- ``Broadcast[coefficients]`` -> coefficients replicated in HBM; no per-
  iteration host broadcast, no persist/unpersist choreography.
- entity-partitioned RDDs -> arrays sharded over the ``entity`` axis.

One mesh with two logical axes covers the framework:
- ``data``:   shards example rows (fixed-effect aggregation axis)
- ``entity``: shards per-entity blocks (random-effect axis)

On a single chip both axes have size 1 and every sharding below is a no-op;
the same code compiles unchanged for a v5e-16 slice.
"""

from __future__ import annotations

import logging
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu.data.batch import DenseBatch, EllBatch

DATA_AXIS = "data"
ENTITY_AXIS = "entity"

# Process-wide default mesh: the drivers' distribution context. When set
# with a >1 data axis, GLMOptimizationProblem.run routes fixed-effect
# solves through the explicit shard_map backend so per-shard shapes stay
# local and the fused Pallas kernel engages on every chip (it has no GSPMD
# partitioning rule, so the GSPMD path would disable it on >1 device —
# ops/pallas_kernels.pallas_supported).
_default_mesh: Optional[Mesh] = None


def set_default_mesh(mesh: Optional[Mesh]) -> None:
    global _default_mesh
    _default_mesh = mesh


def get_default_mesh() -> Optional[Mesh]:
    return _default_mesh


def largest_entity_divisor(num_devices: int, requested: int) -> int:
    """Largest divisor of ``num_devices`` that is <= ``requested``.

    The mesh must factor as data x entity over all devices, so an entity
    axis that doesn't divide the device count can't be honored exactly;
    this is the deterministic fallback (always >= 1)."""
    k = max(1, min(int(requested), int(num_devices)))
    while num_devices % k != 0:
        k -= 1
    return k


def setup_default_mesh(num_entity: int = 1) -> Optional[Mesh]:
    """Driver bootstrap: build an all-devices (data x entity) mesh and make
    it the process default. Single-device processes get no mesh (every
    sharding is a no-op there).

    A requested ``num_entity`` that doesn't evenly divide the device count
    falls back to the largest divisor that does (with a logged warning)
    instead of failing the run — the driver's ``--re-entity-shards auto``
    contract."""
    n = len(jax.devices())
    if n <= 1:
        set_default_mesh(None)
        return None
    granted = largest_entity_divisor(n, num_entity)
    if granted != num_entity:
        logging.getLogger(__name__).warning(
            "entity axis %d does not divide %d devices; falling back to "
            "%d entity shards", num_entity, n, granted)
    mesh = make_mesh(num_entity=granted)
    set_default_mesh(mesh)
    return mesh


def make_mesh(
    num_data: Optional[int] = None,
    num_entity: int = 1,
    devices: Optional[list] = None,
) -> Mesh:
    """Build a (data x entity) mesh over the available devices.

    Defaults to all devices on the data axis — the right layout for
    fixed-effect-dominated workloads; GAME drivers pass ``num_entity`` to
    split the mesh (e.g. 4x2 on 8 chips).
    """
    devs = np.asarray(devices if devices is not None else jax.devices())
    n = devs.size
    if num_data is None:
        num_data = n // num_entity
    if num_data * num_entity != n:
        raise ValueError(
            f"mesh {num_data}x{num_entity} != {n} available devices")
    return Mesh(devs.reshape(num_data, num_entity), (DATA_AXIS, ENTITY_AXIS))


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Rows sharded over the data axis (1-D arrays and leading dim of 2-D)."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch, mesh: Mesh):
    """Place a batch with rows sharded over the mesh data axis.

    Rows must be a multiple of the data-axis size — callers pad with
    zero-weight rows first (data/batch.pad_batch), the moral equivalent of
    the reference's partition balancing.
    """
    n_shards = mesh.shape[DATA_AXIS]
    rows = batch.labels.shape[0]
    if rows % n_shards != 0:
        raise ValueError(
            f"batch rows {rows} not divisible by data axis {n_shards}; "
            "pad with zero-weight rows first")
    row_sharded = NamedSharding(mesh, P(DATA_AXIS))
    if isinstance(batch, DenseBatch):
        return DenseBatch(
            X=jax.device_put(batch.X, row_sharded),
            labels=jax.device_put(batch.labels, row_sharded),
            offsets=jax.device_put(batch.offsets, row_sharded),
            weights=jax.device_put(batch.weights, row_sharded),
        )
    if isinstance(batch, EllBatch):
        return EllBatch(
            indices=jax.device_put(batch.indices, row_sharded),
            values=jax.device_put(batch.values, row_sharded),
            labels=jax.device_put(batch.labels, row_sharded),
            offsets=jax.device_put(batch.offsets, row_sharded),
            weights=jax.device_put(batch.weights, row_sharded),
            dim=batch.dim,
        )
    raise TypeError(f"unknown batch type {type(batch)}")


def pad_rows_to_multiple(n: int, multiple: int) -> int:
    return -(-n // multiple) * multiple


import functools


@functools.lru_cache(maxsize=32)
def _replicator(mesh: Mesh):
    return jax.jit(lambda a: a, out_shardings=NamedSharding(mesh, P()))


def ensure_addressable(x):
    """Make a device array fully addressable from this process (replicating
    NON-fully-addressable global arrays over their own mesh) WITHOUT
    fetching it to host. Callers that batch several arrays into one
    ``jax.device_get`` (the lazy trackers' single-fetch materialization)
    route each through here first so the same code runs single-chip,
    multi-chip, and multi-host. The replicating jit is cached per mesh so
    repeated calls don't re-trace."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        x = _replicator(x.sharding.mesh)(x)
    return x


def host_array(x) -> np.ndarray:
    """``np.asarray`` that also handles NON-fully-addressable global
    arrays (multi-controller runs) via :func:`ensure_addressable`. The
    host-side trackers (per-entity iteration/convergence counts) use this
    so the same coordinate code runs single-chip, multi-chip, and
    multi-host."""
    return np.asarray(ensure_addressable(x))
