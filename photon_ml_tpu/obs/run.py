"""Run-scoped observability: ``--trace-dir`` integration for the drivers.

:func:`start_observed_run` turns one driver invocation into an observed
run: it installs the process-global tracer, writes the run manifest
immediately (a crashed run still leaves provenance behind), starts the
stall-detecting heartbeat appending live to ``metrics.jsonl`` and
spilling closed spans live to ``spans.jsonl`` (bounded span buffer;
a killed run keeps everything spilled so far), and — at
:meth:`ObservedRun.finish` — rebuilds the Chrome trace from the spill
and appends the final metrics snapshot::

    <trace-dir>/
      run_manifest.json   # jax version, backend, devices, flags, git
      trace.json          # Chrome trace events (Perfetto-loadable)
      spans.jsonl         # one span per line (jq/pandas-friendly, live)
      metrics.jsonl       # heartbeat lines (live) + final counter dump
      telemetry.jsonl     # --telemetry-endpoint fallback stream (only
                          # when a socket consumer never connects)

In multi-host runs every process passes its ``process_index`` with
``num_processes > 1`` and writes ``trace.<i>.json`` /
``metrics.<i>.jsonl`` / … so a shared trace dir holds the whole gang's
streams side by side.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from typing import Callable, Optional

from photon_ml_tpu.obs import trace
from photon_ml_tpu.obs.export import TELEMETRY_PROTO, TelemetrySink
from photon_ml_tpu.obs.heartbeat import Heartbeat
from photon_ml_tpu.obs.metrics import REGISTRY, MetricsRegistry
from photon_ml_tpu.utils.faults import fault_point
from photon_ml_tpu.utils.retry import (
    RetryExhaustedError,
    RetryPolicy,
    call_with_retry,
)

#: Trace-export retry: short and bounded — observability I/O must never
#: stall (or kill) the run it is observing.
_FLUSH_RETRY = RetryPolicy(max_attempts=3, base_delay_seconds=0.01,
                           max_delay_seconds=0.1)


def _git_describe(cwd: str) -> Optional[str]:
    try:
        out = subprocess.run(
            ["git", "describe", "--always", "--dirty", "--tags"],
            cwd=cwd, capture_output=True, text=True, timeout=10)
    except (OSError, subprocess.TimeoutExpired):
        return None
    return out.stdout.strip() or None


def run_manifest(flags: Optional[dict] = None,
                 process_index: int = 0,
                 num_processes: int = 1,
                 probe_backend: bool = True) -> dict:
    """Provenance record for one run: versions, backend, devices, the
    resolved driver flags, and the repo's git-describe (when available).

    ``probe_backend=False`` skips the ``jax.device_count()`` /
    ``jax.default_backend()`` queries — querying them INITIALIZES the
    local backend, and a multi-host worker that has not yet called
    ``jax.distributed.initialize`` must not do that (jax raises
    "initialize() must be called before any JAX computations" at gang
    formation). The multi-host ObservedRun writes the manifest with the
    backend fields deferred and fills them in at finish(), when the gang
    is long formed."""
    import jax

    if probe_backend:
        try:
            device_count = jax.device_count()
            backend = jax.default_backend()
        except RuntimeError:  # backend not initializable (bare host)
            device_count, backend = 0, "uninitialized"
    else:
        device_count, backend = None, "deferred"
    repo_dir = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return {
        "kind": "run_manifest",
        "telemetry_proto": TELEMETRY_PROTO,
        "time": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "argv": list(sys.argv),
        "python": sys.version.split()[0],
        "jax_version": jax.__version__,
        "backend": backend,
        "device_count": device_count,
        "process_index": process_index,
        "num_processes": num_processes,
        "git_describe": _git_describe(repo_dir),
        "flags": {} if flags is None else {
            k: v for k, v in sorted(flags.items())
            if isinstance(v, (bool, int, float, str, type(None)))},
    }


class ObservedRun:
    """One driver invocation's tracer + heartbeat + output files.

    Spans spill incrementally: every heartbeat drains the tracer's
    buffer into ``spans.jsonl``, so a multi-day run's span buffer stays
    bounded by one heartbeat interval and a killed run keeps everything
    spilled so far; ``trace.json`` is rebuilt from the spill at
    :meth:`finish`.

    ``preserve_existing=True`` (a supervisor-relaunched worker) keeps
    the crashed incarnation's evidence instead of truncating it: the
    metrics stream is appended to (delimited by a ``run_restart``
    record — its stalled-heartbeat trail is the postmortem) and prior
    ``trace.json`` / ``spans.jsonl`` / ``run_manifest.json`` files are
    rotated to ``<name>.prev`` rather than overwritten.
    """

    def __init__(self, trace_dir: str,
                 process_index: int = 0,
                 num_processes: int = 1,
                 flags: Optional[dict] = None,
                 heartbeat_seconds: float = 10.0,
                 stall_seconds: float = 120.0,
                 warn: Optional[Callable[[str], None]] = None,
                 registry: Optional[MetricsRegistry] = None,
                 preserve_existing: bool = False,
                 telemetry_endpoint: Optional[str] = None,
                 device_telemetry: bool = False):
        self.trace_dir = trace_dir
        self._registry = registry or REGISTRY
        # --device-telemetry: arm the device plane (compile/retrace
        # attribution + HBM accounting). Imported lazily — the armed
        # modules touch jax only inside armed calls, so an un-flagged
        # run (and a bare multi-host worker pre-gang) never pays for it.
        self._device_telemetry = device_telemetry
        self._devicemem = None
        self._sample_on_beat = False
        if device_telemetry:
            from photon_ml_tpu.obs import compile as obs_compile
            from photon_ml_tpu.obs import devicemem

            obs_compile.arm(registry=self._registry)
            devicemem.arm(registry=self._registry)
            self._devicemem = devicemem
            # a multi-host worker must not probe devices before the
            # gang forms (the probe would initialize the local backend
            # and break jax.distributed.initialize) — its heartbeats
            # skip sampling; the finish() sample still stamps the peak
            self._sample_on_beat = num_processes == 1
        self._process_index = process_index
        self._exit_status = "ok"
        self._exit_reason = ""
        suffix = f".{process_index}" if num_processes > 1 else ""
        self.trace_path = os.path.join(trace_dir, f"trace{suffix}.json")
        self.spans_path = os.path.join(trace_dir, f"spans{suffix}.jsonl")
        self.telemetry_path = os.path.join(
            trace_dir, f"telemetry{suffix}.jsonl")
        self.metrics_path = os.path.join(
            trace_dir, f"metrics{suffix}.jsonl")
        self.manifest_path = os.path.join(
            trace_dir, f"run_manifest{suffix}.json")
        os.makedirs(trace_dir, exist_ok=True)
        if preserve_existing:
            for path in (self.trace_path, self.spans_path,
                         self.manifest_path):
                if os.path.exists(path):
                    os.replace(path, path + ".prev")

        # Multi-host: the worker has NOT called jax.distributed.initialize
        # yet, and probing the backend here would initialize it locally and
        # make gang formation raise — defer the backend fields to finish()
        self._manifest_args = dict(flags=flags,
                                   process_index=process_index,
                                   num_processes=num_processes)
        manifest = run_manifest(probe_backend=(num_processes == 1),
                                **self._manifest_args)
        with open(self.manifest_path, "w") as fh:
            json.dump(manifest, fh, indent=1)

        # Live telemetry plane (--telemetry-endpoint): a bounded
        # non-blocking sink shipping NDJSON records to a local consumer,
        # falling back to telemetry.jsonl in the trace dir when none
        # connects. The manifest is the stream's first record — a
        # consumer knows who it is watching before any span arrives.
        self.sink: Optional[TelemetrySink] = None
        if telemetry_endpoint:
            self.sink = TelemetrySink(
                telemetry_endpoint, fallback_path=self.telemetry_path,
                registry=self._registry, warn=warn)
            self.sink.emit(manifest)
        if preserve_existing and os.path.exists(self.metrics_path):
            with open(self.metrics_path, "a") as fh:
                fh.write(json.dumps({
                    "kind": "run_restart",
                    "time": time.strftime("%Y-%m-%dT%H:%M:%S")}) + "\n")
        else:
            # truncate a prior run's stream: heartbeat + final dump append
            open(self.metrics_path, "w").close()
        open(self.spans_path, "w").close()  # this incarnation's spill

        self._warn = warn
        self._spill_lock = threading.Lock()
        self._pending: list = []  # drained but not yet durably written
        self.tracer = trace.enable(process_index=process_index)
        self.heartbeat = Heartbeat(
            self.tracer, out_path=self.metrics_path,
            interval_seconds=heartbeat_seconds,
            stall_seconds=stall_seconds, warn=warn,
            registry=self._registry, on_beat=self._spill,
            on_record=self._export_record).start()
        self._finished = False

    def _export_record(self, record: dict) -> None:
        """Ship one kind-tagged record (heartbeat, run_end) on the live
        sink; a no-op without ``--telemetry-endpoint``."""
        if self.sink is not None:
            self.sink.emit({**record,
                            "process_index": self._process_index})

    def _spill(self) -> None:
        """Drain the tracer's closed spans into ``spans.jsonl`` (runs on
        every heartbeat and once more at finish). Drained spans are only
        discarded once the write succeeds — a transient full disk keeps
        them pending (capped at the tracer's buffer bound) for the next
        beat instead of losing the interval."""
        if self._sample_on_beat:
            # heartbeat-cadence device-memory sample BEFORE the metric
            # totals are read, so every heartbeat carries fresh
            # hbm_bytes gauges (contained: sampling must never take the
            # heartbeat down with it)
            try:
                self._devicemem.sample()
            except Exception:
                pass
        with self._spill_lock:
            drained = self.tracer.drain()
            if self.sink is not None:
                # exported exactly once, at drain time: a failed FILE
                # spill keeps spans pending for the next beat without
                # duplicating them on the live stream
                for e in drained:
                    self.sink.emit({"kind": "span",
                                    "process_index": self._process_index,
                                    **e})
            self._pending.extend(drained)
            if not self._pending:
                return
            cap = self.tracer.max_buffered_spans
            if len(self._pending) > cap:
                self.tracer.spans_dropped += len(self._pending) - cap
                self._pending = self._pending[-cap:]

            def write():
                # the obs.flush drill site: a full disk / flaky trace
                # mount retries briefly and then keeps the interval
                # PENDING — observability I/O can degrade, never kill
                fault_point("obs.flush", path=self.spans_path)
                with open(self.spans_path, "a") as fh:
                    for e in self._pending:
                        fh.write(json.dumps(e) + "\n")

            call_with_retry(write, site="obs.flush", policy=_FLUSH_RETRY)
            self._pending = []

    def set_exit_status(self, status: str, reason: str = "") -> None:
        """Record how the run is ending ("ok" default, "abort" on a
        clean abort, "preempted" on a graceful stop honored at a commit
        barrier, "error" otherwise) — written as the ``run_end`` record
        at :meth:`finish` so ``tools/photon_status.py`` can tell a
        finished run from an aborted or requeue-pending one."""
        self._exit_status = status
        self._exit_reason = reason

    def finish(self) -> None:
        """Stop the heartbeat and flush trace + metrics files
        (idempotent; call from the driver's ``finally``). Every export
        step is CONTAINED: a dead disk at exit loses trace output (with
        a warning), never the run's exit status."""
        if self._finished:
            return
        self._finished = True
        self.heartbeat.stop()
        for step, fn in (("spill", self._spill),
                         ("manifest", self._finish_manifest),
                         ("trace", self._finish_trace),
                         ("metrics", self._finish_metrics),
                         ("run_end", self._finish_run_end)):
            try:
                fn()
            except (OSError, ValueError, RetryExhaustedError) as e:
                if self._warn is not None:
                    self._warn(f"trace export ({step}) failed at finish: "
                               f"{e!r} — continuing")
        if self.sink is not None:
            self.sink.close()
        if self._device_telemetry:
            from photon_ml_tpu.obs import compile as obs_compile

            obs_compile.disarm()
            if self._devicemem is not None:
                self._devicemem.disarm()
        if trace.get_tracer() is self.tracer:
            trace.disable()

    def _finish_manifest(self) -> None:
        if self._manifest_args["num_processes"] > 1:
            # the gang is formed (or the run is over): the backend can be
            # probed safely now — rewrite the manifest with the live
            # backend/device fields the deferred first write skipped
            with open(self.manifest_path, "w") as fh:
                json.dump(run_manifest(probe_backend=True,
                                       **self._manifest_args), fh, indent=1)

    def _finish_trace(self) -> None:
        events = []
        with open(self.spans_path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except ValueError:
                    continue  # torn tail line from a killed incarnation
        doc = trace.chrome_document(events, self.tracer.process_index,
                                    self.tracer.start_unix)
        with open(self.trace_path, "w") as fh:
            json.dump(doc, fh)

    def _finish_metrics(self) -> None:
        def write():
            fault_point("obs.flush", path=self.metrics_path)
            with open(self.metrics_path, "a") as fh:
                for record in self._registry.snapshot():
                    fh.write(json.dumps(record) + "\n")

        call_with_retry(write, site="obs.flush", policy=_FLUSH_RETRY)

    def _finish_run_end(self) -> None:
        """Terminal record: the metrics stream (and the live telemetry
        stream) ends with how the run ended, so a status consumer can
        tell "finished clean" from "aborted" from "still running /
        killed" (no run_end line at all)."""
        record = {"kind": "run_end",
                  "time": time.strftime("%Y-%m-%dT%H:%M:%S"),
                  "status": self._exit_status,
                  "reason": self._exit_reason,
                  "uptime_s": round(self.tracer.uptime_seconds(), 3),
                  # final counter totals ride the terminal record: a
                  # SOCKET consumer has no exit snapshot file to read,
                  # and a short run's last heartbeat can predate the
                  # tail of the work (photon-top reads these)
                  "metric_totals": self._registry.totals()}
        if self._devicemem is not None:
            # one last sample (the gang — if any — is formed or gone by
            # now), then the run-wide HBM peak on the terminal record:
            # the capacity-planning number a finished run is asked for
            try:
                self._devicemem.sample()
            except Exception:
                pass
            record["peak_hbm_bytes"] = self._devicemem.peak_bytes()
        self._export_record(record)

        def write():
            fault_point("obs.flush", path=self.metrics_path)
            with open(self.metrics_path, "a") as fh:
                fh.write(json.dumps(record) + "\n")

        call_with_retry(write, site="obs.flush", policy=_FLUSH_RETRY)


def start_observed_run(trace_dir: str, **kwargs) -> ObservedRun:
    return ObservedRun(trace_dir, **kwargs)


def start_observed_run_from_flags(ns, process_index: int = 0,
                                  num_processes: int = 1,
                                  warn=None,
                                  preserve_existing: bool = False
                                  ) -> Optional[ObservedRun]:
    """Install the run-scoped tracer/heartbeat when the parsed driver
    flags carry ``--trace-dir`` (returns the ObservedRun to finish(), or
    None) — the one adapter both GAME drivers share."""
    endpoint = getattr(ns, "telemetry_endpoint", None)
    device_telemetry = bool(getattr(ns, "device_telemetry", False))
    if not getattr(ns, "trace_dir", None):
        if endpoint:
            # the sink rides the ObservedRun's tracer/heartbeat/spill
            # machinery; silently ignoring the endpoint would hand the
            # operator a consumer that never hears anything
            raise ValueError(
                "--telemetry-endpoint requires --trace-dir (the live "
                "stream is fed by the run's span spill + heartbeat)")
        if device_telemetry:
            # same contract: the device plane's spans/gauges ride the
            # trace dir's spill + heartbeat stream
            raise ValueError(
                "--device-telemetry requires --trace-dir (compile spans "
                "and hbm gauges ride the run's span spill + heartbeat)")
        return None
    return start_observed_run(
        ns.trace_dir, process_index=process_index,
        num_processes=num_processes, flags=vars(ns),
        heartbeat_seconds=ns.trace_heartbeat_seconds,
        stall_seconds=ns.trace_stall_seconds, warn=warn,
        preserve_existing=preserve_existing,
        telemetry_endpoint=endpoint,
        device_telemetry=device_telemetry)
