"""Device-memory accounting: HBM gauges, peaks, per-coordinate watermarks.

The second device-plane half of ``--device-telemetry``. Armed, it:

- samples ``device.memory_stats()`` for every local device at heartbeat
  cadence (the ObservedRun's span-spill hook) into the
  ``hbm_bytes{device, kind}`` gauge family — ``bytes_in_use`` /
  ``peak_bytes_in_use`` where the runtime reports them (TPU/GPU), with
  a ``live_bytes`` fallback summed from ``jax.live_arrays()`` metadata
  on backends that don't (CPU), so the gauge family exists everywhere
  the tests run;
- tracks the run-wide peak (:func:`peak_bytes`), which the ObservedRun
  stamps into the ``run_end`` record as ``peak_hbm_bytes`` — the one
  number a capacity reviewer wants from a finished run;
- attributes watermarks per coordinate: the CD commit path calls
  :func:`note_coordinate` after installing a block (metadata-only —
  enumerating live arrays never syncs the device), and the existing
  sweep-boundary drain calls :func:`drain_coordinate_watermarks`,
  emitting a ``hbm_watermark_bytes{coordinate}`` gauge plus one
  ``cd.hbm_watermark`` span per coordinate touched that sweep.

Everything is gated on :func:`armed` so the un-flagged hot path pays
one module-global check, and jax is imported lazily so ``obs.run``
stays importable on a bare host.
"""

from __future__ import annotations

import threading
from typing import Optional

from photon_ml_tpu.obs import trace
from photon_ml_tpu.obs.metrics import REGISTRY, MetricsRegistry

_ARMED = False
_REGISTRY: MetricsRegistry = REGISTRY
_LOCK = threading.Lock()
_PEAK_BYTES = 0
#: coordinate id -> max live bytes observed at any of its commits since
#: the last sweep-boundary drain.
_COORD_WATERMARKS: dict[str, int] = {}

#: memory_stats keys worth exporting (the runtime reports many more;
#: these are the capacity-planning set).
_STAT_KINDS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
               "largest_alloc_size")


def arm(registry: Optional[MetricsRegistry] = None) -> None:
    global _ARMED, _REGISTRY, _PEAK_BYTES
    _REGISTRY = registry or REGISTRY
    with _LOCK:
        _PEAK_BYTES = 0
        _COORD_WATERMARKS.clear()
    _ARMED = True


def disarm() -> None:
    global _ARMED
    _ARMED = False


def armed() -> bool:
    return _ARMED


def peak_bytes() -> int:
    """Run-wide HBM peak over every :func:`sample` so far (bytes)."""
    with _LOCK:
        return _PEAK_BYTES


def _live_bytes() -> int:
    """Σ nbytes over live arrays — metadata-only, never a device sync."""
    import jax

    try:
        return sum(int(getattr(a, "nbytes", 0) or 0)
                   for a in jax.live_arrays())
    except Exception:  # pragma: no cover - backend without live_arrays
        return 0


def _note_peak(n: int) -> None:
    global _PEAK_BYTES
    with _LOCK:
        if n > _PEAK_BYTES:
            _PEAK_BYTES = n


def sample(registry: Optional[MetricsRegistry] = None) -> int:
    """One heartbeat-cadence sample of every local device's memory
    stats into ``hbm_bytes{device, kind}``. Returns the total in-use
    bytes across devices (live-bytes fallback where the runtime has no
    allocator stats)."""
    if not _ARMED:
        return 0
    import jax

    reg = registry or _REGISTRY
    gauge = reg.gauge("hbm_bytes")
    total_in_use = 0
    have_stats = False
    try:
        devices = jax.local_devices()
    except RuntimeError:  # backend not initializable
        devices = []
    for d in devices:
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        have_stats = True
        dev = f"{d.platform}:{d.id}"
        for kind in _STAT_KINDS:
            if kind in stats:
                gauge.set(int(stats[kind]), device=dev, kind=kind)
        total_in_use += int(stats.get("bytes_in_use", 0))
    if not have_stats:
        # CPU (and any runtime without allocator stats): the live-array
        # footprint is the best available in-use proxy
        total_in_use = _live_bytes()
        gauge.set(total_in_use, device="host", kind="live_bytes")
    _note_peak(total_in_use)
    return total_in_use


def note_coordinate(coordinate_id: str) -> None:
    """Record the current live-byte footprint against a coordinate —
    called by the CD commit path right after a block installs, so the
    per-coordinate watermark reflects that coordinate's update at its
    most buffer-heavy point the host can see."""
    if not _ARMED:
        return
    n = _live_bytes()
    _note_peak(n)
    with _LOCK:
        prev = _COORD_WATERMARKS.get(coordinate_id, 0)
        if n > prev:
            _COORD_WATERMARKS[coordinate_id] = n


def drain_coordinate_watermarks(
        sweep: int, registry: Optional[MetricsRegistry] = None) -> dict:
    """Flush the per-coordinate watermarks accumulated this sweep into
    ``hbm_watermark_bytes{coordinate}`` gauges + ``cd.hbm_watermark``
    spans (rides the sweep-boundary drain, where the hot loop already
    pays a host round-trip). Returns the drained map."""
    if not _ARMED:
        return {}
    with _LOCK:
        drained = dict(_COORD_WATERMARKS)
        _COORD_WATERMARKS.clear()
    if not drained:
        return drained
    reg = registry or _REGISTRY
    gauge = reg.gauge("hbm_watermark_bytes")
    for cid, n in sorted(drained.items()):
        gauge.set(n, coordinate=cid)
        with trace.span("cd.hbm_watermark", sweep=sweep, coordinate=cid,
                        watermark_bytes=n):
            pass
    return drained
