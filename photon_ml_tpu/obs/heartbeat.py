"""Stall-detecting heartbeat: periodic progress records for long runs.

The observable complement to the multi-host worker supervision (PR 1):
the supervisor notices a DEAD worker, the heartbeat notices a LIVE one
that has stopped making progress — a wedged collective, a hung input
read, an XLA compile gone pathological. A daemon thread wakes every
``interval_seconds``, asks the tracer how long ago the last span closed,
and appends one JSON line to the run's ``metrics.jsonl``::

    {"kind": "heartbeat", "uptime_s": ..., "spans_closed": ...,
     "spans_dropped": ..., "last_span_close_age_s": ...,
     "open_spans": [...], "stalled": false}

When no span has closed within ``stall_seconds`` the record is flagged
``stalled``, the warning is logged once per stall episode (via
``utils/logging``-style ``warn`` callables), and the ``stalls`` counter
increments — so a stalled multi-host gang is visible in every process's
metrics stream even when stdout is silent.

:meth:`Heartbeat.check` is the single evaluation step and is callable
directly (tests drive it without sleeping through real intervals).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Optional

from photon_ml_tpu.obs.metrics import REGISTRY, MetricsRegistry
from photon_ml_tpu.obs.trace import Tracer


class Heartbeat:
    """Periodic progress/stall records off a :class:`Tracer`."""

    def __init__(self, tracer: Tracer,
                 out_path: Optional[str] = None,
                 interval_seconds: float = 10.0,
                 stall_seconds: float = 120.0,
                 warn: Optional[Callable[[str], None]] = None,
                 registry: Optional[MetricsRegistry] = None,
                 on_beat: Optional[Callable[[], None]] = None,
                 on_record: Optional[Callable[[dict], None]] = None):
        self.tracer = tracer
        self.out_path = out_path
        self.interval_seconds = float(interval_seconds)
        self.stall_seconds = float(stall_seconds)
        self._warn = warn
        self._registry = registry or REGISTRY
        self._on_beat = on_beat
        self._on_record = on_record
        self.stalled = False
        self.beats = 0
        self._write_failed = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._write_lock = threading.Lock()

    def check(self) -> dict:
        """One heartbeat evaluation: build the record, append it to
        ``out_path`` (when set), flag/log stall transitions, and run the
        ``on_beat`` hook (the ObservedRun's span spill)."""
        if self._on_beat is not None:
            try:
                self._on_beat()
            except Exception as e:  # a full disk must not kill the beat
                if self._warn is not None:
                    self._warn(f"heartbeat: on_beat hook raised: {e!r}")
        age = self.tracer.seconds_since_last_close()
        stalled = age > self.stall_seconds
        record = {
            "kind": "heartbeat",
            "time": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "uptime_s": round(self.tracer.uptime_seconds(), 3),
            "spans_closed": self.tracer.spans_closed,
            "spans_dropped": self.tracer.spans_dropped,
            "last_span_close_age_s": round(age, 3),
            "open_spans": self.tracer.open_spans()[:8],
            "stalled": stalled,
            # compact live counters: the telemetry stream / run-dir tail
            # answers "how hard is the run working" WITHOUT waiting for
            # the exit snapshot (tools/photon_status.py reads these)
            "metric_totals": self._registry.totals(),
        }
        if stalled and not self.stalled:
            self._registry.counter("stalls").inc()
            if self._warn is not None:
                # dump the FULL open-span stack with per-span ages into
                # the driver log — a hung chaos run must be diagnosable
                # from the log alone (which span is wedged, how long)
                report = self.tracer.open_span_report()
                stack_dump = ("\n  ".join(report) if report
                              else "(no open spans)")
                self._warn(
                    f"heartbeat: STALL — no span closed in {age:.1f}s "
                    f"(window {self.stall_seconds:.1f}s); open-span "
                    f"stack:\n  {stack_dump}")
        self.stalled = stalled
        self.beats += 1
        if self._on_record is not None:
            try:  # the live-export hook must not kill the beat either
                self._on_record(record)
            except Exception as e:
                if self._warn is not None:
                    self._warn(f"heartbeat: on_record hook raised: {e!r}")
        if self.out_path is not None:
            try:
                with self._write_lock:
                    with open(self.out_path, "a") as fh:
                        fh.write(json.dumps(record) + "\n")
                self._write_failed = False
            except OSError as e:
                # a full disk / vanished trace dir must not kill the
                # daemon: stall DETECTION (the warn above) still works
                # even when the record can't be persisted
                if not self._write_failed and self._warn is not None:
                    self._warn(f"heartbeat: cannot append to "
                               f"{self.out_path}: {e!r}")
                self._write_failed = True
        return record

    # -- thread lifecycle --------------------------------------------------

    def start(self) -> "Heartbeat":
        if self.interval_seconds <= 0:  # <= 0 disables the daemon
            return self
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()  # a start() after stop() must actually beat
        self._thread = threading.Thread(
            target=self._loop, name="photon-heartbeat", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_seconds):
            try:
                self.check()
            except Exception as e:  # the beat must outlive any one check
                if self._warn is not None:
                    self._warn(f"heartbeat: check failed: {e!r}")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            if not self._thread.is_alive():  # a wedged thread (NFS append
                # stuck past the join timeout) stays tracked so a restart
                # can't spawn a second writer against the same file
                self._thread = None
