"""Labeled metrics registry: counters, gauges, histograms.

The process-wide metrics half of the observability layer (``obs/trace.py``
is the spans half). Prometheus-shaped without the dependency: a metric is
a name plus a map from a label set (sorted ``(key, value)`` tuples) to a
value, so ``counter("host_fetches").inc(site="cd.epilogue")`` gives
per-site attribution for free while ``total()`` stays the label-sum the
legacy ``utils/sync_telemetry.host_fetch_count()`` contract needs.

Everything here is stdlib-only and never touches jax — incrementing a
counter can never introduce a device sync, so instrumented hot loops stay
green under the transfer-guard test and photonlint's W1xx family.

Export is JSONL (:meth:`MetricsRegistry.snapshot` → one dict per
metric/label-set), written by the driver's ``--trace-dir`` integration
(``obs/run.py``) next to the Chrome trace.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

_LabelKey = tuple  # sorted ((key, value), ...) pairs


def _label_key(labels: dict) -> _LabelKey:
    return tuple(sorted(labels.items()))


class Counter:
    """Monotonically increasing value per label set."""

    kind = "counter"

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._values: dict[_LabelKey, float] = {}

    def inc(self, n: float = 1, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + n

    def total(self) -> float:
        """Sum over every label set (the unlabeled legacy view)."""
        with self._lock:
            return sum(self._values.values())

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0)

    def by_label(self, label: str) -> dict[str, float]:
        """Aggregate totals keyed by one label's values (label sets
        without that label land under ``""``)."""
        out: dict[str, float] = {}
        with self._lock:
            for key, v in self._values.items():
                name = dict(key).get(label, "")
                out[name] = out.get(name, 0) + v
        return out

    def items(self) -> dict[_LabelKey, float]:
        with self._lock:
            return dict(self._values)

    def reset(self) -> None:
        with self._lock:
            self._values.clear()

    def records(self) -> list[dict]:
        with self._lock:
            return [{"kind": self.kind, "name": self.name,
                     "labels": dict(key), "value": v}
                    for key, v in sorted(self._values.items())]


class Gauge(Counter):
    """Last-written value per label set (same storage as Counter)."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = value


#: Default histogram buckets: powers of two — wide enough for iteration
#: counts, lane counts, and millisecond durations alike.
DEFAULT_BUCKETS = tuple(2 ** i for i in range(0, 15))


class Histogram:
    """Bucketed distribution per label set (count/sum/min/max + cumulative
    ``le`` bucket counts, Prometheus-style)."""

    kind = "histogram"

    def __init__(self, name: str,
                 buckets: Optional[Sequence[float]] = None):
        self.name = name
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        self._lock = threading.Lock()
        # key -> [count, sum, min, max, per-bucket counts]
        self._values: dict[_LabelKey, list] = {}

    def observe(self, x: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            slot = self._values.get(key)
            if slot is None:
                slot = [0, 0.0, x, x, [0] * (len(self.buckets) + 1)]
                self._values[key] = slot
            slot[0] += 1
            slot[1] += x
            slot[2] = min(slot[2], x)
            slot[3] = max(slot[3], x)
            for i, le in enumerate(self.buckets):
                if x <= le:
                    slot[4][i] += 1
                    break
            else:
                slot[4][-1] += 1  # overflow bucket

    def snapshot(self, **labels) -> Optional[dict]:
        key = _label_key(labels)
        with self._lock:
            slot = self._values.get(key)
            if slot is None:
                return None
            return self._record(dict(key), slot)

    def _record(self, labels: dict, slot: list) -> dict:
        # storage is per-interval; export is CUMULATIVE (Prometheus
        # ``le`` semantics: le_X counts observations <= X, le_inf = count)
        buckets = {}
        running = 0
        for g, c in zip(self.buckets, slot[4]):
            running += c
            buckets[f"le_{g}"] = running
        buckets["le_inf"] = running + slot[4][-1]
        return {"kind": self.kind, "name": self.name, "labels": labels,
                "count": slot[0], "sum": slot[1],
                "min": slot[2], "max": slot[3], "buckets": buckets}

    def reset(self) -> None:
        with self._lock:
            self._values.clear()

    def records(self) -> list[dict]:
        with self._lock:
            return [self._record(dict(key), slot)
                    for key, slot in sorted(self._values.items())]


class MetricsRegistry:
    """Name-indexed metric store; ``counter``/``gauge``/``histogram`` are
    get-or-create, so call sites never coordinate registration order."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def _get(self, name: str, cls, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, **kwargs)
                self._metrics[name] = m
            elif type(m) is not cls:  # exact: Gauge must not pass as Counter
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str,
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        h = self._get(name, Histogram, buckets=buckets)
        if buckets is not None and tuple(sorted(buckets)) != h.buckets:
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{h.buckets}, not {tuple(sorted(buckets))}")
        return h

    def snapshot(self) -> list[dict]:
        """Every metric/label-set as a JSONL-able record."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: list[dict] = []
        for m in sorted(metrics, key=lambda m: m.name):
            out.extend(m.records())
        return out

    def totals(self) -> dict:
        """``{name: label-summed total}`` for counters and gauges plus
        ``{name: {"count", "sum"}}`` for histograms — the compact
        per-heartbeat snapshot the live telemetry stream (and
        ``tools/photon_status.py``) rides on. The histogram entry keeps
        a distribution like ``re_chunk_active_lanes`` visible live
        (count and running sum; full bucket records still only ship in
        the exit snapshot). Scalar consumers key on scalar names, so
        the dict-valued entries never collide with them.

        A LABELED histogram's entry additionally carries ``series``:
        the per-label-set records (count/sum/min/max + cumulative
        ``le`` buckets), so a consumer like ``photon_status --fleet``
        can estimate per-label percentiles (the
        ``serve_stage_ms{stage}`` breakdown) from heartbeat totals
        alone. Additive: scalar-shaped consumers never see it, and
        unlabeled histograms stay in the compact two-key form."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: dict = {}
        for m in sorted(metrics, key=lambda m: m.name):
            if isinstance(m, Counter):
                out[m.name] = m.total()
            elif isinstance(m, Histogram):
                records = m.records()
                entry = {
                    "count": sum(r["count"] for r in records),
                    "sum": sum(r["sum"] for r in records)}
                if any(r["labels"] for r in records):
                    entry["series"] = [
                        {"labels": r["labels"], "count": r["count"],
                         "sum": r["sum"], "min": r["min"],
                         "max": r["max"], "buckets": r["buckets"]}
                        for r in records]
                out[m.name] = entry
        return out

    def reset(self) -> None:
        """Zero every metric (bench/test isolation; registrations stay)."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m.reset()


#: The process-wide registry every instrumented site writes to.
REGISTRY = MetricsRegistry()
