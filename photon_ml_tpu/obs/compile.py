"""Compile/retrace attribution: the device plane's "why did XLA build
an executable" half of the observability layer.

The package's hot jitted entry points (the CD fused epilogue, the
random-effect block dispatch, the three fixed-effect solvers) route
their calls through :func:`call`, a site-labeled indirection that is a
plain passthrough while disarmed (one module-global check — the
default, so nothing here costs the untraced hot path anything) and,
when armed via ``--device-telemetry``:

- keys each call on the site's *abstract signature* (array shapes /
  dtypes / weak types, pytree structure, static values, function
  identities — the same things jax's dispatch cache keys on),
- on a signature never seen at that site, runs the compile explicitly
  via the AOT API (``fn.lower(*args).compile()``) inside an
  ``xla.compile`` span, records ``compiles{site}`` and
  ``compile_secs{site}``, and captures the executable's
  ``cost_analysis()`` flops / bytes-accessed into the span labels (and
  the ``xla_flops{site}`` / ``xla_bytes_accessed{site}`` gauges, which
  ``tools/trace_report.py --device`` joins with span self-time),
- diffs every *retrace* (a new signature at a site that already
  compiled one) against the site's previous signature and emits a
  zero-duration ``xla.retrace`` span naming the argument that changed
  and how (shape / dtype / static value / structure) — the record
  rides the normal span spill into ``spans.jsonl`` and the live
  telemetry stream,
- answers subsequent calls with the cached compiled executable
  (measured: indistinguishable from jit's C++ fastpath), with the
  site's declared static positions stripped from the argument list.

Armed overhead is gated by the same <2% warm-pass contract as span
tracing (tests/test_obs_device.py); the signature walk is metadata-only
(``shape``/``dtype`` attributes, never values), so the armed path adds
zero device syncs and stays green under the transfer-guard test.

Every AOT step is CONTAINED: a function the AOT API cannot lower (or an
executable whose calling convention surprises us) permanently falls the
*signature* back to the plain call — instrumentation can degrade to
uninstrumented, never break training.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

from photon_ml_tpu.obs import trace
from photon_ml_tpu.obs.metrics import REGISTRY, MetricsRegistry

_ARMED = False
_REGISTRY: MetricsRegistry = REGISTRY

#: site -> _Site; module-level so repeated runs (the warm bench pass)
#: reuse compiled executables exactly like jit's dispatch cache would.
_SITES: dict[str, "_Site"] = {}

#: Signature cache entries use this sentinel for "AOT failed here — call
#: the plain jitted function for this signature forever".
_FALLBACK = object()


class _Site:
    __slots__ = ("name", "cache", "last_sig", "last_arg_names")

    def __init__(self, name: str):
        self.name = name
        self.cache: dict = {}  # signature -> Compiled | _FALLBACK
        self.last_sig: Optional[tuple] = None
        self.last_arg_names: Optional[Sequence[str]] = None


def arm(registry: Optional[MetricsRegistry] = None) -> None:
    """Switch the instrumented call sites live (idempotent)."""
    global _ARMED, _REGISTRY
    _REGISTRY = registry or REGISTRY
    _ARMED = True


def disarm() -> None:
    global _ARMED
    _ARMED = False


def is_armed() -> bool:
    return _ARMED


def reset() -> None:
    """Drop every site's executable cache and signature history (test
    isolation; a long-lived process keeps its cache across runs)."""
    _SITES.clear()


def describe(x) -> tuple:
    """One argument's abstract signature: shapes/dtypes for arrays,
    recursed structure for containers and pytrees, identity for
    callables, value for hashable statics. Metadata-only — never reads
    array VALUES, so building a signature cannot sync the device."""
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return ("array", tuple(x.shape), str(x.dtype),
                bool(getattr(x, "weak_type", False)))
    if isinstance(x, (list, tuple)):
        return ("seq", type(x).__name__, tuple(describe(e) for e in x))
    if isinstance(x, dict):
        return ("dict", tuple(sorted(
            (str(k), describe(v)) for k, v in x.items())))
    if x is None or isinstance(x, (bool, int, float, str)):
        return ("static", repr(x))
    if callable(x):
        # function statics hash by identity in jax's cache too: a fresh
        # closure per batch IS a retrace, and this makes it visible
        return ("fn", getattr(x, "__qualname__", type(x).__name__), id(x))
    try:
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(x)
        if len(leaves) == 1 and leaves[0] is x:
            # unregistered object: tree_flatten returns it as its own
            # single leaf — recursing would never terminate
            return ("opaque", type(x).__name__, id(x))
        return ("pytree", str(treedef), tuple(describe(l) for l in leaves))
    except Exception:
        return ("opaque", type(x).__name__, id(x))


def _short(d) -> str:
    """Human-readable rendering of one argument descriptor for the
    retrace-cause record (bounded length — these land in span labels)."""
    if not isinstance(d, tuple) or not d:
        return repr(d)[:120]
    kind = d[0]
    if kind == "array":
        return f"{d[2]}{list(d[1])}" + ("w" if d[3] else "")
    if kind == "seq":
        inner = ",".join(_short(e) for e in d[2][:4])
        more = f",+{len(d[2]) - 4}" if len(d[2]) > 4 else ""
        return f"{d[1]}[{inner}{more}]"
    if kind == "static":
        return d[1][:120]
    if kind == "fn":
        return f"fn:{d[1]}@{d[2]:x}"
    if kind == "pytree":
        return f"pytree({len(d[2])} leaves)"
    return repr(d)[:120]


def _diff_field(old, new) -> str:
    """Which FACET of one argument's descriptor changed."""
    if not (isinstance(old, tuple) and isinstance(new, tuple)):
        return "value"
    if old[:1] != new[:1]:
        return "kind"
    kind = old[0]
    if kind == "array":
        if old[1] != new[1]:
            return "shape"
        if old[2] != new[2]:
            return "dtype"
        return "weak_type"
    if kind == "static":
        return "static_value"
    if kind == "fn":
        return "function_identity"
    if kind in ("seq", "dict", "pytree"):
        return "structure"
    return "value"


def _retrace_cause(old_sig, new_sig, arg_names):
    """(arg, field, old, new) for the FIRST differing argument — the
    record a shape-perturbed run needs to name its own bug. Signature
    element 0 is the function descriptor (the epilogue factory hands a
    distinct jitted function per (task, N)); elements 1.. are args."""
    for i, (o, n) in enumerate(zip(old_sig, new_sig)):
        if o != n:
            if i == 0:
                name = "<function>"
            elif arg_names and i - 1 < len(arg_names):
                name = arg_names[i - 1]
            else:
                name = f"arg{i - 1}"
            return name, _diff_field(o, n), _short(o), _short(n)
    if len(old_sig) != len(new_sig):
        return "<arity>", "arg_count", str(len(old_sig)), str(len(new_sig))
    return "<unknown>", "unknown", "", ""


def _cost_analysis(compiled) -> tuple[Optional[float], Optional[float]]:
    """(flops, bytes_accessed) from the executable's cost analysis, or
    (None, None) where the backend doesn't report one."""
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return None, None
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if not isinstance(cost, dict):
        return None, None
    flops = cost.get("flops")
    nbytes = cost.get("bytes accessed")
    return (float(flops) if flops is not None else None,
            float(nbytes) if nbytes is not None else None)


def _compile_here(site: "_Site", fn, args, static_argnums, signature):
    """Signature miss: run the compile EXPLICITLY (AOT), attribute it,
    cache the executable. Returns the call's result."""
    registry = _REGISTRY
    is_retrace = site.last_sig is not None
    # photonlint: allow-W201(host-side compile timing: call() bypasses this whole path when a jax trace is active)
    t0 = time.perf_counter()
    try:
        compiled = fn.lower(*args).compile()
    except Exception:
        # not AOT-lowerable (or convention mismatch): the plain call
        # still compiles through jit's own cache — time THAT as the
        # compile cost (first call = trace+compile+run) and pin this
        # signature to the plain path.
        result = fn(*args)
        # photonlint: allow-W201(host-side compile timing: call() bypasses this whole path when a jax trace is active)
        secs = time.perf_counter() - t0
        site.cache[signature] = _FALLBACK
        flops = nbytes = None
    else:
        # photonlint: allow-W201(host-side compile timing: call() bypasses this whole path when a jax trace is active)
        secs = time.perf_counter() - t0
        site.cache[signature] = compiled
        flops, nbytes = _cost_analysis(compiled)
        result = _call_compiled(site, fn, compiled, args, static_argnums,
                                signature)
    labels = {"site": site.name, "secs": round(secs, 6)}
    if flops is not None:
        labels["flops"] = flops
        registry.gauge("xla_flops").set(flops, site=site.name)
    if nbytes is not None:
        labels["bytes_accessed"] = nbytes
        registry.gauge("xla_bytes_accessed").set(nbytes, site=site.name)
    registry.counter("compiles").inc(site=site.name)
    registry.counter("compile_secs").inc(secs, site=site.name)
    with trace.span("xla.compile", **labels):
        pass
    if is_retrace:
        arg, field, old, new = _retrace_cause(
            site.last_sig, signature, site.last_arg_names)
        registry.counter("retrace_causes").inc(site=site.name, field=field)
        with trace.span("xla.retrace", site=site.name, arg=str(arg),
                        field=field, old=old, new=new):
            pass
    site.last_sig = signature
    return result


def _call_compiled(site, fn, compiled, args, static_argnums, signature):
    """Invoke a cached executable: jax's compiled calling convention
    takes the DYNAMIC arguments only, so the site's declared static
    positions are stripped. A convention surprise falls this signature
    back to the plain call permanently."""
    if static_argnums:
        statics = frozenset(static_argnums)
        dynamic = [a for i, a in enumerate(args) if i not in statics]
    else:
        dynamic = args
    try:
        return compiled(*dynamic)
    except (TypeError, ValueError):
        site.cache[signature] = _FALLBACK
        return fn(*args)


def call(site_name: str, fn, args: Sequence,
         static_argnums: Sequence[int] = (),
         arg_names: Optional[Sequence[str]] = None):
    """Call ``fn(*args)`` through the compile-attribution layer.

    ``fn`` must be a jit-wrapped callable whose static arguments (by
    POSITION in ``args``, after jax resolves ``static_argnames`` to
    positions) are listed in ``static_argnums``; ``arg_names`` (parallel
    to ``args``) names arguments in retrace-cause records. Disarmed —
    the default — this is ``fn(*args)`` plus one global check."""
    if not _ARMED:
        return fn(*args)
    import jax.core

    if not jax.core.trace_state_clean():
        # called under jit/vmap/shard_map tracing (e.g. the vmapped
        # per-entity solver): the inner call compiles into the OUTER
        # executable — nothing to attribute here, and AOT would break
        return fn(*args)
    site = _SITES.get(site_name)
    if site is None:
        site = _SITES[site_name] = _Site(site_name)
    site.last_arg_names = arg_names
    signature = (("fn", getattr(fn, "__qualname__", type(fn).__name__),
                  id(fn)),) + tuple(describe(a) for a in args)
    cached = site.cache.get(signature)
    if cached is None:
        return _compile_here(site, fn, args, static_argnums, signature)
    site.last_sig = signature
    if cached is _FALLBACK:
        return fn(*args)
    return _call_compiled(site, fn, cached, args, static_argnums, signature)
