"""Streaming telemetry export: live span/metric/heartbeat records.

The on-line half of the observability layer (``obs/run.py`` is the
flush-at-exit half): a :class:`TelemetrySink` ships records as
line-delimited JSON over a local TCP or Unix socket to whatever consumer
is listening (``tools/photon_status.py`` is the first), with a file-tail
fallback when no consumer ever connects — so a long multi-host run is
watchable WHILE it trains instead of opaque until it exits.

The contract that makes this safe to wire into the CD hot loop:

- :meth:`TelemetrySink.emit` is a **bounded non-blocking enqueue**
  (``put_nowait`` on a bounded queue). A slow, dead, or never-connected
  consumer can only ever cause records to be DROPPED — counted on the
  ``telemetry_dropped{kind=...}`` counter — never block or kill the run.
- All I/O happens on one daemon writer thread: connects and writes go
  through ``utils/retry`` (site ``obs.export``, the registered drillable
  fault point), sends carry a short socket timeout so a consumer that
  stops reading looks like a failed write (dropped, counted), and a
  failed batch marks the connection dead so the next batch reconnects
  under deterministic backoff instead of burning the retry schedule on
  every record.
- Like the rest of ``obs/``: stdlib-only, no jax import, zero device
  work.

Endpoints (``--telemetry-endpoint`` on both GAME drivers):

- ``host:port`` or ``tcp://host:port`` — TCP consumer
- ``unix:/path/to.sock`` (or ``unix:///path``) — Unix-domain consumer
- ``file:/path/out.jsonl`` (or a bare path) — append NDJSON to a file
  (``tail -f``-able; also the fallback target when a socket consumer
  never shows up)

Line protocol (version :data:`TELEMETRY_PROTO`, carried in the run
manifest): one JSON object per ``\\n``-terminated line, every record
tagged ``kind`` ∈ {``run_manifest``, ``span``, ``heartbeat``,
``run_end``} plus ``process_index``; span records carry the
``spans.jsonl`` schema (``name``/``ts_us``/``dur_us``/``tid``/``depth``/
``labels``), heartbeat records the ``metrics.jsonl`` heartbeat schema
(including the ``metric_totals`` snapshot), and the first record on a
stream is the run manifest. A killed producer can tear at most the LAST
line — every complete line always parses.
"""

from __future__ import annotations

import json
import os
import queue
import socket
import threading
import time
from typing import Callable, Optional

from photon_ml_tpu.obs.metrics import REGISTRY, MetricsRegistry
from photon_ml_tpu.utils.faults import fault_point
from photon_ml_tpu.utils.retry import (
    RetryExhaustedError,
    RetryPolicy,
    call_with_retry,
)

#: Telemetry line-protocol version, stamped into every run manifest
#: (file and stream): consumers dispatch on it instead of sniffing
#: record shapes when the schema evolves.
TELEMETRY_PROTO = 1

#: Export retry: short and bounded — telemetry I/O must never stall the
#: run it is observing (same stance as obs/run's flush retry).
_EXPORT_RETRY = RetryPolicy(max_attempts=3, base_delay_seconds=0.01,
                            max_delay_seconds=0.1)

#: Seconds a failed connect blacklists the socket endpoint before the
#: writer tries again (between attempts batches flow to the fallback
#: file, or are dropped+counted when there is none).
_RECONNECT_SECONDS = 2.0

#: Socket send timeout: a consumer that stopped reading (TCP buffers
#: full) looks like a failed write within this bound, so backpressure
#: turns into counted drops instead of a wedged writer thread.
_SEND_TIMEOUT_SECONDS = 0.5

DEFAULT_MAX_QUEUED_RECORDS = 4096


def parse_endpoint(endpoint: str) -> tuple[str, object]:
    """``(scheme, address)`` from an endpoint string: ``("tcp", (host,
    port))``, ``("unix", path)``, or ``("file", path)``.

    Raises ``ValueError`` on an EXPLICIT ``tcp://`` endpoint without a
    valid ``host:port`` — silently treating a typo'd socket address as
    a file path would ship the stream into a file named after the host
    while the intended consumer hears nothing."""
    ep = endpoint.strip()
    if ep.startswith("tcp://"):
        ep = ep[len("tcp://"):]
        host, sep, port = ep.rpartition(":")
        if not (sep and host and port.isdigit()):
            raise ValueError(
                f"telemetry endpoint {endpoint!r}: tcp:// needs "
                f"host:port with a numeric port")
        return "tcp", (host, int(port))
    if ep.startswith("unix://"):
        return "unix", ep[len("unix://"):] or "/"
    elif ep.startswith("unix:"):
        return "unix", ep[len("unix:"):]
    elif ep.startswith("file://"):
        return "file", ep[len("file://"):] or "/"
    elif ep.startswith("file:"):
        return "file", ep[len("file:"):]
    host, sep, port = ep.rpartition(":")
    if sep and host and port.isdigit():
        return "tcp", (host, int(port))
    return "file", ep  # a bare path: file-tail mode


class TelemetrySink:
    """Non-blocking NDJSON record shipper with a daemon writer thread.

    ``emit()`` never blocks and never raises: a full queue (or a closed
    sink) drops the record and counts it on ``telemetry_dropped{kind}``.
    The writer drains the queue in batches and ships them to the
    endpoint; when a socket endpoint cannot be connected (or a batch
    write exhausts its retries) the batch falls back to
    ``fallback_path`` when one is set, else it is dropped (counted).
    """

    def __init__(self, endpoint: str,
                 fallback_path: Optional[str] = None,
                 max_queued_records: int = DEFAULT_MAX_QUEUED_RECORDS,
                 registry: Optional[MetricsRegistry] = None,
                 warn: Optional[Callable[[str], None]] = None):
        self.scheme, self.address = parse_endpoint(endpoint)
        self.endpoint = endpoint
        self.fallback_path = fallback_path
        self._registry = registry or REGISTRY
        self._warn = warn
        # separate warn-once flags: "no consumer, falling back" is
        # expected degradation, "fallback unwritable, dropping" is the
        # serious one — the first must not silence the second
        self._warned_no_consumer = False
        self._warned_drop = False
        self._queue: "queue.Queue[dict]" = queue.Queue(
            maxsize=max_queued_records)
        self._sock: Optional[socket.socket] = None
        self._connect_blocked_until = 0.0
        self._stop = threading.Event()
        self._closed = False
        self._thread = threading.Thread(
            target=self._writer_loop, name="photon-telemetry", daemon=True)
        self._thread.start()

    # -- producer side (hot-loop safe) ------------------------------------

    def emit(self, record: dict) -> bool:
        """Enqueue one record; NEVER blocks. Returns False (and counts
        the drop) when the queue is full or the sink is closed."""
        if self._closed:
            self._drop(record)
            return False
        try:
            self._queue.put_nowait(record)
            return True
        except queue.Full:
            self._drop(record)
            return False

    def _drop(self, record: dict) -> None:
        self._registry.counter("telemetry_dropped").inc(
            kind=str(record.get("kind", "?")))

    def dropped_total(self) -> float:
        return self._registry.counter("telemetry_dropped").total()

    # -- writer thread -----------------------------------------------------

    def _writer_loop(self) -> None:
        while True:
            batch = self._next_batch()
            if batch is None:
                break
            if batch:
                try:
                    self._ship(batch)
                except Exception as e:  # the writer must outlive any batch
                    for record in batch:
                        self._drop(record)
                    if self._warn is not None:
                        self._warn(f"telemetry: unexpected export "
                                   f"failure, batch dropped: {e!r}")
        self._disconnect()

    def _next_batch(self, max_records: int = 256) -> Optional[list]:
        """Up to ``max_records`` queued records; [] on an idle tick,
        None when stopped AND drained (writer exit)."""
        try:
            first = self._queue.get(timeout=0.1)
        except queue.Empty:
            return None if self._stop.is_set() else []
        batch = [first]
        while len(batch) < max_records:
            try:
                batch.append(self._queue.get_nowait())
            except queue.Empty:
                break
        return batch

    def _encode(self, batch: list) -> bytes:
        return b"".join(
            json.dumps(record, default=str).encode("utf-8") + b"\n"
            for record in batch)

    def _ship(self, batch: list) -> None:
        payload = self._encode(batch)
        if self.scheme != "file" and self._ensure_connected():
            try:
                call_with_retry(lambda: self._send(payload),
                                site="obs.export", policy=_EXPORT_RETRY)
                return
            # OSError too: FileNotFoundError (a unix socket path that
            # never existed) is permanent to the retry layer and
            # propagates unwrapped
            except (RetryExhaustedError, OSError):
                # consumer died / stopped reading mid-run: blacklist the
                # endpoint briefly; this batch (and the next ones, until
                # the blackout lapses) flow to the fallback file
                self._disconnect()
                self._connect_blocked_until = (
                    time.monotonic() + _RECONNECT_SECONDS)
        if self.scheme == "file":
            target: Optional[str] = str(self.address)
        else:
            target = self.fallback_path
        if target is None:
            for record in batch:
                self._drop(record)
            return
        try:
            call_with_retry(lambda: self._append(target, payload),
                            site="obs.export", policy=_EXPORT_RETRY)
        except (RetryExhaustedError, OSError) as e:
            for record in batch:
                self._drop(record)
            if not self._warned_drop and self._warn is not None:
                self._warned_drop = True
                self._warn(f"telemetry: cannot write {target}: {e!r} — "
                           f"records are being dropped (counted on "
                           f"telemetry_dropped)")

    def _send(self, payload: bytes) -> None:
        """One send attempt. A failed attempt (timeout from a consumer
        that stopped reading, EPIPE from one that died, an injected
        fault) tears the connection down so the retry re-ships the WHOLE
        payload on a FRESH connection — a consumer may see a batch
        twice across reconnects, never half a line spliced into the
        next record (each connection's stream stays line-clean)."""
        if self._sock is None:
            self._sock = self._connect()
        try:
            fault_point("obs.export")
            self._sock.sendall(payload)
        except BaseException:
            self._disconnect()
            raise

    def _append(self, path: str, payload: bytes) -> None:
        fault_point("obs.export", path=path)
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(path, "ab") as fh:
            fh.write(payload)

    def _ensure_connected(self) -> bool:
        if self._sock is not None:
            return True
        now = time.monotonic()
        if now < self._connect_blocked_until:
            return False
        try:
            self._sock = call_with_retry(
                self._connect, site="obs.export", policy=_EXPORT_RETRY)
            self._connect_blocked_until = 0.0
            return True
        except (RetryExhaustedError, OSError) as e:
            self._connect_blocked_until = now + _RECONNECT_SECONDS
            if not self._warned_no_consumer and self._warn is not None:
                self._warned_no_consumer = True
                where = (f"falling back to {self.fallback_path}"
                         if self.fallback_path else
                         "records are being dropped (counted on "
                         "telemetry_dropped)")
                self._warn(f"telemetry: no consumer at {self.endpoint} "
                           f"({getattr(e, 'last', e)!r}) — {where}")
            return False

    def _connect(self) -> socket.socket:
        fault_point("obs.export")
        if self.scheme == "unix":
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                sock.settimeout(_SEND_TIMEOUT_SECONDS)
                sock.connect(str(self.address))
            except BaseException:
                sock.close()
                raise
            return sock
        sock = socket.create_connection(
            self.address, timeout=_SEND_TIMEOUT_SECONDS)
        return sock

    def _disconnect(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    # -- lifecycle ---------------------------------------------------------

    def close(self, timeout: float = 2.0) -> None:
        """Stop accepting records, give the writer ``timeout`` seconds to
        drain what is queued, then drop (and count) the rest. Idempotent;
        never raises — exporter teardown must not change a run's exit."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        self._thread.join(timeout=timeout)
        while True:  # whatever the writer didn't drain in time
            try:
                self._drop(self._queue.get_nowait())
            except queue.Empty:
                break
