"""Observability layer: span tracing, labeled metrics, run manifests.

One subsystem replacing three disjoint fragments (the bench-only
wall-clock splits, the single process-global fetch counter, the
log-only event bus):

- ``obs.trace`` — thread-safe nestable span tracer
  (``trace.span("cd.update", coordinate=cid)``), exported as Chrome
  trace-event JSON (Perfetto-loadable) and structured JSONL. Disabled by
  default; zero jax, zero device syncs.
- ``obs.metrics`` — counters/gauges/histograms with labels
  (``REGISTRY``); ``utils/sync_telemetry`` is now a thin shim over the
  ``host_fetches`` counter, so per-site fetch attribution is free while
  the legacy ``host_fetch_count()`` total keeps its contract.
- ``obs.bridge`` — event-bus listener mirroring fault/recovery/
  quarantine events into counters.
- ``obs.heartbeat`` — stall-detecting progress records for long runs.
- ``obs.export`` — the live telemetry plane: a bounded non-blocking
  sink streaming span/heartbeat/run-end records as line-delimited JSON
  to a local socket (or file-tail) consumer while the run trains.
- ``obs.run`` — the drivers' ``--trace-dir`` integration: run manifest,
  live heartbeat stream, final trace/metrics flush, and the
  ``--telemetry-endpoint`` / ``--device-telemetry`` wiring.
- ``obs.compile`` — the device plane's compile/retrace attribution:
  site-labeled AOT compiles (``xla.compile`` spans with
  ``cost_analysis()`` flops/bytes) and retrace-cause records naming
  the argument whose shape/dtype/static value changed.
- ``obs.devicemem`` — HBM accounting: heartbeat-cadence
  ``hbm_bytes{device, kind}`` gauges, per-coordinate watermarks at the
  CD sweep drain, run-wide ``peak_hbm_bytes`` on the run_end record.
- ``obs.otlp`` — the standard-protocol exit: NDJSON telemetry →
  OTLP/HTTP JSON traces + metrics (``tools/otlp_bridge.py`` is the
  CLI), versioned against ``telemetry_proto``.
"""

from photon_ml_tpu.obs import compile  # noqa: F401,A004
from photon_ml_tpu.obs import devicemem, trace  # noqa: F401
from photon_ml_tpu.obs.bridge import MetricsEventListener  # noqa: F401
from photon_ml_tpu.obs.export import (  # noqa: F401
    TELEMETRY_PROTO,
    TelemetrySink,
)
from photon_ml_tpu.obs.heartbeat import Heartbeat  # noqa: F401
from photon_ml_tpu.obs.metrics import (  # noqa: F401
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from photon_ml_tpu.obs.run import (  # noqa: F401
    ObservedRun,
    run_manifest,
    start_observed_run,
    start_observed_run_from_flags,
)
