"""Event-bus → metrics bridge: fault/recovery telemetry as counters.

The typed event bus (``utils/events.py``) already announces every fault,
recovery action, and quarantine; this listener folds those streams into
the metrics registry so a run's ``metrics.jsonl`` answers "how many
faults, of what kind, recovered how" without replaying driver logs::

    emitter.register_listener(MetricsEventListener())

Counters written (all on the process registry unless one is injected):

- ``faults{point, coordinate}`` — one per :class:`FaultEvent`
- ``recoveries{action}`` — retried / recovered / skipped / aborted
- ``quarantines{coordinate}`` — per-coordinate freeze events
- ``faults{point="io.shard"}`` — data shards lost to degraded ingest
  (the per-stage ``quarantined_shards`` counter is written directly by
  the :class:`~photon_ml_tpu.data.ingest.IngestPolicy`)
- ``optimization_logs`` — per-model optimization records (legacy driver)
"""

from __future__ import annotations

from typing import Optional

from photon_ml_tpu.obs.metrics import REGISTRY, MetricsRegistry
from photon_ml_tpu.utils.events import (
    CoordinateQuarantinedEvent,
    Event,
    FaultEvent,
    PhotonOptimizationLogEvent,
    RecoveryEvent,
    ShardQuarantinedEvent,
)


class MetricsEventListener:
    """EventEmitter listener that mirrors fault-tolerance events into
    labeled counters (idempotent per event — register it once)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._registry = registry or REGISTRY

    def __call__(self, event: Event) -> None:
        r = self._registry
        if isinstance(event, FaultEvent):
            r.counter("faults").inc(
                point=event.point, coordinate=event.coordinate_id or "")
        elif isinstance(event, CoordinateQuarantinedEvent):
            # before RecoveryEvent: both are terminal records, but a
            # quarantine is NOT a recovery action
            r.counter("quarantines").inc(coordinate=event.coordinate_id)
        elif isinstance(event, ShardQuarantinedEvent):
            # the IngestPolicy already counts quarantined_shards{stage}
            # directly (it must work without an event bus); here the
            # event only contributes to the faults stream for symmetry
            r.counter("faults").inc(point="io.shard", coordinate="")
        elif isinstance(event, RecoveryEvent):
            r.counter("recoveries").inc(action=event.action)
        elif isinstance(event, PhotonOptimizationLogEvent):
            r.counter("optimization_logs").inc()
